"""Two-tier embedding table: device hash-table cache in HBM + host-RAM store.

The TPU-native counterpart of the reference's PMem backend architecture
(`variable/PmemEmbeddingTable.h`: a DRAM LRU cache in front of persistent pools,
ICDE 2023) and the reason the reference can train 175 GB+ models on small devices:
here HBM holds a fixed-capacity hash-table cache (`tables/hash_table.py`) and the
full (unbounded) table lives in host RAM, so table size is bounded by HOST memory,
not HBM.

Protocol (host-driven, between jitted steps — ids are known host-side from the
input pipeline, like the reference's client-side request assembly):

1. `prepare(ids)`: ids previously evicted to the host are ADMITTED back into the
   device cache (one jitted scatter: rows + optimizer slots restored exactly);
   brand-new ids are left to the device table's insert-on-pull (their slots carry
   initializer values). If admission would push occupancy over the high-water
   mark, COLD residents are evicted first (see 3).
2. the train step runs entirely on device against the cache (normal hash path).
3. eviction under pressure is clock/second-chance (`eviction="clock"`,
   default, the TPU equivalent of the reference's per-item LRU,
   `PmemEmbeddingTable.h:143-163`): every resident id carries a referenced
   bit, set when a prepare() touches it; `evict_cold()` moves only the
   UNreferenced rows to the host store and rebuilds the cache keeping hot
   rows on device (host<->device traffic O(cold), a stable hot set stops
   round-tripping). The whole-cache `flush()` remains as the fallback when
   the hot set leaves no room (and as `eviction="flush"`, the coarse policy):
   every resident (id, row, slots) pulled host-side, merged into the host
   store (id-sorted arrays + searchsorted, same layout as checkpoint and
   standalone export), cache reset.

Exactness: a row's weights AND optimizer state round-trip bit-identically through
evict/admit, so training with a small cache equals training with an infinite table
whenever the initializer is slot-independent (e.g. Constant) — tested in
`tests/test_host_offload.py`. With slot-position-dependent random init, first-touch
values differ (the documented init-on-slot divergence of `tables/hash_table.py`).

Pipelining (round 14, arXiv:1905.04035; ring depth round 18): with
`pipeline=True` a one-worker staging thread buffers up to `stage_depth`
future batches' host lookups + device uploads (`stage(ids)`, driven by
`Trainer.offload_stage`) while the current step computes; the matching
`prepare(ids)` consumes the payload and pays only the jitted scatter.
Staging is a HINT — a residency epoch plus a `HostStore.version` counter
invalidate stale payloads (a residency-only change revalidates by
re-splitting the batch and accepting iff the non-resident set is unchanged,
the depth>1 steady state), and mismatches fall back to the synchronous
path, so correctness never depends on the loop shape.
`offload.pipeline_occupancy{slot=}` gauges per-ring-slot hit rate.
Admit shapes pad to powers of two (like the eviction pads), so the pipelined
path compiles a bounded program set and `assert_no_recompile` enforces it.
`densify_k=K` batches the evict/flush writebacks: K rounds append into
compact pending chunks and fold last-wins into ONE sorted merge
(`HostStore.defer`/`drain`), with lookups overlaying pending chunks so reads
stay exact mid-accumulation.
"""

from __future__ import annotations

import threading
import time
import warnings
from collections import deque
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..embedding import EmbeddingSpec, EmbeddingTableState, init_table_state
from ..optimizers import SparseOptimizer
from ..utils import metrics


class HostStore:
    """Id-sorted host arrays (weights + slots) with merge-update.

    Thread-safe (one RLock around every read/write): the pipelined staging
    worker reads via `lookup` while the training thread writes via
    `merge`/`defer`. Writebacks can be DEFERRED (`defer` + `drain`, the
    arXiv:1905.04035 densified accumulation): K eviction rounds append
    pending chunks instead of paying K sorted merges, and `drain` folds them
    last-wins into ONE merge. `lookup` overlays pending chunks, so a
    deferred row reads back correctly before the drain — callers never see
    the batching."""

    def __init__(self, dim: int, slot_widths: Dict[str, int]):
        self._lock = threading.RLock()
        self.ids = np.empty((0,), np.int64)  # guarded-by: self._lock
        self.weights = np.empty((0, dim), np.float32)  # guarded-by: self._lock
        self.slots = {k: np.empty((0, w), np.float32)
                      for k, w in slot_widths.items()}  # guarded-by: self._lock
        # deferred writeback chunks, oldest first: [(sorted ids, w, slots)]
        self._pending = []  # guarded-by: self._lock
        # content version: bumped on every mutation `lookup` could observe
        # (merge/defer/replace_all). Staged payloads record the version they
        # looked up against; a changed version invalidates them.
        self.version = 0  # guarded-by: self._lock

    def __len__(self) -> int:
        return len(self.ids)

    def lookup(self, ids: np.ndarray) -> Tuple[np.ndarray, np.ndarray, Dict]:
        """-> (hit mask, weight rows, slot rows) for `ids` (unknown ids return
        zero rows and hit=False). Pending deferred chunks overlay the base
        arrays newest-wins, so reads are exact mid-densification."""
        with self._lock:
            if len(self.ids) == 0:
                hit = np.zeros((len(ids),), bool)
                w = np.zeros((len(ids),) + self.weights.shape[1:], np.float32)
                s = {k: np.zeros((len(ids),) + v.shape[1:], np.float32)
                     for k, v in self.slots.items()}
            else:
                pos = np.searchsorted(self.ids, ids)
                pos_c = np.clip(pos, 0, len(self.ids) - 1)
                hit = self.ids[pos_c] == ids
                w = np.where(hit[:, None], self.weights[pos_c], 0.0)
                s = {k: np.where(hit[:, None], v[pos_c], 0.0)
                     for k, v in self.slots.items()}
            for pids, pw, ps in self._pending:  # oldest -> newest: last wins
                pos = np.searchsorted(pids, ids)
                pos_c = np.clip(pos, 0, len(pids) - 1)
                h = pids[pos_c] == ids
                if h.any():
                    hit = hit | h
                    w[h] = pw[pos_c[h]]
                    for k in s:
                        s[k][h] = ps[k][pos_c[h]]
            return hit, w, s

    def defer(self, ids: np.ndarray, weights: np.ndarray,
              slots: Dict[str, np.ndarray]) -> None:
        """Queue an upsert for the next `drain` (ids unique within the call)."""
        if len(ids) == 0:
            return
        order = np.argsort(ids, kind="stable")
        with self._lock:
            self.version += 1
            self._pending.append((
                np.asarray(ids)[order].astype(np.int64),
                np.asarray(weights)[order].astype(np.float32),
                {k: np.asarray(v)[order].astype(np.float32)
                 for k, v in slots.items()}))

    def drain(self) -> int:
        """Fold every pending chunk into the base arrays with ONE merge
        (last write per id wins, matching the per-call merge order). Returns
        the number of rows merged."""
        with self._lock:
            if not self._pending:
                return 0
            ids = np.concatenate([c[0] for c in self._pending])
            w = np.concatenate([c[1] for c in self._pending])
            s = {k: np.concatenate([c[2][k] for c in self._pending])
                 for k in self._pending[0][2]}
            self._pending = []
            # keep the LAST occurrence of each id: unique() on the reversed
            # array marks each id's first-from-the-end position
            _, ridx = np.unique(ids[::-1], return_index=True)
            keep = len(ids) - 1 - ridx
            self.merge(ids[keep], w[keep], {k: v[keep] for k, v in s.items()})
            return int(keep.size)

    def merge(self, ids: np.ndarray, weights: np.ndarray,
              slots: Dict[str, np.ndarray]) -> None:
        """Upsert rows (ids need not be sorted; duplicates of existing update)."""
        if len(ids) == 0:
            return
        order = np.argsort(ids, kind="stable")
        ids, weights = ids[order], weights[order]
        slots = {k: v[order] for k, v in slots.items()}
        with self._lock:
            self.version += 1
            if len(self.ids) == 0:
                exists = np.zeros((len(ids),), bool)
                pos_c = np.zeros((len(ids),), np.int64)
            else:
                pos = np.searchsorted(self.ids, ids)
                pos_c = np.clip(pos, 0, len(self.ids) - 1)
                exists = self.ids[pos_c] == ids
            # update existing in place
            if exists.any():
                self.weights[pos_c[exists]] = weights[exists]
                for k in self.slots:
                    self.slots[k][pos_c[exists]] = slots[k][exists]
            # insert the rest (merge two sorted runs)
            new = ~exists
            if new.any():
                self.ids = np.concatenate([self.ids, ids[new]])
                self.weights = np.concatenate([self.weights, weights[new]])
                for k in self.slots:
                    self.slots[k] = np.concatenate([self.slots[k],
                                                    slots[k][new]])
                order = np.argsort(self.ids, kind="stable")
                self.ids = self.ids[order]
                self.weights = self.weights[order]
                for k in self.slots:
                    self.slots[k] = self.slots[k][order]

    def nbytes(self) -> int:
        with self._lock:
            return (self.ids.nbytes + self.weights.nbytes
                    + sum(v.nbytes for v in self.slots.values())
                    + sum(c[0].nbytes + c[1].nbytes
                          + sum(v.nbytes for v in c[2].values())
                          for c in self._pending))

    def snapshot(self) -> "HostStore":
        """Copy for async writers: `merge` mutates rows in place, so a store
        handed to a persist worker thread must be decoupled from later flushes.
        Pending deferred chunks drain first — a snapshot is always fully
        merged."""
        with self._lock:
            self.drain()
            out = HostStore.__new__(HostStore)
            out.ids = self.ids.copy()
            out.weights = self.weights.copy()
            out.slots = {k: v.copy() for k, v in self.slots.items()}
            out._lock = threading.RLock()
            out._pending = []
            out.version = 0
            return out

    def replace_all(self, ids: np.ndarray, weights: np.ndarray,
                    slots: Dict[str, np.ndarray]) -> None:
        """Wholesale replacement (checkpoint load); ids must be unique."""
        order = np.argsort(ids, kind="stable")
        with self._lock:
            self.version += 1
            self._pending = []  # stale by definition: the store they patched is gone
            self.ids = ids[order].astype(np.int64)
            self.weights = weights[order].astype(np.float32)
            self.slots = {k: v[order].astype(np.float32)
                          for k, v in slots.items()}


def _admit_fn(state: EmbeddingTableState, ids, w_rows, s_rows, known):
    """Jitted: insert ALL `ids` into the cache (claiming slots); overwrite rows
    and optimizer slots only for host-`known` ids — brand-new ids keep their
    claimed slot's initializer values (insert-on-pull semantics).

    Also returns the per-id admitted mask (slot actually claimed) so the host
    can track residency truthfully: an overflowed id never got a row written,
    and marking it resident would make later prepare() calls skip re-admitting
    it while lookups read zeros from the device path."""
    from .hash_table import hash_find_or_insert

    keys, slot, overflow = hash_find_or_insert(state.keys, ids)
    capacity = state.keys.shape[0]
    admitted = slot < capacity
    ok = known & admitted
    target = jnp.where(ok, slot, capacity)
    weights = state.weights.at[target].set(
        w_rows.astype(state.weights.dtype), mode="drop")
    slots = {k: state.slots[k].at[target].set(
        s_rows[k].astype(state.slots[k].dtype), mode="drop")
        for k in state.slots}
    new_state = state.replace(keys=keys, weights=weights, slots=slots,
                              overflow=state.overflow + overflow)
    return new_state, admitted


def _evict_fn(state, cold_ids, hot_ids, fresh):
    """Jitted clock eviction (single device): gather the COLD rows out for the
    host store, then rebuild the cache from a fresh template keeping the HOT
    rows entirely on device — the host<->device traffic is O(cold), not
    O(cache) (the whole-cache flush's cost). The reference's per-item LRU
    achieves the same end inside its DRAM cache (`PmemEmbeddingTable.h:143-163`).

    Open-addressed probe chains cannot delete in place (a vacated slot would
    terminate later probes early), hence the rebuild: fresh keys, hot ids
    re-inserted, their rows copied old-slot -> new-slot on device."""
    from .hash_table import hash_find, hash_find_or_insert

    cap = state.keys.shape[0]
    cslot = hash_find(state.keys, cold_ids)
    cfound = cslot < cap
    cidx = jnp.clip(cslot, 0, cap - 1)
    cold_w = jnp.take(state.weights, cidx, axis=0)
    cold_s = {k: jnp.take(v, cidx, axis=0) for k, v in state.slots.items()}

    hslot = hash_find(state.keys, hot_ids)
    hfound = hslot < cap
    hidx = jnp.clip(hslot, 0, cap - 1)
    hot_w = jnp.take(state.weights, hidx, axis=0)
    hot_s = {k: jnp.take(v, hidx, axis=0) for k, v in state.slots.items()}

    keys, slot, overflow = hash_find_or_insert(fresh.keys, hot_ids)
    ok = hfound & (slot < cap)
    target = jnp.where(ok, slot, cap)
    weights = fresh.weights.at[target].set(hot_w, mode="drop")
    slots = {k: fresh.slots[k].at[target].set(hot_s[k], mode="drop")
             for k in fresh.slots}
    # a hot row whose re-insert overflowed the probe chain (rare) must reach
    # the store, not vanish: hand its data back with the lost mask
    lost = hfound & (slot >= cap)
    lost_w = jnp.where(lost[:, None], hot_w, 0.0)
    lost_s = {k: jnp.where(lost[:, None], v, 0.0) for k, v in hot_s.items()}
    new_state = state.replace(keys=keys, weights=weights, slots=slots,
                              overflow=state.overflow + overflow)
    return new_state, cfound, cold_w, cold_s, ok, lost, lost_w, lost_s


def _make_mesh_evict(mesh, axis, state_pspec, slot_names):
    """shard_map'd clock eviction for the row-sharded cache: each shard serves
    its own cold rows and rebuilds its local key range with its local hot
    ids (same ownership rule as `_make_mesh_admit`)."""
    from jax.sharding import PartitionSpec as P
    from .hash_table import hash_find, hash_find_or_insert

    def evict(state, cold_ids, hot_ids, fresh):
        from .hash_table import shard_probe
        keys = state.keys
        cap = keys.shape[0]

        cmine, cprobe = shard_probe(keys, cold_ids, axis)
        cslot = hash_find(keys, cprobe)
        cfound_l = cmine & (cslot < cap)
        cidx = jnp.clip(cslot, 0, cap - 1)
        cold_w = jnp.where(cfound_l[:, None],
                           jnp.take(state.weights, cidx, axis=0), 0.0)
        cold_s = {k: jnp.where(cfound_l[:, None],
                               jnp.take(v, cidx, axis=0), 0.0)
                  for k, v in state.slots.items()}

        hmine, hprobe = shard_probe(keys, hot_ids, axis)
        hslot = hash_find(keys, hprobe)
        hfound_l = hmine & (hslot < cap)
        hidx = jnp.clip(hslot, 0, cap - 1)
        hot_w = jnp.take(state.weights, hidx, axis=0)
        hot_s = {k: jnp.take(v, hidx, axis=0) for k, v in state.slots.items()}

        new_keys, slot, oflow = hash_find_or_insert(fresh.keys, hprobe)
        ok = hfound_l & (slot < cap)
        target = jnp.where(ok, slot, cap)
        weights = fresh.weights.at[target].set(hot_w, mode="drop")
        slots = {k: fresh.slots[k].at[target].set(hot_s[k], mode="drop")
                 for k in fresh.slots}
        lost_l = hfound_l & (slot >= cap)
        lost_w = jnp.where(lost_l[:, None], hot_w, 0.0)
        lost_s = {k: jnp.where(lost_l[:, None], v, 0.0)
                  for k, v in hot_s.items()}
        # each row lives on exactly one shard: psum assembles the global masks
        # and the cold/lost payloads (zeros elsewhere)
        cfound = jax.lax.psum(cfound_l.astype(jnp.int32), axis) > 0
        kept = jax.lax.psum(ok.astype(jnp.int32), axis) > 0
        lost = jax.lax.psum(lost_l.astype(jnp.int32), axis) > 0
        cold_w = jax.lax.psum(cold_w, axis)
        cold_s = {k: jax.lax.psum(v, axis) for k, v in cold_s.items()}
        lost_w = jax.lax.psum(lost_w, axis)
        lost_s = {k: jax.lax.psum(v, axis) for k, v in lost_s.items()}
        overflow = state.overflow + jax.lax.psum(oflow, axis)
        new_state = state.replace(keys=new_keys, weights=weights, slots=slots,
                                  overflow=overflow)
        return new_state, cfound, cold_w, cold_s, kept, lost, lost_w, lost_s

    slot_specs = {k: P() for k in slot_names}
    in_specs = (state_pspec, P(), P(), state_pspec)
    out_specs = (state_pspec, P(), P(), slot_specs, P(), P(), P(), slot_specs)
    return jax.jit(jax.shard_map(evict, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False),
                   donate_argnums=(0,))


def _make_mesh_admit(mesh, axis, state_pspec, slot_names):
    """shard_map'd admission for a row-sharded cache: each device claims only
    the ids it owns (`id % S == shard_index`, the layout `parallel/sharded.py`
    routes by) and probes its LOCAL key range — the same probe sequence the
    in-step `hash_lookup_train` uses on that shard, so admitted rows are found
    by the train step."""
    from jax.sharding import PartitionSpec as P
    from .hash_table import hash_find_or_insert

    def admit(state, ids, w_rows, s_rows, known):
        from .hash_table import shard_probe
        keys = state.keys
        mine, probe = shard_probe(keys, ids, axis)
        new_keys, slot, oflow = hash_find_or_insert(keys, probe)
        cps = keys.shape[0]
        admitted_local = mine & (slot < cps)
        ok = known & admitted_local
        target = jnp.where(ok, slot, cps)
        weights = state.weights.at[target].set(
            w_rows.astype(state.weights.dtype), mode="drop")
        slots = {k: state.slots[k].at[target].set(
            s_rows[k].astype(state.slots[k].dtype), mode="drop")
            for k in state.slots}
        admitted = jax.lax.psum(admitted_local.astype(jnp.int32), axis) > 0
        overflow = state.overflow + jax.lax.psum(oflow, axis)
        new_state = state.replace(keys=new_keys, weights=weights, slots=slots,
                                  overflow=overflow)
        return new_state, admitted

    in_specs = (state_pspec, P(), P(), {k: P() for k in slot_names}, P())
    out_specs = (state_pspec, P())
    return jax.jit(jax.shard_map(admit, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False),
                   donate_argnums=(0,))


class HostOffloadTable:
    """Owns the device cache state between steps; see module docstring for the
    prepare -> step -> (rebind) protocol. `capacity` = device slots; the host
    store is unbounded (host RAM).

    With `mesh`/`axis` the cache is row-sharded over the mesh exactly like a
    normal `MeshTrainer` hash table (keys `P(axis)`, rows `P(axis)`) and
    admission runs under shard_map; the host store stays process-global. The
    reference's analogue selects the PMem-backed table per variable at init
    (`EmbeddingInitOperator.cpp:146-168`) with a DRAM cache in front
    (`PmemEmbeddingOptimizerVariable.h:88-198`). Multi-host note: `flush()`
    gathers the cache with `np.asarray`, which requires the table to be
    process-addressable — single-process meshes (one host driving its chips)
    only; a per-process flush is the multi-host extension point."""

    def __init__(self, spec: EmbeddingSpec, optimizer: SparseOptimizer, *,
                 seed: int = 0, high_water: float = 0.6,
                 mesh=None, axis=None, eviction: str = "clock",
                 pipeline: bool = False, stage_depth: int = 1,
                 densify_k: int = 1):
        if not spec.use_hash_table:
            raise ValueError("host offload needs a hash-table spec "
                             "(input_dim=-1 + capacity)")
        if not 0 < high_water <= 1:
            raise ValueError("high_water in (0, 1]")
        if eviction not in ("clock", "flush"):
            raise ValueError("eviction must be 'clock' or 'flush'")
        if int(densify_k) < 1:
            raise ValueError("densify_k >= 1 (1 = merge every writeback)")
        if int(stage_depth) < 1:
            raise ValueError("stage_depth >= 1 (1 = single staging slot)")
        self.spec = spec
        self.optimizer = optimizer
        self.seed = seed
        self.high_water = high_water
        self.eviction = eviction
        self.mesh = mesh
        self.axis = axis
        self.num_shards = int(mesh.devices.size) if mesh is not None else 1
        self._pspec = None
        if mesh is not None:
            from jax.sharding import PartitionSpec as P
            # ONE copy of the mesh table layout (must agree with
            # `MeshTrainer._table_pspec`): init shardings, admit in/out specs
            self._pspec = EmbeddingTableState(
                weights=P(axis),
                slots={k: P(axis)
                       for k in optimizer.slot_shapes(spec.output_dim)},
                keys=P(axis), overflow=P())
            self._mk_fresh = self._compile_sharded_fresh()
        else:
            self._mk_fresh = jax.jit(
                lambda: init_table_state(spec, optimizer, seed=seed))
        # fresh state regenerated ON DEVICE (same seed -> bit-identical every
        # time): resets and eviction rebuilds never move a full cache of bytes
        # over the host boundary
        self.state = self._mk_fresh()
        self.capacity = self.state.keys.shape[0]
        self.rows_per_shard = self.capacity // self.num_shards
        self._key_bytes_per_row = (
            self.state.keys.dtype.itemsize * (self.state.keys.shape[1]
                                              if self.state.keys.ndim == 2
                                              else 1))
        self.store = HostStore(spec.output_dim,
                               optimizer.slot_shapes(spec.output_dim))
        # sorted id array: O(batch log cache) membership in prepare() with no
        # per-id Python boxing (a set would cost O(occupancy) host work right
        # when the cache is large — the feature's point)
        self._resident_sorted = np.empty((0,), np.int64)
        # second-chance bit per resident id (clock eviction): set when a
        # prepare() touches the id, cleared for survivors at each eviction
        self._ref = np.empty((0,), bool)
        self._shard_counts = np.zeros((self.num_shards,), np.int64)
        # cumulative overflow carried across cache resets: the device counter
        # restarts at 0 every flush, but dropped ids must stay observable
        # ("managed, not just counted")
        self._overflow_flushed = 0
        if mesh is not None:
            self._admit = _make_mesh_admit(mesh, axis, self._pspec,
                                           list(self.state.slots))
            self._evict = _make_mesh_evict(mesh, axis, self._pspec,
                                           list(self.state.slots))
        else:
            self._admit = jax.jit(_admit_fn, donate_argnums=(0,))
            self._evict = jax.jit(_evict_fn, donate_argnums=(0,))
        # densified writeback (arXiv:1905.04035): evict/lost rows defer into
        # the store's pending chunks and fold last-wins into ONE merge every
        # `densify_k` writebacks (snapshot/sync paths drain first, so
        # externally-visible store content never lags)
        self.densify_k = int(densify_k)
        self._defer_count = 0
        # pipelined staging (ring, depth D): `stage(ids)` runs a FUTURE
        # batch's host lookup + device upload on this worker while the
        # current step computes; up to `stage_depth` batches may be in
        # flight, oldest first. `prepare(ids)` consumes the matching staged
        # payload when nothing invalidated it (`_epoch` bumps on every
        # residency mutation, `HostStore.version` on every store mutation);
        # a residency-only change re-splits the staged batch against the
        # CURRENT residency snapshot and accepts iff the non-resident set is
        # unchanged — the deep-ring steady state, where earlier in-flight
        # batches admit disjoint ids. Everything else falls back to the
        # synchronous path. Admit shapes pad to powers of two, so the
        # pipelined path never re-jits (`assert_no_recompile` below).
        self.pipeline = bool(pipeline)
        self.stage_depth = int(stage_depth)
        self._epoch = 0
        # oldest first: (raw ids copy, epoch at stage, store version at
        # stage, ring slot label, Future). Ring + slot counters are
        # TRAINING-THREAD-OWNED (not lock-guarded): stage()/prepare() both
        # run on the training thread; the one-worker pool only executes the
        # submitted closure, which touches the store (own RLock) and the
        # device — never this ring. Audited round 19 (oeweave
        # host_offload_store scenario drives the cross-thread half).
        self._stage_ring: deque = deque()
        self._stage_seq = 0
        self._pipe_hits = 0
        self._pipe_misses = 0
        self._slot_hits: Dict[int, int] = {}
        self._slot_misses: Dict[int, int] = {}
        self._stage_pool = None
        if self.pipeline:
            from concurrent.futures import ThreadPoolExecutor
            self._stage_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix=f"oetpu-stage-{spec.name}")
            from ..utils.guards import assert_no_recompile
            # one program per pow2 admit size up to capacity (+1 for the
            # sub-1 edge): any retrace beyond that is a pipeline bug
            self._admit = assert_no_recompile(
                self._admit, max_traces=self.capacity.bit_length() + 2,
                label=f"offload.admit[{spec.name}]")

    def _compile_sharded_fresh(self):
        """Compiled fresh-state builder for the sharded cache (same recipe as
        `MeshTrainer.init_tables`: jit + out_shardings, never materialized on
        one device)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec, opt = self.spec, self.optimizer
        S = self.num_shards
        rows = spec.rows_per_shard(S) * S

        def mk():
            key = jax.random.fold_in(jax.random.PRNGKey(self.seed),
                                     spec.variable_id * 131071)
            weights = spec.initializer(key, (rows, spec.output_dim), spec.dtype)
            slots = opt.init_slots(rows, spec.output_dim)
            from .hash_table import fresh_keys
            keys = fresh_keys(rows)
            overflow = jnp.zeros((), jnp.int32)
            return EmbeddingTableState(weights=weights, slots=slots, keys=keys,
                                       overflow=overflow)

        shardings = jax.tree_util.tree_map(
            lambda p: NamedSharding(self.mesh, p), self._pspec,
            is_leaf=lambda x: isinstance(x, P))
        return jax.jit(mk, out_shardings=shardings)

    @property
    def resident_count(self) -> int:
        return int(self._resident_sorted.size)

    def is_resident(self, id_: int) -> bool:
        i = int(np.searchsorted(self._resident_sorted, id_))
        return (i < self._resident_sorted.size
                and int(self._resident_sorted[i]) == int(id_))

    def resident_ids(self) -> np.ndarray:
        return self._resident_sorted.copy()

    @property
    def total_overflow(self) -> int:
        """Dropped-id count across the table's lifetime, surviving cache
        resets (reads the live device counter — cheap scalar transfer)."""
        return self._overflow_flushed + int(np.asarray(self.state.overflow))

    def adopt(self, table_state: EmbeddingTableState) -> None:
        """Take ownership of the (post-step) table pytree. The Trainer's jitted
        step donates and replaces the arrays, so the Trainer hands the current
        state back before every prepare/flush."""
        self.state = table_state

    def device_cache_bytes(self) -> int:
        """Analytic PER-DEVICE bytes of the HBM cache tier (the fixed
        `capacity`-slot state): this shard's weights/slots/keys rows + the
        replicated overflow scalar — the utils/memwatch ledger figure (the
        full host table is `self.store.nbytes()`, host-flagged)."""
        rows = self.rows_per_shard
        item = jnp.dtype(self.spec.dtype).itemsize
        widths = sum(self.optimizer.slot_shapes(
            self.spec.output_dim).values())
        return (rows * self.spec.output_dim * item + rows * 4 * widths
                + rows * self._key_bytes_per_row + 4)

    def _would_exceed(self, new_ids: np.ndarray) -> bool:
        """Per-shard high-water check: a hot shard can fill while global
        occupancy is low (owner shard = id % S)."""
        counts = self._shard_counts + np.bincount(
            new_ids % self.num_shards, minlength=self.num_shards)
        return bool((counts > self.high_water * self.rows_per_shard).any())

    @staticmethod
    def _split_batch(flat: np.ndarray, resident: np.ndarray):
        """Partition a unique sorted id batch against a residency snapshot:
        -> (clipped positions, hit mask, the non-resident ids)."""
        if resident.size:
            pos = np.searchsorted(resident, flat)
            pos_c = np.minimum(pos, resident.size - 1)
            hit = resident[pos_c] == flat
            return pos_c, hit, flat[~hit]
        return (np.zeros((0,), np.int64), np.zeros((flat.size,), bool), flat)

    def _staged_payload(self, new: np.ndarray):
        """Host store lookup + pow2-padded device upload for `new` ids (the
        work `stage` moves off the training thread). Padded tail ids are -1:
        `hash_find_or_insert` claims no slot for them and `known`=False
        writes no row — the same inertness the eviction pads lean on."""
        known_hit, w, s = self.store.lookup(new)
        n = int(new.size)
        pad = (1 << max(0, (n - 1).bit_length())) - n
        if pad:
            new = np.concatenate([new, np.full((pad,), -1, np.int64)])
            known_hit = np.concatenate([known_hit, np.zeros((pad,), bool)])
            w = np.concatenate([w, np.zeros((pad,) + w.shape[1:],
                                            np.float32)])
            s = {k: np.concatenate([v, np.zeros((pad,) + v.shape[1:],
                                                np.float32)])
                 for k, v in s.items()}
        staged_bytes = (w.nbytes + sum(v.nbytes for v in s.values())
                        + new.nbytes)
        metrics.observe("offload.staged_bytes", float(staged_bytes))
        return (self._ids_to_device(new), jnp.asarray(w),
                {k: jnp.asarray(v) for k, v in s.items()},
                jnp.asarray(known_hit))

    def _admit_ids(self, new: np.ndarray, payload, *,
                   stage_s: float = 0.0) -> None:
        """Run the admit jit on a (padded) payload and account residency for
        the `new` ids it covers."""
        t0 = time.perf_counter()
        ids_dev, w_dev, s_dev, known_dev = payload
        with metrics.vtimer("offload", "admit"):
            self.state, admitted = self._admit(
                self.state, ids_dev, w_dev, s_dev, known_dev)
        admitted = np.asarray(admitted)[:new.size]
        got = new[admitted]
        # O(n+m) sorted merge (got is sorted: a subset of np.unique output)
        at = np.searchsorted(self._resident_sorted, got)
        self._resident_sorted = np.insert(self._resident_sorted, at, got)
        # fresh admits enter UNreferenced: a one-shot id is evictable at the
        # next pressure round, while a recurring id gets its bit set by the
        # mark-on-touch at the top of the next prepare() — which runs BEFORE
        # eviction, so the current batch is always protected
        self._ref = np.insert(self._ref, at, False)
        self._shard_counts += np.bincount(got % self.num_shards,
                                          minlength=self.num_shards)
        self._epoch += 1  # residency changed: staged lookups are stale
        metrics.observe("offload.admitted", int(admitted.sum()))
        if stage_s:
            # how much of the staging work ran in the shadow of the step:
            # 1.0 = the admit found everything uploaded, 0.5 = stage cost as
            # much as the admit it fed
            admit_s = time.perf_counter() - t0
            metrics.observe("offload.overlap_ratio",
                            stage_s / (stage_s + admit_s + 1e-12), "gauge")

    def stage(self, ids) -> None:
        """Pipelined stage-ahead: run a FUTURE batch's host lookup + device
        upload on the staging worker while the current step computes. Up to
        `stage_depth` batches ride the ring (oldest dropped, counted as a
        miss, when a new stage would exceed the depth). No-op unless built
        with pipeline=True. The matching `prepare(ids)` consumes the
        payload; an invalidating residency/store change or a different batch
        falls back to the sync path, so staging is only ever a hint — never
        a correctness dependency. Known conservative case: with depth >= 2,
        an id newly introduced in TWO in-flight batches makes the later
        batch's non-resident set shrink when the earlier one admits it, so
        the later stage misses (still bit-identical via the sync path)."""
        if not self.pipeline:
            return
        raw = np.array(ids, copy=True)
        epoch = self._epoch
        sver = self.store.version
        resident = self._resident_sorted  # replaced-not-mutated: safe to share

        def work():
            from ..ops.id64 import np_ids_as_int64
            t0 = time.perf_counter()
            with metrics.vtimer("offload", "stage"):
                flat = np.unique(np_ids_as_int64(raw))
                flat = flat[flat >= 0]
                pos_c, hit, new = self._split_batch(flat, resident)
                payload = self._staged_payload(new) if new.size else None
            return {"flat": flat, "pos_c": pos_c, "hit": hit, "new": new,
                    "payload": payload,
                    "stage_s": time.perf_counter() - t0}

        while len(self._stage_ring) >= self.stage_depth:
            # drop-oldest: staged but never consumed is wasted overlap
            _, _, _, slot, _ = self._stage_ring.popleft()
            self._pipe_miss(slot)
        slot = self._stage_seq % self.stage_depth
        self._stage_seq += 1
        self._stage_ring.append(
            (raw, epoch, sver, slot, self._stage_pool.submit(work)))

    def _pipe_hit(self, slot: int) -> None:
        self._pipe_hits += 1
        self._slot_hits[slot] = self._slot_hits.get(slot, 0) + 1
        metrics.observe("offload.pipeline_hits", 1)
        self._observe_occupancy()

    def _pipe_miss(self, slot: int) -> None:
        self._pipe_misses += 1
        self._slot_misses[slot] = self._slot_misses.get(slot, 0) + 1
        metrics.observe("offload.pipeline_misses", 1)
        self._observe_occupancy()

    def _take_staged(self, ids):
        """The staged result iff a ring entry matches this prepare call and
        is still valid; None otherwise. Entries staged for other batches in
        front of the match are popped and counted as misses; entries BEHIND
        the match (later batches in a deep ring) stay staged. Validity:
        exact when neither residency epoch nor store version moved; when
        only residency moved, the batch is re-split against the current
        snapshot and accepted iff the non-resident set is unchanged (the
        staged store lookup then still covers exactly the admit set — a
        changed set could overwrite trained rows with stale store values)."""
        now = np.asarray(ids)
        while self._stage_ring:
            raw, epoch, sver, slot, fut = self._stage_ring.popleft()
            if (raw.shape != now.shape or raw.dtype != now.dtype
                    or not np.array_equal(raw, now)):
                self._pipe_miss(slot)
                continue
            res = fut.result()  # join the worker before touching shared state
            if epoch == self._epoch and sver == self.store.version:
                res["slot"] = slot
                return res
            if sver == self.store.version:
                pos_c, hit, new = self._split_batch(res["flat"],
                                                    self._resident_sorted)
                if np.array_equal(new, res["new"]):
                    return dict(res, pos_c=pos_c, hit=hit, slot=slot)
            self._pipe_miss(slot)
            return None
        return None

    def _observe_occupancy(self) -> None:
        total = self._pipe_hits + self._pipe_misses
        if total:
            metrics.observe("offload.pipeline_occupancy",
                            self._pipe_hits / total, "gauge")
        for slot in sorted(set(self._slot_hits) | set(self._slot_misses)):
            h = self._slot_hits.get(slot, 0)
            t = h + self._slot_misses.get(slot, 0)
            if t:
                metrics.observe("offload.pipeline_occupancy", h / t, "gauge",
                                labels={"slot": str(slot)})

    def prepare(self, ids) -> None:
        """Make the cache ready for a batch: evict/flush if needed, re-admit
        evicted ids (split-pair batches are joined to int64 host-side — the
        residency set, the store, and the shard accounting all speak int64).
        Call BEFORE the train step; rebind `self.state` after it.

        Over high-water with `eviction="clock"` (default): cold residents
        (untouched since the last eviction round) move to the store, hot rows
        stay ON DEVICE (`evict_cold`) — falling back to the whole-cache flush
        only when the hot set itself leaves no room.

        With pipeline=True a matching `stage(ids)` payload is consumed here
        (the lookup + upload already happened under the previous step);
        eviction pressure and mismatches fall back to the path below."""
        staged = self._take_staged(ids)
        if staged is not None:
            flat, new = staged["flat"], staged["new"]
            hit = staged["hit"]
            if hit.any():
                # second-chance bit: this batch's residents are HOT
                self._ref[staged["pos_c"][hit]] = True
            self._pipe_hit(staged["slot"])
            if new.size == 0:
                return
            if not self._would_exceed(new):
                self._admit_ids(new, staged["payload"],
                                stage_s=staged["stage_s"])
                return
            # pressure: eviction rewrites residency/store, so the staged
            # payload is only reusable when the id set survives unchanged —
            # re-run the tail of the sync path instead (rare by design:
            # occupancy crossing high-water, not the steady state)
            self._pressure(new, flat)
            return
        from ..ops.id64 import np_ids_as_int64
        flat = np.unique(np_ids_as_int64(ids))
        flat = flat[flat >= 0]
        pos_c, hit, new = self._split_batch(flat, self._resident_sorted)
        if hit.any():
            # second-chance bit: this batch's residents are HOT
            self._ref[pos_c[hit]] = True
        if new.size == 0:
            return
        if self._would_exceed(new):
            self._pressure(new, flat)
            return
        self._admit_ids(new, self._staged_payload(new))

    def _pressure(self, new: np.ndarray, flat: np.ndarray) -> None:
        """The over-high-water tail of prepare(): evict or flush, then admit
        whatever the batch still needs (the whole batch after a flush — it
        evicted the batch's previously-resident ids too, and the train step
        would otherwise reinsert them with initializer values)."""
        if self.eviction == "clock":
            self.evict_cold()
        if self.eviction != "clock" or self._would_exceed(new):
            self.flush()
            new = flat
        per_shard = self._shard_counts + np.bincount(
            new % self.num_shards, minlength=self.num_shards)
        if per_shard.max(initial=0) > self.rows_per_shard:
            warnings.warn(
                f"batch puts {int(per_shard.max())} unique ids on one "
                f"shard (> {self.rows_per_shard} slots); the device cache "
                "cannot hold one batch and some rows will overflow — "
                "raise `capacity` or shrink the batch", RuntimeWarning)
        self._admit_ids(new, self._staged_payload(new))

    def _ids_to_device(self, ids64: np.ndarray):
        from ..ops.id64 import np_split_ids
        if self.state.keys.ndim == 2:
            return jnp.asarray(np_split_ids(ids64))
        return jnp.asarray(ids64.astype(self.state.keys.dtype))

    def _store_write(self, ids: np.ndarray, weights: np.ndarray,
                     slots: Dict[str, np.ndarray]) -> None:
        """Writeback entry point for evicted rows: direct merge at
        densify_k=1, else defer and fold K writebacks into one merge
        (`HostStore.drain`) — the compact-accumulation half of the pipelined
        offload (reads stay exact via the pending overlay in lookup)."""
        if self.densify_k <= 1:
            self.store.merge(ids, weights, slots)
            return
        self.store.defer(ids, weights, slots)
        self._defer_count += 1
        if self._defer_count >= self.densify_k:
            with metrics.vtimer("offload", "drain"):
                merged = self.store.drain()
            self._defer_count = 0
            metrics.observe("offload.densified_merges", 1)
            metrics.observe("offload.drained_rows", merged)

    def evict_cold(self) -> int:
        """Clock/second-chance eviction: move residents whose referenced bit is
        clear to the host store and rebuild the cache keeping the hot rows on
        device; survivors' bits are cleared (they must be touched again to
        survive the next round). Host<->device traffic is O(cold rows) — the
        whole-cache flush's O(cache) cost only happens via the explicit
        fallback in prepare(). Returns the number of rows evicted."""
        cold = self._resident_sorted[~self._ref]
        hot = self._resident_sorted[self._ref]
        if cold.size == 0:
            return 0

        # pad each list to a power of two: stable compile cache across rounds
        def pad(a):
            n = 1 << max(0, (a.size - 1).bit_length())
            return np.concatenate([a, np.full((n - a.size,), -1, np.int64)])

        cold_p = pad(cold)
        hot_p = pad(hot) if hot.size else np.full((1,), -1, np.int64)
        with metrics.vtimer("offload", "evict"):
            fresh = self._mk_fresh()
            (self.state, cfound, cw, cs, kept, lost,
             lost_w, lost_s) = self._evict(
                self.state, self._ids_to_device(cold_p),
                self._ids_to_device(hot_p), fresh)
            cfound = np.asarray(cfound)[:cold.size]
            self._store_write(
                cold[cfound],
                np.asarray(cw)[:cold.size][cfound].astype(np.float32),
                {k: np.asarray(v)[:cold.size][cfound].astype(np.float32)
                 for k, v in cs.items()})
        nh = hot.size
        kept = np.asarray(kept)[:nh] if nh else np.zeros((0,), bool)
        lost = np.asarray(lost)[:nh] if nh else np.zeros((0,), bool)
        if lost.any():
            # hot rows whose re-insert overflowed (rare): bank them in the
            # store — they re-admit on their next appearance
            self._store_write(
                hot[lost],
                np.asarray(lost_w)[:nh][lost].astype(np.float32),
                {k: np.asarray(v)[:nh][lost].astype(np.float32)
                 for k, v in lost_s.items()})
        survivors = np.sort(hot[kept])
        self._resident_sorted = survivors
        self._ref = np.zeros((survivors.size,), bool)  # second chance expired
        self._shard_counts = np.bincount(
            survivors % self.num_shards, minlength=self.num_shards
        ).astype(np.int64)
        self._epoch += 1  # residency + store changed: staged lookups stale
        metrics.observe("offload.evicted_cold", int(cfound.sum()))
        metrics.observe("offload.kept_hot", int(survivors.size))
        return int(cfound.sum())

    def sync_to_store(self) -> None:
        """Write every resident (id, row, slots) back to the host store WITHOUT
        resetting the cache — a consistent full snapshot for checkpoint/persist
        while training continues undisturbed."""
        with metrics.vtimer("offload", "sync"):
            from ..ops.id64 import np_resident_ids
            # drain BEFORE the resident merge: pending chunks hold OLDER
            # (evicted-at-the-time) values and must not overwrite the fresher
            # device rows written next
            self.store.drain()
            self._defer_count = 0
            sel, ids64 = np_resident_ids(np.asarray(self.state.keys))
            self.store.merge(
                ids64,
                np.asarray(self.state.weights)[sel].astype(np.float32),
                {k: np.asarray(v)[sel].astype(np.float32)
                 for k, v in self.state.slots.items()})

    def flush(self) -> None:
        """Evict the whole cache to the host store and reset the device table."""
        with metrics.vtimer("offload", "flush"):
            self.sync_to_store()
            self.reset_cache()
        metrics.observe("offload.flushes", 1)

    def reset_cache(self) -> None:
        """Fresh device cache + empty residency WITHOUT writing to the store
        (checkpoint load: the store was just replaced wholesale and the cache
        contents are stale). The device overflow counter restarts at 0, so its
        current value is banked first (`total_overflow` stays monotonic)."""
        self._overflow_flushed += int(np.asarray(self.state.overflow))
        self.state = self._mk_fresh()
        self._resident_sorted = np.empty((0,), np.int64)
        self._ref = np.empty((0,), bool)
        self._shard_counts[:] = 0
        self._epoch += 1  # residency changed: staged lookups are stale

    def load_store(self, ids: np.ndarray, weights: np.ndarray,
                   slots: Dict[str, np.ndarray]) -> None:
        """Checkpoint restore: replace the host store and invalidate the cache.
        Missing optimizer slots (include_optimizer=False dumps) get fresh
        optimizer init values, like the reference's state reset on such loads."""
        full_slots = {}
        fresh = {k: np.asarray(v)
                 for k, v in jax.device_get(
                     self.optimizer.init_slots(1, self.spec.output_dim)).items()}
        for k in fresh:
            if k in slots:
                full_slots[k] = slots[k]
            else:
                full_slots[k] = np.broadcast_to(
                    fresh[k], (len(ids),) + fresh[k].shape[1:]).copy()
        self.store.replace_all(np.asarray(ids, np.int64),
                               np.asarray(weights), full_slots)
        self._defer_count = 0  # replace_all dropped the pending chunks
        self.reset_cache()

    def lookup_anywhere(self, ids) -> np.ndarray:
        """Read rows wherever they live; absent ids -> zeros. Implemented as a
        store write-back + host read so it is correct for any mesh layout.
        For eval/export, not the hot path."""
        from ..ops.id64 import is_pair, np_ids_as_int64
        self.sync_to_store()
        raw = np.asarray(ids)
        flat = np_ids_as_int64(raw)
        out_shape = raw.shape[:-1] if is_pair(raw) else raw.shape
        _, host_rows, _ = self.store.lookup(flat)
        return host_rows.reshape(out_shape + (self.spec.output_dim,))
