"""Two-tier embedding table: device hash-table cache in HBM + host-RAM store.

The TPU-native counterpart of the reference's PMem backend architecture
(`variable/PmemEmbeddingTable.h`: a DRAM LRU cache in front of persistent pools,
ICDE 2023) and the reason the reference can train 175 GB+ models on small devices:
here HBM holds a fixed-capacity hash-table cache (`tables/hash_table.py`) and the
full (unbounded) table lives in host RAM, so table size is bounded by HOST memory,
not HBM.

Protocol (host-driven, between jitted steps — ids are known host-side from the
input pipeline, like the reference's client-side request assembly):

1. `prepare(ids)`: ids previously evicted to the host are ADMITTED back into the
   device cache (one jitted scatter: rows + optimizer slots restored exactly);
   brand-new ids are left to the device table's insert-on-pull (their slots carry
   initializer values). If admission would push occupancy over the high-water
   mark, the cache is FLUSHED first.
2. the train step runs entirely on device against the cache (normal hash path).
3. `flush()`: every resident (id, row, slots) is pulled host-side, merged into
   the host store (id-sorted arrays + searchsorted, same layout as checkpoint and
   standalone export), and the cache resets. Coarse whole-cache eviction — the
   reference evicts per-item LRU; a slot-granular policy is a later refinement
   (PERF.md lists it).

Exactness: a row's weights AND optimizer state round-trip bit-identically through
evict/admit, so training with a small cache equals training with an infinite table
whenever the initializer is slot-independent (e.g. Constant) — tested in
`tests/test_host_offload.py`. With slot-position-dependent random init, first-touch
values differ (the documented init-on-slot divergence of `tables/hash_table.py`).
"""

from __future__ import annotations

import warnings
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..embedding import EmbeddingSpec, EmbeddingTableState, init_table_state
from ..optimizers import SparseOptimizer
from ..utils import metrics


class HostStore:
    """Id-sorted host arrays (weights + slots) with merge-update."""

    def __init__(self, dim: int, slot_widths: Dict[str, int]):
        self.ids = np.empty((0,), np.int64)
        self.weights = np.empty((0, dim), np.float32)
        self.slots = {k: np.empty((0, w), np.float32)
                      for k, w in slot_widths.items()}

    def __len__(self) -> int:
        return len(self.ids)

    def lookup(self, ids: np.ndarray) -> Tuple[np.ndarray, np.ndarray, Dict]:
        """-> (hit mask, weight rows, slot rows) for `ids` (unknown ids return
        zero rows and hit=False)."""
        if len(self.ids) == 0:
            return (np.zeros((len(ids),), bool),
                    np.zeros((len(ids),) + self.weights.shape[1:], np.float32),
                    {k: np.zeros((len(ids),) + v.shape[1:], np.float32)
                     for k, v in self.slots.items()})
        pos = np.searchsorted(self.ids, ids)
        pos_c = np.clip(pos, 0, len(self.ids) - 1)
        hit = self.ids[pos_c] == ids
        w = np.where(hit[:, None], self.weights[pos_c], 0.0)
        s = {k: np.where(hit[:, None], v[pos_c], 0.0)
             for k, v in self.slots.items()}
        return hit, w, s

    def merge(self, ids: np.ndarray, weights: np.ndarray,
              slots: Dict[str, np.ndarray]) -> None:
        """Upsert rows (ids need not be sorted; duplicates of existing update)."""
        if len(ids) == 0:
            return
        order = np.argsort(ids, kind="stable")
        ids, weights = ids[order], weights[order]
        slots = {k: v[order] for k, v in slots.items()}
        if len(self.ids) == 0:
            exists = np.zeros((len(ids),), bool)
            pos_c = np.zeros((len(ids),), np.int64)
        else:
            pos = np.searchsorted(self.ids, ids)
            pos_c = np.clip(pos, 0, len(self.ids) - 1)
            exists = self.ids[pos_c] == ids
        # update existing in place
        if exists.any():
            self.weights[pos_c[exists]] = weights[exists]
            for k in self.slots:
                self.slots[k][pos_c[exists]] = slots[k][exists]
        # insert the rest (merge two sorted runs)
        new = ~exists
        if new.any():
            self.ids = np.concatenate([self.ids, ids[new]])
            self.weights = np.concatenate([self.weights, weights[new]])
            for k in self.slots:
                self.slots[k] = np.concatenate([self.slots[k], slots[k][new]])
            order = np.argsort(self.ids, kind="stable")
            self.ids = self.ids[order]
            self.weights = self.weights[order]
            for k in self.slots:
                self.slots[k] = self.slots[k][order]

    def nbytes(self) -> int:
        return (self.ids.nbytes + self.weights.nbytes
                + sum(v.nbytes for v in self.slots.values()))


def _admit_fn(state: EmbeddingTableState, ids, w_rows, s_rows, known):
    """Jitted: insert ALL `ids` into the cache (claiming slots); overwrite rows
    and optimizer slots only for host-`known` ids — brand-new ids keep their
    claimed slot's initializer values (insert-on-pull semantics).

    Also returns the per-id admitted mask (slot actually claimed) so the host
    can track residency truthfully: an overflowed id never got a row written,
    and marking it resident would make later prepare() calls skip re-admitting
    it while lookups read zeros from the device path."""
    from .hash_table import hash_find_or_insert

    keys, slot, overflow = hash_find_or_insert(state.keys, ids)
    capacity = state.keys.shape[0]
    admitted = slot < capacity
    ok = known & admitted
    target = jnp.where(ok, slot, capacity)
    weights = state.weights.at[target].set(
        w_rows.astype(state.weights.dtype), mode="drop")
    slots = {k: state.slots[k].at[target].set(
        s_rows[k].astype(state.slots[k].dtype), mode="drop")
        for k in state.slots}
    new_state = state.replace(keys=keys, weights=weights, slots=slots,
                              overflow=state.overflow + overflow)
    return new_state, admitted


class HostOffloadTable:
    """Owns the device cache state between steps; see module docstring for the
    prepare -> step -> (rebind) protocol. `capacity` = device slots; the host
    store is unbounded (host RAM)."""

    def __init__(self, spec: EmbeddingSpec, optimizer: SparseOptimizer, *,
                 seed: int = 0, high_water: float = 0.6):
        if not spec.use_hash_table:
            raise ValueError("host offload needs a hash-table spec "
                             "(input_dim=-1 + capacity)")
        if not 0 < high_water <= 1:
            raise ValueError("high_water in (0, 1]")
        self.spec = spec
        self.optimizer = optimizer
        self.seed = seed
        self.high_water = high_water
        self.state = init_table_state(spec, optimizer, seed=seed)
        self._fresh = jax.device_get(self.state)  # template for cache resets
        self.capacity = self.state.keys.shape[0]
        self.store = HostStore(spec.output_dim,
                               optimizer.slot_shapes(spec.output_dim))
        self._resident: set = set()
        self._admit = jax.jit(_admit_fn, donate_argnums=(0,))

    @property
    def resident_count(self) -> int:
        return len(self._resident)

    def prepare(self, ids) -> None:
        """Make the cache ready for a batch: flush if needed, re-admit evicted
        ids. Call BEFORE the train step; rebind `self.state` after it."""
        flat = np.unique(np.asarray(ids).reshape(-1))
        flat = flat[flat >= 0]
        new = [int(i) for i in flat if int(i) not in self._resident]
        if not new:
            return
        if len(self._resident) + len(new) > self.high_water * self.capacity:
            self.flush()
            # The flush just evicted the batch's previously-resident ids too;
            # admit the WHOLE batch back or the train step would reinsert those
            # ids with initializer values, losing their weights/slots.
            new = [int(i) for i in flat]
            if len(new) > self.capacity:
                warnings.warn(
                    f"batch has {len(new)} unique ids > cache capacity "
                    f"({self.capacity}); the device cache cannot hold one "
                    "batch and some rows will overflow — raise `capacity` or "
                    "shrink the batch", RuntimeWarning)
        known_hit, w, s = self.store.lookup(np.asarray(new, np.int64))
        ids_dev = jnp.asarray(np.asarray(new, np.int64))
        with metrics.vtimer("offload", "admit"):
            self.state, admitted = self._admit(
                self.state, ids_dev, jnp.asarray(w),
                {k: jnp.asarray(v) for k, v in s.items()},
                jnp.asarray(known_hit))
        admitted = np.asarray(admitted)
        self._resident.update(i for i, a in zip(new, admitted) if a)
        metrics.observe("offload.admitted", int(admitted.sum()))

    def flush(self) -> None:
        """Evict the whole cache to the host store and reset the device table."""
        with metrics.vtimer("offload", "flush"):
            keys = np.asarray(self.state.keys)
            sel = keys >= 0
            self.store.merge(
                keys[sel].astype(np.int64),
                np.asarray(self.state.weights)[sel].astype(np.float32),
                {k: np.asarray(v)[sel].astype(np.float32)
                 for k, v in self.state.slots.items()})
            self.state = jax.device_put(self._fresh)
            self._resident.clear()
        metrics.observe("offload.flushes", 1)

    def lookup_anywhere(self, ids) -> np.ndarray:
        """Read rows wherever they live (device cache first, then host store);
        absent ids -> zeros. For eval/export, not the hot path."""
        from ..embedding import lookup

        flat = np.asarray(ids).reshape(-1)
        dev = np.asarray(lookup(self.spec, self.state, jnp.asarray(flat)))
        on_dev = np.asarray([int(i) in self._resident for i in flat])
        _, host_rows, _ = self.store.lookup(flat.astype(np.int64))
        out = np.where(on_dev[:, None], dev, host_rows)
        return out.reshape(np.asarray(ids).shape + (self.spec.output_dim,))
