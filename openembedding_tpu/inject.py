"""Import-hook auto-injection: run an UNMODIFIED Keras training script on this
framework's sharded TPU tables.

    python -m openembedding_tpu.inject your_keras_script.py [args...]

The reference ships this as an interpreter-startup monkeypatch
(`laboratory/inject/openembedding_inject_tensorflow.py:11-40` swaps
`tf.keras.layers.Embedding`/`Model`/every optimizer class inside
`sitecustomize.py`, gated by an env var) so that scripts written against plain
Keras train their embeddings on the parameter servers. The TPU-native
equivalent needs no class swaps: Keras 3 on the JAX backend already traces
into XLA, so this runner only (a) forces `KERAS_BACKEND=jax` before the user
script imports keras and (b) wraps `keras.Model.fit` — when the compiled model
contains Embedding layers, fit converts it with `keras_compat.from_keras_model`
(tables become shardable/hashable framework tables, the dense remainder stays
the user's own Keras graph) and drives the jitted Trainer; trained weights are
written back into the live Keras variables so `predict()`/`save()` behave as
the script expects. Models without Embedding layers fall through to native
Keras fit untouched.

Scope (documented, like the reference's laboratory status): numpy/array `x`
(dict keyed by input name, single array, or list in `model.inputs` order)
with array `y`, OR a batch iterable (`tf.data.Dataset`, generator, or any
iterable yielding `(x_batch, y_batch)` — generators need `steps_per_epoch`,
re-iterables restart per epoch); `batch_size`/`epochs`/`shuffle`;
`callbacks` (REAL Keras callbacks — the live model is synced with the
trained state every epoch, so `ModelCheckpoint` saves what was actually
trained and `EarlyStopping`'s `model.stop_training` is honored — the
reference's hook script drives `ModelCheckpoint` the same way,
`examples/criteo_deepctr_hook.py`); a compiled AUC metric reports pooled
train AUC per epoch. `OETPU_INJECT_MESH=1` trains data-parallel +
row-sharded over every visible device (MeshTrainer) instead of
single-device.
"""

from __future__ import annotations

import os
import sys
from typing import Any, Dict


def _as_input_dict(model, x) -> Dict[str, Any]:
    import numpy as np

    def rank_fix(v, t):
        # Keras fit auto-expands (B,) columns to a (None, 1) input; match it
        v = np.asarray(v)
        while v.ndim < len(t.shape):
            v = v[..., None]
        return v

    names = [t.name for t in model.inputs]
    if isinstance(x, dict):
        missing = [n for n in names if n not in x]
        if missing:
            raise ValueError(f"fit(x=dict) is missing inputs {missing}")
        return {t.name: rank_fix(x[t.name], t) for t in model.inputs}
    xs = x if isinstance(x, (list, tuple)) else [x]
    if len(xs) != len(names):
        raise ValueError(
            f"fit got {len(xs)} input arrays for {len(names)} model inputs")
    return {t.name: rank_fix(v, t) for t, v in zip(model.inputs, xs)}


_SUPPORTED_DEFAULTS = {"validation_split": 0.0,
                       "validation_data": None, "class_weight": None,
                       "sample_weight": None, "initial_epoch": 0,
                       "validation_steps": None,
                       "validation_batch_size": None, "validation_freq": 1}


def _is_batch_iterable(x, y) -> bool:
    """Dataset-style input: yields (x_batch, y_batch) tuples. Arrays/dicts/
    lists-of-arrays (the array path) all come WITH a y."""
    import numpy as np
    if y is not None or x is None:
        return False
    if isinstance(x, (dict, np.ndarray, list, tuple)):
        return False
    return hasattr(x, "__iter__")


def _unpack_item(item):
    """One yielded dataset element -> (x_batch, y_batch)."""
    if not isinstance(item, (list, tuple)) or len(item) not in (2, 3):
        raise ValueError(
            "dataset/generator input must yield (x_batch, y_batch) tuples "
            f"(got {type(item).__name__})")
    if len(item) == 3 and item[2] is not None:
        raise ValueError("per-batch sample_weight is not supported by the "
                         "inject fit path")
    return item[0], item[1]


def _fit_via_framework(model, x, y, *, batch_size=32, epochs=1, shuffle=True,
                       verbose="auto", callbacks=None, steps_per_epoch=None,
                       **unsupported):
    import types

    import numpy as np

    import keras

    from .keras_compat import (KerasDenseModule, export_keras_rows,
                               from_keras_model, import_keras_rows)
    from .model import Trainer
    from .utils import metrics as M

    # reject ANY fit option this path cannot honor — silently ignoring
    # class_weight / validation_split / ... would change results vs Keras
    for key, value in unsupported.items():
        default = _SUPPORTED_DEFAULTS.get(key, object())
        # no `==`/truthiness on the raw value: an ndarray kwarg (e.g.
        # sample_weight=np.ones(n)) would raise numpy's ambiguous-truth error
        # instead of the actionable message below
        if value is None and default is None:
            continue
        if isinstance(value, (int, float)) and not isinstance(value, bool) \
                and value == default:
            continue
        if isinstance(value, (list, tuple, dict)) and not value \
                and default in (None, 0.0, 0):
            continue
        raise ValueError(
            f"inject fit does not support {key}={value!r}; call keras "
            "fit directly (model without Embedding layers) or use the "
            "Trainer API")
    if batch_size is None:
        batch_size = 32  # the keras default

    emodel, opt = from_keras_model(model)
    if opt is None:
        raise ValueError("model.compile(optimizer=...) before fit")
    if os.environ.get("OETPU_INJECT_DEBUG"):
        print(f"[inject] routing fit through the framework trainer "
              f"(tables: {sorted(emodel.ps_specs())})", file=sys.stderr,
              flush=True)
    use_mesh = os.environ.get("OETPU_INJECT_MESH") == "1"
    if use_mesh:
        from .parallel import MeshTrainer
        trainer = MeshTrainer(emodel, opt)
    else:
        trainer = Trainer(emodel, opt)

    # keyed by the FEEDING INPUTS' names (a shared layer's synthesized
    # layer-name feature exists only after batch_transform, inside jit —
    # spec.feature_name would KeyError on the user's input dict here)
    from .keras_compat import sparse_input_names
    sparse_feats = sparse_input_names(model)
    # a compiled AUC metric -> pooled train AUC per epoch (the reference's
    # benchmark prints it the same pooled way, `test/benchmark/criteo_deepctr.py`).
    # Pre-fit the CompileMetrics wrapper is unbuilt, so read the user's raw list.
    def _metric_names():
        for mm in getattr(model, "metrics", []):
            yield str(getattr(mm, "name", mm))
            for u in (getattr(mm, "_user_metrics", None) or []):
                yield str(getattr(u, "name", u))
    want_auc = any("auc" in name.lower() for name in _metric_names())

    iterable_mode = _is_batch_iterable(x, y)
    if not iterable_mode:
        inputs = _as_input_dict(model, x)
        y_arr = np.asarray(y).reshape(-1).astype(np.float32)
        n = y_arr.shape[0]

    def make_batch(inp, yb, B):
        """Fixed-size batch: short batches pad to B with weight-0 rows (ONE
        compiled step; the weighted loss matches Keras's mean over the real
        rows)."""
        yb = np.asarray(yb).reshape(-1).astype(np.float32)
        b = yb.shape[0]
        if b > B:
            raise ValueError(
                f"dataset batch of {b} rows exceeds the first batch's "
                f"{B} (the compiled step shape); keep batches uniform")
        pad = B - b

        def padrow(a):
            a = np.asarray(a)
            if pad == 0:
                return a
            return np.concatenate([a, np.repeat(a[:1], pad, axis=0)], axis=0)

        weight = np.ones((B,), np.float32)
        if pad:
            weight[b:] = 0.0
        sparse = {f: padrow(inp[f]).astype(np.int32) for f in sparse_feats}
        dn = [k for k in inp if k not in sparse_feats]
        if not dn:
            dense = None
        elif len(dn) == 1:
            dense = padrow(inp[dn[0]]).astype(np.float32)
        else:
            dense = {k: padrow(inp[k]).astype(np.float32) for k in dn}
        return {"sparse": sparse, "dense": dense, "label": padrow(yb),
                "weight": weight}, b

    persistent_it = None
    if iterable_mode and isinstance(x, types.GeneratorType):
        # Keras semantics: a plain generator is consumed ACROSS epochs, so an
        # epoch needs an explicit length
        if steps_per_epoch is None:
            raise ValueError(
                "a generator input needs steps_per_epoch (re-iterables like "
                "tf.data.Dataset restart each epoch and do not)")
        persistent_it = iter(x)

    cbs = None
    if callbacks:
        cbs = keras.callbacks.CallbackList(list(callbacks), add_history=False,
                                           add_progbar=False, model=model)
        cbs.set_params({"epochs": epochs, "verbose": 0,
                        "steps": steps_per_epoch})
    model.stop_training = False

    state = None
    step = None
    B = [None]
    rng = np.random.default_rng(0)
    history: Dict[str, Any] = {"loss": []}

    def train_one(bdict):
        nonlocal state, step
        if state is None:
            state = trainer.init(bdict)
            state = import_keras_rows(trainer, state, model)
            step = (trainer.jit_train_step(bdict, state) if use_mesh
                    else trainer.jit_train_step())
        state, m = step(state, bdict)
        return m

    def sync_back():
        # the LIVE Keras model reflects the trained state — ModelCheckpoint
        # (and the user's predict()/save() after fit) see real weights
        module = emodel.module
        assert isinstance(module, KerasDenseModule)
        module.write_back(state.dense_params)
        export_keras_rows(trainer, state, model)

    if cbs is not None:
        cbs.on_train_begin()
    ran_epochs = 0
    for epoch in range(epochs):
        if cbs is not None:
            cbs.on_epoch_begin(epoch)
        losses, counts = [], []
        pool_s, pool_l = [], []

        def run_batch(inp, yb):
            if B[0] is None:
                B[0] = int(np.asarray(yb).reshape(-1).shape[0])
            bdict, real = make_batch(inp, yb, B[0])
            m = train_one(bdict)
            losses.append(float(m["loss"]))
            counts.append(real)
            if want_auc and real:
                pool_s.append(np.asarray(m["logits"]).reshape(-1)[:real])
                pool_l.append(bdict["label"][:real])

        if iterable_mode:
            it = persistent_it if persistent_it is not None else iter(x)
            taken = 0
            while steps_per_epoch is None or taken < steps_per_epoch:
                try:
                    item = next(it)
                except StopIteration:
                    if persistent_it is not None:
                        raise ValueError(
                            "generator exhausted before steps_per_epoch "
                            f"({taken}/{steps_per_epoch} at epoch {epoch})")
                    break
                xb, yb = _unpack_item(item)
                run_batch(_as_input_dict(model, xb), yb)
                taken += 1
            if not losses:
                raise ValueError("the dataset yielded no batches")
        else:
            order = rng.permutation(n) if shuffle else np.arange(n)
            if steps_per_epoch is not None:
                order = order[:steps_per_epoch * batch_size]
            B[0] = batch_size
            for start in range(0, order.size, batch_size):
                idx = order[start:start + batch_size]
                run_batch({k: v[idx] for k, v in inputs.items()}, y_arr[idx])

        logs = {"loss": float(np.average(losses, weights=counts))}
        if want_auc and pool_l:
            logs["auc"] = float(M.auc(np.concatenate(pool_l),
                                      np.concatenate(pool_s)))
            history.setdefault("auc", []).append(logs["auc"])
        history["loss"].append(logs["loss"])
        ran_epochs = epoch + 1
        if cbs is not None:
            sync_back()
            cbs.on_epoch_end(epoch, logs)
        if verbose:
            print("[inject] epoch {}/{} ".format(epoch + 1, epochs)
                  + " ".join(f"{k} {v:.4f}" for k, v in logs.items()),
                  flush=True)
        if getattr(model, "stop_training", False):
            break
    if cbs is not None:
        cbs.on_train_end()

    if state is not None and cbs is None:
        # with callbacks the last epoch's pre-on_epoch_end sync already wrote
        # the live model; repeating it would re-export every table
        sync_back()

    class _History:
        pass

    h = _History()
    h.history = history
    h.epoch = list(range(ran_epochs))
    h.model = model
    h.params = {"epochs": epochs,
                "steps": (steps_per_epoch if iterable_mode
                          else -(-n // batch_size)),
                "verbose": verbose}
    return h


def install() -> None:
    """Wrap keras.Model.fit: embedding-bearing models train through this
    framework, everything else falls through to native Keras."""
    import keras

    from .keras_compat import _require_jax_backend

    _require_jax_backend(keras)
    native_fit = keras.Model.fit
    # Keras 3 fit's positional parameter order after (x, y) — bound here so
    # scripts calling fit positionally (m.fit(x, y, 64)) keep working
    fit_pos = ("batch_size", "epochs", "verbose", "callbacks",
               "validation_split", "validation_data", "shuffle",
               "class_weight", "sample_weight", "initial_epoch",
               "steps_per_epoch")

    def fit(self, x=None, y=None, *args, **kw):
        for name, value in zip(fit_pos, args):
            if name in kw:
                raise TypeError(f"fit() got multiple values for {name!r}")
            kw[name] = value
        has_embedding = any(isinstance(l, keras.layers.Embedding)
                            for l in getattr(self, "layers", []))
        if not has_embedding or not getattr(self, "inputs", None):
            return native_fit(self, x=x, y=y, **kw)
        return _fit_via_framework(self, x, y, **kw)

    keras.Model.fit = fit


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: python -m openembedding_tpu.inject script.py [args...]",
              file=sys.stderr)
        return 2
    if "keras" in sys.modules:
        import keras as _k
        if _k.config.backend() != "jax":
            print("inject: keras was already imported with the "
                  f"{_k.config.backend()!r} backend; start a fresh "
                  "interpreter", file=sys.stderr)
            return 2
    os.environ["KERAS_BACKEND"] = "jax"
    install()
    import runpy
    sys.argv = argv
    runpy.run_path(argv[0], run_name="__main__")
    return 0


if __name__ == "__main__":
    sys.exit(main())
