"""Import-hook auto-injection: run an UNMODIFIED Keras training script on this
framework's sharded TPU tables.

    python -m openembedding_tpu.inject your_keras_script.py [args...]

The reference ships this as an interpreter-startup monkeypatch
(`laboratory/inject/openembedding_inject_tensorflow.py:11-40` swaps
`tf.keras.layers.Embedding`/`Model`/every optimizer class inside
`sitecustomize.py`, gated by an env var) so that scripts written against plain
Keras train their embeddings on the parameter servers. The TPU-native
equivalent needs no class swaps: Keras 3 on the JAX backend already traces
into XLA, so this runner only (a) forces `KERAS_BACKEND=jax` before the user
script imports keras and (b) wraps `keras.Model.fit` — when the compiled model
contains Embedding layers, fit converts it with `keras_compat.from_keras_model`
(tables become shardable/hashable framework tables, the dense remainder stays
the user's own Keras graph) and drives the jitted Trainer; trained weights are
written back into the live Keras variables so `predict()`/`save()` behave as
the script expects. Models without Embedding layers fall through to native
Keras fit untouched.

Scope (documented, like the reference's laboratory status): numpy/array `x`
(dict keyed by input name, single array, or list in `model.inputs` order),
array `y`, `batch_size`/`epochs`/`shuffle`; `OETPU_INJECT_MESH=1` trains
data-parallel + row-sharded over every visible device (MeshTrainer) instead
of single-device.
"""

from __future__ import annotations

import os
import sys
from typing import Any, Dict


def _as_input_dict(model, x) -> Dict[str, Any]:
    import numpy as np
    names = [t.name for t in model.inputs]
    if isinstance(x, dict):
        missing = [n for n in names if n not in x]
        if missing:
            raise ValueError(f"fit(x=dict) is missing inputs {missing}")
        return {n: np.asarray(x[n]) for n in names}
    xs = x if isinstance(x, (list, tuple)) else [x]
    if len(xs) != len(names):
        raise ValueError(
            f"fit got {len(xs)} input arrays for {len(names)} model inputs")
    return {n: np.asarray(v) for n, v in zip(names, xs)}


_SUPPORTED_DEFAULTS = {"callbacks": None, "validation_split": 0.0,
                       "validation_data": None, "class_weight": None,
                       "sample_weight": None, "initial_epoch": 0,
                       "steps_per_epoch": None, "validation_steps": None,
                       "validation_batch_size": None, "validation_freq": 1}


def _fit_via_framework(model, x, y, *, batch_size=32, epochs=1, shuffle=True,
                       verbose="auto", **unsupported):
    import numpy as np

    import openembedding_tpu as embed
    from .keras_compat import (KerasDenseModule, export_keras_rows,
                               from_keras_model, import_keras_rows)
    from .model import Trainer

    # reject ANY fit option this path cannot honor — silently ignoring
    # class_weight / validation_split / ... would change results vs Keras
    for key, value in unsupported.items():
        default = _SUPPORTED_DEFAULTS.get(key, object())
        # no `==`/truthiness on the raw value: an ndarray kwarg (e.g.
        # sample_weight=np.ones(n)) would raise numpy's ambiguous-truth error
        # instead of the actionable message below
        if value is None and default is None:
            continue
        if isinstance(value, (int, float)) and not isinstance(value, bool) \
                and value == default:
            continue
        if isinstance(value, (list, tuple, dict)) and not value \
                and default in (None, 0.0, 0):
            continue
        raise ValueError(
            f"inject fit does not support {key}={value!r}; call keras "
            "fit directly (model without Embedding layers) or use the "
            "Trainer API")
    if batch_size is None:
        batch_size = 32  # the keras default

    emodel, opt = from_keras_model(model)
    if opt is None:
        raise ValueError("model.compile(optimizer=...) before fit")
    if os.environ.get("OETPU_INJECT_DEBUG"):
        print(f"[inject] routing fit through the framework trainer "
              f"(tables: {sorted(emodel.ps_specs())})", file=sys.stderr,
              flush=True)
    use_mesh = os.environ.get("OETPU_INJECT_MESH") == "1"
    if use_mesh:
        from .parallel import MeshTrainer
        trainer = MeshTrainer(emodel, opt)
    else:
        trainer = Trainer(emodel, opt)

    inputs = _as_input_dict(model, x)
    y = np.asarray(y).reshape(-1).astype(np.float32)
    n = y.shape[0]
    sparse_feats = {s.feature_name for s in emodel.ps_specs().values()} | \
                   {s.feature_name for s in emodel.sad_specs().values()}
    dense_names = [k for k in inputs if k not in sparse_feats]

    def batch_of(idx):
        """Fixed-size batch: a trailing partial batch pads to batch_size with
        weight-0 rows (Keras trains the tail too; padding keeps ONE compiled
        step and the weighted loss matches Keras's mean over the real rows)."""
        pad = batch_size - idx.size
        if pad:
            idx = np.concatenate([idx, np.zeros((pad,), idx.dtype)])
        weight = np.ones((batch_size,), np.float32)
        if pad:
            weight[-pad:] = 0.0
        sparse = {f: inputs[f][idx].astype(np.int32) for f in sparse_feats}
        if not dense_names:
            dense = None
        elif len(dense_names) == 1:
            dense = inputs[dense_names[0]][idx].astype(np.float32)
        else:
            dense = {k: inputs[k][idx].astype(np.float32)
                     for k in dense_names}
        return {"sparse": sparse, "dense": dense, "label": y[idx],
                "weight": weight}, batch_size - pad

    state = None
    step = None
    rng = np.random.default_rng(0)
    history = {"loss": []}
    for epoch in range(epochs):
        order = rng.permutation(n) if shuffle else np.arange(n)
        losses, counts = [], []
        for start in range(0, n, batch_size):
            b, real = batch_of(order[start:start + batch_size])
            if state is None:
                state = trainer.init(b)
                state = import_keras_rows(trainer, state, model)
                step = (trainer.jit_train_step(b, state) if use_mesh
                        else trainer.jit_train_step())
            state, m = step(state, b)
            losses.append(float(m["loss"]))
            counts.append(real)
        history["loss"].append(float(np.average(losses, weights=counts)))
        if verbose:
            print(f"[inject] epoch {epoch + 1}/{epochs} "
                  f"loss {history['loss'][-1]:.4f}", flush=True)

    if state is not None:
        # make the user's Keras object serve what was trained (mesh tables
        # deinterleave host-side inside export_keras_rows)
        module = emodel.module
        assert isinstance(module, KerasDenseModule)
        module.write_back(state.dense_params)
        export_keras_rows(trainer, state, model)

    class _History:
        pass

    h = _History()
    h.history = history
    h.epoch = list(range(epochs))
    h.model = model
    h.params = {"epochs": epochs, "steps": -(-n // batch_size),
                "verbose": verbose}
    return h


def install() -> None:
    """Wrap keras.Model.fit: embedding-bearing models train through this
    framework, everything else falls through to native Keras."""
    import keras

    from .keras_compat import _require_jax_backend

    _require_jax_backend(keras)
    native_fit = keras.Model.fit
    # Keras 3 fit's positional parameter order after (x, y) — bound here so
    # scripts calling fit positionally (m.fit(x, y, 64)) keep working
    fit_pos = ("batch_size", "epochs", "verbose", "callbacks",
               "validation_split", "validation_data", "shuffle",
               "class_weight", "sample_weight", "initial_epoch",
               "steps_per_epoch")

    def fit(self, x=None, y=None, *args, **kw):
        for name, value in zip(fit_pos, args):
            if name in kw:
                raise TypeError(f"fit() got multiple values for {name!r}")
            kw[name] = value
        has_embedding = any(isinstance(l, keras.layers.Embedding)
                            for l in getattr(self, "layers", []))
        if not has_embedding or not getattr(self, "inputs", None):
            return native_fit(self, x=x, y=y, **kw)
        return _fit_via_framework(self, x, y, **kw)

    keras.Model.fit = fit


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if not argv:
        print("usage: python -m openembedding_tpu.inject script.py [args...]",
              file=sys.stderr)
        return 2
    if "keras" in sys.modules:
        import keras as _k
        if _k.config.backend() != "jax":
            print("inject: keras was already imported with the "
                  f"{_k.config.backend()!r} backend; start a fresh "
                  "interpreter", file=sys.stderr)
            return 2
    os.environ["KERAS_BACKEND"] = "jax"
    install()
    import runpy
    sys.argv = argv
    runpy.run_path(argv[0], run_name="__main__")
    return 0


if __name__ == "__main__":
    sys.exit(main())
