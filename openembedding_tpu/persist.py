"""Frequent async persistence — the PMem backend's TPU-native equivalent.

The reference's Intel PMem backend (`variable/Pmem*.h`, ICDE 2023 paper) gives
near-instant checkpoints by keeping the table in persistent memory and committing a
checkpoint marker per `work_id`, with a `persist_pending_window` bounding how many
in-flight commits may be pending, and a server->client `should_persist` signal that
drives the benchmark harness's `AutoPersist` callback
(`test/benchmark/criteo_deepctr.py:113-124`; API surface `exb.py:697-705`:
`should_persist_server_model` / `persist_server_model(path, window)` /
`restore_server_model`).

On TPU there is no persistent device memory; the equivalent is a device->host->disk
pipeline: `persist()` snapshots the train state to host RAM synchronously (the state
is DONATED by the next train step, so the device read must happen before training
continues — this is the fast part, HBM->host DMA) and writes the checkpoint to disk on
a background thread. A bounded queue of `window` pending writes gives the reference's
pending-window semantics: exceeding it blocks (backpressure) instead of dropping.

Commit protocol: each persist writes `<root>/persist_<step>/` then a `COMMIT` marker
file last; `restore()` loads the newest directory WITH a marker, so a crash mid-write
is never restored (the reference's `flush_committing_checkpoint` work-id protocol,
`PmemEmbeddingTable.h:236-300`).
"""

from __future__ import annotations

import os
import queue
import re
import shutil
import threading
import time
from typing import List, Optional, Tuple

import jax

from .utils import metrics

COMMIT_FILE = "COMMIT"
_PERSIST_RE = re.compile(r"persist_(\d+)$")


class PersistPolicy:
    """When to persist: every N steps and/or every T seconds (the reference's
    `should_persist` pressure signal comes from pmem cache occupancy; a TPU table
    has no such pressure, so the policy is time/step based)."""

    def __init__(self, every_steps: int = 0, every_seconds: float = 0.0):
        if every_steps <= 0 and every_seconds <= 0:
            raise ValueError("set every_steps and/or every_seconds")
        self.every_steps = every_steps
        self.every_seconds = every_seconds
        self._last_step = 0
        self._last_time = time.monotonic()

    def should_persist(self, step: int) -> bool:
        if self.every_steps > 0 and step - self._last_step >= self.every_steps:
            return True
        if (self.every_seconds > 0
                and time.monotonic() - self._last_time >= self.every_seconds):
            return True
        return False

    def mark(self, step: int) -> None:
        self._last_step = step
        self._last_time = time.monotonic()


def list_persists(root: str) -> List[Tuple[int, str]]:
    """(step, path) of committed persists, oldest first."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        m = _PERSIST_RE.match(name)
        path = os.path.join(root, name)
        if m and os.path.exists(os.path.join(path, COMMIT_FILE)):
            out.append((int(m.group(1)), path))
    return sorted(out)


def latest_persist(root: str) -> Optional[str]:
    persists = list_persists(root)
    return persists[-1][1] if persists else None


class AsyncPersister:
    """Device->host->disk checkpoint pipeline with pending-window backpressure.

    Usage:
        persister = AsyncPersister(trainer, model, root, window=2)
        for batch in data:
            state, m = step(state, batch)
            persister.maybe_persist(state)     # policy-driven
        persister.close()
    """

    def __init__(self, trainer, model, root: str, *, window: int = 2,
                 keep: int = 2, include_optimizer: bool = True,
                 policy: Optional[PersistPolicy] = None,
                 commit_timeout: float = 600.0):
        from .checkpoint import save_server_model  # noqa: F401 (validated import)

        if window < 1:
            raise ValueError("window must be >= 1")
        self.trainer = trainer
        self.model = model
        self.root = root
        self.keep = keep
        self.include_optimizer = include_optimizer
        self.commit_timeout = commit_timeout
        self.policy = policy or PersistPolicy(every_steps=1000)
        os.makedirs(root, exist_ok=True)
        # Clear stale `.writing` dirs (partial attempts of a CRASHED prior
        # run) NOW, at construction: no writer of THIS run can be active yet
        # — training steps are collectives, so no peer can outrun this
        # constructor into its first persist(). Cleaning any later (the
        # writer thread used to rmtree at write time) races a faster peer's
        # already-finished shard + done marker out of existence, and the
        # commit wait then times out (observed under full-suite contention).
        if jax.process_index() == 0:
            import glob as _glob
            for d in _glob.glob(os.path.join(root, "persist_*.writing")):
                shutil.rmtree(d, ignore_errors=True)
        self._q: "queue.Queue" = queue.Queue(maxsize=window)
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(target=self._writer, daemon=True)
        self._thread.start()

    # -- producer side ------------------------------------------------------

    def should_persist(self, step: int) -> bool:
        """reference `should_persist_server_model` (`exb.py:697-699`)."""
        return self.policy.should_persist(int(step))

    def maybe_persist(self, state) -> bool:
        step = int(state.step)
        if not self.should_persist(step):
            return False
        self.persist(state)
        return True

    def persist(self, state) -> str:
        """Snapshot to host NOW (before the caller's next step donates the state),
        enqueue the disk write; blocks only when `window` writes are pending
        (reference `persist_server_model(path, window)`, `exb.py:700-702`).

        Sharded states snapshot per-addressable-shard (each process copies only
        its own shards — a multi-host global table is never gathered; the r1
        whole-state `device_get` breaks on non-fully-addressable arrays)."""
        self._raise_pending_error()
        step = int(state.step)
        if getattr(self.trainer, "offload", None):
            # host-cached tables snapshot their WHOLE host store (a consistent
            # copy — the live store keeps mutating under later flushes). Bound
            # peak host memory at one pending copy by draining earlier writes
            # first: effective window=1 for the store, the device-state window
            # is unchanged.
            self._q.join()
            self._raise_pending_error()
        with metrics.vtimer("persist", "snapshot"):
            if self.trainer.num_shards > 1:
                from .parallel.checkpoint import snapshot_addressable
                snapshot = snapshot_addressable(state, self.trainer.num_shards)
            else:
                snapshot = jax.device_get(state)
            # host-cached tables: resident rows are synced into each host store
            # and a decoupled copy rides along (later flushes mutate the live
            # store in place; the writer thread must not see them)
            stores = self.trainer.offload_store_snapshots(state) \
                if getattr(self.trainer, "offload", None) else {}
        path = os.path.join(self.root, f"persist_{step:012d}")
        self._q.put((snapshot, stores, step, path))  # backpressure when full
        self.policy.mark(step)
        metrics.observe("persist.submitted", 1)
        return path

    # -- writer thread ------------------------------------------------------

    def _writer(self) -> None:
        from .checkpoint import save_server_model

        while True:
            item = self._q.get()
            if item is None:
                return
            snapshot, stores, step, path = item
            try:
                with metrics.vtimer("persist", "write"):
                    self._write_one(snapshot, stores, step, path)
                metrics.observe("persist.committed", 1)
                if jax.process_index() == 0:
                    self._gc()
            except BaseException as e:  # noqa: BLE001 - surfaced to producer
                self._error = e
            finally:
                self._q.task_done()

    def _write_one(self, snapshot, stores, step: int, path: str) -> None:
        """Write this process's shards into `<path>.writing`, then commit.

        Multi-host commit protocol (the reference's work-id commit,
        `PmemEmbeddingTable.h:236-300`, re-expressed over a shared FS): every
        process streams its own shards into the SAME `.writing` dir and drops a
        `done.<process_index>` marker; only process 0 — after ALL markers are
        present — renames the dir into place and writes COMMIT. A fast process
        can therefore never commit (or garbage-collect) a checkpoint another
        host is still writing, and restore never sees a partial dump."""
        from .checkpoint import save_server_model

        tmp = f"{path}.writing"
        pidx, pcount = jax.process_index(), jax.process_count()
        # NOTE: stale-dir cleanup happens in persist() (main thread,
        # barrier-fenced); an rmtree here would race a faster peer's
        # already-finished write out of existence — see persist().
        if self.trainer.num_shards > 1:
            from .parallel.checkpoint import save_sharded
            save_sharded(snapshot, self.model, tmp,
                         include_optimizer=self.include_optimizer,
                         num_shards=self.trainer.num_shards,
                         offload_stores=stores)
        else:
            save_server_model(snapshot, self.model, tmp,
                              include_optimizer=self.include_optimizer,
                              num_shards=self.trainer.num_shards,
                              offload_stores=stores)
        with open(os.path.join(tmp, f"done.{pidx}"), "w") as f:
            f.write(str(step))
        if pidx != 0:
            return  # process 0 owns the rename + COMMIT
        deadline = time.monotonic() + self.commit_timeout
        while True:
            done = [p for p in range(pcount)
                    if os.path.exists(os.path.join(tmp, f"done.{p}"))]
            if len(done) == pcount:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"persist commit: only {len(done)}/{pcount} processes "
                    f"finished writing {tmp!r} within {self.commit_timeout}s")
            time.sleep(0.05)
        # an existing dir at `path` — a crash between replace and COMMIT, or a
        # committed persist of the same step from a previous run — would make
        # os.replace fail with ENOTEMPTY forever; the fresh persist supersedes
        if os.path.exists(path):
            shutil.rmtree(path)
        os.replace(tmp, path)
        with open(os.path.join(path, COMMIT_FILE), "w") as f:
            f.write(str(step))

    def _gc(self) -> None:
        persists = list_persists(self.root)
        for _, path in persists[:-self.keep] if self.keep > 0 else []:
            shutil.rmtree(path, ignore_errors=True)

    def _raise_pending_error(self) -> None:
        if self._error is not None:
            e, self._error = self._error, None
            raise RuntimeError(f"async persist failed: {e}") from e

    # -- lifecycle ----------------------------------------------------------

    def wait(self) -> None:
        """Drain pending writes (reference: dump waits the async_tasks counter)."""
        self._q.join()
        self._raise_pending_error()

    def close(self) -> None:
        try:
            self.wait()
        finally:
            # always stop the writer, even when wait() raises a deferred write
            # error — otherwise the thread (and its queued host snapshots) leak
            self._q.put(None)
            self._thread.join(timeout=30)

    def __enter__(self) -> "AsyncPersister":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- restore ------------------------------------------------------------

    def restore(self, state):
        return restore_server_model(state, self.model, self.root,
                                    trainer=self.trainer)


# -- module-level API parity with `exb.py:697-705` ---------------------------


def persist_server_model(trainer, model, state, root: str, window: int = 2) -> str:
    """One-shot blocking persist (API parity; the loop-integrated path is
    `AsyncPersister`)."""
    with AsyncPersister(trainer, model, root, window=window) as p:
        return p.persist(state)


def restore_server_model(state, model, root: str, *, trainer=None):
    """Restore the newest COMMITTED persist under `root` (crash-consistent:
    uncommitted directories are ignored; reference `restore_server_model`,
    `exb.py:703-705`)."""
    path = latest_persist(root)
    if path is None:
        raise FileNotFoundError(f"no committed persist under {root!r}")
    num_shards = trainer.num_shards if trainer is not None else 1
    offload = getattr(trainer, "offload", None) or None
    from .parallel.checkpoint import checkpoint_layout, load_sharded
    if checkpoint_layout(path) == "sharded":
        return load_sharded(state, model, path, num_shards=num_shards,
                            offload=offload)
    from .checkpoint import load_server_model
    return load_server_model(state, model, path, num_shards=num_shards,
                             offload=offload)
