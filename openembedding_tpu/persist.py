"""Frequent async persistence — the PMem backend's TPU-native equivalent.

The reference's Intel PMem backend (`variable/Pmem*.h`, ICDE 2023 paper) gives
near-instant checkpoints by keeping the table in persistent memory and committing a
checkpoint marker per `work_id`, with a `persist_pending_window` bounding how many
in-flight commits may be pending, and a server->client `should_persist` signal that
drives the benchmark harness's `AutoPersist` callback
(`test/benchmark/criteo_deepctr.py:113-124`; API surface `exb.py:697-705`:
`should_persist_server_model` / `persist_server_model(path, window)` /
`restore_server_model`).

On TPU there is no persistent device memory; the equivalent is a device->host->disk
pipeline: `persist()` snapshots the train state to host RAM synchronously (the state
is DONATED by the next train step, so the device read must happen before training
continues — this is the fast part, HBM->host DMA) and writes the checkpoint to disk on
a background thread. A bounded queue of `window` pending writes gives the reference's
pending-window semantics: exceeding it blocks (backpressure) instead of dropping.

Commit protocol: each persist writes `<root>/persist_<step>/` then a `COMMIT` marker
file last; `restore()` loads the newest directory WITH a marker, so a crash mid-write
is never restored (the reference's `flush_committing_checkpoint` work-id protocol,
`PmemEmbeddingTable.h:236-300`).
"""

from __future__ import annotations

import os
import queue
import re
import shutil
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import numpy as np

from .utils import metrics, trace

COMMIT_FILE = "COMMIT"
_PERSIST_RE = re.compile(r"persist_(\d+)$")


class PersistPolicy:
    """When to persist: every N steps and/or every T seconds (the reference's
    `should_persist` pressure signal comes from pmem cache occupancy; a TPU table
    has no such pressure, so the policy is time/step based)."""

    def __init__(self, every_steps: int = 0, every_seconds: float = 0.0):
        if every_steps <= 0 and every_seconds <= 0:
            raise ValueError("set every_steps and/or every_seconds")
        self.every_steps = every_steps
        self.every_seconds = every_seconds
        self._last_step = 0
        self._last_time = time.monotonic()

    def should_persist(self, step: int) -> bool:
        if self.every_steps > 0 and step - self._last_step >= self.every_steps:
            return True
        if (self.every_seconds > 0
                and time.monotonic() - self._last_time >= self.every_seconds):
            return True
        return False

    def mark(self, step: int) -> None:
        self._last_step = step
        self._last_time = time.monotonic()


def list_persists(root: str) -> List[Tuple[int, str]]:
    """(step, path) of committed persists, oldest first."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        m = _PERSIST_RE.match(name)
        path = os.path.join(root, name)
        if m and os.path.exists(os.path.join(path, COMMIT_FILE)):
            out.append((int(m.group(1)), path))
    return sorted(out)


def latest_persist(root: str) -> Optional[str]:
    persists = list_persists(root)
    return persists[-1][1] if persists else None


class AsyncPersister:
    """Device->host->disk checkpoint pipeline with pending-window backpressure.

    Usage:
        persister = AsyncPersister(trainer, model, root, window=2)
        for batch in data:
            state, m = step(state, batch)
            persister.maybe_persist(state)     # policy-driven
        persister.close()
    """

    def __init__(self, trainer, model, root: str, *, window: int = 2,
                 keep: int = 2, include_optimizer: bool = True,
                 policy: Optional[PersistPolicy] = None,
                 commit_timeout: float = 600.0, prune_deltas: bool = True):
        from .checkpoint import save_server_model  # noqa: F401 (validated import)

        if window < 1:
            raise ValueError("window must be >= 1")
        if jax.process_count() > 1 and policy is not None \
                and policy.every_seconds > 0:
            # The SPMD defense the spmd-divergence lint pass checks for:
            # persist() drives mesh-global compiled programs (hot_sync /
            # externalize) and, incrementally, a host allgather — a
            # wall-clock policy fires at different steps on different
            # hosts, so one process enters that rendezvous and the rest
            # never do. Step-driven policies are lockstep-uniform.
            raise ValueError(
                "multi-process persisters need a step-driven policy "
                "(every_steps): wall-clock policies fire at different "
                "steps on different hosts, and persist() is a collective "
                "rendezvous (hot_sync/externalize, delta allgather)")
        self.trainer = trainer
        self.model = model
        self.root = root
        self.keep = keep
        self.prune_deltas = prune_deltas
        self.include_optimizer = include_optimizer
        self.commit_timeout = commit_timeout
        self.policy = policy or PersistPolicy(every_steps=1000)
        os.makedirs(root, exist_ok=True)
        # Clear stale `.writing` dirs (partial attempts of a CRASHED prior
        # run) NOW, at construction: no writer of THIS run can be active yet
        # — training steps are collectives, so no peer can outrun this
        # constructor into its first persist(). Cleaning any later (the
        # writer thread used to rmtree at write time) races a faster peer's
        # already-finished shard + done marker out of existence, and the
        # commit wait then times out (observed under full-suite contention).
        if jax.process_index() == 0:
            import glob as _glob
            for pat in ("persist_*.writing", "delta_*.writing"):
                for d in _glob.glob(os.path.join(root, pat)):
                    shutil.rmtree(d, ignore_errors=True)
        self._q: "queue.Queue" = queue.Queue(maxsize=window)
        self._error: Optional[BaseException] = None
        self._close_mu = threading.Lock()
        self._closed = False  # guarded-by: self._close_mu
        self._thread = threading.Thread(target=self._writer, daemon=True)
        self._thread.start()

    # -- producer side ------------------------------------------------------

    def should_persist(self, step: int) -> bool:
        """reference `should_persist_server_model` (`exb.py:697-699`)."""
        return self.policy.should_persist(int(step))

    def maybe_persist(self, state, batch=None) -> bool:
        """`batch` is accepted (and ignored) so call sites can drive
        AsyncPersister and IncrementalPersister interchangeably."""
        del batch
        step = int(state.step)
        if not self.should_persist(step):
            return False
        self.persist(state)  # oelint: disable=spmd-divergence -- __init__ rejects wall-clock policies for process_count > 1, so should_persist is step-driven and lockstep-uniform across processes
        return True

    def persist(self, state) -> str:
        """Snapshot to host NOW (before the caller's next step donates the state),
        enqueue the disk write; blocks only when `window` writes are pending
        (reference `persist_server_model(path, window)`, `exb.py:700-702`).

        Sharded states snapshot per-addressable-shard (each process copies only
        its own shards — a multi-host global table is never gathered; the r1
        whole-state `device_get` breaks on non-fully-addressable arrays).

        Hot-replicated rows (MeshTrainer(hot_rows=...)) write back into their
        owner shards first (`trainer.hot_sync`, identity off-mesh), so the
        persisted bytes equal a hot-off run's. ZeRO-sharded dense slots
        unshard the same way (`trainer.externalize` folds both)."""
        self._raise_pending_error()
        state = self.trainer.externalize(state)
        step = int(state.step)
        if getattr(self.trainer, "offload", None):
            # host-cached tables snapshot their WHOLE host store (a consistent
            # copy — the live store keeps mutating under later flushes). Bound
            # peak host memory at one pending copy by draining earlier writes
            # first: effective window=1 for the store, the device-state window
            # is unchanged.
            self._q.join()
            self._raise_pending_error()
        with metrics.vtimer("persist", "snapshot"):
            if self.trainer.num_shards > 1:
                from .parallel.checkpoint import snapshot_addressable
                snapshot = snapshot_addressable(state, self.trainer.num_shards)
            else:
                snapshot = jax.device_get(state)
            # host-cached tables: resident rows are synced into each host store
            # and a decoupled copy rides along (later flushes mutate the live
            # store in place; the writer thread must not see them)
            stores = self.trainer.offload_store_snapshots(state) \
                if getattr(self.trainer, "offload", None) else {}
        path = os.path.join(self.root, f"persist_{step:012d}")
        write_cb = lambda tmp: self._write_full_payload(snapshot, stores, tmp)  # noqa: E731
        self._q.put((write_cb, step, path))  # backpressure when full
        self.policy.mark(step)
        metrics.observe("persist.submitted", 1)
        return path

    # -- writer thread ------------------------------------------------------

    def _writer(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                # balance the sentinel's put: a wait()/close() AFTER this
                # close would otherwise block forever in _q.join() on the
                # never-finished sentinel task (oeweave async_persister
                # scenario: racing double close deadlocked here)
                self._q.task_done()
                return
            write_cb, step, path = item
            try:
                with metrics.vtimer("persist", "write"):
                    self._write_one(write_cb, step, path)
                metrics.observe("persist.committed", 1)
                if jax.process_index() == 0:
                    with trace.span("persist", "gc"):
                        self._gc()
            except BaseException as e:  # noqa: BLE001 - surfaced to producer
                self._error = e
            finally:
                self._q.task_done()

    def _write_full_payload(self, snapshot, stores, tmp: str) -> None:
        from .checkpoint import save_server_model

        if self.trainer.num_shards > 1:
            from .parallel.checkpoint import save_sharded
            save_sharded(snapshot, self.model, tmp,
                         include_optimizer=self.include_optimizer,
                         num_shards=self.trainer.num_shards,
                         offload_stores=stores)
        else:
            save_server_model(snapshot, self.model, tmp,
                              include_optimizer=self.include_optimizer,
                              num_shards=self.trainer.num_shards,
                              offload_stores=stores)

    def _write_one(self, write_cb, step: int, path: str) -> None:
        """Write this process's payload into `<path>.writing`, then commit.

        Multi-host commit protocol (the reference's work-id commit,
        `PmemEmbeddingTable.h:236-300`, re-expressed over a shared FS): every
        process streams its own shards into the SAME `.writing` dir and drops a
        `done.<process_index>` marker; only process 0 — after ALL markers are
        present — renames the dir into place and writes COMMIT. A fast process
        can therefore never commit (or garbage-collect) a checkpoint another
        host is still writing, and restore never sees a partial dump."""
        tmp = f"{path}.writing"
        pidx, pcount = jax.process_index(), jax.process_count()
        # NOTE: stale-dir cleanup happens in persist() (main thread,
        # barrier-fenced); an rmtree here would race a faster peer's
        # already-finished write out of existence — see persist().
        write_cb(tmp)
        with open(os.path.join(tmp, f"done.{pidx}"), "w") as f:
            f.write(str(step))
        if pidx != 0:
            return  # process 0 owns the rename + COMMIT
        deadline = time.monotonic() + self.commit_timeout
        while True:
            done = [p for p in range(pcount)
                    if os.path.exists(os.path.join(tmp, f"done.{p}"))]
            if len(done) == pcount:
                break
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"persist commit: only {len(done)}/{pcount} processes "
                    f"finished writing {tmp!r} within {self.commit_timeout}s")
            time.sleep(0.05)
        # an existing dir at `path` — a crash between replace and COMMIT, or a
        # committed persist of the same step from a previous run — would make
        # os.replace fail with ENOTEMPTY forever; the fresh persist supersedes
        if os.path.exists(path):
            shutil.rmtree(path)
        os.replace(tmp, path)
        with open(os.path.join(path, COMMIT_FILE), "w") as f:
            f.write(str(step))
        trace.event("persist", "commit", step=int(step),
                    what=os.path.basename(path))

    def _gc(self) -> None:
        """Retention after every commit (process 0 only): keep the newest
        `keep` FULL persists, and — unless `prune_deltas=False` — drop every
        `delta_<step>` at or below the newest full's step: `delta_chain`
        anchors at the newest committed full, so those deltas are never
        replayable again, and without pruning a long online-training run
        leaks one directory per persist interval. The opt-out exists for
        sync publishers (`sync/publisher.py`) that deliberately retain
        history for slow subscribers; with pruning on, size
        `full_every * keep` to cover the worst-case subscriber lag."""
        persists = list_persists(self.root)
        if self.prune_deltas and persists:
            newest_full = persists[-1][0]
            for step, path in list_deltas(self.root):
                if step <= newest_full:
                    shutil.rmtree(path, ignore_errors=True)
        for _, path in persists[:-self.keep] if self.keep > 0 else []:
            shutil.rmtree(path, ignore_errors=True)

    def _raise_pending_error(self) -> None:
        if self._error is not None:
            e, self._error = self._error, None
            raise RuntimeError(f"async persist failed: {e}") from e

    # -- lifecycle ----------------------------------------------------------

    def wait(self) -> None:
        """Drain pending writes (reference: dump waits the async_tasks counter)."""
        self._q.join()
        self._raise_pending_error()

    def close(self) -> None:
        # idempotent, including racing closes (`with persister:` + an
        # explicit close, or an atexit hook): only the first caller drains
        # and stops the writer; later/racing callers just wait for it
        with self._close_mu:
            first, self._closed = not self._closed, True
        if not first:
            self._thread.join(timeout=30)
            return
        try:
            self.wait()
        finally:
            # always stop the writer, even when wait() raises a deferred write
            # error — otherwise the thread (and its queued host snapshots) leak
            self._q.put(None)
            self._thread.join(timeout=30)

    def __enter__(self) -> "AsyncPersister":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- restore ------------------------------------------------------------

    def restore(self, state):
        return restore_server_model(state, self.model, self.root,
                                    trainer=self.trainer)


# -- incremental (dirty-window) persistence ----------------------------------
#
# The reference's PMem tables make a persist near-instant because the rows are
# ALREADY persistent — committing a checkpoint only flushes the pending window
# and writes a work-id marker (`PmemEmbeddingTable.h:236-300`, "lightweight
# checkpoints", `documents/en/pmem.md`). A TPU table lives in HBM, so rows must
# cross device->host->disk — but only the rows TOUCHED since the last persist
# changed. The incremental pipeline makes persist cost O(touched), not
# O(model): a full base persist, then `delta_<step>` directories holding the
# touched rows (+ the small dense tree), chained by parent pointers under the
# same COMMIT protocol; restore = base + replay.

_DELTA_RE = re.compile(r"delta_(\d+)$")
DELTA_FORMAT = "oetpu-delta-v1"


def list_deltas(root: str) -> List[Tuple[int, str]]:
    """(step, path) of committed deltas, oldest first."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        m = _DELTA_RE.match(name)
        path = os.path.join(root, name)
        if m and os.path.exists(os.path.join(path, COMMIT_FILE)):
            out.append((int(m.group(1)), path))
    return sorted(out)


def delta_chain(root: str) -> Tuple[Optional[str], List[str]]:
    """-> (base_persist_path, [delta paths to replay in order]).

    The newest committed FULL persist anchors the chain; committed deltas
    newer than it are walked by parent pointer and the chain stops at the
    first break (a missing/uncommitted link) — replaying a consistent prefix
    restores the state at that link's step, never a torn mix."""
    import json

    base = latest_persist(root)
    if base is None:
        return None, []
    base_step = list_persists(root)[-1][0]
    chain = []
    parent = base_step
    remaining = {s: p for s, p in list_deltas(root) if s > base_step}
    for step in sorted(remaining):
        path = remaining[step]
        try:
            with open(os.path.join(path, "meta.json")) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            break
        if meta.get("parent") != parent or meta.get("format") != DELTA_FORMAT:
            break
        chain.append(path)
        parent = step
    return base, chain


class DirtyTracker:
    """Host-side touched-id accumulation per embedding table, fed from the
    input stream (the same place the reference's client knows its pull ids,
    `EmbeddingPullOperator.cpp:60-112`). observe() only uniques the BATCH
    (O(batch log batch)) and appends; the cross-batch union is deferred to
    take(), once per persist — re-sorting the whole window every step would
    put O(window log window) host work on the training hot loop."""

    def __init__(self, model):
        self._feats = {name: spec.feature_name
                       for name, spec in model.ps_specs().items()
                       if spec.storage != "host_cached"}
        # shared-Embedding Keras conversions synthesize a feature (the layer
        # name) via batch_transform inside the jitted paths; the host-side
        # tracker must apply the same transform or its feature lookup KeyErrors
        self._transform = getattr(model, "batch_transform", None)
        self._chunks = {name: [] for name in self._feats}
        self.observed = 0

    @staticmethod
    def _host_view(x):
        """Batch leaf -> host array. Multi-process global batches are not
        fully addressable; each process observes the rows IT fed (its
        addressable shards) — the cross-process union happens at persist
        time (`multihost.allgather_host_ids`)."""
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            return np.concatenate(
                [np.asarray(s.data) for s in x.addressable_shards], axis=0)
        return np.asarray(x)

    def observe(self, batch) -> None:
        from .ops.id64 import np_ids_as_int64
        if self._transform is not None:
            batch = self._transform(batch)
        for name, feat in self._feats.items():
            ids = np.unique(np_ids_as_int64(
                self._host_view(batch["sparse"][feat])))
            ids = ids[ids >= 0]
            if ids.size:
                self._chunks[name].append(ids)
        self.observed += 1

    def take(self):
        """-> {name: sorted unique ids}; resets the window."""
        out = {name: (np.unique(np.concatenate(chunks)) if chunks
                      else np.empty((0,), np.int64))
               for name, chunks in self._chunks.items()}
        self._chunks = {name: [] for name in self._feats}
        self.observed = 0
        return out


def _ceil_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def _array_global_idx(ids, rows: int, num_shards: int):
    """Storage row for an id in a (possibly sharded) ARRAY table: shard-major
    layout — shard = id % S, local = id // S, row = shard * rps + local
    (`parallel/sharded.py` layout converters are the bulk counterparts)."""
    import jax.numpy as jnp
    if num_shards == 1:
        return ids
    rps = rows // num_shards
    return (ids % num_shards) * rps + ids // num_shards


def _read_rows(spec, num_shards: int, ts, ids):
    """Gather (found, weights, slots) for flat padded ids. Array tables work
    at any shard count (index math above; XLA reshards the O(touched)
    gather); hash tables only at S == 1 — their probe sequence is per-shard
    (`_make_mesh_row_reader` is the sharded path)."""
    import jax.numpy as jnp
    if spec.use_hash_table:
        from .tables.hash_table import hash_find
        slot = hash_find(ts.keys, ids)
        cap = ts.keys.shape[0]
        found = slot < cap
        idx = jnp.clip(slot, 0, cap - 1)
    else:
        found = (ids >= 0) & (ids < spec.input_dim)
        idx = jnp.clip(_array_global_idx(ids, ts.weights.shape[0],
                                         num_shards),
                       0, ts.weights.shape[0] - 1)
    w = jnp.take(ts.weights, idx, axis=0)
    s = {k: jnp.take(v, idx, axis=0) for k, v in ts.slots.items()}
    return found, w, s


def _ef_as_slot(ts):
    """ts (or a pspec pytree) with the error-feedback leaf riding the slot
    dict under the reserved name "__ef__" — the same trick the sharded
    checkpoint uses, so every slot-generic reader/writer below persists ef
    without knowing about it. None-safe identity."""
    if getattr(ts, "ef", None) is None:
        return ts
    return ts.replace(slots={**ts.slots, "__ef__": ts.ef}, ef=None)


def _make_mesh_row_reader(mesh, axis, state_pspec):
    """shard_map'd touched-row read for a row-sharded HASH table: each shard
    probes its local key range for the ids it owns (same ownership/probe
    rules as the live lookup), rows psum-assemble (zeros elsewhere)."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from .tables.hash_table import hash_find

    def read(ts, ids):
        from .tables.hash_table import shard_probe
        keys = ts.keys
        mine, probe = shard_probe(keys, ids, axis)
        slot = hash_find(keys, probe)
        cap = keys.shape[0]
        found_l = mine & (slot < cap)
        idx = jnp.clip(slot, 0, cap - 1)
        w = jnp.where(found_l[:, None],
                      jnp.take(ts.weights, idx, axis=0), 0.0)
        s = {k: jnp.where(found_l[:, None], jnp.take(v, idx, axis=0), 0.0)
             for k, v in ts.slots.items()}
        found = jax.lax.psum(found_l.astype(jnp.int32), axis) > 0
        w = jax.lax.psum(w, axis)
        s = {k: jax.lax.psum(v, axis) for k, v in s.items()}
        return found, w, s

    slot_specs = {k: P() for k in
                  (state_pspec.slots if isinstance(state_pspec.slots, dict)
                   else {})}
    return jax.jit(jax.shard_map(
        read, mesh=mesh, in_specs=(state_pspec, P()),
        out_specs=(P(), P(), slot_specs), check_vma=False))


def _make_shard_row_reader(mesh, axis, state_pspec, use_hash: bool,
                           input_dim: int):
    """shard_map'd touched-row read with PER-SHARD outputs: every shard reads
    the rows it owns out of the same replicated padded id list, and the
    outputs stay sharded over `axis` — so in a multi-process mesh each
    process's addressable output shards hold exactly the rows its local
    table shards own (the reference's per-node dump locality,
    `EmbeddingDumpOperator.cpp:36-96`), with no cross-host row traffic."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from .tables.hash_table import hash_find, shard_probe

    def read(ts, ids):
        if use_hash:
            keys = ts.keys
            mine, probe = shard_probe(keys, ids, axis)
            slot = hash_find(keys, probe)
            cap = keys.shape[0]
            found = mine & (slot < cap)
            idx = jnp.clip(slot, 0, cap - 1)
        else:
            S = jax.lax.axis_size(axis)
            me = jax.lax.axis_index(axis)
            ok = (ids >= 0) & (ids < input_dim)
            mine = ok & ((ids % S).astype(jnp.int32) == me)
            local = jnp.clip(ids // S, 0, ts.weights.shape[0] - 1)
            found = mine
            idx = local
        w = jnp.where(found[:, None],
                      jnp.take(ts.weights, idx, axis=0), 0.0)
        s = {k: jnp.where(found[:, None], jnp.take(v, idx, axis=0), 0.0)
             for k, v in ts.slots.items()}
        return found, w, s

    # trimmed spellings (P(axis), not P(axis, None)): trailing Nones are
    # placement-identical but cache-key-unequal — the sharding lint rule
    slot_specs = {k: P(axis) for k in
                  (state_pspec.slots if isinstance(state_pspec.slots, dict)
                   else {})}
    return jax.jit(jax.shard_map(
        read, mesh=mesh, in_specs=(state_pspec, P()),
        out_specs=(P(axis), P(axis), slot_specs), check_vma=False))


class IncrementalPersister(AsyncPersister):
    """AsyncPersister whose steady-state persists are O(touched rows).

    Drive it like AsyncPersister but hand it the batches too:

        p = IncrementalPersister(trainer, model, root,
                                 policy=PersistPolicy(every_steps=50))
        for batch in data:
            state, m = step(state, batch)
            p.maybe_persist(state, batch=batch)   # observes + maybe persists

    (or call `p.observe(batch)` per step and `maybe_persist(state)` as before —
    EVERY trained batch must be observed, else its rows go stale in the deltas;
    an unobserved window falls back to a full persist with a warning.)

    Persist schedule: a full base every `full_every` persists (bounds the
    restore replay chain), deltas in between. Works on one device, on a
    single-host mesh, AND on multi-process meshes (sharded tables: array
    rows address through the shard-major layout, hash rows through a
    shard_map'd probe). Multi-process deltas follow the reference's per-node
    dump (`EmbeddingDumpOperator.cpp:36-96`): the touched-id set is unioned
    across processes (host allgather — every process must drive persist at
    the same steps, which synchronous SPMD training guarantees), each
    process writes ONLY the rows its local shards own
    (`table_<name>.p<idx>.npz`), and the done-marker/COMMIT protocol of the
    full path makes the delta crash-consistent. Host-cached tables fall
    back to full persists — their store already lives host-side and the
    admission bookkeeping, not the snapshot, is their cost."""

    def __init__(self, trainer, model, root: str, *, full_every: int = 8,
                 **kw):
        # multi-process wall-clock policies are rejected by
        # AsyncPersister.__init__ (the defense covers both persisters)
        if full_every < 1:
            raise ValueError("full_every must be >= 1")
        super().__init__(trainer, model, root, **kw)
        self.full_every = full_every
        self.tracker = DirtyTracker(model)
        self._since_full = 0
        self._last_persist_step: Optional[int] = None
        self._readers = {}

    def observe(self, batch) -> None:
        self.tracker.observe(batch)

    def maybe_persist(self, state, batch=None) -> bool:
        if batch is not None:
            self.observe(batch)
        return super().maybe_persist(state)

    # -- touched-row device read (the O(touched) snapshot) -------------------

    def _reader(self, name, spec, padded_n: int):
        key = (name, padded_n)
        if key not in self._readers:
            S = self.trainer.num_shards
            if jax.process_count() > 1:
                # ef injection must mirror _read_touched's _ef_as_slot
                self._readers[key] = _make_shard_row_reader(
                    self.trainer.mesh, self.trainer.axis,
                    _ef_as_slot(self.trainer._table_pspec(spec)),
                    spec.use_hash_table, spec.input_dim)
            elif spec.use_hash_table and S > 1:
                self._readers[key] = _make_mesh_row_reader(
                    self.trainer.mesh, self.trainer.axis,
                    _ef_as_slot(self.trainer._table_pspec(spec)))
            else:
                self._readers[key] = jax.jit(
                    lambda ts, ids: _read_rows(spec, S, ts, ids))
        return self._readers[key]

    def _read_touched(self, state, name, ids64: np.ndarray):
        """-> host dict {ids, weights, slot_<k>...} for the touched rows that
        exist in the table (overflow-dropped ids have no row to persist)."""
        from .ops.id64 import np_split_ids
        spec = self.model.specs[name]
        ts = _ef_as_slot(state.tables[name])
        n = ids64.size
        padded = _ceil_pow2(max(1, n))
        pad = np.full((padded - n,), -1, np.int64)
        ids_h = np.concatenate([ids64, pad])
        pair = spec.use_hash_table and ts.keys is not None and ts.keys.ndim == 2
        if pair:
            ids_dev = np_split_ids(ids_h)
        elif spec.use_hash_table:
            ids_dev = ids_h.astype(ts.keys.dtype)  # x64-on single lane
        else:
            ids_dev = ids_h.astype(np.int32)  # array vocab always < 2^31
        found, w, s = self._reader(name, spec, padded)(ts, ids_dev)
        if jax.process_count() > 1:
            return self._collect_local(ids_h, found, w, s)
        found = np.asarray(found)[:n] if n else np.zeros((0,), bool)
        keep = found
        out = {"ids": ids64[keep],
               "weights": np.asarray(w)[:n][keep].astype(np.float32)}
        for k, v in s.items():
            out[f"slot_{k}"] = np.asarray(v)[:n][keep].astype(np.float32)
        return out

    @staticmethod
    def _collect_local(ids_h, found, w, slots):
        """Per-process delta payload from the shard reader's SHARDED outputs:
        every shard's (padded,)-long verdict masks the same global id list,
        and this process keeps only the rows its addressable shards found —
        disjoint across processes because row ownership is unique."""
        by_dev = lambda arr: {sh.device: np.asarray(sh.data)  # noqa: E731
                              for sh in arr.addressable_shards}
        fd, wd = by_dev(found), by_dev(w)
        sd = {k: by_dev(v) for k, v in slots.items()}
        ids_p, w_p = [], []
        s_p = {k: [] for k in slots}
        for dev in fd:
            keep = fd[dev].astype(bool)
            ids_p.append(ids_h[keep])
            w_p.append(wd[dev][keep])
            for k in sd:
                s_p[k].append(sd[k][dev][keep])
        out = {"ids": np.concatenate(ids_p),
               "weights": np.concatenate(w_p).astype(np.float32)}
        for k, parts in s_p.items():
            out[f"slot_{k}"] = np.concatenate(parts).astype(np.float32)
        return out

    # -- persist dispatch ----------------------------------------------------

    def persist(self, state) -> str:
        self._raise_pending_error()
        # delta readers pull touched rows straight off the shards — hot-cached
        # rows must land there first, and the delta's dense payload reads
        # dense_slots in the baseline layout (the full-persist branch syncs
        # again in super().persist; a second writeback is noise)
        state = self.trainer.externalize(state)
        step = int(state.step)
        touched = self.tracker.take()
        if jax.process_count() > 1:
            # COLLECTIVE union of the per-host touched sets (sorted table
            # order so every process gathers in the same sequence); also
            # makes the full-vs-delta decision below identical on all hosts
            from .parallel.multihost import allgather_host_ids
            touched = {name: allgather_host_ids(touched[name])
                       for name in sorted(touched)}
        unobserved = (not any(v.size for v in touched.values())
                      and self._last_persist_step is not None
                      and step > self._last_persist_step)
        full = (self._last_persist_step is None
                or self._since_full >= self.full_every
                or bool(getattr(self.trainer, "offload", None))
                or unobserved)
        if unobserved and self._since_full < self.full_every \
                and not getattr(self.trainer, "offload", None):
            import warnings
            warnings.warn(
                "IncrementalPersister: steps advanced but no batches were "
                "observed since the last persist — falling back to a FULL "
                "persist. Call observe(batch) (or maybe_persist(state, "
                "batch=batch)) for every trained batch.", RuntimeWarning)
        if full:
            path = super().persist(state)
            self._since_full = 0
            self._last_persist_step = step
            return path

        with metrics.vtimer("persist", "snapshot_delta"):
            parent = self._last_persist_step
            tables = {name: self._read_touched(state, name, ids)
                      for name, ids in touched.items() if ids.size}
            from .checkpoint import _flatten_params
            dense = {
                "params": _flatten_params(jax.device_get(state.dense_params)),
                "slots": _flatten_params(jax.device_get(state.dense_slots)),
            }
            # birth_time: the delta's zero point for end-to-end serving
            # freshness (sync subscriber's birth->swap chain). Captured
            # AFTER the touched-set allgather above — a wall-clock read
            # feeding collective-adjacent code would diverge across hosts,
            # but this value only lands in process 0's meta.json
            scalars = {"step": step,
                       "model_version": int(state.model_version),
                       "birth_time": time.time()}
        path = os.path.join(self.root, f"delta_{step:012d}")
        write_cb = lambda tmp: self._write_delta_payload(  # noqa: E731
            tables, dense, scalars, parent, tmp)
        self._q.put((write_cb, step, path))
        self.policy.mark(step)
        self._since_full += 1
        self._last_persist_step = step
        metrics.observe("persist.submitted_delta", 1)
        return path

    def _write_delta_payload(self, tables, dense, scalars, parent: int,
                             tmp: str) -> None:
        import json
        os.makedirs(tmp, exist_ok=True)
        pidx, pcount = jax.process_index(), jax.process_count()
        # per-process shard files (reference: per-node dump); single-process
        # keeps the unsuffixed name so existing delta roots stay readable
        suffix = f".p{pidx}" if pcount > 1 else ""
        for name, payload in tables.items():
            np.savez(os.path.join(tmp, f"table_{name}{suffix}.npz"),
                     **payload)
        if pidx != 0:
            return  # dense tree + meta are replicated; process 0 writes them
        np.savez(os.path.join(tmp, "dense.npz"),
                 **{f"params/{k}": v for k, v in dense["params"].items()},
                 **{f"slots/{k}": v for k, v in dense["slots"].items()})
        meta = {"format": DELTA_FORMAT, "parent": parent,
                "tables": sorted(tables), **scalars}
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)


def _load_delta_table(path: str, name: str):
    """-> concatenated (ids, weights, slots) for one table of one delta:
    the single-process `table_<name>.npz` or the union of per-process
    `table_<name>.p<idx>.npz` shard files (rows are disjoint — each process
    wrote only the rows its shards own)."""
    import glob as _glob

    single = os.path.join(path, f"table_{name}.npz")
    if os.path.exists(single):
        files = [single]
    else:
        files = _glob.glob(os.path.join(path, f"table_{name}.p*.npz"))
        files.sort(key=lambda p: int(
            re.search(r"\.p(\d+)\.npz$", p).group(1)))
    ids_l, w_l, slots_l = [], [], None
    for fp in files:
        with np.load(fp) as z:
            ids_l.append(z["ids"])
            w_l.append(z["weights"])
            s = {k[len("slot_"):]: z[k] for k in z.files
                 if k.startswith("slot_")}
        if slots_l is None:
            slots_l = {k: [] for k in s}
        for k, v in s.items():
            slots_l[k].append(v)
    if not files:
        return np.empty((0,), np.int64), np.empty((0, 0), np.float32), {}
    return (np.concatenate(ids_l), np.concatenate(w_l),
            {k: np.concatenate(v) for k, v in (slots_l or {}).items()})


def _apply_delta(state, model, path: str, *, trainer=None, _cache=None):
    """Replay one committed delta onto the state: jitted row scatter per
    table — hash ids re-found-or-inserted with the live probe kernel (under
    shard_map on a mesh), array ids written at their shard-major rows.
    `_cache` (shared across a chain) holds the compiled kernels; ids pad to
    the next power of two so a whole chain replays with ONE compile per
    table instead of one per delta."""
    import json

    import jax.numpy as jnp

    from .ops.id64 import np_split_ids

    S = trainer.num_shards if trainer is not None else 1
    cache = _cache if _cache is not None else {}
    with open(os.path.join(path, "meta.json")) as f:
        meta = json.load(f)
    new_tables = dict(state.tables)
    for name in meta["tables"]:
        spec = model.specs[name]
        ts = new_tables[name]
        ids64, w, slots = _load_delta_table(path, name)
        if ids64.size == 0:
            continue
        # ef residuals ride the delta as the reserved slot "__ef__"
        # (emitted by _read_touched's slot loop); inject the live ef into
        # the slot dict so the scatter kernels stay slot-generic, hoist it
        # back out after. A delta carrying residuals into an ef-less state
        # (or vice versa) degrades gracefully: the extra column is dropped /
        # the live residuals are left as they are.
        inject_ef = "__ef__" in slots and getattr(ts, "ef", None) is not None
        if inject_ef:
            ts = _ef_as_slot(ts)
        else:
            slots.pop("__ef__", None)

        def _hoist(nt, inject=inject_ef):
            if not inject:
                return nt
            sl = dict(nt.slots)
            ef = sl.pop("__ef__")
            return nt.replace(slots=sl, ef=ef)

        n = ids64.size
        padded = _ceil_pow2(n)
        ids_p = np.concatenate(
            [ids64, np.full((padded - n,), -1, np.int64)])
        w_dev = jnp.asarray(np.concatenate(
            [w, np.zeros((padded - n,) + w.shape[1:], w.dtype)]))
        s_dev = {k: jnp.asarray(np.concatenate(
            [v, np.zeros((padded - n,) + v.shape[1:], v.dtype)]))
            for k, v in slots.items()}
        if spec.use_hash_table:
            pair = ts.keys.ndim == 2
            ids_dev = jnp.asarray(np_split_ids(ids_p) if pair
                                  else ids_p.astype(ts.keys.dtype))
            if S > 1:
                # the host-offload mesh admission IS the sharded
                # insert-and-write-rows kernel (known = every real delta row;
                # sentinel-padded ids carry known=False and never insert)
                if ("admit", name) not in cache:
                    from .tables.host_offload import _make_mesh_admit
                    pspec = trainer._table_pspec(spec)
                    if inject_ef:  # pspec injection must mirror the ts's
                        pspec = _ef_as_slot(pspec)
                    cache[("admit", name)] = _make_mesh_admit(
                        trainer.mesh, trainer.axis, pspec, list(ts.slots))
                known = jnp.asarray(np.arange(padded) < n)
                new_ts, _ = cache[("admit", name)](ts, ids_dev, w_dev, s_dev,
                                                   known)
                new_tables[name] = _hoist(new_ts)
                continue

            if ("hash", name) not in cache:

                def write(ts, ids, w, s):
                    from .tables.hash_table import hash_find_or_insert
                    keys, slot, overflow = hash_find_or_insert(ts.keys, ids)
                    cap = keys.shape[0]
                    target = jnp.where(slot < cap, slot, cap)
                    weights = ts.weights.at[target].set(
                        w.astype(ts.weights.dtype), mode="drop")
                    new_slots = {k: ts.slots[k].at[target].set(
                        s[k].astype(ts.slots[k].dtype), mode="drop")
                        for k in ts.slots}
                    return ts.replace(keys=keys, weights=weights,
                                      slots=new_slots,
                                      overflow=ts.overflow + overflow)

                cache[("hash", name)] = jax.jit(write, donate_argnums=(0,))
            new_tables[name] = _hoist(cache[("hash", name)](
                ts, ids_dev, w_dev, s_dev))
        else:
            if ("array", name) not in cache:

                def write(ts, ids, w, s):
                    rows = ts.weights.shape[0]
                    ok = (ids >= 0) & (ids < spec.input_dim)
                    tgt = jnp.where(
                        ok, _array_global_idx(ids, rows, S), rows)
                    weights = ts.weights.at[tgt].set(
                        w.astype(ts.weights.dtype), mode="drop")
                    new_slots = {k: ts.slots[k].at[tgt].set(
                        s[k].astype(ts.slots[k].dtype), mode="drop")
                        for k in ts.slots}
                    return ts.replace(weights=weights, slots=new_slots)

                cache[("array", name)] = jax.jit(write, donate_argnums=(0,))
            new_tables[name] = _hoist(cache[("array", name)](
                ts, jnp.asarray(ids_p.astype(np.int32)), w_dev, s_dev))

    with np.load(os.path.join(path, "dense.npz")) as z:
        from .checkpoint import _unflatten_params
        params = _unflatten_params(
            {k[len("params/"):]: z[k] for k in z.files
             if k.startswith("params/")})
        dslots = _unflatten_params(
            {k[len("slots/"):]: z[k] for k in z.files
             if k.startswith("slots/")})

    rep = None
    if trainer is not None and getattr(trainer, "mesh", None) is not None:
        from jax.sharding import NamedSharding, PartitionSpec as P
        rep = NamedSharding(trainer.mesh, P())  # dense/scalars replicate

    def _like(leaf, value):
        arr = jnp.asarray(value).astype(leaf.dtype).reshape(leaf.shape)
        sharding = rep if rep is not None else getattr(leaf, "sharding", None)
        return jax.device_put(arr, sharding) if sharding is not None else arr

    def _match(template, loaded):
        """Rebuild the template's pytree with loaded leaves (dtype, shape,
        and sharding pinned to the live state's)."""
        leaves, treedef = jax.tree_util.tree_flatten(template)
        new_leaves = treedef.flatten_up_to(loaded)
        return jax.tree_util.tree_unflatten(
            treedef, [_like(l, nl) for l, nl in zip(leaves, new_leaves)])

    return state.replace(
        tables=new_tables,
        dense_params=_match(state.dense_params, params),
        dense_slots=_match(state.dense_slots, dslots),
        step=_like(state.step, meta["step"]),
        model_version=_like(state.model_version, meta["model_version"]),
    )


class _StateMeshShim:
    """Trainer-like facade recovered from a live SHARDED state's
    NamedShardings, so serving-side restore (no Trainer in the process) can
    replay delta chains: supplies the mesh/axis/num_shards and per-table
    pspecs that the sharded row-scatter kernels need. Host-cached tables
    are out of scope (`offload=None`) — their restore goes through the real
    trainer's offload handles."""

    offload = None

    def __init__(self, state, model):
        from jax.sharding import NamedSharding

        self.mesh = self.axis = None
        for ts in state.tables.values():
            sh = getattr(ts.weights, "sharding", None)
            if (isinstance(sh, NamedSharding) and len(sh.device_set) > 1
                    and len(sh.spec) > 0):
                axis = sh.spec[0]
                if isinstance(axis, (tuple, list)):
                    axis = axis[0]
                if axis is None:
                    continue
                self.mesh, self.axis = sh.mesh, axis
                break
        if self.mesh is None:
            raise ValueError(
                "state is sharded but no table carries a row-sharded "
                "NamedSharding to recover the mesh from")
        self.num_shards = int(self.mesh.shape[self.axis])
        self._slot_names = {name: list(ts.slots)
                            for name, ts in state.tables.items()}
        self._has_ef = {name: getattr(ts, "ef", None) is not None
                        for name, ts in state.tables.items()}

    def _table_pspec(self, spec):
        from jax.sharding import PartitionSpec as P

        from .embedding import EmbeddingTableState
        # trimmed spelling (`P(axis)`): must match MeshTrainer._table_pspec —
        # a `P(axis, None)`-committed restore would re-trace the train step
        return EmbeddingTableState(
            weights=P(self.axis),
            slots={k: P(self.axis)
                   for k in self._slot_names[spec.name]},
            keys=P(self.axis) if spec.use_hash_table else None,
            overflow=P() if spec.use_hash_table else None,
            ef=P(self.axis) if self._has_ef[spec.name] else None,
        )


# -- module-level API parity with `exb.py:697-705` ---------------------------


def persist_server_model(trainer, model, state, root: str, window: int = 2) -> str:
    """One-shot blocking persist (API parity; the loop-integrated path is
    `AsyncPersister`)."""
    with AsyncPersister(trainer, model, root, window=window) as p:
        return p.persist(state)


def restore_server_model(state, model, root: str, *, trainer=None):
    """Restore the newest COMMITTED persist under `root`, then replay any
    committed delta chain on top (crash-consistent at every level: uncommitted
    directories are ignored, a broken chain replays only its consistent
    prefix; reference `restore_server_model`, `exb.py:703-705`)."""
    path, deltas = delta_chain(root)
    if path is None:
        raise FileNotFoundError(f"no committed persist under {root!r}")
    # trainerless restore of a SHARDED state (serving-side): recover the
    # mesh/axis/pspecs from the state's own shardings — both the base load's
    # shard count and the delta replay's row scatter depend on them
    drv = trainer
    if drv is None and _state_is_sharded(state):
        drv = _StateMeshShim(state, model)
    num_shards = drv.num_shards if drv is not None else 1
    offload = getattr(drv, "offload", None) or None
    # ZeRO template states carry flat sharded dense_slots; on disk the slots
    # are always the baseline per-leaf layout — restore in that layout and
    # re-shard at the end (identities when ZeRO is off / trainerless)
    zero_on = trainer is not None and getattr(trainer, "zero_enabled", False)
    if zero_on:
        state = trainer.dense_to_replicated(state)
    from .parallel.checkpoint import checkpoint_layout, load_sharded
    if checkpoint_layout(path) == "sharded":
        state = load_sharded(state, model, path, num_shards=num_shards,
                             offload=offload)
    else:
        from .checkpoint import load_server_model
        state = load_server_model(state, model, path, num_shards=num_shards,
                                  offload=offload)
    cache: Dict = {}
    for d in deltas:
        state = _apply_delta(state, model, d, trainer=drv, _cache=cache)
    if zero_on:
        state = trainer.dense_to_sharded(state)
    return state


def _state_is_sharded(state) -> bool:
    for ts in state.tables.values():
        sh = getattr(ts.weights, "sharding", None)
        if sh is not None and len(getattr(sh, "device_set", ())) > 1:
            return True
    return False
