"""PlacementController: drives hot-cache sizing, refresh pacing and
cold-tail migration with no operator in the loop.

Division of labour (nothing here touches the jitted step):

- A background WATCHER thread (optional, `start()`) snapshots the sketches
  + metrics on a wall-clock cadence, runs the policy, and parks the
  resulting `PlacementDecision`. It never touches trainer state — JAX
  state threading is functional, so only the training loop may swap it.
- The training loop calls `on_step(state, step)` between steps (cheap: an
  int compare off-cadence). On the decision cadence — or when the watcher
  parked a decision — it applies refreshes via
  `MeshTrainer.refresh_hot_rows` and migrations via
  `MeshTrainer.migrate_rows`, both content-swaps of trace-time-static
  arrays: the steady-state step NEVER recompiles.
- `prime(state)` runs once before the step is jitted: it sizes each
  table's static hot capacity (and the migration annex) from the policy's
  byte budget and attaches the placement state. Sizing changes shapes, so
  this is the ONE moment re-jitting is allowed — prime before
  `jit_train_step`, or accept one recompile when enabling placement on a
  live trainer.

Every decision exports `placement.*` gauges and a flight-recorder event
(`utils/trace.py`), and `render_status()` feeds the `/statusz` placement
panel, so "why did the controller refresh at step 1200?" is answerable
from the node itself.
"""

from __future__ import annotations

import threading
import weakref
from typing import Dict, List, Optional

import numpy as np

from ..utils import metrics as _metrics
from ..utils import trace as _trace
from .migration import candidate_weights, plan_migration
from .policy import PlacementDecision, PlacementPolicy, TableDecision, \
    TableTelemetry

# live controllers for the /statusz panel (weakrefs: a controller's
# lifetime belongs to its owner, not to the status page)
_CONTROLLERS: "List[weakref.ref]" = []
_CONTROLLERS_LOCK = threading.Lock()


def _controllers() -> List["PlacementController"]:
    with _CONTROLLERS_LOCK:
        alive = [r() for r in _CONTROLLERS]
        _CONTROLLERS[:] = [r for r, c in zip(_CONTROLLERS, alive)
                           if c is not None]
        return [c for c in alive if c is not None]


def render_status() -> str:
    """The /statusz placement panel: one block per live controller."""
    ctrls = _controllers()
    if not ctrls:
        return "(no placement controllers)"
    return "\n".join(c.render_text() for c in ctrls)


class PlacementController:
    """Autonomous placement driver for one `MeshTrainer`.

    `monitor`: the `SkewMonitor` feeding the decisions (defaults to the
    trainer's `enable_skew_monitor()` feed, falling back to the global
    `utils.sketch.MONITOR`). Give it `decay=` so a drifting workload
    rotates the sketches — the controller only ever sees what the sketches
    see. `interval_steps`: decision cadence for the inline `on_step` path.
    """

    def __init__(self, trainer, policy: PlacementPolicy, *,
                 monitor=None, interval_steps: int = 50,
                 manage_wire: bool = False):
        self.trainer = trainer
        self.policy = policy
        self._monitor = monitor
        self.interval_steps = int(interval_steps)
        # opt-in: let the controller also set per-table wire precision
        # (policy.recommend_wire -> MeshTrainer(wire={...})). Off by default
        # because a format change is a re-jit, not a content swap.
        self.manage_wire = bool(manage_wire)
        self._wire_active: Dict[str, str] = {}
        self._wire_rejits = 0
        # dense-gradient wire management (guarded-by: self._lock)
        self._dense_wire_rejits = 0
        self._last_dense_wire_step = -10**9
        self._dense_wire_reason = ""
        self._lock = threading.Lock()
        # guarded-by: self._lock
        self._pending: Optional[PlacementDecision] = None
        # guarded-by: self._lock
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._primed = False
        # decision history for /statusz (guarded-by: self._lock)
        self._last_refresh_step: Dict[str, int] = {}
        self._last_refresh_reason: Dict[str, str] = {}
        self._hot_target: Dict[str, int] = {}
        self._predicted_hit: Dict[str, float] = {}
        self._migrations_applied = 0
        self._migrated_rows: Dict[str, int] = {}
        self._decisions = 0
        self._step = 0
        with _CONTROLLERS_LOCK:
            _CONTROLLERS.append(weakref.ref(self))

    # -- telemetry -----------------------------------------------------------

    @property
    def monitor(self):
        if self._monitor is not None:
            return self._monitor
        mon = getattr(self.trainer, "_skew", None)
        if mon is not None:
            return mon
        from ..utils import sketch
        return sketch.MONITOR

    def _managed_tables(self) -> Dict[str, object]:
        return {n: s for n, s in self.trainer.model.ps_specs().items()
                if not s.sparse_as_dense and s.storage != "host_cached"}

    def _shard_positions(self, name: str) -> Optional[np.ndarray]:
        """Measured per-shard load from the published gauges (the loop's
        `metrics.record_step_stats` keeps them fresh each step)."""
        import re
        rep = _metrics.report()
        pat = re.compile(
            r'^exchange\.shard_positions\{shard="(\d+)",table="%s"\}$'
            % re.escape(name))
        vals = {}
        for key, v in rep.items():
            m = pat.match(key)
            if m:
                vals[int(m.group(1))] = v
        if not vals:
            return None
        return np.asarray([vals.get(i, 0.0)
                           for i in range(max(vals) + 1)], np.float64)

    def telemetry(self) -> List[TableTelemetry]:
        mon = self.monitor
        out = []
        for name, spec in self._managed_tables().items():
            sk = mon.sketch(name)
            # optimizer-slot floats per row, as weight-column multiples
            # (Adagrad: one accumulator column per weight column -> 1)
            widths = self.trainer.opt_for(spec).slot_shapes(spec.output_dim)
            slot_cols = int(round(sum(int(v) for v in widths.values())
                                  / max(spec.output_dim, 1)))
            out.append(TableTelemetry(
                name=name, dim=spec.output_dim,
                coverage=sk.coverage(),
                total=float(sk.total),
                top_ids=[(i, e) for i, e, _err in sk.topk()],
                shard_positions=self._shard_positions(name),
                slot_cols=slot_cols))
        return out

    # -- sizing / prime ------------------------------------------------------

    def prime(self, state):
        """Size the static placement capacities from the byte budget and
        attach placement state — call ONCE, before jitting the step (the
        only shape-changing moment; everything after is content swaps).
        Needs warm sketches: feed the monitor a few batches first (or let
        the first training window run placement-off and prime at its end).
        Returns the state with hot caches + migration directories
        attached."""
        tel = self.telemetry()
        if self.manage_wire:
            # set the formats BEFORE the sizing re-jit below so enabling
            # wire management at prime time costs zero extra compiles
            state = self.apply_wire(state, self.policy.recommend_wire(tel))
            tr0 = self.trainer
            if getattr(tr0, "zero_enabled", False) \
                    and tr0.dense_wire in ("int8", "sparse_topk") \
                    and not tr0.dense_stats:
                # the measured gradient density feeds
                # `recommend_dense_wire`; turning the stat on is a
                # trace-time change, folded into prime's one re-jit
                tr0.dense_stats = True
                tr0._train_step_fn = None
                tr0._train_many_fn = None
        sizes = self.policy.size_hot(tel)
        hot_rows = {n: int(h) for n, h in sizes.items() if h > 0}
        # per-table annex capacity off the measured cold-tail imbalance
        # (policy.size_mig); tables the telemetry doesn't cover keep the
        # static default
        sized_mig = self.policy.size_mig(tel)
        mig_rows = {n: int(sized_mig.get(n, self.policy.mig_rows))
                    for n in self._managed_tables()}
        tr = self.trainer
        # memory preflight BEFORE the one-time re-jit: would the grown
        # hot caches + annexes still fit the device budget? A rejection
        # keeps the CURRENT capacities (no shape change, no re-jit) —
        # an oversized placement plan must never OOM the step
        delta = self._resize_delta_bytes(hot_rows, mig_rows)
        if delta > 0:
            from ..utils import memwatch as _memwatch
            if not _memwatch.WATCH.preflight(delta, reason="placement_prime"):
                _trace.event("placement", "prime_rejected",
                             delta_bytes=int(delta))
                hot_rows = self._current_sizes("hot_rows")
                mig_rows = self._current_sizes("mig_rows")
        changed = False
        for attr, val in (("hot_rows", hot_rows), ("mig_rows", mig_rows)):
            cur = getattr(tr, attr)
            cur_map = {n: (cur.get(n, 0) if isinstance(cur, dict)
                           else int(cur)) for n in self._managed_tables()}
            new_map = {n: val.get(n, 0) for n in self._managed_tables()}
            if cur_map != new_map:
                setattr(tr, attr, val)
                changed = True
        if changed:
            # capacities are trace-time shapes: drop compiled artifacts so
            # the NEXT jit builds the placement-enabled program (this is the
            # documented one-time re-jit; prime before jit_train_step and
            # it is the only compile at all)
            tr._train_step_fn = None
            tr._eval_step_fn = None
            tr._train_many_fn = None
            tr._hot_fns = {}
            tr._mig_fns = {}
        self._hot_target = dict(hot_rows)
        for n, h in hot_rows.items():
            _metrics.observe("placement.hot_rows", float(h), "gauge",
                             labels={"table": n})
        for n, m in mig_rows.items():
            _metrics.observe("placement.mig_rows", float(m), "gauge",
                             labels={"table": n})
        _trace.event("placement", "prime",
                     hot_rows=dict(hot_rows),
                     mig_rows=dict(mig_rows),
                     budget_bytes=self.policy.hot_budget_bytes)
        if tr.mig_enabled:
            state = tr.migrate_rows(state)  # attach empty directories
        if tr.hot_enabled:
            state = tr.refresh_hot_rows(state, monitor=self.monitor)
            with self._lock:
                for n in hot_rows:
                    self._last_refresh_step[n] = self._step
                    self._last_refresh_reason[n] = "prime"
        self._primed = True
        return state

    def _current_sizes(self, attr: str) -> Dict[str, int]:
        """The trainer's INSTALLED per-table capacity map for one attr."""
        cur = getattr(self.trainer, attr)
        return {n: (int(cur.get(n, 0)) if isinstance(cur, dict)
                    else int(cur)) for n in self._managed_tables()}

    def _resize_delta_bytes(self, hot_rows: Dict[str, int],
                            mig_rows: Dict[str, int]) -> int:
        """Per-device byte delta of installing these hot/mig capacities in
        place of the current ones (the trainer's analytic shape model)."""
        tr = self.trainer
        cur_hot = self._current_sizes("hot_rows")
        cur_mig = self._current_sizes("mig_rows")
        delta = 0
        for name, spec in self._managed_tables().items():
            delta += (tr._hot_device_bytes(spec, int(hot_rows.get(name, 0)))
                      - tr._hot_device_bytes(spec, cur_hot.get(name, 0)))
            delta += (tr._mig_device_bytes(spec, int(mig_rows.get(name, 0)))
                      - tr._mig_device_bytes(spec, cur_mig.get(name, 0)))
        return delta

    def _mig_cap(self, name: str) -> int:
        """The table's INSTALLED annex capacity (a trace-time shape the
        trainer holds after prime) — plans must fit it; falls back to the
        policy's static default before prime sizes the annexes."""
        cap = getattr(self.trainer, "mig_rows", 0)
        if isinstance(cap, dict):
            return int(cap.get(name, 0)) or self.policy.mig_rows
        return int(cap) or self.policy.mig_rows

    # -- decide --------------------------------------------------------------

    def decide(self, state=None) -> PlacementDecision:
        """Run the policy over current telemetry -> a decision (no state
        mutation; `apply` installs it). `state` supplies the installed hot
        sets for churn/gain math; without it the installed set is assumed
        empty (dry-run mode — what skew_report --recommend prints)."""
        tel = self.telemetry()
        sizes = dict(self._hot_target) or self.policy.size_hot(tel)
        tables: Dict[str, TableDecision] = {}
        refresh = migrate = False
        reasons = []
        for t in tel:
            H = int(sizes.get(t.name, 0))
            installed = np.zeros((0,), np.int64)
            mig_installed = None
            if state is not None:
                ts = state.tables.get(t.name)
                if ts is not None and ts.hot is not None:
                    installed = self.trainer._np_id_list(ts.hot.ids)
                if ts is not None and ts.mig is not None:
                    mig_installed = self.trainer._np_id_list(ts.mig.ids)
            with self._lock:
                since = self._step - self._last_refresh_step.get(
                    t.name, -10**9)
            due, reason, gain = self.policy.refresh_due(
                t, installed, H, since)
            churn = self.policy.churn(installed, t.top_ids[:H])
            _metrics.observe("placement.churn", churn, "gauge",
                             labels={"table": t.name})
            _metrics.observe("placement.predicted_hit_gain", gain, "gauge",
                             labels={"table": t.name})
            hot_ids = np.asarray([i for i, _e in t.top_ids[:H]], np.int64)
            mig_due, mig_reason = self.policy.migration_due(t)
            moves = (np.zeros((0,), np.int64), np.zeros((0,), np.int64))
            if mig_due and t.shard_positions is not None:
                # Plan the FULL directory from the sketch-derived EXPECTED
                # load, not the measured snapshot. The measured vector
                # already reflects the active directory (a fresh plan from
                # it would find nothing and installing that would de-
                # migrate the rows doing the balancing), and any one step's
                # sample is noisy enough that planning against it churns
                # assignments every cycle. Instead build the un-migrated
                # picture the sketch predicts — per-candidate load
                # `est/cold_total` of the measured cold positions on its
                # hash home, the un-tracked tail uniform — and solve that.
                # Deterministic given the sketch: when converged the plan
                # reproduces the current assignment (install skipped), a
                # drifted-out id stops being a candidate (evicted, its
                # annex slot freed for the new head), and a past move
                # whose owner has become the hot spot is re-assigned
                # rather than pinned forever. The measured vector stays
                # the TRIGGER (`migration_due`); the model is the plan.
                S = self.trainer.num_shards
                cur = {}
                if state is not None:
                    ts = state.tables.get(t.name)
                    if ts is not None and ts.mig is not None:
                        cur_ids = self.trainer._np_id_list(ts.mig.ids)
                        cur_own = np.asarray(ts.mig.owners)[:cur_ids.size]
                        cur = {i: int(o) for i, o in
                               zip(cur_ids.tolist(), cur_own.tolist())
                               if int(o) >= 0}
                cands = candidate_weights(t.top_ids, hot_ids)
                step_load = float(np.asarray(t.shard_positions,
                                             np.float64).sum())
                hot_set = set(hot_ids.tolist())
                hot_est = sum(float(e) for i, e in t.top_ids
                              if int(i) in hot_set)
                cold_tot = max(t.total - hot_est, 1.0)
                w_steps = [max(float(w), 0.0) / cold_tot * step_load
                           for _i, w in cands]
                tail = max(step_load - sum(w_steps), 0.0)
                base = np.full((S,), tail / S, np.float64)
                for (i, _w), ws in zip(cands, w_steps):
                    base[int(i) % S] += ws
                ids, owners, proj = plan_migration(
                    base, cands, num_shards=S,
                    max_moves=self._mig_cap(t.name),
                    target=self.policy.imbalance_target,
                    total=cold_tot, exclude=hot_ids)
                moves = (ids, owners)
                if dict(zip(ids.tolist(), owners.tolist())) == cur:
                    # converged: the plan reproduces the active directory —
                    # skip the install rather than churning the annex
                    moves = None
                    mig_due = False
                    mig_reason += " (plan unchanged)"
                else:
                    mig_reason += (f"; {ids.size} moves, projected "
                                   f"imbalance {proj:.3f}")
            elif mig_installed is not None and mig_installed.size \
                    and not mig_due:
                # keep the current directory: re-planning to empty would
                # de-migrate a balanced steady state
                moves = None
            tables[t.name] = TableDecision(
                hot_rows=H,
                predicted_hit=t.share_at(H),
                hot_ids=hot_ids,
                moves=moves,
                reason=f"refresh: {reason}; migrate: {mig_reason}")
            refresh |= due
            migrate |= mig_due
            reasons.append(f"{t.name}: {tables[t.name].reason}")
        with self._lock:
            self._decisions += 1
        _metrics.observe("placement.decisions", 1)
        return PlacementDecision(tables=tables, refresh=refresh,
                                 migrate=migrate, reason=" | ".join(reasons))

    # -- wire precision ------------------------------------------------------

    def apply_wire(self, state, rec: Dict[str, str]):
        """Install a per-table wire recommendation (`policy.recommend_wire`).
        The content path: when every table's RESOLVED format already matches
        the recommendation this is a pure no-op — no re-jit, nothing dropped
        — which is the steady state once the traffic shape stabilizes. A
        real format change swaps `trainer.wire` to a per-table dict, attaches
        or drops the int8 error-feedback residuals to match (zeros-reset is
        safe: EF is a convergence aid, not model state), and drops the
        compiled step — ONE re-jit, counted in `placement.wire_rejits`."""
        tr = self.trainer
        if tr.num_shards <= 1:
            return state
        managed = self._managed_tables()
        rec = {n: f for n, f in rec.items() if n in managed}
        if all(tr.wire_for(n) == f for n, f in rec.items()):
            with self._lock:
                self._wire_active = {n: tr.wire_for(n) for n in managed}
            return state
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        new_wire = dict(rec)
        new_wire["*"] = tr.wire_default()
        tr.wire = new_wire
        tables = dict(state.tables)
        for n, spec in managed.items():
            ts = tables.get(n)
            if ts is None:
                continue
            need = tr.ef_for(n)
            if need and ts.ef is None:
                tables[n] = ts.replace(ef=jax.device_put(
                    jnp.zeros((ts.weights.shape[0], spec.output_dim),
                              jnp.float32),
                    NamedSharding(tr.mesh, P(tr.axis))))
            elif not need and ts.ef is not None:
                tables[n] = ts.replace(ef=None)
        state = state.replace(tables=tables)
        # formats are trace-time statics: drop the compiled artifacts so the
        # next jit builds the new (dim, fmt) grouping
        tr._train_step_fn = None
        tr._eval_step_fn = None
        tr._train_many_fn = None
        tr._hot_fns = {}
        tr._mig_fns = {}
        with self._lock:
            self._wire_rejits += 1
            self._wire_active = {n: tr.wire_for(n) for n in managed}
        _metrics.observe("placement.wire_rejits", 1)
        _trace.event("placement", "wire", step=self._step,
                     formats=dict(sorted(self._wire_active.items())))
        return state

    def apply_dense_wire(self, state):
        """Density-adaptive dense-gradient wire (the decision half of the
        sparse collective): read the measured `dense.grad_density` gauge,
        price sparse vs dense via `policy.recommend_dense_wire`, and flip
        the trainer through `MeshTrainer.set_dense_wire` when the verdict
        changes — a counted re-jit, hysteresis + cooldown gated. Only
        active once the operator chose a narrow dense wire (int8 or
        sparse_topk); fp32/bf16 runs are left alone."""
        tr = self.trainer
        if not getattr(tr, "zero_enabled", False):
            return state
        current = tr.dense_wire
        if current not in ("int8", "sparse_topk"):
            return state
        density = _metrics.report().get("dense.grad_density")
        if density is None:
            return state  # stat not published yet (dense_stats off or
            # no step recorded) — nothing measured to decide on
        plan = tr._zero_plan_for(tr._dense_trainable(state))
        with self._lock:
            since = self._step - self._last_dense_wire_step
        mode, k, reason = self.policy.recommend_dense_wire(
            float(density), current, chunk=plan.chunk, steps_since=since)
        _metrics.observe("placement.dense_wire_sparse",
                         1.0 if mode == "sparse_topk" else 0.0, "gauge")
        target_k = k if mode == "sparse_topk" else None
        with self._lock:
            self._dense_wire_reason = reason
        if mode == current and target_k == tr.dense_topk:
            return state
        if since < self.policy.dense_wire_cooldown_steps:
            # the policy's cooldown covers mode flips; this also paces
            # same-mode k resizes — every change here is a re-jit
            return state
        state = tr.set_dense_wire(state, mode, target_k)
        with self._lock:
            self._dense_wire_rejits += 1
            self._last_dense_wire_step = self._step
        _trace.event("placement", "dense_wire", step=self._step,
                     mode=mode, k=target_k, density=float(density),
                     reason=reason[:200])
        return state

    # -- apply ---------------------------------------------------------------

    def apply(self, state, decision: PlacementDecision):
        """Install a decision between steps (content swaps only)."""
        tr = self.trainer
        if decision.migrate and tr.mig_enabled:
            moves = {n: d.moves for n, d in decision.tables.items()
                     if d.moves is not None and d.moves[0].size}
            if moves:
                state = tr.migrate_rows(state, moves)
                with self._lock:
                    self._migrations_applied += 1
                    for n, (ids, _o) in moves.items():
                        self._migrated_rows[n] = int(ids.size)
                _trace.event("placement", "migrate", step=self._step,
                             rows={n: int(m[0].size)
                                   for n, m in moves.items()})
        if decision.refresh and tr.hot_enabled:
            hot_ids = {n: d.hot_ids[:d.hot_rows]
                       for n, d in decision.tables.items() if d.hot_rows}
            state = tr.refresh_hot_rows(state, hot_ids=hot_ids)
            with self._lock:
                for n, d in decision.tables.items():
                    self._last_refresh_step[n] = self._step
                    self._last_refresh_reason[n] = d.reason
                    self._predicted_hit[n] = d.predicted_hit
            for n, d in decision.tables.items():
                _metrics.observe("placement.predicted_hit",
                                 d.predicted_hit, "gauge",
                                 labels={"table": n})
            _metrics.observe("placement.refreshes", 1)
            _trace.event("placement", "refresh", step=self._step,
                         reason=decision.reason[:200])
        return state

    # -- loop hooks ----------------------------------------------------------

    def on_step(self, state, step: Optional[int] = None):
        """Call between training steps. Off-cadence this is an int compare;
        on cadence (or when the watcher parked a decision) it decides +
        applies. Returns the (possibly updated) state."""
        self._step = int(step) if step is not None else self._step + 1
        with self._lock:
            pending, self._pending = self._pending, None
        if pending is None:
            if self.interval_steps <= 0 or \
                    self._step % self.interval_steps != 0:
                return state
            pending = self.decide(state)
        state = self.apply(state, pending)
        if self.manage_wire:
            state = self.apply_wire(
                state, self.policy.recommend_wire(self.telemetry()))
            state = self.apply_dense_wire(state)
        return state

    # -- background watcher --------------------------------------------------

    def start(self, interval_s: float = 5.0) -> None:
        """Start the watcher thread: computes decisions off the training
        thread on a wall-clock cadence and parks them for the next
        `on_step` to apply. Idempotent."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._watch, args=(float(interval_s),), daemon=True,
                name="oetpu-placement-controller")
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            t = self._thread
        if t is not None:
            t.join(timeout=10)

    def _watch(self, interval_s: float) -> None:
        while not self._stop.wait(interval_s):
            try:
                # watcher decides WITHOUT state (installed sets unknown ->
                # gain is an upper bound); on_step applies under the real
                # cooldown bookkeeping
                decision = self.decide()
                if decision.refresh or decision.migrate:
                    with self._lock:
                        self._pending = decision
            except Exception:  # noqa: BLE001 — telemetry must never crash
                _metrics.observe("placement.watch_errors", 1)

    # -- operator surface ----------------------------------------------------

    def status(self) -> dict:
        with self._lock:
            return {
                "primed": self._primed,
                "step": self._step,
                "interval_steps": self.interval_steps,
                "hot_budget_bytes": self.policy.hot_budget_bytes,
                "hot_rows": dict(self._hot_target),
                "predicted_hit": dict(self._predicted_hit),
                "last_refresh_step": dict(self._last_refresh_step),
                "last_refresh_reason": dict(self._last_refresh_reason),
                "migrations_applied": self._migrations_applied,
                "migrated_rows": dict(self._migrated_rows),
                "decisions": self._decisions,
                "imbalance_target": self.policy.imbalance_target,
                "manage_wire": self.manage_wire,
                "wire_formats": dict(self._wire_active),
                "wire_rejits": self._wire_rejits,
                "dense_wire": getattr(self.trainer, "dense_wire", None)
                or "fp32",
                "dense_wire_rejits": self._dense_wire_rejits,
                "dense_wire_reason": self._dense_wire_reason,
            }

    def render_text(self) -> str:
        st = self.status()
        lines = [f"controller: step={st['step']} primed={st['primed']} "
                 f"decisions={st['decisions']} "
                 f"budget={st['hot_budget_bytes']}B "
                 f"imbalance_target={st['imbalance_target']}"
                 + (f" manage_wire=on wire_rejits={st['wire_rejits']}"
                    f" dense_wire={st['dense_wire']}"
                    f" dense_wire_rejits={st['dense_wire_rejits']}"
                    if st["manage_wire"] else "")]
        import re
        rep = _metrics.report()
        for name in sorted(self._managed_tables()):
            h = st["hot_rows"].get(name, 0)
            imb = rep.get(f'exchange.shard_imbalance{{table="{name}"}}')
            hit = rep.get(f'hot.hit_ratio{{table="{name}"}}')
            parts = [f"table {name}: hot_rows={h}"]
            if self.trainer.num_shards > 1:
                # active per-table wire format (resolved, not the raw knob)
                parts.append(f"wire={self.trainer.wire_for(name)}")
            if st["predicted_hit"].get(name) is not None:
                parts.append(
                    f"predicted_hit={st['predicted_hit'][name]:.3f}")
            if hit is not None:
                parts.append(f"live_hit={hit:.3f}")
            if st["last_refresh_step"].get(name) is not None:
                reason = re.sub(r"\s+", " ", st["last_refresh_reason"]
                                .get(name, ""))[:120]
                parts.append(f"last_refresh=step {st['last_refresh_step'][name]}"
                             f" ({reason})")
            if st["migrated_rows"].get(name):
                parts.append(f"migrated_rows={st['migrated_rows'][name]}")
            if imb is not None:
                parts.append(f"imbalance={imb:.3f}")
            lines.append("  " + " ".join(parts))
        lines.append(f"  migrations_applied={st['migrations_applied']}")
        return "\n".join(lines)
