"""Placement decision math — pure numpy, no jax, no side effects.

Everything here maps MEASURED telemetry (sketch coverage curves, per-shard
load vectors, the live hot-cache hit ratio) to placement decisions. The
functions are deliberately free of trainer/serving dependencies so the same
policy runs three ways: live inside `PlacementController`, dry-run from a
/metrics scrape (`tools/skew_report.py --recommend`), and in unit tests with
synthetic curves.

Budget semantics: `hot_budget_bytes` bounds the PER-DEVICE bytes of
replicated hot-row payload — sum over tables of H_t rows x row_bytes_t
(fp32 weights + fp32 optimizer-slot columns; the thing every device carries
a copy of AND the backward's dense psum ships every step). The solver walks
each table's coverage curve and spends the budget on the segments with the
highest traffic-per-byte — the knee of a heavily skewed table beats the
head of a flat one, which is exactly "budget flows to the most skewed
tables".
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


def row_bytes(dim: int, slot_cols: int = 1) -> int:
    """Replicated bytes one cached row costs: fp32 weights + fp32 optimizer
    slot columns (Adagrad: one accumulator column per weight column)."""
    return 4 * dim * (1 + slot_cols)


@dataclasses.dataclass
class TableTelemetry:
    """One table's measured inputs to the policy (built from live sketches
    by the controller, or from a /metrics scrape by skew_report)."""

    name: str
    dim: int
    # coverage curve [(k, cumulative traffic share of the top-k ids)] —
    # `SpaceSaving.coverage()`; monotone, <= 1.0
    coverage: List[Tuple[int, float]]
    total: float = 0.0                 # ids observed (the share denominator)
    # heavy hitters [(id, est)] hottest-first (the promotion candidates)
    top_ids: List[Tuple[int, int]] = dataclasses.field(default_factory=list)
    # per-shard duplicate-weighted load (exchange.shard_positions); None
    # until the trainer has published a step's stats
    shard_positions: Optional[np.ndarray] = None
    slot_cols: int = 1

    def share_at(self, k: int) -> float:
        """Interpolated cumulative traffic share of the top-k ids."""
        if k <= 0 or not self.coverage:
            return 0.0
        pts = [(0, 0.0)] + [(int(a), float(b)) for a, b in self.coverage]
        for (k0, s0), (k1, s1) in zip(pts, pts[1:]):
            if k <= k1:
                if k1 == k0:
                    return s1
                return s0 + (s1 - s0) * (k - k0) / (k1 - k0)
        return pts[-1][1]


@dataclasses.dataclass
class TableDecision:
    hot_rows: int                     # ids to install in the hot cache
    predicted_hit: float              # sketch-predicted hit ratio at that H
    hot_ids: np.ndarray               # the ids, hottest first
    moves: Tuple[np.ndarray, np.ndarray]  # (ids, owners) for migrate_rows
    reason: str = ""


@dataclasses.dataclass
class PlacementDecision:
    tables: Dict[str, TableDecision]
    refresh: bool                     # install the hot sets this cycle?
    migrate: bool                     # install the move lists this cycle?
    reason: str = ""


class PlacementPolicy:
    """Sizing + hysteresis rules. Stateless: every method is a pure function
    of its telemetry arguments, so the controller (and the dry-run tool) own
    all bookkeeping.

    - `hot_budget_bytes`: the ONE knob operators must set — per-device
      replicated-cache byte budget (see module doc).
    - `mig_rows`: migration annex scale (capacity is static per table;
      contents rotate freely). The annex costs rows x `row_bytes` per
      shard per table — cheap next to the hot budget, so it is a default,
      not a budget term. `size_mig` adapts the per-table capacity within
      [mig_rows/4, 4*mig_rows] off the measured cold-tail imbalance; the
      flat value is the no-telemetry fallback.
    - `refresh_min_gain`: predicted hit-ratio gain (new top-H coverage minus
      installed-set coverage) a refresh must clear — the hysteresis band
      that stops the controller chasing sketch noise.
    - `refresh_cooldown_steps`: hard floor between refreshes, whatever the
      predicted gain says.
    - `imbalance_target`: migrate only while max/mean `shard_positions`
      exceeds this (1.0 = perfectly flat; the E2E gate accepts <= 1.15).
    """

    def __init__(self, hot_budget_bytes: int, *, mig_rows: int = 64,
                 refresh_min_gain: float = 0.02,
                 refresh_cooldown_steps: int = 50,
                 imbalance_target: float = 1.05,
                 min_hot_rows: int = 0):
        if hot_budget_bytes < 0:
            raise ValueError(f"hot_budget_bytes={hot_budget_bytes} < 0")
        self.hot_budget_bytes = int(hot_budget_bytes)
        self.mig_rows = int(mig_rows)
        self.refresh_min_gain = float(refresh_min_gain)
        self.refresh_cooldown_steps = int(refresh_cooldown_steps)
        self.imbalance_target = float(imbalance_target)
        self.min_hot_rows = int(min_hot_rows)

    # -- auto-sizing ---------------------------------------------------------

    def size_hot(self, tables: Sequence[TableTelemetry]) -> Dict[str, int]:
        """Solve per-table H against the byte budget: greedy
        traffic-per-byte over every table's coverage-curve segments.

        Each segment (k0 -> k1) of a table's curve buys
        `(share(k1) - share(k0)) * total` absolute traffic for
        `(k1 - k0) * row_bytes(dim)` replicated bytes; segments are taken
        best-rate first (a curve's own segments stay in order — coverage is
        concave in practice, and out-of-order picks are impossible anyway
        because a later segment's rate only falls). Partial segments
        allocate proportionally, so small budgets still split sensibly."""
        segs = []  # (rate, table_idx, seg_idx, k0, k1, bytes_per_row)
        for ti, t in enumerate(tables):
            bpr = row_bytes(t.dim, t.slot_cols)
            pts = [(0, 0.0)] + [(int(k), float(s)) for k, s in t.coverage]
            for si, ((k0, s0), (k1, s1)) in enumerate(zip(pts, pts[1:])):
                if k1 <= k0:
                    continue
                gain = max(s1 - s0, 0.0) * max(t.total, 1.0)
                rate = gain / ((k1 - k0) * bpr)
                segs.append((rate, ti, si, k0, k1, bpr))
        segs.sort(key=lambda x: -x[0])
        alloc = {t.name: 0 for t in tables}
        done_upto = {t.name: 0 for t in tables}
        budget = float(self.hot_budget_bytes)
        # multi-pass: a segment can only extend its table's allocated
        # prefix, and float jitter in the rates can order two equal-rate
        # segments against curve order — sweep until a pass allocates
        # nothing so no affordable segment is ever skipped permanently
        progress = True
        while progress and budget > 0:
            progress = False
            for rate, ti, _si, k0, k1, bpr in segs:
                t = tables[ti]
                done = done_upto[t.name]
                if rate <= 0 or budget < bpr or done < k0 or done >= k1:
                    continue
                rows = min(k1 - done, int(budget // bpr))
                if rows <= 0:
                    continue
                alloc[t.name] += rows
                done_upto[t.name] = done + rows
                budget -= rows * bpr
                progress = True
        for t in tables:
            if self.min_hot_rows and t.coverage:
                alloc[t.name] = max(alloc[t.name], self.min_hot_rows)
        return alloc

    def size_mig(self, tables: Sequence[TableTelemetry]) -> Dict[str, int]:
        """Per-table migration annex capacity M off the MEASURED cold-tail
        imbalance (`shard_positions` — the same vector `migration_due`
        gates on).

        The annex is a static shape: every row costs `row_bytes` per shard
        whether used or not, and capacity can only change at a re-jit. A
        flat table wastes the static default; a heavily skewed one starves
        at it. Sizing rule per table: count the sketch heavy hitters homed
        on the hottest shard whose estimated step traffic covers that
        shard's excess over `imbalance_target`, double it (draining the
        head exposes followers the planner also wants to move), clamp to
        [mig_rows/4, 4*mig_rows]. A within-target table gets the floor; a
        table whose tracked mass cannot cover the excess gets the cap (the
        skew lives below the sketch's horizon — give the planner room).
        Tables with no load vector or no sketch data keep the static
        `mig_rows` default."""
        lo = max(self.mig_rows // 4, 1)
        hi = max(self.mig_rows * 4, 1)
        out: Dict[str, int] = {}
        for t in tables:
            if t.shard_positions is None or not t.top_ids:
                out[t.name] = self.mig_rows
                continue
            load = np.asarray(t.shard_positions, np.float64)
            mean = float(load.mean())
            if mean <= 0:
                out[t.name] = self.mig_rows
                continue
            S = int(load.size)
            hot_shard = int(load.argmax())
            excess = float(load[hot_shard]) - self.imbalance_target * mean
            if excess <= 0:
                out[t.name] = lo
                continue
            step_total = float(load.sum())
            total = max(t.total, 1.0)
            need, covered = 0, 0.0
            for i, e in t.top_ids:
                if int(i) % S != hot_shard:
                    continue
                need += 1
                covered += max(float(e), 0.0) / total * step_total
                if covered >= excess:
                    break
            if covered < excess:
                out[t.name] = hi
            else:
                out[t.name] = int(np.clip(2 * need, lo, hi))
        return out

    # -- refresh hysteresis --------------------------------------------------

    @staticmethod
    def churn(installed_ids, top_ids) -> float:
        """Top-K rotation rate: share of the current sketch top-H missing
        from the installed hot set (0 = identical, 1 = fully rotated)."""
        top = [i for i, _ in top_ids]
        if not top:
            return 0.0
        inst = set(int(i) for i in np.asarray(
            installed_ids, np.int64).reshape(-1).tolist())
        missing = sum(1 for i in top if int(i) not in inst)
        return missing / len(top)

    def refresh_due(self, t: TableTelemetry, installed_ids, H: int,
                    steps_since: int) -> Tuple[bool, str, float]:
        """Hysteresis gate for one table -> (due, reason, predicted_gain).
        Predicted gain = coverage of the sketch's CURRENT top-H minus the
        coverage the INSTALLED set still commands (est mass of installed ids
        over the stream total) — i.e. the hit-ratio points a refresh is
        expected to buy. Never fires inside the cooldown."""
        if H <= 0 or not t.top_ids:
            return False, "no hot budget or no sketch data", 0.0
        if steps_since < self.refresh_cooldown_steps:
            return False, f"cooldown ({steps_since} < " \
                f"{self.refresh_cooldown_steps} steps)", 0.0
        inst = set(int(i) for i in np.asarray(
            installed_ids, np.int64).reshape(-1).tolist())
        total = max(t.total, 1.0)
        est = {int(i): float(e) for i, e in t.top_ids}
        cov_installed = sum(est.get(i, 0.0) for i in inst) / total
        cov_new = sum(float(e) for _i, e in t.top_ids[:H]) / total
        gain = cov_new - cov_installed
        if not inst:
            return True, "initial promotion", gain
        if gain >= self.refresh_min_gain:
            return True, (f"predicted hit gain {gain:.3f} >= "
                          f"{self.refresh_min_gain}"), gain
        return False, f"predicted gain {gain:.3f} below threshold", gain

    # -- wire-precision recommendation ---------------------------------------

    # knobs for `recommend_wire` (class-level so skew_report's dry run and
    # the controller agree by construction):
    # a table whose rows are at most this wide ships fp32 — the id lanes
    # dominate its wire bytes and int8's scale lanes would WIDEN dim-1 rows
    wire_fp32_max_dim = 4
    # int8 needs real skew (EF residuals converge on revisited rows) and
    # enough row width to amortize the in-band scale lanes
    wire_int8_min_dim = 8
    wire_int8_min_share = 0.5          # top-`wire_int8_top_k` traffic share
    wire_int8_top_k = 1024

    def recommend_wire(self, tables: Sequence[TableTelemetry]) \
            -> Dict[str, str]:
        """Per-table wire format off the measured coverage curves — the
        precision dimension of the placement budget (feeds
        `MeshTrainer(wire={...})` via the controller, or prints from
        `skew_report --recommend`):

        - dim <= `wire_fp32_max_dim`: "fp32" — tiny rows are id-lane bound,
          quantizing them buys nothing and costs scale lanes;
        - dim >= `wire_int8_min_dim` AND the top-1024 ids carry >=
          `wire_int8_min_share` of traffic: "int8" — wide rows under heavy
          skew are exactly where 4x compression + error feedback holds AUC
          (PERF.md round 13);
        - otherwise "bf16" — the unbiased 2x default for flat-traffic or
          unmeasured tables.
        """
        out: Dict[str, str] = {}
        for t in tables:
            if t.dim <= self.wire_fp32_max_dim:
                out[t.name] = "fp32"
            elif (t.dim >= self.wire_int8_min_dim
                  and t.share_at(self.wire_int8_top_k)
                  >= self.wire_int8_min_share):
                out[t.name] = "int8"
            else:
                out[t.name] = "bf16"
        return out

    # -- dense-wire recommendation -------------------------------------------

    # knobs for `recommend_dense_wire` (class-level for the same reason as
    # the `recommend_wire` set). The sparse_topk codec ships ~5.125 B per
    # transmitted element (int8 value + in-band fp32 block scales + a
    # bitcast int32 index lane) vs int8 dense's ~1.125 B per element, so
    # sparse pays off only below density ~0.22 — the Densifying
    # (arXiv:1905.04035) crossover for this payload shape.
    dense_wire_crossover = 0.22
    # hysteresis band, as fractions of the crossover: enter sparse only
    # well below it, fall back to dense only near it — a density sitting on
    # the boundary must not thrash re-jits
    dense_sparse_enter = 0.6
    dense_sparse_exit = 0.9
    # k headroom over the measured nonzeros per destination row, so a
    # density estimate that wobbles upward does not silently truncate
    dense_topk_margin = 1.5
    # re-jit floor: flipping the dense wire recompiles the step
    dense_wire_cooldown_steps = 200
    # sparse k is padded to the in-band codec's block (ops/wire.INBAND_BLOCK
    # — mirrored here so the policy stays numpy-pure)
    dense_topk_block = 32

    def recommend_dense_wire(self, density: float, current: str = "int8", *,
                             chunk: Optional[int] = None,
                             steps_since: int = 10**9) \
            -> Tuple[str, Optional[int], str]:
        """Dense-gradient wire off the MEASURED gradient density
        (`dense.grad_density` — mean nonzero fraction over the fleet) ->
        (mode, k, reason). `mode` is "sparse_topk" or the dense fallback
        (`current` when already dense, else "int8"); `k` sizes the sparse
        payload (None for dense). Hysteresis: enter sparse below
        `dense_sparse_enter x crossover`, leave above
        `dense_sparse_exit x crossover`, never flip inside the cooldown."""
        dense_mode = current if current != "sparse_topk" else "int8"
        d = float(density)
        if not np.isfinite(d) or d < 0:
            return dense_mode, None, f"density {density!r} unusable"
        enter = self.dense_sparse_enter * self.dense_wire_crossover
        exit_ = self.dense_sparse_exit * self.dense_wire_crossover
        want_sparse = (d <= enter if current != "sparse_topk"
                       else d < exit_)
        target = "sparse_topk" if want_sparse else dense_mode
        if target != current and steps_since < self.dense_wire_cooldown_steps:
            k = None
            if current == "sparse_topk" and chunk:
                k = self._dense_topk(d, int(chunk))
            return current, k, (
                f"cooldown ({steps_since} < "
                f"{self.dense_wire_cooldown_steps} steps)")
        if not want_sparse:
            if current == "sparse_topk":
                return dense_mode, None, (
                    f"density {d:.3f} >= exit {exit_:.3f} "
                    f"(crossover {self.dense_wire_crossover})")
            return dense_mode, None, (
                f"density {d:.3f} above enter {enter:.3f} "
                f"(crossover {self.dense_wire_crossover})")
        k = self._dense_topk(d, int(chunk)) if chunk else None
        side = "<" if current == "sparse_topk" else "<="
        bound = exit_ if current == "sparse_topk" else enter
        return "sparse_topk", k, (
            f"density {d:.3f} {side} {bound:.3f} "
            f"(crossover {self.dense_wire_crossover})")

    def _dense_topk(self, density: float, chunk: int) -> int:
        """Sparse payload size for a measured density: margin over the
        expected nonzeros per destination row, padded to the codec block,
        clamped to the row."""
        if chunk <= 0:
            return 0
        k = int(np.ceil(max(density, 0.0) * chunk * self.dense_topk_margin))
        b = self.dense_topk_block
        k = -(-max(k, 1) // b) * b
        return max(1, min(k, chunk))

    # -- cold-tail migration gate --------------------------------------------

    def migration_due(self, t: TableTelemetry) -> Tuple[bool, str]:
        if t.shard_positions is None:
            return False, "no shard load vector yet"
        load = np.asarray(t.shard_positions, np.float64)
        mean = load.mean()
        if mean <= 0:
            return False, "no measured load"
        imb = float(load.max() / mean)
        if imb > self.imbalance_target:
            return True, (f"imbalance {imb:.3f} > target "
                          f"{self.imbalance_target}")
        return False, f"imbalance {imb:.3f} within target"
