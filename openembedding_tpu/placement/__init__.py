"""Self-driving placement: the control plane that closes the telemetry ->
placement loop (ROADMAP "Self-driving placement").

Rounds 9-10 built the measurement half of Parallax-style hybrid placement
(arXiv:1808.02621): heavy-hitter sketches with coverage curves
(`utils/sketch.py`), per-shard load vectors computed inside the jitted
exchange (`parallel/sharded.exchange_load_stats`), and the mechanisms —
`MeshTrainer(hot_rows=...)` replication plus the round-11 cold-tail
migration directory (`mig_rows=...`). This package removes the operator
from the loop:

- `PlacementPolicy` (policy.py) — pure numpy decision math: sizes each
  table's hot set from its coverage curve against ONE replicated-byte
  budget (budget flows to the most skewed tables), gates refreshes on
  predicted hit-ratio gain with hysteresis + cooldown, and decides when
  the cold tail needs re-sharding.
- `plan_migration` (migration.py) — the balancer: turns measured per-shard
  load vectors + heavy-but-not-hot ids into an explicit id -> owner move
  list that flattens `exchange.shard_imbalance` toward 1.0.
- `PlacementController` (controller.py) — the driver: watches the sketches
  (optionally on a background thread), applies refreshes via
  `MeshTrainer.refresh_hot_rows` and migrations via
  `MeshTrainer.migrate_rows` between steps, and exports `placement.*`
  gauges + flight-recorder events for every decision. `/statusz` renders
  its status; `tools/skew_report.py --recommend` runs the same policy
  dry-run offline from any /metrics scrape.

Everything here runs OFF the hot path; the applied mechanisms are
content-swaps of trace-time-static arrays, so the steady-state jitted step
never recompiles (tests/test_placement.py pins it with `trace_counter`).
"""

from .controller import PlacementController, render_status
from .migration import plan_migration
from .policy import PlacementPolicy, TableTelemetry

__all__ = ["PlacementController", "PlacementPolicy", "TableTelemetry",
           "plan_migration", "render_status"]
