"""Cold-tail migration planning: measured load -> explicit id -> owner moves.

The balancer is deliberately simple and fully observable: take the per-shard
duplicate-weighted load vector the jitted exchange already publishes
(`exchange.shard_positions`), estimate each heavy-but-not-hot id's per-step
load from its sketch estimate, and greedily re-home ids from overloaded
shards onto the currently-lightest shard while that improves the projected
max/mean imbalance. The output is a plain (ids, owners) pair —
`MeshTrainer.migrate_rows` input, also printable by
`tools/skew_report.py --recommend` so an operator can audit every move the
controller would make before enabling it.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np


def plan_migration(shard_positions, candidates: Sequence[Tuple[int, float]],
                   *, num_shards: int, max_moves: int,
                   target: float = 1.05, total: float = 0.0,
                   exclude=None) -> Tuple[np.ndarray, np.ndarray, float]:
    """-> (ids, owners, projected_imbalance).

    `shard_positions`: (S,) measured per-step load by owner shard (already
    reflects any ACTIVE directory — the load vector is computed from the
    routed plan, so re-planning from a migrated steady state is stable).
    `candidates`: [(id, weight)] heaviest-first COLD ids (caller must have
    removed the hot set); weights are sketch estimates, `total` the
    sketch's observed-stream total on the same scale — each id's per-step
    load is priced as its traffic SHARE of the measured load vector.
    `exclude`: ids never to move (e.g. the hot set, belt and braces).

    Greedy: walk candidates hottest-first; move an id off its CURRENT home
    (its `id % S` hash home — ids already re-homed by an active directory
    are re-planned from scratch, since `migrate_rows` installs a full
    directory, not a delta) onto the lightest shard whenever its home is
    above the mean and the move shrinks the home/dest spread. Stops at
    `max_moves` (the annex capacity) or when projected max/mean <= target."""
    S = int(num_shards)
    load = np.asarray(shard_positions, np.float64).copy()
    if load.size != S or load.sum() <= 0 or not candidates:
        imb = float(load.max() / load.mean()) if load.size and \
            load.mean() > 0 else 0.0
        return (np.zeros((0,), np.int64), np.zeros((0,), np.int64), imb)
    excl = set() if exclude is None else \
        set(int(i) for i in np.asarray(exclude, np.int64).reshape(-1))
    # price sketch estimates in per-step load units: an id with traffic
    # share w/total absorbs that share of the measured positions
    wtot = max(float(total), sum(max(w, 0.0) for _i, w in candidates), 1.0)
    step_load = float(load.sum())
    ids_out: List[int] = []
    own_out: List[int] = []
    for cid, w in candidates:
        if len(ids_out) >= int(max_moves):
            break
        if float(load.max()) / float(load.mean()) <= target:
            break
        cid = int(cid)
        if cid < 0 or cid in excl:
            continue
        home = cid % S
        if load[home] <= float(load.mean()):
            continue  # its shard is not the problem
        w_step = min(max(float(w), 0.0) / wtot * step_load,
                     float(load[home]))
        dest = int(np.argmin(load))
        if dest == home or w_step <= 0:
            continue
        if max(load[home] - w_step, load[dest] + w_step) >= load[home]:
            # accept only strictly-improving moves: the home/dest pair's
            # local max must fall, or the id just flips the hot spot
            continue
        load[home] -= w_step
        load[dest] += w_step
        ids_out.append(cid)
        own_out.append(dest)
    imb = float(load.max() / load.mean()) if load.mean() > 0 else 0.0
    return (np.asarray(ids_out, np.int64), np.asarray(own_out, np.int64),
            imb)


def candidate_weights(top_ids: Sequence[Tuple[int, float]],
                      hot_ids) -> List[Tuple[int, float]]:
    """Heavy-but-not-hot candidates: the sketch's top-K minus the installed
    hot set, hottest first — the ids replication did not absorb but whose
    placement still matters."""
    hot = set(int(i) for i in np.asarray(
        hot_ids, np.int64).reshape(-1).tolist()) if hot_ids is not None \
        else set()
    return [(int(i), float(e)) for i, e in top_ids if int(i) not in hot]
