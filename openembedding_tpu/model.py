"""EmbeddingModel + Trainer: the train-step builder.

Counterpart of the reference's `distributed_optimizer` / `distributed_model`
(`tensorflow/exb.py:446-642`). The reference splits one Keras optimizer into (a) the
dense path (Horovod-allreduced Keras apply) and (b) the PS sparse path (translated
config, server-side apply). Here ONE `SparseOptimizer` drives both paths with identical
math: dense params are updated as single-row "tables" (every leaf touched every step, so
per-row beta^t == Keras's global iteration count), and embedding tables via the fused
sparse apply. No fake-grad trick is needed (`exb.py:89-97`): dense grads psum under
pjit/shard_map, sparse grads ride the all-to-all push path.

Batch convention: {"sparse": {var_name: int ids (B,) or (B, F)},
                   "dense":  optional float (B, D),
                   "label":  (B,) or (B, 1)}.

The flax dense module is called as `module.apply({'params': p}, embedded, dense)` where
`embedded` maps var_name -> (B, ..., dim) pulled rows.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from .embedding import (Embedding, EmbeddingSpec, EmbeddingTableState,
                        apply_gradients, combine, init_table_state, lookup,
                        lookup_train)
from .optimizers import Adagrad, SparseOptimizer
from .utils import trace as _trace


def binary_logloss(logits: jax.Array, labels: jax.Array,
                   weight: Optional[jax.Array] = None) -> jax.Array:
    """Mean sigmoid binary cross-entropy (the reference benchmarks train CTR models
    with keras BinaryCrossentropy, `test/benchmark/criteo_deepctr.py`). `weight`
    (per-sample, e.g. 0 for the padded tail rows of a partial batch from
    `data.CriteoBatcher`) turns the mean into a weighted mean."""
    logits = logits.reshape(-1)
    labels = labels.reshape(-1).astype(logits.dtype)
    per = (jnp.clip(logits, 0) - logits * labels +
           jnp.log1p(jnp.exp(-jnp.abs(logits))))
    if weight is None:
        return jnp.mean(per)
    w = weight.reshape(-1).astype(per.dtype)
    return jnp.sum(per * w) / jnp.maximum(jnp.sum(w), 1.0)


# ---------------------------------------------------------------------------
# Dense-path optimizer reuse: every dense leaf is a 1-row table.
# ---------------------------------------------------------------------------

def init_dense_slots(optimizer: SparseOptimizer, params) -> Any:
    return jax.tree_util.tree_map(
        lambda p: optimizer.init_slots(1, p.size, p.dtype), params)


def dense_apply(optimizer: SparseOptimizer, params, slots, grads) -> Tuple[Any, Any]:
    leaves, treedef = jax.tree_util.tree_flatten(params)
    slot_leaves = treedef.flatten_up_to(slots)
    grad_leaves = treedef.flatten_up_to(grads)
    ones = jnp.ones((1,), jnp.int32)
    new_params, new_slots = [], []
    for p, s, g in zip(leaves, slot_leaves, grad_leaves):
        # optimizer math in f32 (see SparseOptimizer.init_slots) even for bf16 params
        nw, ns = optimizer.apply(p.reshape(1, -1).astype(jnp.float32), s,
                                 g.reshape(1, -1).astype(jnp.float32), ones)
        new_params.append(nw.reshape(p.shape).astype(p.dtype))
        new_slots.append(ns)
    return (jax.tree_util.tree_unflatten(treedef, new_params),
            jax.tree_util.tree_unflatten(treedef, new_slots))


# Reserved key inside the `embedded` dict handed to modules that declare
# `takes_ids = True`: maps variable name -> that variable's RAW id batch.
# Lets such modules derive id-level masks (e.g. SASRec's key-padding mask
# from `ids >= 0` / `pair_valid`) instead of heuristics over pulled rows (an
# all-zero embedding row is NOT proof of padding). Opt-in, because the
# documented module contract is "embedded maps variable name -> pulled rows"
# and modules may iterate the dict.
IDS_KEY = "__ids__"


def raw_ids(model: "EmbeddingModel", batch) -> Dict[str, jax.Array]:
    """The {var_name: raw id batch} map published under `embedded[IDS_KEY]`
    (train/eval/init/serving) when the dense module sets `takes_ids`."""
    return {name: jnp.asarray(batch["sparse"][spec.feature_name])
            for name, spec in model.specs.items()}


def attach_ids(embedded: Dict[str, Any], model: "EmbeddingModel",
               batch) -> Dict[str, Any]:
    """Add `embedded[IDS_KEY]` iff the module opted in via `takes_ids`."""
    if getattr(model.module, "takes_ids", False):
        embedded[IDS_KEY] = raw_ids(model, batch)
    return embedded


def sad_rows(table: jax.Array, ids: jax.Array) -> jax.Array:
    """Dense-mirrored ('Cache' mode) table gather through `lookup_rows` — the
    ONE implementation of the invalid-id contract (-1 pads and out-of-range
    ids pull zero rows and train nothing, in value and gradient). A bare
    `jnp.take(table, ids)` would wrap -1 onto the last table row; serving's
    lookups already zero-fill, so anything else here is train/serve skew."""
    from .ops.sparse import lookup_rows
    return lookup_rows(table, ids)


class TrainState(struct.PyTreeNode):
    """All mutable training state as one pytree (shards/donates/checkpoints whole)."""

    step: jax.Array
    dense_params: Any
    dense_slots: Any
    tables: Dict[str, EmbeddingTableState]
    # model_version mirrors the reference's float64 CPU counter used to build serving
    # signs `uuid-floor(version)` (`exb.py:131-138`); incremented 0.1 per step there,
    # +1 per step here with signs taken at save time.
    model_version: jax.Array


class EmbeddingModel:
    """A flax dense module + its embedding variables.

    reference: `distributed_model()` clone-replacing Keras Embedding layers
    (`exb.py:593-642`); here the user declares the embeddings explicitly (idiomatic
    functional style) or uses the models in `openembedding_tpu.models` which do it.
    """

    def __init__(self, module, embeddings: List[Embedding],
                 loss_fn: Callable = binary_logloss,
                 config: Optional[dict] = None):
        # `config` (family + kwargs, set by the `models.make_*` factories) lets a
        # standalone export rebuild the dense module for serving (`export.py`) the way
        # the reference's SavedModel carries its graph (`exb.py:506-547`). None for
        # hand-built modules: export still works, predict() just needs the module
        # passed back in explicitly.
        self.module = module
        self.config = config
        # optional pure fn batch -> batch applied at the top of every
        # train/eval/init path (jit-traceable). The Keras converter uses it to
        # synthesize the concatenated id feature of a SHARED Embedding layer
        # (one table, N call sites — reference `exb.py:593-642` clones such
        # graphs without restriction); None for everything else.
        self.batch_transform = None
        self.specs: Dict[str, EmbeddingSpec] = {}
        for i, e in enumerate(embeddings):
            spec = dataclasses.replace(e.spec, variable_id=i)
            if spec.name in self.specs:
                raise ValueError(f"duplicate embedding name {spec.name!r}")
            if spec.sparse_as_dense and spec.optimizer is not None:
                # sad tables train on the dense path with the Trainer's optimizer
                # (reference parity: 'Cache' vars are plain mirrored tf.Variables,
                # `exb.py:241-248`); honoring a per-variable optimizer there would
                # silently lie, so reject the combination.
                raise ValueError(
                    f"embedding {spec.name!r}: sparse_as_dense tables cannot have a "
                    "per-variable optimizer (they train with the dense optimizer)")
            self.specs[spec.name] = spec
        self.loss_fn = loss_fn

    def sad_specs(self) -> Dict[str, EmbeddingSpec]:
        """sparse_as_dense variables (the reference's 'Cache' mode, `exb.py:241-248`):
        small tables kept as dense mirrored params, trained by the dense path."""
        return {n: s for n, s in self.specs.items() if s.sparse_as_dense}

    def ps_specs(self) -> Dict[str, EmbeddingSpec]:
        return {n: s for n, s in self.specs.items() if not s.sparse_as_dense}

    def dim_groups(self) -> List[List[str]]:
        """PS-table names grouped by embedding dim (declaration order): the
        unit of the fused multi-table exchange. A dim-group's tables share one
        set of 3 all_to_alls per train step (`parallel/sharded.grouped_*`), so
        a T-table model with G groups launches 3*G collectives, not 3*T.
        Static per model — built once and cached."""
        if getattr(self, "_dim_groups", None) is None:
            groups: Dict[int, List[str]] = {}
            for name, spec in self.ps_specs().items():
                groups.setdefault(spec.output_dim, []).append(name)
            self._dim_groups = list(groups.values())
        return self._dim_groups


class Trainer:
    """Builds jitted train/eval steps for an EmbeddingModel on one device.

    The multi-device version (mesh / shard_map, DP dense + row-sharded tables) is
    `parallel.MeshTrainer`, which reuses these per-device step functions.
    """

    num_shards = 1  # MeshTrainer overrides with the mesh size

    def __init__(self, model: EmbeddingModel,
                 optimizer: Optional[SparseOptimizer] = None, seed: int = 0,
                 *, offload_pipeline: bool = False, offload_densify: int = 1,
                 offload_stage_depth: int = 1,
                 sentinel: bool = False, halt_on_nonfinite: bool = False,
                 measure_every: int = 0):
        self.model = model
        self.optimizer = optimizer or Adagrad()
        self.seed = seed
        # numerics sentinel: adds additive health stats to the step's stats
        # dict (per-table grad sumsq / non-finite counts, loss finiteness, ef
        # residual magnitude, int8/bf16 quantization error), folded into
        # `health.*` gauges by `metrics.record_step_stats`. A static Python
        # bool, so sentinel=False traces byte-identical HLO to before.
        # halt_on_nonfinite implies sentinel and makes
        # `Trainer.record_step_stats` raise NonFiniteError naming the
        # offending table/phase.
        self.halt_on_nonfinite = bool(halt_on_nonfinite)
        self.sentinel = bool(sentinel) or self.halt_on_nonfinite
        # sampled measured step timing (utils/stepwatch.StepWatch): sample one
        # call in N with a block_until_ready bracket into `trainer.step_ms`
        # plus HLO-byte attribution and `exchange.cost_drift`; 0 = off
        self.measure_every = int(measure_every)
        self._stepwatch = None
        # host_cached pipeline knobs (tables/host_offload.py): pipeline=True
        # double-buffers the next batch's host lookup + admit upload on a
        # background thread (drive it via `offload_stage`); densify K>1
        # accumulates evict/flush writebacks and merges once per K batches;
        # stage_depth D>1 turns the single staging slot into a ring so the
        # loop can run the host lookup up to D batches ahead
        self.offload_pipeline = bool(offload_pipeline)
        self.offload_densify = int(offload_densify)
        self.offload_stage_depth = int(offload_stage_depth)
        # storage="host_cached" variables (tables/host_offload.py), filled by
        # init_tables; empty when every table lives fully in HBM
        self.offload: Dict[str, Any] = {}
        # heavy-hitter skew telemetry (utils/sketch.py), opt-in via
        # enable_skew_monitor(): per-table id batches feed the global
        # Space-Saving sketches off the hot path
        self._skew = None

    def enable_skew_monitor(self, monitor=None):
        """Feed every trained batch's ids (per table) into the heavy-hitter
        sketches (`utils/sketch.MONITOR` unless one is given). The feed is a
        bounded-queue put per table per batch — batches are DROPPED (and
        counted in `skew.dropped_batches`) when the sketch worker falls
        behind, so it can never slow the loop it measures."""
        from .utils import sketch
        self._skew = monitor if monitor is not None else sketch.MONITOR
        return self._skew

    def record_batch_skew(self, batch) -> None:
        """Enqueue one batch's per-table ids into the skew monitor (no-op
        until `enable_skew_monitor()`). Called by `offload_prepare`, so the
        example loops get it for free; scan windows pass stacked batches
        (the sketch flattens)."""
        if self._skew is None:
            return
        if self.model.batch_transform is not None:
            batch = self.model.batch_transform(batch)
        sparse = batch.get("sparse") or {}
        for name, spec in self.model.ps_specs().items():
            ids = sparse.get(spec.feature_name)
            if ids is not None:
                self._skew.observe(name, ids)

    # -- checkpointing (reference: model.save/save_weights/load_weights wiring,
    #    `exb.py:550-583`) -------------------------------------------------------
    def _stage_save(self, write_fn, path: str):
        """Remote-URI checkpoints write locally then push through the URI's
        filesystem adapter (`utils/fs.py` — the reference's HDFS dump via
        hadoop pipes, `EmbeddingShardFile.h`). Each process pushes only the
        files it wrote, so multi-host uploads compose."""
        from .utils import fs as fsmod
        if not fsmod.is_remote(path):
            return write_fn(path)
        import shutil
        import tempfile
        local = tempfile.mkdtemp(prefix="oetpu_ckpt_out_")
        try:
            meta = write_fn(local)
            fsmod.stage_out(local, path)
            return meta
        finally:
            shutil.rmtree(local, ignore_errors=True)

    def _stage_load(self, read_fn, path: str):
        from .utils import fs as fsmod
        with fsmod.staged(path) as local:
            return read_fn(local)

    def save(self, state: "TrainState", path: str, **kw):
        from .checkpoint import save_server_model
        return self._stage_save(
            lambda p: save_server_model(
                state, self.model, p, num_shards=self.num_shards,
                offload_stores=self.offload_store_snapshots(state), **kw),
            path)

    def load(self, state: "TrainState", path: str):
        """Dispatches on the checkpoint layout: single-file (this class's save)
        or per-shard streaming (`MeshTrainer.save` / `parallel/checkpoint.py`) —
        either loads at any target mesh size. Remote URIs stage to local disk
        first (the loaders are random-access/memmap'd)."""
        def read(p):
            from .parallel.checkpoint import checkpoint_layout, load_sharded
            if checkpoint_layout(p) == "sharded":
                return load_sharded(state, self.model, p,
                                    num_shards=self.num_shards,
                                    offload=self.offload)
            from .checkpoint import load_server_model
            return load_server_model(state, self.model, p,
                                     num_shards=self.num_shards,
                                     offload=self.offload)

        return self._stage_load(read, path)

    # -- host offload drivers (storage="host_cached" variables) ---------------
    #
    # The reference picks the PMem-backed table per variable at init
    # (`EmbeddingInitOperator.cpp:146-168`) and its cache admission rides pull
    # requests server-side; here ids are known host-side from the input
    # pipeline, so the Trainer drives the cache around the jitted step:
    #
    #     state = trainer.offload_prepare(state, batch)   # admit/flush
    #     state, metrics = step(state, batch)             # pure device step
    #
    # For scan-fused multi-step driving (`jit_train_many`), pass the stacked
    # batches: the union of the K batches' ids is admitted up front.

    def offload_prepare(self, state: "TrainState", batch) -> "TrainState":
        """Admit the batch's ids into each host-cached table's device cache
        (flushing first if the cache would exceed its high-water mark) and
        return the state with the refreshed cache tables. No-op without
        host-cached variables. Also the per-batch host-side hook the skew
        monitor rides (`record_batch_skew` — no-op unless enabled)."""
        self.record_batch_skew(batch)
        if not self.offload:
            return state
        if self.model.batch_transform is not None:
            batch = self.model.batch_transform(batch)
        new_tables = dict(state.tables)
        for name, ot in self.offload.items():
            ot.adopt(state.tables[name])
            ot.prepare(batch["sparse"][self.model.specs[name].feature_name])
            new_tables[name] = ot.state
        self._offload_prepared = True  # train_many's trace-time guard
        return state.replace(tables=new_tables)

    def offload_stage(self, batch) -> None:
        """Kick off the background host lookup + upload for a FUTURE batch
        while the device is busy with the current step (no-op unless the
        trainer was built with offload_pipeline=True). Pipelined loop shape:

            trainer.offload_stage(batches[0])
            for i, batch in enumerate(batches):
                state = trainer.offload_prepare(state, batch)  # consumes stage
                if i + 1 < len(batches):
                    trainer.offload_stage(batches[i + 1])      # overlaps step
                state, m = step(state, batch)

        With offload_stage_depth=D > 1 the stage slot is a ring: call this up
        to D batches ahead (`trainer.offload_stage(batches[i + d])` for
        d = 1..D) and each `offload_prepare` consumes the oldest matching
        entry, so D host lookups run under D device steps.

        Staging is a hint: `offload_prepare` verifies the staged ids match and
        falls back to the synchronous path when they don't."""
        if not self.offload:
            return
        if self.model.batch_transform is not None:
            batch = self.model.batch_transform(batch)
        for name, ot in self.offload.items():
            ot.stage(batch["sparse"][self.model.specs[name].feature_name])

    def offload_flush(self, state: "TrainState") -> "TrainState":
        """Write every resident row back to the host store and reset the
        caches (end of training / before handing tables elsewhere)."""
        if not self.offload:
            return state
        new_tables = dict(state.tables)
        for name, ot in self.offload.items():
            ot.adopt(state.tables[name])
            ot.flush()
            new_tables[name] = ot.state
        return state.replace(tables=new_tables)

    # hot-row replication is a mesh concept (MeshTrainer(hot_rows=...));
    # the base hooks are identities so persisters/loops drive either trainer
    # uniformly (see parallel/sharded.py "HOT-ROW REPLICATION")
    hot_enabled = False

    def hot_sync(self, state: "TrainState") -> "TrainState":
        """Write replicated hot rows back into their owner shards before any
        external consumer reads raw table state. No-op off-mesh; MeshTrainer
        overrides (the persisters call it before every snapshot/delta so
        on-disk artifacts stay byte-identical to a hot-off run)."""
        return state

    def externalize(self, state: "TrainState") -> "TrainState":
        """Return the state in its CANONICAL external layout: hot/migrated
        rows written home (`hot_sync`) and — under MeshTrainer(dense_shard=
        True) — the flat sharded dense optimizer state unsharded back to the
        per-leaf baseline form. Checkpoint/persist/export writers go through
        this hook, which is what keeps their artifacts byte-identical to a
        placement-off, ZeRO-off run. The returned state is for EXTERNAL
        readers; keep training on the original."""
        return self.hot_sync(state)

    @staticmethod
    def overflow_count(metrics) -> int:
        """Exchange-bucket drops in a step's (or scan window's) metrics.
        Single-device tables have no bounded buckets — always 0 here;
        MeshTrainer overrides with the real counter read, so training loops
        can call the governance hooks on either trainer."""
        del metrics
        return 0

    def check_overflow(self, metrics, **kw) -> bool:
        """Overflow-policy hook (no-op off-mesh; see MeshTrainer)."""
        del metrics, kw
        return False

    def table_overflow(self, state: "TrainState", name: str) -> int:
        """Lifetime dropped-id count for one table — includes overflow banked
        across host-offload cache resets (the device counter alone restarts at
        0 on every flush)."""
        ts = state.tables.get(name)
        dev = int(ts.overflow) if ts is not None and ts.overflow is not None \
            else 0
        if name in self.offload:
            return self.offload[name]._overflow_flushed + dev
        return dev

    def offload_store_snapshots(self, state: Optional["TrainState"] = None):
        """{name: HostStore snapshot} with all resident rows written back —
        what the checkpoint writers serialize for host-cached variables.
        Empty dict when nothing is offloaded."""
        out = {}
        for name, ot in self.offload.items():
            if state is not None:
                ot.adopt(state.tables[name])
            ot.sync_to_store()
            out[name] = ot.store.snapshot()
        return out

    def opt_for(self, spec: EmbeddingSpec) -> SparseOptimizer:
        return spec.optimizer or self.optimizer

    def _loss(self, logits, batch):
        """Pass the per-sample weight through when the batch carries one (padded
        tail batches from `data.CriteoBatcher`); loss fns without a weight arg
        keep working for weightless batches."""
        w = batch.get("weight")
        if w is None:
            return self.model.loss_fn(logits, batch["label"])
        return self.model.loss_fn(logits, batch["label"], jnp.asarray(w))

    # -- init ---------------------------------------------------------------

    def init(self, sample_batch: Dict[str, Any]) -> TrainState:
        # the one warning jit can't emit: int64 ids under x64-off silently
        # truncate at the device boundary (hi lane lost) — the pair layout
        # (`ops/id64.py`, `synthetic_criteo(ids_dtype='pair')`) is the fix
        if not jax.config.jax_enable_x64:
            import numpy as _np
            for name, spec in self.model.ps_specs().items():
                if not spec.use_hash_table:
                    continue
                ids = _np.asarray(sample_batch["sparse"][spec.feature_name])
                if ids.dtype == _np.int64 and (ids >= (1 << 31)).any():
                    import warnings
                    warnings.warn(
                        f"embedding {name!r}: int64 ids >= 2^31 with "
                        "jax_enable_x64 off TRUNCATE to int32 on device "
                        "(ids congruent mod 2^32 collide). Feed the split-"
                        "pair layout instead (ops/id64.np_split_ids or "
                        "ids_dtype='pair').", UserWarning)
        key = jax.random.PRNGKey(self.seed)
        if self.model.batch_transform is not None:
            sample_batch = self.model.batch_transform(sample_batch)
        embedded = self._fake_embedded(sample_batch)
        dense_inputs = sample_batch.get("dense")
        variables = self.module_init(key, embedded, dense_inputs)
        params = variables["params"]
        # sparse_as_dense tables live inside dense params under a reserved scope
        sad = {}
        for name, spec in self.model.sad_specs().items():
            k = jax.random.fold_in(key, 7919 + spec.variable_id)
            sad[name] = spec.initializer(k, (spec.input_dim, spec.output_dim),
                                         spec.dtype)
        if sad:
            params = dict(params)
            params["__embeddings__"] = sad
        # optimizer slots only for the TRAINABLE subtree: modules carrying
        # frozen state (Keras BatchNorm stats, seed-generator counters) split
        # it out — those leaves update from the forward pass, never the
        # optimizer, and integer leaves cannot take optimizer math anyway
        split = getattr(self.model.module, "split_params", None)
        slots_over = split(params)[0] if split is not None else params
        tables = self.init_tables()
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            dense_params=params,
            dense_slots=init_dense_slots(self.optimizer, slots_over),
            tables=tables,
            model_version=jnp.zeros((), jnp.int32),
        )

    def _check_num_shards(self) -> None:
        """`EmbeddingSpec.num_shards` exists for reference API parity
        (`exb.py:388-419`: rows spread over N PS processes, placement round-
        robined in `WorkerContext.cpp:66-85`). Under SPMD there are no server
        processes to place onto — every table shards over the WHOLE mesh, which
        strictly dominates sub-mesh placement on TPU (the all_to_all spans all
        ICI links either way; fewer shards would only idle devices). A value
        other than -1/mesh-size is therefore NOT honored, and silence would be
        a lying knob — say so loudly."""
        for name, spec in self.model.ps_specs().items():
            if spec.num_shards not in (-1, self.num_shards):
                import warnings
                warnings.warn(
                    f"embedding {name!r}: num_shards={spec.num_shards} is not "
                    f"honored — tables always shard over the whole mesh "
                    f"({self.num_shards} device(s)) under SPMD; see "
                    "PARITY.md 'num_shards'", UserWarning)

    def init_tables(self) -> Dict[str, EmbeddingTableState]:
        """Hook: single-device tables. MeshTrainer overrides to create the tables
        directly sharded (a huge table must never materialize on one device)."""
        self._check_num_shards()
        tables = {}
        for name, spec in self.model.ps_specs().items():
            if spec.storage == "host_cached":
                from .tables.host_offload import HostOffloadTable
                ot = HostOffloadTable(spec, self.opt_for(spec), seed=self.seed,
                                      pipeline=self.offload_pipeline,
                                      densify_k=self.offload_densify,
                                      stage_depth=self.offload_stage_depth)
                self.offload[name] = ot
                tables[name] = ot.state
            else:
                tables[name] = init_table_state(spec, self.opt_for(spec),
                                                seed=self.seed)
        return tables

    def module_init(self, key, embedded, dense_inputs):
        return self.model.module.init(key, embedded, dense_inputs)

    def _fake_embedded(self, batch):
        from .ops.id64 import is_pair
        out = {}
        for name, spec in self.model.specs.items():
            ids = jnp.asarray(batch["sparse"][spec.feature_name])
            shape = (ids.shape[:-1] if spec.use_hash_table and is_pair(ids)
                     else ids.shape)
            if spec.combiner:  # pooling collapses the trailing field axis
                shape = shape[:-1]
            out[name] = jnp.zeros(shape + (spec.output_dim,), spec.dtype)
        attach_ids(out, self.model, batch)
        return out

    # -- the per-device step (pure; shard_map-able) -------------------------

    # oelint: hot-path device_get=0 (the traced step: zero host syncs; the
    # ONE allowed per-step device_get lives in metrics.record_step_stats)
    def train_step(self, state: TrainState, batch, *,
                   packed=None) -> Tuple[TrainState, Dict]:
        """One synchronous step: pull -> fwd/bwd -> dense apply + sparse apply.

        The reference needs a 4-RPC protocol with batch-version gating for this
        (`EmbeddingPullOperator`/`Push`/`Store` + `exb_barrier`); under SPMD the whole
        step is one XLA program and is synchronous by construction.

        `packed`: {name: column layout} for tables whose state currently holds
        the packed weights+slots array (only inside `train_many`'s scan; see
        `ops/sparse.packed_layout`).

        The step phases carry `trainer.{pull,compute,apply}` spans
        (`utils/trace.py` -> `oetpu_trainer_*_ms` histograms). Under jit they
        fire at TRACE time — once per compile, measuring how long each phase
        takes to trace/build, not per-step device time (per-step wall time is
        the CALLER's span around the jitted fn, e.g. `vtimer("train",
        "step")`). Run the step eagerly (no jit) and the same spans measure
        real per-phase execution.
        """
        model = self.model
        if model.batch_transform is not None:
            batch = model.batch_transform(batch)
        ps_specs = model.ps_specs()
        sad_specs = model.sad_specs()
        packed = packed or {}
        # modules with frozen (non-trainable) state: differentiate only the
        # trainable subtree, thread the frozen one through as a constant, and
        # take its NEW values from the training forward pass (Keras BatchNorm
        # moving stats / seed counters; reference graphs train them the same
        # way inside `distributed_model()`, `exb.py:593-642`)
        split = getattr(model.module, "split_params", None)
        train_apply = getattr(model.module, "apply_train", None)
        if split is not None:
            tr0, fr0 = split(state.dense_params)
        else:
            tr0, fr0 = state.dense_params, None

        # PULL: gather rows for this batch (non-differentiated w.r.t. the table — the
        # rows themselves are the leaf, exactly the reference's pull/push contract).
        # Hash tables insert unseen ids here, so pull threads the table state.
        # MeshTrainer overrides tables_pull/tables_apply with the fused
        # multi-table exchange (3 all_to_alls per dim-group, not per table).
        with _trace.span("trainer", "pull"):
            pulled_tables, pulled, stats, pull_plans = self.tables_pull(
                state.tables, batch, ps_specs, packed)

        return self._train_step_tail(state, batch, ps_specs, sad_specs,
                                     packed, tr0, fr0, pulled_tables, pulled,
                                     stats, pull_plans)

    def _train_step_tail(self, state, batch, ps_specs, sad_specs, packed,
                         tr0, fr0, pulled_tables, pulled, stats, pull_plans):
        """The post-pull remainder of `train_step` — fwd/bwd + dense apply +
        sparse apply — factored out so the software-pipelined
        `MeshTrainer.train_many` can feed it a pull PREFETCHED one scan
        iteration earlier (parallel/trainer.py). The serial path calls it
        straight after its own pull: pure code motion (the getattr re-lookups
        below trace no equations), so pipeline-off HLO stays byte-identical.
        `batch` is the already-transformed batch."""
        model = self.model
        split = getattr(model.module, "split_params", None)
        train_apply = getattr(model.module, "apply_train", None)

        def loss_fn(tr_params, pulled_rows):
            dense_params = (model.module.merge_params(tr_params, fr0)
                            if split is not None else tr_params)
            # combiner pooling happens INSIDE the differentiated function so
            # autodiff hands table_apply per-slot (B, F, dim) grads that line
            # up with the (B, F) id array; the mask multiply zeroes pad-slot
            # grads (see embedding.combine)
            embedded = {
                name: combine(ps_specs[name],
                              jnp.asarray(batch["sparse"][
                                  ps_specs[name].feature_name]), rows)
                for name, rows in pulled_rows.items()}
            for name, spec in sad_specs.items():
                table = dense_params["__embeddings__"][name]
                ids = jnp.asarray(batch["sparse"][spec.feature_name])
                embedded[name] = combine(spec, ids, sad_rows(table, ids))
            attach_ids(embedded, model, batch)
            if train_apply is not None:
                logits, fr_new = train_apply({"params": dense_params},
                                             embedded, batch.get("dense"))
            else:
                logits = model.module.apply({"params": dense_params},
                                            embedded, batch.get("dense"))
                fr_new = None
            return self._loss(logits, batch), (logits, fr_new)

        with _trace.span("trainer", "compute"):
            (loss, (logits, fr_new)), (dense_grads, row_grads) = \
                jax.value_and_grad(loss_fn, argnums=(0, 1), has_aux=True)(
                    tr0, pulled)

            # sentinel reads the PRE-reduction dense grads: per-shard local
            # sumsq psums (via reduce_metrics) to one well-defined global
            # quantity in both the allreduce and the ZeRO (unreduced-here)
            # paths
            raw_dense_grads = dense_grads if self.sentinel else None
            stats.update(self.dense_grad_stats(dense_grads))
            dense_grads = self.reduce_dense_grads(dense_grads)

        with _trace.span("trainer", "apply"):
            # DENSE apply (reference: Keras optimizer after Horovod allreduce;
            # MeshTrainer(dense_shard=True) overrides with the ZeRO-sharded
            # reduce_scatter -> chunk update -> all_gather path)
            new_params, new_slots = self.dense_update(
                tr0, state.dense_slots, dense_grads)
            if split is not None:
                fr = fr_new if fr_new is not None else fr0
                new_params = model.module.merge_params(
                    new_params, self.reduce_module_state(fr))

            # SPARSE push+update (reference: PushGradients + UpdateWeights
            # store op)
            new_tables = dict(state.tables)
            applied, push_stats = self.tables_apply(
                ps_specs, pulled_tables, batch, row_grads, packed, pull_plans)
            new_tables.update(applied)
            stats.update(push_stats)
            if self.sentinel:
                stats.update(self._sentinel_stats(
                    loss, raw_dense_grads, row_grads, new_tables))

        new_state = TrainState(
            step=state.step + 1,
            dense_params=new_params,
            dense_slots=new_slots,
            tables=new_tables,
            model_version=state.model_version + 1,
        )
        metrics = self.reduce_metrics({"loss": loss, "logits": logits,
                                       "stats": stats})
        return new_state, metrics

    # hooks overridden by MeshTrainer:
    def tables_pull(self, tables, batch, ps_specs, packed):
        """Pull every PS table's rows for this batch. Default: one pull per
        table. MeshTrainer overrides with the fused dim-group exchange.
        -> ({name: new_table}, {name: rows}, {stat: v}, {name: plan})."""
        pulled_tables, pulled, stats, plans = {}, {}, {}, {}
        for name, spec in ps_specs.items():
            ids = jnp.asarray(batch["sparse"][spec.feature_name])
            pull = self._packed_pull if name in packed else self.table_pull
            pulled_tables[name], pulled[name], pull_stats, plans[name] = \
                pull(spec, tables[name], ids)
            for k, v in pull_stats.items():
                stats[f"{name}/{k}"] = v
        return pulled_tables, pulled, stats, plans

    def tables_apply(self, ps_specs, pulled_tables, batch, row_grads, packed,
                     plans):
        """Push + fused update for every PS table. Default: one push per
        table. MeshTrainer overrides with the fused dim-group exchange.
        -> ({name: new_table}, {stat: v})."""
        new_tables, stats = {}, {}
        for name, spec in ps_specs.items():
            ids = jnp.asarray(batch["sparse"][spec.feature_name])
            if name in packed:
                new_tables[name], push_stats = self._packed_apply(
                    spec, pulled_tables[name], ids, row_grads[name],
                    packed[name], plans[name])
            else:
                new_tables[name], push_stats = self.table_apply(
                    spec, pulled_tables[name], ids, row_grads[name],
                    plans[name])
            for k, v in push_stats.items():
                stats[f"{name}/{k}"] = v
        return new_tables, stats

    def reduce_dense_grads(self, grads):
        return grads

    def dense_grad_stats(self, grads):
        """Stats read off the PRE-reduction dense grads (they ride the
        step's per-key stats psum like everything else in `stats`).
        Default: none. MeshTrainer(dense_stats=True) publishes the
        `dense/grad_density` nonzero fraction the sparse dense-wire policy
        prices against."""
        del grads
        return {}

    def dense_update(self, params, slots, grads):
        """Apply the dense optimizer update. `grads` arrive already reduced
        by `reduce_dense_grads`. MeshTrainer(dense_shard=True) overrides with
        the ZeRO-sharded update (parallel/zero.py)."""
        return dense_apply(self.optimizer, params, slots, grads)

    def reduce_module_state(self, fr):
        """Frozen-state updates from the training forward pass. On meshes the
        float leaves (BatchNorm moving stats computed from LOCAL batch
        statistics — same per-replica behavior the reference's Horovod DP
        has) pmean to one replicated value; integer leaves (seed counters,
        identical on every shard) pass through."""
        return fr

    def reduce_metrics(self, metrics):
        return metrics

    # oelint: hot-path device_get=0 (pure traced math appended to the step's
    # stats dict — the ONE host sync still happens in record_step_stats)
    def _sentinel_stats(self, loss, dense_grads, row_grads,
                        tables) -> Dict[str, jax.Array]:
        """Numerics-sentinel stats for this shard, every value ADDITIVE so
        `MeshTrainer.reduce_metrics`'s per-key psum yields the global figure:
        sumsq (host takes sqrt after the psum), non-finite element counts, ef
        abs-sum + element counts, and the wire-quantization error sumsq
        (fp32-vs-roundtrip through `ops.wire.pack_inband`, skipped when the
        exchange ships fp32 or there is no exchange at all)."""
        f32 = jnp.float32
        out: Dict[str, jax.Array] = {}
        loss_arr = jnp.asarray(loss, f32)
        out["health/loss_nonfinite"] = jnp.sum(
            ~jnp.isfinite(loss_arr)).astype(f32)
        sumsq = jnp.zeros((), f32)
        nonfin = jnp.zeros((), f32)
        for leaf in jax.tree_util.tree_leaves(dense_grads):
            g = jnp.asarray(leaf, f32)
            sumsq = sumsq + jnp.sum(jnp.square(g))
            nonfin = nonfin + jnp.sum(~jnp.isfinite(g)).astype(f32)
        out["health/dense_grad_sumsq"] = sumsq
        out["health/dense_grad_nonfinite"] = nonfin
        fmt = None
        if self.num_shards > 1:
            from .ops.wire import wire_format
            fmt = wire_format(getattr(self, "wire", None))
            if fmt == "fp32":
                fmt = None
        for name, g in (row_grads or {}).items():
            g = jnp.asarray(g, f32)
            out[f"{name}/grad_sumsq"] = jnp.sum(jnp.square(g))
            out[f"{name}/grad_nonfinite"] = jnp.sum(
                ~jnp.isfinite(g)).astype(f32)
            if fmt is not None and g.ndim >= 2 and g.shape[-1] > 0:
                from .ops.wire import pack_inband, unpack_inband
                rows = g.reshape(-1, g.shape[-1])
                back = unpack_inband(pack_inband(rows, fmt),
                                     rows.shape[-1], fmt)
                out[f"{name}/quant_err_sumsq"] = jnp.sum(
                    jnp.square(back - rows))
        for name, ts in tables.items():
            ef = getattr(ts, "ef", None)
            if ef is None:
                continue
            out[f"{name}/ef_abs_sum"] = jnp.sum(jnp.abs(jnp.asarray(ef, f32)))
            # a trace-time constant, shipped as a stat so the host-side mean
            # divides by the GLOBAL (psum'd) element count
            out[f"{name}/ef_elems"] = jnp.asarray(float(ef.size), f32)
        return out

    def record_step_stats(self, step_metrics):
        """Fold one step's metrics through the spine
        (`metrics.record_step_stats` — the single allowed per-step
        device_get) and, with `halt_on_nonfinite=True`, raise
        `metrics.NonFiniteError` naming the offending table/phase when the
        sentinel saw a non-finite loss or gradient. Returns the health
        summary dict."""
        from .utils import metrics as _metrics
        stats = step_metrics
        if isinstance(step_metrics, dict) and "stats" in step_metrics:
            stats = step_metrics["stats"]
        health = _metrics.record_step_stats(stats)
        if self.halt_on_nonfinite and health.get("nonfinite"):
            from .utils import capsule as _capsule
            _capsule.trigger("nonfinite", offenders=health["nonfinite"])
            raise _metrics.NonFiniteError(health["nonfinite"])
        return health

    def _ensure_stepwatch(self):
        """The (lazily created, cached) StepWatch for this trainer — shared
        by the measured step wrapper and the input-wait lane so step samples
        and input waits land under one label with one counter/baseline.
        None when measurement is off (`measure_every` <= 0)."""
        if self.measure_every <= 0:
            return None
        if self._stepwatch is None:
            from .utils.stepwatch import StepWatch
            self._stepwatch = StepWatch(
                every=self.measure_every,
                wire_cost=lambda: getattr(self, "last_wire_cost", None))
        return self._stepwatch

    def _wrap_measured(self, fn):
        """Wrap a jitted step with the sampled measurement mode
        (`measure_every` > 0): one call in N is bracketed host-side with
        `block_until_ready` into `trainer.step_ms` + HLO-byte attribution +
        `exchange.cost_drift`. The watch is cached so repeated
        `jit_train_step()` calls share one sample counter/baseline."""
        watch = self._ensure_stepwatch()
        return fn if watch is None else watch.wrap(fn)

    def input_timed(self, batches):
        """Wrap a batch iterator (typically a `data.ingest.FeedRing`) so the
        time the train loop blocks on each `next()` lands in the
        `trainer.input_wait_ms` histogram — the measured input-wait
        attribution lane (`data.ingest.input_wait_share` folds it against
        step time). Records through this trainer's StepWatch when
        measurement is on, straight into the spine otherwise:

            for batch in trainer.input_timed(ring):
                state, m = step(state, batch)
        """
        from .utils.stepwatch import timed_batches
        return timed_batches(batches, self._ensure_stepwatch())

    def table_pull(self, spec, table, ids):
        """-> (new_table, rows, stats, plan). The plan (routing/dedup state) is handed
        back to table_apply so push reuses pull's work; None on single device."""
        table, rows = lookup_train(spec, table, ids)
        return table, rows, {}, None

    def table_apply(self, spec, table, ids, grads, plan=None):
        """-> (new_table, stats)."""
        return apply_gradients(spec, table, self.opt_for(spec), ids, grads), {}

    def table_lookup(self, spec, table, ids):
        return lookup(spec, table, ids)

    def eval_step(self, state: TrainState, batch) -> Dict:
        model = self.model
        if model.batch_transform is not None:
            batch = model.batch_transform(batch)
        embedded = {
            name: combine(
                spec, jnp.asarray(batch["sparse"][spec.feature_name]),
                self.table_lookup(spec, state.tables[name],
                                  jnp.asarray(batch["sparse"][spec.feature_name])))
            for name, spec in model.ps_specs().items()
        }
        for name, spec in model.sad_specs().items():
            table = state.dense_params["__embeddings__"][name]
            ids = jnp.asarray(batch["sparse"][spec.feature_name])
            embedded[name] = combine(spec, ids, sad_rows(table, ids))
        attach_ids(embedded, model, batch)
        logits = model.module.apply({"params": state.dense_params}, embedded,
                                    batch.get("dense"))
        return {"logits": logits, "loss": self._loss(logits, batch)}

    # -- jitted drivers ------------------------------------------------------

    def jit_train_step(self):
        """NOTE: the input TrainState is DONATED (huge tables must update in place,
        not 2x HBM) — always rebind: `state, metrics = step(state, batch)`; a stale
        `state` reference is dead after the call."""
        return self._wrap_measured(jax.jit(self.train_step,
                                           donate_argnums=(0,)))

    def _packed_layouts(self, state: TrainState):
        """{name: column layout} for tables worth packing inside the scan
        (see `ops/sparse.packed_layout`). Applies per shard under MeshTrainer
        too — its `_packed_pull`/`_packed_apply` hooks route through the
        packed-aware sharded protocol (parallel/sharded.py)."""
        from .ops.sparse import packed_layout
        out = {}
        for name, spec in self.model.ps_specs().items():
            ts = state.tables[name]
            lay = packed_layout(spec.output_dim, ts.slots, ts.weights.dtype)
            if lay is not None:
                out[name] = lay
        return out

    def _packed_pull(self, spec, table, ids):
        """Pull from the packed layout: gather full packed rows (the gather is
        latency-bound, the extra slot bytes ride free) and slice the weight
        columns. Hash tables keep their normal probe/insert (keys are a
        separate array either way)."""
        from .embedding import _flat_ids
        from .ops.sparse import lookup_rows
        flat, out_shape = _flat_ids(spec, ids)
        if spec.use_hash_table:
            from .tables.hash_table import hash_lookup_train
            table, rows = hash_lookup_train(table, flat,
                                            out_dim=spec.output_dim)
        else:
            rows = lookup_rows(table.weights, flat)[:, :spec.output_dim]
        rows = rows.astype(spec.dtype).reshape(out_shape + (spec.output_dim,))
        return table, rows, {}, None

    def _packed_apply(self, spec, table, ids, grads, layout, plan=None):
        from .embedding import _flat_ids
        from .ops.sparse import sparse_apply_packed_table
        flat_ids, _ = _flat_ids(spec, ids)
        flat_grads = grads.reshape(-1, spec.output_dim)
        if spec.use_hash_table:
            from .tables.hash_table import hash_apply_gradients_packed
            return hash_apply_gradients_packed(
                table, self.opt_for(spec), flat_ids, flat_grads, layout,
                spec.output_dim), {}
        packed = sparse_apply_packed_table(
            self.opt_for(spec), table.weights, layout, spec.output_dim,
            flat_ids, flat_grads)
        return table.replace(weights=packed), {}

    def train_many(self, state: TrainState, batches) -> Tuple[TrainState, Dict]:
        """K steps in ONE compiled program via lax.scan over stacked batches
        (every leaf has a leading K dim). One dispatch per K steps instead of K —
        host dispatch latency (worst over remote runtimes) amortizes away, the
        TPU-idiomatic step-fusion the reference cannot do (its step spans 4 RPCs).
        Returns (state, {"loss": (K,)}).

        Packable array tables run the scan on the PACKED weights+slots layout
        (one latency-bound gather/scatter pair per step instead of one per
        array — 1.44x on the fused apply, PERF.md): pack once at entry, unpack
        once at exit, amortized over K steps. State layout outside this
        function is unchanged.

        storage="host_cached" tables work too, but the caller MUST admit the
        union of the K batches' ids first — `offload_prepare(state, batches)`
        does it in one jitted admission (a scan cannot interleave host-side
        admission, so an unprepared cache would silently train initializer
        rows where the host store holds trained ones). Use
        `offload_train_many`, which drives prepare -> scan -> adopt."""
        if self.offload and not getattr(self, "_offload_prepared", False):
            # trace-time fail-fast for the old misuse (an unprepared cache
            # trains initializer rows over the store's trained ones); repeat
            # calls bypass Python, so the per-window prepare contract itself
            # is enforced by convention — offload_train_many does it right
            raise ValueError(
                "train_many on storage='host_cached' tables needs the union "
                "of the K batches' ids admitted first: use "
                "trainer.offload_train_many(state, batches) (or call "
                "offload_prepare(state, batches) before every window).")
        from .ops.sparse import pack_table, unpack_table
        layouts = self._packed_layouts(state)
        if layouts:
            tables = dict(state.tables)
            for name, lay in layouts.items():
                ts = tables[name]
                tables[name] = ts.replace(
                    weights=pack_table(ts.weights, ts.slots, lay), slots={})
            state = state.replace(tables=tables)

        def body(state, batch):
            state, metrics = self.train_step(state, batch, packed=layouts)
            oflow = jnp.zeros((), jnp.int32)
            for k, v in metrics.get("stats", {}).items():
                if k.endswith("_overflow"):
                    oflow = oflow + jnp.asarray(v).astype(jnp.int32)
            return state, (metrics["loss"], oflow)

        state, (losses, oflows) = jax.lax.scan(body, state, batches)

        if layouts:
            tables = dict(state.tables)
            for name, lay in layouts.items():
                spec = self.model.specs[name]
                ts = tables[name]
                w, slots = unpack_table(ts.weights, lay, spec.output_dim,
                                        spec.dtype)
                tables[name] = ts.replace(weights=w, slots=slots)
            state = state.replace(tables=tables)
        # "overflow": exchange-bucket drops summed over the window (the scan
        # returns no per-step stats; this one scalar is what capacity
        # governance needs — see MeshTrainer.check_overflow)
        return state, {"loss": losses, "overflow": jnp.sum(oflows)}

    def jit_train_many(self):
        """Scan-fused multi-step driver (state DONATED, like jit_train_step)."""
        return jax.jit(self.train_many, donate_argnums=(0,))

    def _many_fn(self, batches, state):
        """Cached jitted train_many (MeshTrainer overrides: its jit_train_many
        needs the samples to derive partition specs and caches internally)."""
        if getattr(self, "_cached_many_fn", None) is None:
            self._cached_many_fn = self.jit_train_many()
        return self._cached_many_fn

    def offload_train_many(self, state: TrainState, batches
                           ) -> Tuple[TrainState, Dict]:
        """Scan-fused driving of host-cached models: ONE jitted admission of the
        union of the K batches' ids (flushing first if over high-water), then
        the fused K-step scan — the 2x scan-fusion lever and the >HBM capacity
        story compose instead of excluding each other. The reference serves any
        table through the same hot path regardless of backing store
        (`PmemEmbeddingOptimizerVariable.h:88-198` folds its DRAM cache into
        pull/update); this is the scan-era equivalent.

        The cache must be able to hold the K-batch union: size `capacity` (and
        pick K) so `union_unique_ids <= high_water * capacity`, or admission
        warns and overflowed rows fall back to insert-on-pull semantics.
        Works (as a plain fused scan) for models with no offloaded tables."""
        state = self.offload_prepare(state, batches)
        many = self._many_fn(batches, state)
        state, m = many(state, batches)
        for name, ot in self.offload.items():
            ot.adopt(state.tables[name])
        return state, m

    def jit_eval_step(self):
        return jax.jit(self.eval_step)
