"""Layered, typed configuration tree.

TPU-native counterpart of the reference's config stack: Python `Flags`
(`openembedding/__init__.py:33-40`) -> YAML string -> `core::Configure` -> typed
`EnvConfig` with per-field defaults/checkers/docs (`client/EnvConfig.h/.cpp`), plus the
per-variable nested configs with unknown-key warnings (`variable/Factory.h:35-111`).

Most of the reference's `rpc`/`master` knobs (TCP/RDMA, ZooKeeper, compression) are
obviated on TPU — the JAX runtime plays the master role and ICI/DCN collectives carry the
traffic — so the tree keeps only the knobs that still mean something, and documents the
mapping for the ones that don't.
"""

from __future__ import annotations

import dataclasses
import logging
import typing
import warnings
from typing import Any, Dict, Optional

import yaml

logger = logging.getLogger("openembedding_tpu")


class ConfigNode:
    """Dataclass mixin: build from a dict, warning on unknown keys.

    Mirrors the reference's `Configurable::load_config` unknown-key warnings
    (`variable/Factory.h:85-111`).
    """

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, Any]]):
        d = dict(d or {})
        field_names = {f.name for f in dataclasses.fields(cls)}
        # resolve string annotations (PEP 563) to real types for nested nodes
        hints = typing.get_type_hints(cls)
        kwargs = {}
        for key, value in d.items():
            if key not in field_names:
                warnings.warn(f"{cls.__name__}: unknown config key {key!r} ignored")
                continue
            ftype = hints.get(key)
            if isinstance(value, dict) and isinstance(ftype, type) and issubclass(ftype, ConfigNode):
                value = ftype.from_dict(value)
            kwargs[key] = value
        out = cls(**kwargs)
        out.check()
        return out

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def check(self) -> None:
        """Override to validate field values (reference: EnvConfig checkers)."""


@dataclasses.dataclass
class MeshConfig(ConfigNode):
    """How to lay out the device mesh.

    Replaces the reference's process-role topology (master/servers/workers,
    `EnvConfig.h`): on TPU there is one SPMD program over a Mesh. `data` axis carries
    data parallelism of the dense part (the reference's Horovod ranks); embedding rows
    are sharded over *all* devices (the reference's PS shard axis).
    """

    axis_data: str = "data"     # DP axis name (dense grads psum over this)
    axis_model: str = "model"   # optional second axis for very large tables / MP dense
    num_model_shards: int = 1   # size of the model axis; 1 = pure DP mesh

    def check(self):
        if self.num_model_shards < 1:
            raise ValueError("num_model_shards must be >= 1")


@dataclasses.dataclass
class ServerConfig(ConfigNode):
    """Embedding-engine knobs (reference: `EnvConfig.h` server section).

    - reference `cache_size` (DRAM cache MB) -> `pull_capacity_factor`: static per-step
      unique-id buffer headroom under XLA static shapes.
    - reference `server_concurrency` -> obviated (XLA schedules).
    - reference `update_early_return` -> obviated (no RPC; async dispatch does this).
    - reference `message_compress` -> obviated (ICI, no wire compression).
    """

    pull_capacity_factor: float = 1.0  # unique-id buffer = factor * batch_ids
    default_num_shards: int = -1       # -1 = all mesh devices (reference default: #servers)
    report_interval: int = -1          # seconds between accumulator reports; <=0 = off


@dataclasses.dataclass
class CheckpointConfig(ConfigNode):
    """(reference: `server_dump_files`, pmem persist knobs, `c_api.cc:295-328`)."""

    files_per_shard: int = 1
    include_optimizer: bool = True
    persist_pending_window: int = 2   # async-persist window (pmem equivalent)


@dataclasses.dataclass
class EnvConfig(ConfigNode):
    """Root config tree (reference: `client/EnvConfig.h` Env root)."""

    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    server: ServerConfig = dataclasses.field(default_factory=ServerConfig)
    checkpoint: CheckpointConfig = dataclasses.field(default_factory=CheckpointConfig)

    @classmethod
    def from_yaml(cls, text: str) -> "EnvConfig":
        return cls.from_dict(yaml.safe_load(text) or {})

    def to_yaml(self) -> str:
        return yaml.safe_dump(self.to_dict(), sort_keys=False)


class Flags:
    """Process-level flags singleton (reference: `openembedding/__init__.py:33-40`).

    The reference's `master_endpoint`/`bind_ip`/`num_workers`/`wait_num_servers` describe
    a multi-process cluster; under JAX these map to `jax.distributed` initialization
    (multi-host) or nothing (single host). Kept: `config` (yaml path or string).
    """

    def __init__(self):
        self.config: str = ""
        self._env: Optional[EnvConfig] = None

    @property
    def env(self) -> EnvConfig:
        if self._env is None:
            if self.config:
                try:
                    with open(self.config) as f:
                        text = f.read()
                except (OSError, IOError):
                    text = self.config  # allow inline yaml string like the reference
                self._env = EnvConfig.from_yaml(text)
            else:
                self._env = EnvConfig()
        return self._env

    def reset(self):
        self.config = ""
        self._env = None


flags = Flags()
