"""Serving: model registry, model manager, REST admin + inference HTTP server.

Reference parity map (SURVEY.md §2.3/§2.4, §3.5):
- master KV tree `_hyper-embedding-model_` + ModelMeta status protocol
  (`client/Connection.cpp:214-277`, `variable/Meta.h`) -> file-based `ModelRegistry`
  (atomic JSON writes; one registry dir replaces the master process).
- `ModelManager::find_model_variable` (`client/ModelController.cpp:24-44`: cache by
  model_sign, refuse CREATING, read-only handles) -> `ModelManager`.
- controller binary REST API (`entry/controller.cc:100-205`: POST/GET/DELETE /models,
  GET/DELETE /nodes) -> `ServingHandler` routes, same resources.
- TF-Serving `PullWeights` serving path with `model_sign = uuid + "-" +
  floor(model_version)` (`tensorflow/exb_ops.cpp:261-276`, `entry/py_api.cc:130-138`)
  -> `resolve_sign` + POST /models/<sign>/pull.

Training-side HA (replica shards, dead-node restore) is obviated by SPMD training;
serving HA maps to running N of these servers behind a load balancer, each loading the
same export — the registry is just files, so replicas share it read-only.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import numpy as np

from .export import StandaloneModel
from .utils import trace
from .utils.trace import REQUEST_ID_HEADER


class _BadRequest(Exception):
    """Client sent a syntactically/semantically invalid request body (-> 400)."""

MODEL_STATUS = ("CREATING", "NORMAL", "DELETING", "ERROR")


def _ids_array(v, *, pooled: bool = False) -> np.ndarray:
    """Sparse-id JSON payload -> int64 array.

    `pooled=True` — every spec consuming this feature has a combiner, so the
    field width is free: RAGGED lists of id lists (the natural client
    encoding for multivalent features) pad to the next power-of-two width
    with -1 (pad slots pull zero rows and pooling masks them out,
    `embedding.combine`), rectangular input width-buckets the same way so
    the jit compile cache stays O(log max_width) programs per feature
    (`export.bucket_size`, floor 1), and 1-D input rank-expands to one-id
    lists (Keras fit's convention, mirrored by `inject`).

    `pooled=False` — the model's field count is part of its architecture
    (e.g. DeepFM's 26 columns): the strict rectangular contract stays, and a
    ragged payload raises (-> the caller's 400). Padding here would fabricate
    zero rows into the tower — a silently wrong 200."""
    from .data import is_ragged
    from .export import bucket_size
    if not pooled:
        return np.asarray(v, dtype=np.int64)
    if is_ragged(v):
        return _pad_ragged_bucketed(v)
    ids = np.asarray(v, dtype=np.int64)
    if ids.ndim == 1:
        return ids[:, None]
    if ids.ndim == 2:
        b = bucket_size(ids.shape[-1], floor=1)
        if ids.shape[-1] != b:
            ids = np.pad(ids, [(0, 0), (0, b - ids.shape[-1])],
                         constant_values=-1)
    return ids


def _pad_ragged_bucketed(v) -> np.ndarray:
    """The one ragged-padding policy for serving endpoints: pad to the next
    power-of-two field width with -1 (`export.bucket_size`, floor 1)."""
    from .data import pad_ragged
    from .export import bucket_size
    return pad_ragged(v, width=bucket_size(max(len(s) for s in v), floor=1))


def _pull_ids(v) -> np.ndarray:
    """Pull-endpoint ids: ragged lists pad to the power-of-two width (the
    caller reads pad rows back as zeros — shape-explicit); rectangular input
    passes through UNCHANGED so the response mirrors the requested shape."""
    from .data import is_ragged
    if is_ragged(v):
        return _pad_ragged_bucketed(v)
    return np.asarray(v, dtype=np.int64)


def _pooled_features(servable) -> set:
    """Feature names whose consuming specs ALL pool (combiner set) — the
    features whose width is free at serving time. Specs come from either
    servable kind; unknown specs (recipe-less standalone export) -> empty set
    (strict coercion everywhere). Memoized on the servable (immutable per
    load, and this sits on the predict hot path)."""
    cached = getattr(servable, "_pooled_features_cache", None)
    if cached is not None:
        return cached
    specs = getattr(servable, "specs", None)
    if not isinstance(specs, dict):
        m = getattr(servable, "model", None)
        specs = m.specs if m is not None else {}
    by_feature = {}
    for s in specs.values():
        by_feature.setdefault(s.feature_name, []).append(s)
    out = {f for f, ss in by_feature.items() if all(x.combiner for x in ss)}
    try:
        servable._pooled_features_cache = out
    except AttributeError:  # __slots__ servables: recompute per request
        pass
    return out


def resolve_sign(uuid: str, model_version: float) -> str:
    """uuid + "-" + floor(version) (reference `py_api.cc:130-138`)."""
    return f"{uuid}-{int(math.floor(model_version))}"


class ModelRegistry:
    """File-backed model registry: one JSON per model_sign under <root>/models/.

    Writes are atomic (tmp + rename), so concurrent serving replicas reading the same
    directory never see torn state — the moral equivalent of the reference's master
    tree KV + lock (`Connection.cpp:214-277`)."""

    def __init__(self, root: str):
        self.root = root
        self._dir = os.path.join(root, "models")
        os.makedirs(self._dir, exist_ok=True)
        self._lock = threading.Lock()

    def _path(self, sign: str) -> str:
        if not re.fullmatch(r"[A-Za-z0-9._-]+", sign):
            raise ValueError(f"bad model sign {sign!r}")
        return os.path.join(self._dir, f"{sign}.json")

    def _write(self, sign: str, entry: dict) -> None:
        path = self._path(sign)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(entry, f, indent=2, sort_keys=True)
        os.replace(tmp, path)

    def create_model(self, model_sign: str, uri: str, *, replica_num: int = 1,
                     shard_num: int = 1) -> dict:
        """Register CREATING -> caller loads/validates -> mark NORMAL.
        An existing CREATING entry is overwritten (the reference handles interrupted
        CREATING the same way, `ModelController.cpp:47-85`); NORMAL entries refuse.

        `shard_num` selects the servable kind (1 = materialized StandaloneModel,
        >1 = ShardedModel over that many devices — `ModelManager._load_entry`).
        `replica_num` is DECLARATIVE here: replicas are serving processes the
        operator runs (each node that loads this entry is one replica;
        `ServingClient` fails over between them), unlike the reference where
        the PS itself places replica_num copies of each shard
        (`Model.cpp:153-186`). The field records intent for operators/tooling;
        this registry does not spawn processes."""
        with self._lock:
            cur = self.get(model_sign)
            if cur is not None and cur.get("status") == "NORMAL":
                raise FileExistsError(f"model {model_sign!r} already exists")
            entry = {"model_sign": model_sign, "uri": uri,
                     "replica_num": replica_num, "shard_num": shard_num,
                     "status": "CREATING", "error": "",
                     "create_time": time.time()}
            self._write(model_sign, entry)
            return entry

    def set_status(self, model_sign: str, status: str, error: str = "") -> dict:
        if status not in MODEL_STATUS:
            raise ValueError(f"bad status {status!r}")
        with self._lock:
            entry = self.get(model_sign)
            if entry is None:
                raise KeyError(model_sign)
            entry["status"] = status
            entry["error"] = error
            self._write(model_sign, entry)
            return entry

    def delete_model(self, model_sign: str) -> None:
        with self._lock:
            path = self._path(model_sign)
            if not os.path.exists(path):
                raise KeyError(model_sign)
            os.unlink(path)

    def get(self, model_sign: str) -> Optional[dict]:
        try:
            with open(self._path(model_sign)) as f:
                return json.load(f)
        except FileNotFoundError:
            return None

    def show_models(self) -> Dict[str, dict]:
        out = {}
        for fn in sorted(os.listdir(self._dir)):
            if fn.endswith(".json"):
                with open(os.path.join(self._dir, fn)) as f:
                    entry = json.load(f)
                out[entry["model_sign"]] = entry
        return out


class ModelManager:
    """model_sign -> cached servable; refuses models not in NORMAL state
    (reference `ModelManager::find_model_variable`, `ModelController.cpp:24-44`).

    `shard_num == 1` loads a materialized `StandaloneModel` (export layout,
    small models). `shard_num > 1` loads the model SHARDED over `shard_num`
    devices straight from a (sharded) checkpoint — never materialized in one
    place (`parallel/serving.ShardedModel`) — the reference's serving-from-
    the-sharded-PS path (`exb_ops.cpp:261-276`)."""

    def __init__(self, registry: ModelRegistry):
        self.registry = registry
        # the RCU servable cache: swap/evict/load publish through it, every
        # predict resolves from it (lock discipline enforced by `make lint`,
        # tools/oelint lockset pass)
        self._cache: Dict[str, object] = {}  # guarded-by: self._lock
        self._lock = threading.Lock()
        # per-sign load guards: two first requests racing for the same model
        # must not both run a (device-memory-heavy) sharded load
        self._loading: Dict[str, threading.Lock] = {}  # guarded-by: self._lock

    @staticmethod
    def _load_entry(entry: dict):
        shard_num = int(entry.get("shard_num", 1))
        if shard_num <= 1:
            return StandaloneModel.load(entry["uri"])
        import jax
        from .parallel.mesh import make_mesh
        from .parallel.serving import ShardedModel
        devices = jax.devices()
        if shard_num > len(devices):
            raise ValueError(
                f"shard_num={shard_num} exceeds the {len(devices)} devices "
                "on this serving node")
        return ShardedModel.load(entry["uri"],
                                 mesh=make_mesh(devices[:shard_num]))

    def find_model(self, model_sign: str):
        with self._lock:
            if model_sign in self._cache:
                return self._cache[model_sign]
            guard = self._loading.setdefault(model_sign, threading.Lock())
        with guard:
            with self._lock:  # the winner may have finished while we waited
                if model_sign in self._cache:
                    return self._cache[model_sign]
            entry = self.registry.get(model_sign)
            if entry is None:
                raise KeyError(f"unknown model {model_sign!r}")
            if entry["status"] != "NORMAL":
                raise RuntimeError(
                    f"model {model_sign!r} is {entry['status']}, not servable")
            loaded = self._load_entry(entry)
            with self._lock:
                self._cache[model_sign] = loaded
            return loaded

    def servable_versions(self) -> Dict[str, dict]:
        """{sign: {step, kind}} of every LOADED servable (the /statusz view —
        the registry shows what's registered, this shows what's resident)."""
        with self._lock:
            cache = dict(self._cache)
        return {sign: {"step": int(getattr(m, "step", 0) or 0),
                       "kind": type(m).__name__}
                for sign, m in cache.items()}

    def find_model_variable(self, model_sign: str, variable: str):
        m = self.find_model(model_sign)
        if variable not in m.variable_names:
            raise KeyError(f"model {model_sign!r} has no variable {variable!r}")
        return m, variable

    def evict(self, model_sign: str) -> None:
        with self._lock:
            self._cache.pop(model_sign, None)

    def swap(self, model_sign: str, servable, *, expected=None) -> None:
        """RCU publish of a new servable version (online sync,
        `sync/subscriber.py`): requests that already resolved the old object
        finish on it; the next `find_model` returns the new one. With
        `expected`, the swap is conditional — it refuses when the cached
        servable is no longer the one the update was derived from (a
        concurrent reload/delete won the race; the subscriber re-syncs from
        the fresh servable's version instead of clobbering it)."""
        with self._lock:
            cur = self._cache.get(model_sign)
            if cur is None:
                raise KeyError(
                    f"model {model_sign!r} is not loaded; cannot swap")
            if expected is not None and cur is not expected:
                raise RuntimeError(
                    f"model {model_sign!r} was reloaded concurrently; "
                    "swap abandoned")
            self._cache[model_sign] = servable
        trace.event("serving", "servable_swap", model=model_sign,
                    step=int(getattr(servable, "step", 0) or 0))

    def load_model(self, model_sign: str, uri: str, *, replica_num: int = 1,
                   shard_num: int = 1) -> dict:
        """create_model + validate-load + NORMAL/ERROR transition (the controller's
        create flow, `ModelController.cpp:47-85`, done synchronously)."""
        entry = self.registry.create_model(model_sign, uri,
                                           replica_num=replica_num,
                                           shard_num=shard_num)
        try:
            loaded = self._load_entry(entry)
            with self._lock:
                self._cache[model_sign] = loaded
            return self.registry.set_status(model_sign, "NORMAL")
        except Exception as e:  # noqa: BLE001 - status must record any failure
            self.registry.set_status(model_sign, "ERROR", error=str(e))
            raise


# ---------------------------------------------------------------------------
# REST server (controller + inference parity in one process)
# ---------------------------------------------------------------------------


class ServingHandler(BaseHTTPRequestHandler):
    manager: ModelManager = None  # set by make_server
    batcher: "Optional[MicroBatcher]" = None  # set when batching is enabled
    # model_sign -> publisher/subscriber registries: DELIBERATE class-level
    # shared state — http.server constructs one handler INSTANCE per request,
    # so per-server mutable registries must live on the per-server Handler
    # subclass (make_server assigns fresh dicts; POST publish/sync mutates
    # them across requests by design)
    # oelint: disable=lockset -- per-server registry; make_server subclass gets a fresh dict
    publishers: dict = {}   # model_sign -> sync.SyncPublisher (make_server)
    # oelint: disable=lockset -- per-server registry; make_server subclass gets a fresh dict
    subscribers: dict = {}  # model_sign -> sync.SyncSubscriber (make_server)
    # read-only defaults: make_server replaces these on the subclass; the
    # immutable peers tuple means a stray bare-ServingHandler append fails
    peers: tuple = ()       # default /fleetz scrape set (make_server/--peers)
    # oelint: disable=lockset -- read-only default; make_server assigns a fresh dict per server
    node_info: dict = {}
    quiet = True

    # -- plumbing -----------------------------------------------------------

    def log_message(self, fmt, *args):
        if not self.quiet:
            super().log_message(fmt, *args)

    def send_response(self, code, message=None):
        """Every response echoes the request id (`X-OETPU-Request-Id`),
        stamps this node's wall clock (`X-OETPU-Server-Time`, the Cristian
        clock-offset probe clients read), and records the status onto the
        request's http span."""
        super().send_response(code, message)
        rid = getattr(self, "_request_id", None)
        if rid:
            self.send_header(REQUEST_ID_HEADER, rid)
        self.send_header(trace.SERVER_TIME_HEADER, repr(time.time()))
        sp = getattr(self, "_http_span", None)
        if sp is not None:
            sp.attrs["status"] = int(code)

    def _traced(self, method: str, handler):
        """Trace-context middleware: adopt the client's `X-OETPU-Trace`
        context (falling back to `X-OETPU-Request-Id`, generating an id when
        absent), bind it for the request's lifetime, and wrap the whole
        handler in the root `serving.http` span — every nested span (predict,
        queue wait, batch exec, model call; publisher-side delta serves in a
        sync round) correlates by this id, and the http span's
        `remote_parent` links it under the CALLER's span across the process
        boundary (the stitched fleet trace tree)."""
        ctx = trace.extract_context(self.headers)
        rid = (ctx.trace_id if ctx is not None else None) \
            or trace.new_request_id()
        self._request_id = rid
        with trace.request(rid, remote_parent=ctx.parent_span
                           if ctx is not None else None):
            with trace.span("serving", "http", method=method,
                            path=self.path) as sp:
                self._http_span = sp
                return handler()

    def _json(self, code: int, payload, headers: Optional[dict] = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _blob(self, body: bytes, headers: Optional[dict] = None) -> None:
        self.send_response(200)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(body)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> dict:
        length = int(self.headers.get("Content-Length", 0))
        if length == 0:
            return {}
        data = json.loads(self.rfile.read(length))
        if not isinstance(data, dict):
            raise _BadRequest("request body must be a JSON object")
        return data

    def _route(self):
        from urllib.parse import parse_qs, urlsplit
        parts = urlsplit(self.path)
        self.query = {k: v[-1] for k, v in parse_qs(parts.query).items()}
        path = parts.path.rstrip("/")
        m = re.fullmatch(
            r"/models/([A-Za-z0-9._-]+)/delta/(\d+)"
            r"/(meta|dense|table/[A-Za-z0-9._-]+)", path)
        if m:
            return "delta", m.group(1), (int(m.group(2)), m.group(3))
        m = re.fullmatch(r"/models/([A-Za-z0-9._-]+)"
                         r"(?::(\w+)|/(pull|predict|publish|sync))?",
                         path)
        if m:
            return "model", m.group(1), m.group(2) or m.group(3)
        if path == "/models":
            return "models", None, None
        m = re.fullmatch(r"/nodes/([A-Za-z0-9._-]+)", path)
        if m:
            return "node", m.group(1), None
        if path == "/nodes":
            return "nodes", None, None
        if path == "/healthz":
            return "healthz", None, None
        if path == "/metrics":
            return "metrics", None, None
        if path == "/fleetz":
            return "fleetz", None, None
        if path == "/statusz":
            return "statusz", None, None
        if path == "/tracez":
            return "tracez", None, None
        if path == "/sloz":
            return "sloz", None, None
        if path == "/historz":
            return "historz", None, None
        if path == "/timelinez":
            return "timelinez", None, None
        if path == "/capsule":
            return "capsule", None, None
        return None, None, None

    # -- verbs --------------------------------------------------------------

    def _npz(self, arrays: dict) -> None:
        """Stream a dict of numpy arrays as an uncompressed .npz body."""
        import io
        buf = io.BytesIO()
        np.savez(buf, **arrays)
        body = buf.getvalue()
        self.send_response(200)
        self.send_header("Content-Type", "application/octet-stream")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _text(self, body: str, code: int = 200) -> None:
        raw = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", "text/plain; charset=utf-8")
        self.send_header("Content-Length", str(len(raw)))
        self.end_headers()
        self.wfile.write(raw)

    def _statusz_text(self) -> str:
        """Operator one-pager: build/config, servable versions, sync state
        (with the last DEGRADED reason), publishers, flight-recorder tail."""
        import platform
        lines = ["== openembedding_tpu serving /statusz =="]
        build = {"python": platform.python_version()}
        try:
            import jax
            build["jax"] = jax.__version__
        except Exception:  # noqa: BLE001 — statusz must render regardless
            pass
        lines.append("build: " + " ".join(f"{k}={v}"
                                          for k, v in build.items()))
        lines.append("node: " + json.dumps(self.node_info, sort_keys=True))
        lines.append("")
        lines.append("-- servables (loaded) --")
        versions = self.manager.servable_versions()
        if not versions:
            lines.append("(none loaded)")
        for sign, v in sorted(versions.items()):
            entry = self.manager.registry.get(sign) or {}
            lines.append(f"{sign}: step={v['step']} kind={v['kind']} "
                         f"status={entry.get('status', '?')}")
        lines.append("")
        lines.append("-- sync subscribers --")
        if not self.subscribers:
            lines.append("(none)")
        for sign, sub in sorted(self.subscribers.items()):
            st = sub.status()
            f = st.get("freshness_ms")
            fresh = f"freshness_ms={f:.1f} " if f is not None else ""
            lines.append(
                f"{sign}: state={st['state']} version={st['version']} "
                f"applied={st['applied']} {fresh}"
                f"last_degraded_reason={st.get('last_degraded_reason')}")
        lines.append("")
        lines.append("-- sync publishers --")
        if not self.publishers:
            lines.append("(none)")
        for sign, pub in sorted(self.publishers.items()):
            try:
                feed = pub.versions()
                lines.append(f"{sign}: head_step={feed['head_step']} "
                             f"base_step={feed['base_step']} "
                             f"deltas={len(feed['deltas'])}")
            except Exception as e:  # noqa: BLE001
                lines.append(f"{sign}: (feed error: {e})")
        lines.append("")
        lines.append("-- workload skew (hot ids) --")
        from .utils import sketch
        lines.append(sketch.MONITOR.render_text(
            top=int(self.query.get("top", 8)) if hasattr(self, "query")
            else 8))
        lines.append("")
        lines.append("-- placement (self-driving) --")
        try:
            from .placement.controller import render_status
            lines.append(render_status())
        except Exception as e:  # noqa: BLE001 — statusz must render regardless
            lines.append(f"(placement status unavailable: {e})")
        lines.append("")
        lines.append("-- SLOs (GET /sloz for JSON) --")
        try:
            from .utils import slo
            slo.EVALUATOR.evaluate_now()
            lines.append(slo.EVALUATOR.render_text())
        except Exception as e:  # noqa: BLE001 — statusz must render regardless
            lines.append(f"(slo status unavailable: {e})")
        lines.append("")
        lines.append("-- ingest (line-rate) --")
        try:
            from .utils import metrics as metrics_mod
            ingest = {k: v for k, v in metrics_mod.report(reset=False).items()
                      if k.startswith("ingest.")
                      and not k.endswith((".p50", ".p95", ".p99"))}
            lines.append(metrics_mod._format_table(ingest)
                         if ingest else "(no ingest activity)")
        except Exception as e:  # noqa: BLE001 — statusz must render regardless
            lines.append(f"(ingest status unavailable: {e})")
        lines.append("")
        lines.append("-- metric history (GET /historz for JSON) --")
        try:
            from .utils import history
            lines.append(history.render_sparklines())
        except Exception as e:  # noqa: BLE001 — statusz must render regardless
            lines.append(f"(history unavailable: {e})")
        lines.append("")
        lines.append("-- device memory (memwatch ledger) --")
        try:
            from .utils import memwatch
            mem = memwatch.WATCH.export()
            if mem["components"]:
                for e in sorted(mem["components"],
                                key=lambda e: (e["component"],
                                               sorted(e["labels"].items()))):
                    lbl = ",".join(f"{k}={v}" for k, v in
                                   sorted(e["labels"].items()))
                    tag = e["component"] + (f"{{{lbl}}}" if lbl else "")
                    host = " (host)" if e["host"] else ""
                    lines.append(f"{tag}: {e['bytes']:,}B{host}")
                lines.append(f"device total (model): "
                             f"{mem['device_total_bytes']:,}B")
            else:
                lines.append("(no components registered)")
        except Exception as e:  # noqa: BLE001 — statusz must render regardless
            lines.append(f"(memory ledger unavailable: {e})")
        lines.append("")
        n = int(self.query.get("n", 40)) if hasattr(self, "query") else 40
        lines.append(f"-- flight recorder (last {n}) --")
        lines.append(trace.RECORDER.render_text(n))
        return "\n".join(lines) + "\n"

    def _fleetz_text(self) -> str:
        """Merged fleet /metrics: this node's scrape + every peer's, summed
        per `utils/metrics.merge_prometheus` (counters + histogram buckets
        sum; gauges keep an `instance` label). Peers come from `?peers=`
        (comma-separated base URLs) or the node's `--peers` config;
        unreachable peers degrade to a comment line, never a 500 — a fleet
        view with one dead node is still a fleet view."""
        import urllib.request
        from .utils import metrics as metrics_mod
        from .utils import sketch
        sketch.MONITOR.publish()
        q = self.query.get("peers") if hasattr(self, "query") else None
        peers = ([p for p in q.split(",") if p] if q is not None
                 else list(self.peers))
        scrapes = [(self.node_info.get("node_id", "self"),
                    metrics_mod.prometheus_text())]
        comments = [f"# fleet: {1 + len(peers)} node(s): self + "
                    + (", ".join(peers) if peers else "(no peers)")]
        for peer in peers:
            url = peer.rstrip("/")
            if not url.startswith("http"):
                url = f"http://{url}"
            try:
                req = urllib.request.Request(
                    f"{url}/metrics", headers=trace.inject_headers())
                with urllib.request.urlopen(req, timeout=5.0) as r:
                    scrapes.append((peer, r.read().decode()))
            except Exception as e:  # noqa: BLE001 — degrade, don't 500
                comments.append(f"# fleet: peer {peer} unreachable: {e}")
                metrics_mod.observe("fleet.scrape_errors", 1)
        metrics_mod.observe("fleet.peers", float(len(peers)), "gauge")
        metrics_mod.observe("fleet.nodes_answering", float(len(scrapes)),
                            "gauge")
        merged = metrics_mod.merge_prometheus(scrapes)
        comments.extend(self._fleetz_freshness(merged))
        return "\n".join(comments) + "\n" + merged

    def _fleetz_freshness(self, merged: str) -> list:
        """"Who is stale" comment lines for /fleetz: per-instance
        `sync.freshness_ms` / head / applied version gauges parsed back OUT
        of the merged scrape (gauges keep their `instance` label through the
        merge, so no extra round-trips), plus THIS node's last hop
        breakdown from the lineage book."""
        out = []
        try:
            per: dict = {}
            for line in merged.splitlines():
                for metric, field in (("oetpu_sync_freshness_ms", "fresh"),
                                      ("oetpu_sync_head_version", "head"),
                                      ("oetpu_sync_applied_version",
                                       "applied")):
                    if not line.startswith(metric + "{"):
                        continue
                    m = re.search(r'instance="([^"]*)"', line)
                    inst = m.group(1) if m else "self"
                    try:
                        val = float(line.rsplit(None, 1)[-1])
                    except ValueError:
                        continue
                    per.setdefault(inst, {})[field] = val
            for inst in sorted(per):
                d = per[inst]
                parts = [f"# fleet freshness: {inst}:"]
                if "fresh" in d:
                    parts.append(f"freshness_ms={d['fresh']:.1f}")
                if "head" in d:
                    parts.append(f"head_version={int(d['head'])}")
                if "applied" in d:
                    parts.append(f"applied_version={int(d['applied'])}")
                out.append(" ".join(parts))
            from .sync import lineage
            last = lineage.BOOK.last()
            if last is not None and last.get("hops"):
                hops = " ".join(f"{h}={v:.1f}ms" for h, v in
                                sorted(last["hops"].items()))
                out.append(f"# fleet freshness: self last delta "
                           f"step={last['step']} hops: {hops}")
        except Exception as e:  # noqa: BLE001 — degrade, don't 500
            out.append(f"# fleet freshness: unavailable: {e}")
        return out

    def do_GET(self):  # noqa: N802 (http.server API)
        return self._traced("GET", self._handle_get)

    def _handle_get(self):
        kind, sign, action = self._route()
        try:
            if kind == "models":
                return self._json(200, self.manager.registry.show_models())
            if kind == "model" and action == "versions":
                # online-sync feed (sync/publisher.py): ETag = head commit
                # step; ?after=<step>&wait_s=<s> bounded long-poll -> 304
                # when nothing newer commits inside the window
                pub = self.publishers.get(sign)
                if pub is None:
                    return self._json(
                        404, {"error": f"model {sign!r} has no publisher"})
                after = self.query.get("after")
                after = (self._coerce(int, after, "after")
                         if after is not None else None)
                wait_s = self._coerce(float, self.query.get("wait_s", 0.0),
                                      "wait_s")
                feed, changed = pub.wait_versions(after, wait_s)
                etag = {"ETag": f'"{feed["head_step"]}"'}
                if not changed:
                    self.send_response(304)
                    self.send_header("ETag", etag["ETag"])
                    self.end_headers()
                    return None
                return self._json(200, feed, headers=etag)
            if kind == "model" and action == "syncstate":
                sub = self.subscribers.get(sign)
                if sub is None:
                    return self._json(
                        404, {"error": f"model {sign!r} has no subscriber"})
                return self._json(200, sub.status())
            if kind == "delta":
                pub = self.publishers.get(sign)
                if pub is None:
                    return self._json(
                        404, {"error": f"model {sign!r} has no publisher"})
                step, fname = action
                etag = {"ETag": f'"{step}"'}  # committed deltas are immutable
                if fname == "meta":
                    return self._json(200, pub.delta_meta(step), headers=etag)
                if fname == "dense":
                    return self._blob(pub.delta_dense(step), headers=etag)
                name = fname[len("table/"):]
                fmt = self.query.get("wire")
                if fmt is not None:
                    from .ops.wire import wire_format
                    fmt = self._coerce(wire_format, fmt, "wire")
                return self._blob(pub.delta_table(step, name, fmt),
                                  headers=etag)
            if kind == "model" and action in ("exportmeta", "rows", "dense"):
                # live-replica restore surface (reference
                # `EmbeddingRestoreOperator.cpp:19-106`: iterate a live
                # replica's rows through cursors): a peer pages these three
                # endpoints to rebuild a standalone export with no shared
                # filesystem — see `restore_from_peer`.
                model = self.manager.find_model(sign)
                if action == "exportmeta":
                    return self._json(200, model.export_manifest())
                if action == "dense":
                    return self._npz(model.export_dense())
                var = self.query.get("var")
                if var is None:
                    raise _BadRequest("rows: missing ?var=")
                if var not in model.variable_names:
                    return self._json(
                        404, {"error": f"model {sign!r} has no variable {var!r}"})
                start = self._coerce(int, self.query.get("start", 0), "start")
                count = self._coerce(int, self.query.get("count", 1 << 16),
                                     "count")
                from .export import _BadRange
                try:
                    return self._npz(model.export_rows(var, start, count))
                except _BadRange as e:
                    raise _BadRequest(str(e)) from e
            if kind == "model":
                entry = self.manager.registry.get(sign)
                if entry is None:
                    return self._json(404, {"error": f"unknown model {sign}"})
                return self._json(200, entry)
            if kind == "nodes":
                return self._json(200, {"nodes": [self.node_info]})
            if kind == "healthz":
                return self._json(200, {"status": "ok"})
            if kind == "metrics":
                from .utils import sketch
                from .utils.metrics import prometheus_text
                sketch.MONITOR.publish()  # fold top-K into skew.* gauges
                body = prometheus_text().encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain; version=0.0.4")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return None
            if kind == "fleetz":
                return self._text(self._fleetz_text())
            if kind == "statusz":
                return self._text(self._statusz_text())
            if kind == "tracez":
                n = self._coerce(int, self.query.get("n", 256), "n")
                return self._json(200, {
                    "spans": [s.as_dict() for s in trace.RECORDER.spans(n)],
                    "events": [e.as_dict()
                               for e in trace.RECORDER.events(n)]})
            if kind == "sloz":
                # evaluate on demand (the background thread is optional):
                # every scrape judges the freshest accumulator state
                from .utils import slo
                verdicts = slo.EVALUATOR.evaluate_now()
                if self.query.get("format") == "text":
                    return self._text(slo.EVALUATOR.render_text())
                return self._json(200, {"verdicts": verdicts,
                                        "exit_code":
                                            slo.EVALUATOR.exit_code()})
            if kind == "historz":
                # GET /historz?metric=<name>[&window=<s>][&<label>=<v>...] —
                # a metric's retained ring(s); without ?metric=, the series
                # catalogue (names only, cheap)
                from .utils import history
                metric = self.query.get("metric")
                if metric is None:
                    return self._json(200, {"metrics": history.HISTORY.names()})
                window = self.query.get("window")
                window_s = (self._coerce(float, window, "window")
                            if window is not None else None)
                labels = {k: v for k, v in self.query.items()
                          if k not in ("metric", "window")}
                return self._json(200, {
                    "metric": metric, "window_s": window_s,
                    "series": history.HISTORY.query(
                        metric, window_s=window_s, labels=labels or None)})
            if kind == "timelinez":
                # GET /timelinez[?n=] — this node's flight events/spans with
                # (wall, monotonic) pairs, its delta lineage book, and clock
                # info; `tools/fleet_timeline.py` scrapes N of these, solves
                # per-node skew Cristian-style off `wall_time`, and renders
                # one merged causally-ordered fleet timeline
                from .sync import lineage
                n = self._coerce(int, self.query.get("n", 512), "n")
                return self._json(200, {
                    "node": self.node_info.get("node_id", "self"),
                    "process": trace.PROCESS_ID,
                    "wall_time": time.time(),
                    "events": [e.as_dict()
                               for e in trace.RECORDER.events(n)],
                    "spans": [s.as_dict() for s in trace.RECORDER.spans(n)],
                    "lineage": lineage.BOOK.export()})
            return self._json(404, {"error": "not found"})
        except _BadRequest as e:
            return self._json(400, {"error": str(e)})
        except KeyError as e:
            return self._json(404, {"error": str(e)})
        except Exception as e:  # noqa: BLE001 - every handler error becomes a 500
            return self._json(500, {"error": str(e)})

    @staticmethod
    def _field(body: dict, *names):
        """Required request-body field: first present name wins; absence is the
        CALLER's error (400), never a 404 — 404 is reserved for unknown
        model/variable signs."""
        for n in names:
            if n in body:
                return body[n]
        raise _BadRequest(f"missing required field {names[0]!r}")

    @staticmethod
    def _coerce(fn, value, what: str):
        """Convert a request value, mapping conversion failures to 400 at the
        parse site — a ValueError/TypeError deep inside model code is a real
        server error and must stay a 500."""
        try:
            return fn(value)
        except (ValueError, TypeError) as e:
            raise _BadRequest(f"bad {what!r}: {e}") from e

    def do_POST(self):  # noqa: N802
        return self._traced("POST", self._handle_post)

    def _handle_post(self):
        kind, sign, action = self._route()
        try:
            body = self._body()
            if kind == "capsule":
                # POST /capsule {"reason": ..., ...attrs} — operator-requested
                # postmortem dump; 409 when capsules are not armed (no dir),
                # 429 when the per-reason rate limit suppressed the write
                from .utils import capsule
                if not capsule.enabled():
                    return self._json(409, {
                        "error": "capsules not configured "
                                 "(--capsule-dir / OETPU_CAPSULE_DIR)"})
                reason = str(body.pop("reason", "operator"))
                path = capsule.trigger(reason, **{
                    str(k): v for k, v in body.items()})
                # single exit: 200 with the path, or 429 when the per-reason
                # rate limit (or a write error) suppressed the dump
                return self._json(
                    200 if path else 429,
                    {"reason": reason, "path": path} if path
                    else {"error": "capsule suppressed (rate limit or "
                                   "write error)", "reason": reason})
            if kind == "models" or (kind == "model" and action is None):
                # POST /models {model_sign, model_uri, replica_num, shard_num}
                # (controller.proto CreateModelRequest fields)
                sign = sign or self._field(body, "model_sign")
                entry = self.manager.load_model(
                    sign, self._field(body, "model_uri", "uri"),
                    replica_num=self._coerce(int, body.get("replica_num", 1),
                                             "replica_num"),
                    shard_num=self._coerce(int, body.get("shard_num", 1),
                                           "shard_num"))
                return self._json(200, entry)
            if kind == "model" and action == "publish":
                # register this node as the sync publisher for `sign`:
                # POST /models/<sign>/publish {"persist_root": ..., "wire": ...}
                from .sync import SyncPublisher
                root = self._field(body, "persist_root", "root")
                if not os.path.isdir(root):
                    raise _BadRequest(f"persist_root {root!r} is not a "
                                      "directory")
                pub = SyncPublisher(root, wire=body.get("wire"))
                self.publishers[sign] = pub
                return self._json(200, {"model_sign": sign,
                                        **pub.versions()})
            if kind == "model" and action == "sync":
                # attach a live subscriber on this serving node:
                # POST /models/<sign>/sync {"feed": url, "interval_s": ...,
                #                           "wire": ..., "wait_s": ...}
                from .sync import SyncSubscriber
                feed = self._field(body, "feed")
                old = self.subscribers.pop(sign, None)
                if old is not None:
                    old.stop()
                sub = SyncSubscriber(
                    self.manager, sign, feed,
                    wire=body.get("wire"),
                    interval_s=self._coerce(
                        float, body.get("interval_s", 1.0), "interval_s"),
                    wait_s=self._coerce(
                        float, body.get("wait_s", 0.0), "wait_s"))
                self.subscribers[sign] = sub.start()
                return self._json(200, sub.status())
            if kind == "model" and action == "pull":
                model, variable = self.manager.find_model_variable(
                    sign, self._field(body, "variable"))
                ids = self._coerce(_pull_ids, self._field(body, "ids"),
                                   "ids")
                # heavy-hitter telemetry, off the hot path (bounded queue;
                # predict ids are recorded by the servables themselves)
                from .utils import sketch
                sketch.record_ids(variable, ids)
                rows = model.lookup(variable, ids)
                # content negotiation: `Accept: application/octet-stream`
                # streams the rows as npz — JSON-encoding a big pull is pure
                # overhead for programmatic clients (ServingClient binary=True)
                if "application/octet-stream" in self.headers.get("Accept", ""):
                    return self._npz({"weights": np.asarray(rows)})
                return self._json(200, {"weights": np.asarray(rows).tolist()})
            if kind == "model" and action == "predict":
                # per-request wall time -> labeled latency histogram
                # (oetpu_serving_predict_ms_bucket{model=...}) AND a span
                # under the request's http span — one measurement, two views
                with trace.span("serving", "predict",
                                labels={"model": sign}, model=sign):
                    model = self.manager.find_model(sign)
                    pooled = _pooled_features(model)
                    batch = {
                        "sparse": {k: self._coerce(
                            lambda v, _p=(k in pooled):
                                _ids_array(v, pooled=_p),
                            v, f"sparse.{k}")
                            for k, v in body.get("sparse", {}).items()},
                    }
                    if body.get("dense") is not None:
                        batch["dense"] = self._coerce(
                            lambda v: np.asarray(v, dtype=np.float32),
                            body["dense"], "dense")
                    from .export import RaggedBatchError
                    try:
                        if self.batcher is not None:
                            logits = self.batcher.predict(model, sign, batch)
                        else:
                            with trace.span("serving", "model_call"):
                                logits = model.predict(batch)
                    except KeyError as e:
                        # a feature the model needs is absent from the request
                        # body — the CALLER's error (400), not an unknown sign
                        raise _BadRequest(
                            f"predict request is missing sparse feature {e}"
                        ) from e
                    except RaggedBatchError as e:
                        raise _BadRequest(str(e)) from e
                    # close the delta's lineage chain on its FIRST predict
                    # at this version (idempotent, O(1), no-throw)
                    from .sync import lineage
                    lineage.note_serve(
                        sign, int(getattr(model, "step", 0) or 0))
                    return self._json(
                        200, {"logits": np.asarray(logits).tolist()})
            return self._json(404, {"error": "not found"})
        except _BadRequest as e:
            return self._json(400, {"error": str(e)})
        except json.JSONDecodeError as e:
            return self._json(400, {"error": f"malformed request body: {e}"})
        except KeyError as e:
            return self._json(404, {"error": str(e)})
        except Exception as e:  # noqa: BLE001
            return self._json(500, {"error": str(e)})

    def do_DELETE(self):  # noqa: N802
        return self._traced("DELETE", self._handle_delete)

    def _handle_delete(self):
        kind, sign, _ = self._route()
        try:
            if kind == "model":
                sub = self.subscribers.pop(sign, None)
                if sub is not None:
                    sub.stop()  # a deleted model must not keep syncing
                self.manager.registry.set_status(sign, "DELETING")
                self.manager.evict(sign)
                self.manager.registry.delete_model(sign)
                return self._json(200, {"deleted": sign})
            if kind == "node":
                # reference: controller can shut nodes down
                # (`ModelController.cpp:158-164`); here the node is this process
                # oelint: disable=thread-lifecycle -- shutdown() must run off
                # the request thread (it blocks until this very handler
                # returns); the thread self-terminates with the server
                threading.Thread(target=self.server.shutdown, daemon=True).start()
                return self._json(200, {"shutdown": sign})
            return self._json(404, {"error": "not found"})
        except KeyError as e:
            return self._json(404, {"error": str(e)})
        except Exception as e:  # noqa: BLE001
            return self._json(500, {"error": str(e)})


class ServingClient:
    """REST client with replica failover — the caller-side half of serving HA.

    The reference picks one replica per pull and retries on `NoReplica`
    (`pick_one_replica`, `EmbeddingPullOperator.cpp:50-58`,
    `c_api_test.h:117-121`); here the client walks its replica list starting
    from a rotating offset (spreads load) and fails over to the next node on
    connection errors. Server-side (HTTP) errors are NOT retried — a 400/404
    is the same answer everywhere, and a 500 on one replica is surfaced, not
    masked by silently asking another."""

    def __init__(self, nodes, timeout: float = 30.0):
        if isinstance(nodes, str):
            nodes = [nodes]
        if not nodes:
            raise ValueError("need at least one serving node URL")
        self.nodes = [n.rstrip("/") for n in nodes]
        self.timeout = timeout
        self._next = 0

    def _request(self, method: str, path: str, body=None, *,
                 binary: bool = False):
        import io
        import urllib.error
        import urllib.request
        start, last = self._next, None
        self._next = (self._next + 1) % len(self.nodes)
        for i in range(len(self.nodes)):
            node = self.nodes[(start + i) % len(self.nodes)]
            data = json.dumps(body).encode() if body is not None else None
            req = urllib.request.Request(f"{node}{path}", data=data,
                                         method=method,
                                         headers=trace.inject_headers())
            if data:
                req.add_header("Content-Type", "application/json")
            if binary:
                req.add_header("Accept", "application/octet-stream")
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as r:
                    raw = r.read()
                    if binary and "octet-stream" in r.headers.get(
                            "Content-Type", ""):
                        return dict(np.load(io.BytesIO(raw)))
                    return json.loads(raw)
            except urllib.error.HTTPError:
                raise  # a server ANSWERED; its answer stands (see class doc)
            except (urllib.error.URLError, ConnectionError, OSError) as e:
                last = e  # dead/unreachable replica: try the next
        raise ConnectionError(
            f"no live replica among {self.nodes}: {last}") from last

    @staticmethod
    def _jsonable_ids(v):
        """RAGGED id lists stay lists (np.asarray would raise on inhomogeneous
        shapes before any request is made) — the server pads them
        (`_ids_array`/`_pull_ids`); everything else normalizes through numpy."""
        from .data import is_ragged
        if is_ragged(v):
            return [[int(x) for x in row] for row in v]
        return np.asarray(v).tolist()

    def pull(self, model_sign: str, variable: str, ids, *,
             binary: bool = False) -> np.ndarray:
        """`binary=True` asks for the npz wire format (Accept negotiation) —
        no JSON float round-trip, the right mode for large/hot pulls."""
        out = self._request("POST", f"/models/{model_sign}/pull",
                            {"variable": variable,
                             "ids": self._jsonable_ids(ids)},
                            binary=binary)
        if binary:
            return out["weights"]
        return np.asarray(out["weights"], np.float32)

    def predict(self, model_sign: str, sparse: Dict[str, Any],
                dense=None) -> np.ndarray:
        body = {"sparse": {k: self._jsonable_ids(v)
                           for k, v in sparse.items()}}
        if dense is not None:
            body["dense"] = np.asarray(dense).tolist()
        out = self._request("POST", f"/models/{model_sign}/predict", body)
        return np.asarray(out["logits"], np.float32)

    def create_model(self, model_sign: str, uri: str, *, replica_num: int = 1,
                     shard_num: int = 1) -> dict:
        return self._request("POST", "/models",
                             {"model_sign": model_sign, "model_uri": uri,
                              "replica_num": replica_num,
                              "shard_num": shard_num})

    def show_models(self) -> dict:
        return self._request("GET", "/models")


class MicroBatcher:
    """Aggregate concurrent /predict requests into one padded device batch.

    The reference delegates serving-side batching to TF-Serving's batcher
    (SavedModel + `documents/en/serving.md`); this is the same role for the
    REST node: a request parks up to `window_ms` waiting for companions, then
    one worker runs the whole group as a single `model.predict` (which pads to
    a power-of-two bucket, so grouped requests also share compiled programs).
    Groups are keyed by (model, feature-key set, id rank) — only structurally
    identical requests merge. Failures propagate to every member of the group.
    """

    def __init__(self, manager: "ModelManager", window_ms: float = 2.0,
                 max_batch: int = 4096):
        self.manager = manager
        self.window_s = window_ms / 1e3
        self.max_batch = max_batch
        self._lock = threading.Lock()
        self._full = threading.Condition(self._lock)
        self._groups: Dict[tuple, list] = {}  # guarded-by: self._lock

    @staticmethod
    def _group_key(sign: str, batch: dict) -> tuple:
        """Only structurally identical requests merge: same feature set AND
        same trailing shapes per feature (np.concatenate needs them), same
        dense width."""
        sparse = batch["sparse"]
        dense = batch.get("dense")
        return (sign,
                tuple((k, np.asarray(v).shape[1:])
                      for k, v in sorted(sparse.items())),
                None if dense is None else np.asarray(dense).shape[1:])

    @staticmethod
    def _request_rows(batch: dict) -> int:
        """Leading-dim row count; an INTERNALLY ragged request fails alone at
        enqueue (never poisoning its groupmates), and an empty request is the
        caller's error (KeyError -> the handler's 400)."""
        from .export import RaggedBatchError
        if not batch["sparse"]:
            raise KeyError("predict request has no sparse features")
        ns = {k: int(np.asarray(v).shape[0])
              for k, v in batch["sparse"].items()}
        if batch.get("dense") is not None:
            ns["dense"] = int(np.asarray(batch["dense"]).shape[0])
        if len(set(ns.values())) != 1:
            raise RaggedBatchError(
                f"ragged serving batch: row counts {ns}")
        return next(iter(ns.values()))

    def predict(self, model, sign: str, batch: dict) -> np.ndarray:
        """Blocking: returns this request's logits slice. `model` is the
        handler's already-resolved servable (resolving again inside the
        window would turn a mid-window DELETE into the wrong error class)."""
        n = self._request_rows(batch)
        entry = {"batch": batch, "n": n, "done": threading.Event(),
                 "out": None, "err": None, "t0": time.monotonic()}
        key = self._group_key(sign, batch)
        with self._lock:
            group = self._groups.setdefault(key, [])
            group.append(entry)
            leader = len(group) == 1
            if not leader and sum(e["n"] for e in group) >= self.max_batch:
                self._full.notify_all()  # wake the leader early
        # oelint: disable=atomicity -- leadership is decided once at enqueue
        # (len==1 under the lock) and never contested: followers only wait,
        # and the pop under the re-taken lock is the leader's own key, so the
        # snapshot cannot go stale between the two critical sections
        if leader:
            # the first arrival owns the window + the device call; a full
            # group releases it before the window expires
            with trace.span("serving", "queue_wait", role="leader", rows=n):
                deadline = time.monotonic() + self.window_s
                with self._lock:
                    while (time.monotonic() < deadline
                           and sum(e["n"] for e in self._groups.get(key, ()))
                           < self.max_batch):
                        self._full.wait(timeout=max(
                            0.0, deadline - time.monotonic()))
                    group = self._groups.pop(key, [])
            with trace.span("serving", "batch_exec", requests=len(group),
                            rows=sum(e["n"] for e in group)):
                self._run(model, group)
        else:
            # a follower's wait covers enqueue -> its group's exec finishing
            # (it cannot observe the run start; the leader's spans split it)
            with trace.span("serving", "queue_wait", role="follower", rows=n):
                entry["done"].wait()
        entry["done"].wait()
        if entry["err"] is not None:
            raise entry["err"]
        return entry["out"]

    def _run(self, model, group: list) -> None:
        # chunk so one merged call never exceeds max_batch rows
        chunk, rows = [], 0
        for e in group:
            if chunk and rows + e["n"] > self.max_batch:
                self._run_chunk(model, chunk)
                chunk, rows = [], 0
            chunk.append(e)
            rows += e["n"]
        if chunk:
            self._run_chunk(model, chunk)

    # oelint: hot-path -- every merged predict runs through here; the single
    # np.asarray(model.predict(...)) below is the ONE device sync per batch
    def _run_chunk(self, model, group: list) -> None:
        from .utils import metrics
        # window tunability (the `window_ms` knob): how long requests parked
        # waiting for companions, and how full the merged batch came out —
        # published next to predict_batches/predict_requests so the trade
        # reads straight off /metrics instead of guesswork
        now = time.monotonic()
        for e in group:
            metrics.observe("serving.batch_wait_ms",
                            (now - e["t0"]) * 1e3, "avg")
        metrics.observe("serving.batch_fill_ratio",
                        min(1.0, sum(e["n"] for e in group) / self.max_batch),
                        "avg")
        try:
            batches = [e["batch"] for e in group]
            merged = {"sparse": {
                k: np.concatenate([np.asarray(b["sparse"][k])
                                   for b in batches])
                for k in batches[0]["sparse"]}}
            if batches[0].get("dense") is not None:
                merged["dense"] = np.concatenate(
                    [np.asarray(b["dense"]) for b in batches])
            with trace.span("serving", "model_call",
                            rows=sum(e["n"] for e in group)):
                logits = np.asarray(model.predict(merged))
            metrics.observe("serving.predict_batches", 1)
            metrics.observe("serving.predict_requests", len(group))
            off = 0
            for e in group:
                e["out"] = logits[off:off + e["n"]]
                off += e["n"]
        except Exception as err:  # noqa: BLE001 — delivered to every waiter
            for e in group:
                e["err"] = err
        finally:
            for e in group:
                e["done"].set()


def restore_from_peer(peer: str, model_sign: str, dest: str, *,
                      page: int = 1 << 16, timeout: float = 60.0) -> str:
    """Rebuild a model's standalone export from a LIVE serving peer over REST.

    The reference replaces a dead serving node by iterating another replica's
    shard via (iterator_id, offset) cursors and shipping batched
    indices+weights (`server/EmbeddingRestoreOperator.cpp:19-106`,
    `entry/server.cc:52-55` `--restore`). Here the new node pages the peer's
    `:exportmeta` / `:rows` / `:dense` endpoints and writes a standard
    standalone export under `dest` — no shared filesystem required. Register
    `dest` with the local node (POST /models) to finish the restore.

    Crash safety: everything pages into `dest + ".tmp-<pid>"` and renames
    into place only after the LAST byte (meta/config included) is on disk —
    a mid-page peer death, timeout, or local crash can never leave a
    half-written export at `dest` for a later `ModelManager.create_model`
    to happily load. A pre-existing `dest` (e.g. a prior complete restore)
    is replaced only at that final swap.

    Returns `dest`. Raises on a peer error or a non-NORMAL model.
    """
    import io
    import shutil
    import urllib.request
    from urllib.parse import quote

    def get(path: str) -> bytes:
        req = urllib.request.Request(f"{peer}{path}",
                                     headers=trace.inject_headers())
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.read()

    entry = json.loads(get(f"/models/{model_sign}"))
    if entry.get("status") != "NORMAL":
        raise RuntimeError(
            f"peer model {model_sign!r} is {entry.get('status')!r}, "
            "not restorable")
    manifest = json.loads(get(f"/models/{model_sign}:exportmeta"))

    tmp = dest.rstrip("/\\") + f".tmp-{os.getpid()}"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    try:
        _page_restore(get, manifest, model_sign, tmp, peer, page,
                      final_uri=dest)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)  # never leave partial pages
        raise
    if os.path.exists(dest):
        shutil.rmtree(dest)  # replaced only once tmp is COMPLETE
    os.replace(tmp, dest)
    return dest


def _page_restore(get, manifest, model_sign: str, dest: str, peer: str,
                  page: int, final_uri: str) -> None:
    """Page the peer's rows/dense/meta into `dest` (restore_from_peer's
    staging dir — the caller owns atomic-rename/cleanup); the written meta
    records `final_uri`, where the export will land after the rename."""
    import io
    from urllib.parse import quote

    os.makedirs(dest, exist_ok=True)
    for v in manifest["variables"]:
        vdir = os.path.join(dest, f"variable_{v['variable_id']}")
        os.makedirs(vdir, exist_ok=True)
        chunks: Dict[str, list] = {"weights": [], "ids": []}
        for start in range(0, max(v["rows"], 1), page):
            if start >= v["rows"]:
                break  # zero-row table: write empty payloads below
            data = np.load(io.BytesIO(get(
                f"/models/{model_sign}:rows"
                f"?var={quote(v['storage_name'], safe='')}"
                f"&start={start}&count={page}")))
            chunks["weights"].append(data["weights"])
            if "ids" in data:
                chunks["ids"].append(data["ids"])
        w = (np.concatenate(chunks["weights"]) if chunks["weights"]
             else np.zeros((0, v["dim"]), np.float32))
        if w.shape[0] != v["rows"]:
            raise RuntimeError(
                f"peer returned {w.shape[0]} rows for {v['storage_name']!r}, "
                f"manifest says {v['rows']} (model changed mid-restore?)")
        np.save(os.path.join(vdir, "weights.npy"), w)
        if v["kind"] == "hash":
            ids = (np.concatenate(chunks["ids"]) if chunks["ids"]
                   else np.zeros((0,), np.int64))
            np.save(os.path.join(vdir, "ids.npy"), ids)

    dense = np.load(io.BytesIO(get(f"/models/{model_sign}:dense")))
    np.savez(os.path.join(dest, "dense_params.npz"),
             **{k: dense[k] for k in dense.files})

    meta = dict(manifest["meta"])
    meta["uri"] = final_uri
    meta["num_shards"] = 1  # the restored artifact is a standalone export
    # keep the written meta consistent with the written files: the peer's meta
    # may describe a sharded checkpoint (dense_manifest incl. __embeddings__/
    # entries that export_dense filters out, no `extra` block)
    meta["dense_manifest"] = {
        k: {"shape": list(dense[k].shape), "dtype": str(dense[k].dtype)}
        for k in dense.files}
    meta["extra"] = {"standalone": True,
                     "restored_from": f"{peer}/models/{model_sign}"}
    from .checkpoint import MODEL_META_FILE
    with open(os.path.join(dest, MODEL_META_FILE), "w") as f:
        json.dump(meta, f, indent=2, sort_keys=True)
    if manifest.get("model_config") is not None:
        from .export import MODEL_CONFIG_FILE
        with open(os.path.join(dest, MODEL_CONFIG_FILE), "w") as f:
            json.dump(manifest["model_config"], f, indent=2, sort_keys=True)


def make_server(registry_root: str, host: str = "127.0.0.1", port: int = 0, *,
                batch_window_ms: float = 0.0, max_batch: int = 4096,
                publish: Optional[Dict[str, str]] = None,
                publish_wire: Optional[str] = None,
                peers: Optional[list] = None
                ) -> ThreadingHTTPServer:
    """Build (not start) the serving HTTP server; port 0 picks a free port.
    `batch_window_ms > 0` turns on predict micro-batching (`MicroBatcher`).
    `publish` ({model_sign: persist_root}) registers online-sync publishers
    (the trainer-side half of `sync/`; more can be added at runtime via
    POST /models/<sign>/publish, and subscribers attach via
    POST /models/<sign>/sync). `peers` (base URLs of other fleet nodes)
    seeds the `GET /fleetz` merged-metrics scrape set (overridable per
    request with `?peers=`)."""
    registry = ModelRegistry(registry_root)
    manager = ModelManager(registry)

    class Handler(ServingHandler):
        pass

    Handler.manager = manager
    Handler.batcher = (MicroBatcher(manager, window_ms=batch_window_ms,
                                    max_batch=max_batch)
                       if batch_window_ms > 0 else None)
    Handler.publishers = {}
    Handler.subscribers = {}
    Handler.peers = list(peers or [])
    if publish:
        from .sync import SyncPublisher
        for sign, root in publish.items():
            Handler.publishers[sign] = SyncPublisher(root, wire=publish_wire)
    Handler.node_info = {"node_id": f"{os.uname().nodename}:{os.getpid()}",
                         "registry": registry_root,
                         "batch_window_ms": batch_window_ms,
                         "publishes": sorted(Handler.publishers),
                         "peers": Handler.peers}
    httpd = ThreadingHTTPServer((host, port), Handler)
    httpd.manager = manager
    httpd.publishers = Handler.publishers
    httpd.subscribers = Handler.subscribers
    return httpd


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="openembedding_tpu serving node (REST admin + inference)")
    ap.add_argument("--registry", required=True, help="registry root directory")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=8501)
    ap.add_argument("--batch-window-ms", type=float, default=0.0,
                    help="micro-batch concurrent /predict requests inside this "
                         "window (0 = off; the reference's TF-Serving batcher "
                         "role)")
    ap.add_argument("--max-batch", type=int, default=4096,
                    help="largest merged predict batch (rows)")
    ap.add_argument("--publish", action="append", default=[],
                    metavar="SIGN=PERSIST_ROOT",
                    help="serve this persist root's committed delta chain as "
                         "the online-sync feed for SIGN (repeatable)")
    ap.add_argument("--sync-from", action="append", default=[],
                    metavar="SIGN=FEED_URL",
                    help="keep the loaded model SIGN fresh against a "
                         "publisher node's feed (repeatable; the model must "
                         "be loaded on this node)")
    ap.add_argument("--sync-interval", type=float, default=1.0,
                    help="subscriber poll interval, seconds")
    ap.add_argument("--sync-wire", default=None,
                    help="row encoding on the sync wire "
                         "(fp32|bf16|int8; default fp32)")
    ap.add_argument("--peers", action="append", default=[], metavar="URL",
                    help="other fleet nodes' base URLs (repeatable, or "
                         "comma-separated): GET /fleetz on this node merges "
                         "their /metrics with its own (counters + histogram "
                         "buckets sum, gauges keep an instance label)")
    ap.add_argument("--flight-recorder", type=int, default=0, metavar="N",
                    help="resize the span/event flight recorder ring buffer "
                         "(0 keeps the default; tail shows on GET /statusz, "
                         "full contents on GET /tracez)")
    ap.add_argument("--trace-dump", default=None, metavar="PATH",
                    help="on shutdown, write the flight recorder as "
                         "Chrome-trace JSON to PATH (chrome://tracing / "
                         "Perfetto; summarize with tools/trace_report.py)")
    ap.add_argument("--slo-specs", default=None, metavar="PATH",
                    help="JSON list of SLO specs (utils/slo.py; default: the "
                         "built-in predict-p99 / sync-freshness / numerics "
                         "set). Verdicts on GET /sloz and the /statusz panel")
    ap.add_argument("--slo-interval", type=float, default=0.0,
                    help="also evaluate SLOs on a background thread every S "
                         "seconds (0 = only on /sloz//statusz scrapes) — "
                         "breaches land in the flight recorder even when "
                         "nobody is scraping")
    ap.add_argument("--capsule-dir", default=None, metavar="DIR",
                    help="arm postmortem capsules: SLO breaches, WeaveLeaks "
                         "and POST /capsule write capsule-*.json.gz bundles "
                         "(flight tail + history rings + memory ledger) "
                         "here; render with tools/capsule_report.py")
    args = ap.parse_args(argv)
    if args.flight_recorder > 0:
        trace.configure(args.flight_recorder)
    if args.capsule_dir:
        from .utils import capsule
        capsule.configure(args.capsule_dir)
        capsule.register_context(
            "serving", lambda: {"argv": list(argv) if argv else None,
                                "registry": args.registry,
                                "host": args.host, "port": args.port})
    from .utils import slo
    if args.slo_specs:
        slo.configure(slo.load_specs(args.slo_specs))
    slo_eval = None
    if args.slo_interval > 0:
        slo.EVALUATOR.interval_s = args.slo_interval
        slo_eval = slo.EVALUATOR.start()

    def kv(pairs, what):
        out = {}
        for p in pairs:
            if "=" not in p:
                ap.error(f"--{what} expects SIGN=VALUE, got {p!r}")
            k, v = p.split("=", 1)
            out[k] = v
        return out

    httpd = make_server(args.registry, args.host, args.port,
                        batch_window_ms=args.batch_window_ms,
                        max_batch=args.max_batch,
                        publish=kv(args.publish, "publish"),
                        publish_wire=args.sync_wire,
                        peers=[p for arg in args.peers
                               for p in arg.split(",") if p])
    from .sync import SyncSubscriber
    for sign, feed in kv(args.sync_from, "sync-from").items():
        httpd.subscribers[sign] = SyncSubscriber(
            httpd.manager, sign, feed, wire=args.sync_wire,
            interval_s=args.sync_interval).start()
    print(f"serving on http://{args.host}:{httpd.server_address[1]} "
          f"(registry: {args.registry})")
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        for sub in httpd.subscribers.values():
            sub.stop()
        if slo_eval is not None:
            slo_eval.stop()
        if args.trace_dump:
            print(f"trace dump: {trace.dump_chrome(args.trace_dump)}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
