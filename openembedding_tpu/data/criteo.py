"""Criteo input pipeline: streaming TSV reader, hashing, folding, synthetic data.

reference: `test/benchmark/criteo_deepctr.py:168-240` (CSV/TFRecord/Criteo-1TB TSV
readers with tf.data interleave + prefetch) and the relabel-by-frequency
preprocessors (`test/criteo_preprocess.cpp`, `examples/criteo_preprocess.py`).

TPU-first notes:
- All categorical fields fold into ONE id space (`criteo_fold_offsets` /
  `hash_category` with per-field salts) so the train step pulls (B, 26) ids in a
  single all_to_all (see `models/__init__.py`).
- The host pipeline must stay off the critical path (SURVEY.md §7 hard parts): the
  reader yields fixed-shape numpy batches; `prefetch_to_device` double-buffers
  `jax.device_put` so step N+1's transfer overlaps step N's compute. A native C++
  parser (`native/`) replaces the Python row parser when built.
- Multi-host: pass (host_id, num_hosts) and each host reads its interleaved slice of
  rows — the reference's per-worker file sharding, without a coordinator.

Criteo row format (label \\t I1..I13 \\t C1..C26): integer features log-transformed
(log(x+4)^2 per the reference preprocessor, `examples/criteo_preprocess.py`),
categorical hex tokens hashed.
"""

from __future__ import annotations

import gzip
import itertools
import queue as queue_mod
import threading
import time
from typing import Dict, Iterator, Optional, Sequence

import numpy as np

NUM_DENSE = 13
NUM_SPARSE = 26

_FNV_OFFSET = np.uint64(0xCBF29CE484222325)
_FNV_PRIME = np.uint64(0x100000001B3)


def hash_category(token_hash: np.ndarray, field: np.ndarray,
                  id_space: int) -> np.ndarray:
    """Map (token hash, field index) -> folded id in [0, id_space).

    Salting by field keeps distinct fields' tokens apart in the shared table —
    the moral equivalent of the reference's per-variable hash spaces (input_dim=-1
    tables hash into 2^63 per variable, `exb.py:396-401`)."""
    h = (token_hash.astype(np.uint64) ^ _FNV_OFFSET) * _FNV_PRIME
    h ^= (field.astype(np.uint64) + np.uint64(0x9E3779B97F4A7C15))
    h *= _FNV_PRIME
    h &= np.uint64(0x7FFFFFFFFFFFFFFF)
    return (h % np.uint64(id_space)).astype(np.int64)


def criteo_fold_offsets(vocab_sizes: Sequence[int]) -> np.ndarray:
    """Per-field offsets for folding per-field id spaces into one table
    (relabel-by-frequency data uses contiguous per-field vocabs; reference keeps
    them as separate variables, we concatenate: field f's id i -> offsets[f]+i)."""
    return np.concatenate([[0], np.cumsum(vocab_sizes)[:-1]]).astype(np.int64)


def _parse_rows(rows, id_space: int):
    """rows: list of tab-split string fields."""
    n = len(rows)
    labels = np.zeros((n,), np.float32)
    dense = np.zeros((n, NUM_DENSE), np.float32)
    sparse = np.zeros((n, NUM_SPARSE), np.int64)
    fields = np.arange(NUM_SPARSE, dtype=np.uint64)
    for r, cols in enumerate(rows):
        labels[r] = float(cols[0]) if cols[0] else 0.0
        for i in range(NUM_DENSE):
            v = cols[1 + i]
            x = float(v) if v else 0.0
            dense[r, i] = np.square(np.log(max(x, 0.0) + 4.0))
        # tokens wider than 64 bits saturate (strtoull semantics — keeps the
        # native C++ parser bit-identical on malformed/overlong tokens)
        toks = np.array(
            [min(int(cols[1 + NUM_DENSE + i], 16), 0xFFFFFFFFFFFFFFFF)
             if cols[1 + NUM_DENSE + i] else i
             for i in range(NUM_SPARSE)], dtype=np.uint64)
        sparse[r] = hash_category(toks, fields, id_space)
    return labels, dense, sparse


def read_criteo_tsv(paths, batch_size: int, *, id_space: int = 1 << 25,
                    host_id: int = 0, num_hosts: int = 1,
                    drop_remainder: bool = True,
                    repeat: bool = False,
                    native: str = "auto",
                    native_threads: int = 4) -> Iterator[Dict]:
    """Stream Criteo TSV (optionally .gz) files into fixed-shape batches.

    Rows are interleaved across hosts (row i goes to host i % num_hosts) — the
    per-worker sharding the reference gets from tf.data `shard()`.

    `native`: "auto" uses the C++ parse pipeline (`native/oetpu_data.cpp`) when
    it builds — plain TSV and .gz alike (zlib inflates in the IO thread) —
    falling back to this Python parser; "on" requires it; "off" forces
    Python. Remote URIs always stream through `utils.fs` (Python path)."""
    if isinstance(paths, str):
        paths = [paths]
    if native not in ("auto", "on", "off"):
        raise ValueError(f"bad native mode {native!r}")
    from ..utils import fs as fsmod
    any_remote = any(fsmod.is_remote(str(p)) for p in paths)
    if any_remote and native == "on":
        raise ValueError("native reader reads local files only; remote URIs "
                         "stream through utils.fs (native='off'/'auto')")
    if native != "off" and not any_remote:
        # .gz reads natively too (zlib in the C++ pipeline — Criteo-1TB
        # ships day_*.gz)
        reader = None
        try:
            # only CONSTRUCTION falls back (no compiler / bad build); a failure
            # mid-stream must propagate — silently restarting from row 0 on the
            # Python path would feed duplicate rows into training
            from .. import native as native_mod
            reader = native_mod.NativeCriteoReader(
                paths, batch_size, id_space=id_space, host_id=host_id,
                num_hosts=num_hosts, num_threads=native_threads,
                drop_remainder=drop_remainder, repeat=repeat)
        except (RuntimeError, OSError):
            if native == "on":
                raise
        if reader is not None:
            yield from reader
            return
    while True:
        pending = []
        for path in paths:
            from contextlib import ExitStack
            stack = ExitStack()
            if fsmod.is_remote(str(path)):
                # sequential stream through the URI's adapter (the reference's
                # hadoop-pipe read, `EmbeddingShardFile.h`); .gz decodes on
                # the fly. GzipFile does NOT close its fileobj, so the pipe
                # reader (whose close() waits the subprocess and surfaces a
                # nonzero exit) enters the stack explicitly — a mid-stream
                # transport failure must propagate, same invariant as the
                # native reader above.
                import io
                raw = stack.enter_context(fsmod.open_stream(str(path), "rb"))
                f = stack.enter_context(io.TextIOWrapper(
                    gzip.GzipFile(fileobj=raw) if str(path).endswith(".gz")
                    else raw))
            else:
                opener = gzip.open if str(path).endswith(".gz") else open
                f = stack.enter_context(opener(path, "rt"))
            with stack:
                for i, line in enumerate(f):
                    if i % num_hosts != host_id:
                        continue
                    cols = line.rstrip("\n").split("\t")
                    if len(cols) < 1 + NUM_DENSE + NUM_SPARSE:
                        cols = cols + [""] * (1 + NUM_DENSE + NUM_SPARSE - len(cols))
                    pending.append(cols)
                    if len(pending) == batch_size:
                        labels, dense, sparse = _parse_rows(pending, id_space)
                        yield {"sparse": {"categorical": sparse},
                               "dense": dense, "label": labels}
                        pending = []
        if pending and not drop_remainder:
            labels, dense, sparse = _parse_rows(pending, id_space)
            yield {"sparse": {"categorical": sparse}, "dense": dense,
                   "label": labels}
        if not repeat:
            return


def _fold_int_ids(sparse_cols: np.ndarray, id_space: Optional[int],
                  vocab_sizes: Optional[Sequence[int]]) -> np.ndarray:
    """Fold per-field integer ids (preprocessed/relabeled data) into the shared
    table: contiguous offsets when per-field vocab sizes are known (reference
    keeps separate variables; we concatenate), else field-salted hashing."""
    n, f = sparse_cols.shape
    if vocab_sizes is not None:
        offs = criteo_fold_offsets(vocab_sizes)
        return sparse_cols.astype(np.int64) + offs[None, :]
    fields = np.broadcast_to(np.arange(f, dtype=np.uint64), (n, f))
    return hash_category(sparse_cols.astype(np.uint64), fields,
                         id_space or (1 << 25))


def read_criteo_tfrecord(paths, batch_size: int, *,
                         id_space: Optional[int] = None,
                         vocab_sizes: Optional[Sequence[int]] = None,
                         host_id: int = 0, num_hosts: int = 1,
                         drop_remainder: bool = True,
                         repeat: bool = False,
                         engine: str = "tf") -> Iterator[Dict]:
    """Stream the reference's TFRecord format (`test/benchmark/criteo_tfrecord.py`:
    label int64[1], I1..I13 float32[1], C1..C26 int64[1] — categorical already
    relabeled to ints). `engine="tf"` uses tf.data (import-guarded so the core
    library never depends on TF); `engine="native"` uses the C++ reader
    (`native.NativeCriteoTFRecordReader` — no TF at all, CRC-verified framing,
    threaded proto parse) and yields bit-identical batches."""
    if engine == "native":
        from ..native import NativeCriteoTFRecordReader
        for batch in NativeCriteoTFRecordReader(
                paths, batch_size, host_id=host_id, num_hosts=num_hosts,
                drop_remainder=drop_remainder, repeat=repeat):
            yield {"sparse": {"categorical": _fold_int_ids(
                       batch["sparse"]["categorical"], id_space, vocab_sizes)},
                   "dense": batch["dense"],
                   "label": batch["label"]}
        return
    if engine != "tf":
        raise ValueError(f"engine must be 'tf' or 'native', got {engine!r}")
    import tensorflow as tf  # local import: optional dependency

    if isinstance(paths, str):
        paths = [paths]
    columns = {"label": tf.io.FixedLenFeature([1], tf.int64)}
    for i in range(1, NUM_DENSE + 1):
        columns[f"I{i}"] = tf.io.FixedLenFeature([1], tf.float32)
    for i in range(1, NUM_SPARSE + 1):
        columns[f"C{i}"] = tf.io.FixedLenFeature([1], tf.int64)

    ds = tf.data.Dataset.from_tensor_slices(list(paths))
    # cycle_length=1: deterministic file-sequential record order on EVERY
    # machine (AUTOTUNE picks a core-count-dependent interleave width, which
    # silently changes the data order between hosts); the native reader
    # (`engine="native"`) pins the same order
    ds = ds.interleave(lambda p: tf.data.TFRecordDataset(p), cycle_length=1,
                       num_parallel_calls=tf.data.AUTOTUNE)
    if num_hosts > 1:
        ds = ds.shard(num_hosts, host_id)
    ds = ds.batch(batch_size, drop_remainder=drop_remainder)
    if repeat:
        # repeat AFTER batch: per-epoch batch boundaries, the same repeat
        # semantics as every other reader here (TSV/CSV/native restart the
        # pass per epoch; batches never span epochs)
        ds = ds.repeat()
    ds = ds.map(lambda x: tf.io.parse_example(x, columns),
                num_parallel_calls=tf.data.AUTOTUNE)
    ds = ds.prefetch(tf.data.AUTOTUNE)
    for ex in ds.as_numpy_iterator():
        dense = np.concatenate([ex[f"I{i}"] for i in range(1, NUM_DENSE + 1)],
                               axis=1).astype(np.float32)
        cats = np.concatenate([ex[f"C{i}"] for i in range(1, NUM_SPARSE + 1)],
                              axis=1)
        yield {"sparse": {"categorical": _fold_int_ids(cats, id_space,
                                                       vocab_sizes)},
               "dense": dense,
               "label": ex["label"].reshape(-1).astype(np.float32)}


def read_criteo_csv(path, batch_size: int, *, id_space: Optional[int] = None,
                    vocab_sizes: Optional[Sequence[int]] = None,
                    host_id: int = 0, num_hosts: int = 1,
                    drop_remainder: bool = True,
                    repeat: bool = False) -> Iterator[Dict]:
    """Stream the reference's preprocessed CSV (header `,label,I1..I13,C1..C26`,
    dense already normalized floats, categorical already relabeled ints —
    `examples/train100.csv`)."""
    import csv

    while True:
        with open(path, newline="") as f:
            reader = csv.reader(f)
            header = next(reader)
            col = {name: j for j, name in enumerate(header)}
            ncol_i = [col[f"I{i}"] for i in range(1, NUM_DENSE + 1)]
            ncol_c = [col[f"C{i}"] for i in range(1, NUM_SPARSE + 1)]
            lcol = col["label"]
            rows = []
            for i, line in enumerate(reader):
                if i % num_hosts != host_id:
                    continue
                rows.append(line)
                if len(rows) == batch_size:
                    yield _csv_batch(rows, lcol, ncol_i, ncol_c, id_space,
                                     vocab_sizes)
                    rows = []
            if rows and not drop_remainder:
                yield _csv_batch(rows, lcol, ncol_i, ncol_c, id_space,
                                 vocab_sizes)
        if not repeat:
            return


def _csv_batch(rows, lcol, ncol_i, ncol_c, id_space, vocab_sizes) -> Dict:
    n = len(rows)
    labels = np.asarray([float(r[lcol] or 0) for r in rows], np.float32)
    dense = np.asarray([[float(r[j] or 0) for j in ncol_i] for r in rows],
                       np.float32)
    cats = np.asarray([[int(r[j] or 0) for j in ncol_c] for r in rows],
                      np.int64)
    return {"sparse": {"categorical": _fold_int_ids(cats, id_space,
                                                    vocab_sizes)},
            "dense": dense, "label": labels}


def synthetic_criteo(batch_size: int, *, id_space: int = 1 << 25,
                     num_fields: int = NUM_SPARSE, dense_dim: int = NUM_DENSE,
                     seed: int = 0, alpha: float = 1.05,
                     steps: Optional[int] = None,
                     ids_dtype=np.int64) -> Iterator[Dict]:
    """Synthetic Criteo-like stream with Zipfian ids (hot-key skew like real CTR
    logs — exercises the dedup path the way Criteo does; uniform ids would make
    dedup look uselessly cheap). Labels come from a fixed random linear model so
    loss actually decreases in smoke tests."""
    rng = np.random.default_rng(seed)
    w_dense = rng.normal(size=(dense_dim,)).astype(np.float32) * 0.3
    it = itertools.count() if steps is None else range(steps)
    for _ in it:
        # Zipf via inverse-CDF on uniform: id = floor(u^(-1/(alpha-1))) clipped
        u = rng.random((batch_size, num_fields))
        raw = np.floor(np.clip(u ** (-1.0 / (alpha - 1.0)), 1.0, 2.0 ** 62)
                       ).astype(np.int64)
        fields = np.broadcast_to(np.arange(num_fields, dtype=np.uint64),
                                 (batch_size, num_fields))
        ids64 = hash_category(raw.astype(np.uint64), fields, id_space)
        if ids_dtype == "pair":
            # the split-pair 63-bit layout for x64-off runs (ops/id64.py)
            from ..ops.id64 import np_split_ids
            ids = np_split_ids(ids64)
        else:
            ids = ids64.astype(ids_dtype)
        dense = rng.normal(size=(batch_size, dense_dim)).astype(np.float32)
        logit = (dense @ w_dense
                 + 0.01 * (ids64 % 97 - 48).sum(axis=1) / num_fields)
        labels = (rng.random(batch_size) < 1.0 / (1.0 + np.exp(-logit))
                  ).astype(np.float32)
        yield {"sparse": {"categorical": ids}, "dense": dense, "label": labels}


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Deterministic 64-bit mix (SplitMix64) — the per-id weight hash for the
    planted-signal generator; vectorized, no Python loops."""
    x = (x + np.uint64(0x9E3779B97F4A7C15)).astype(np.uint64)
    x = ((x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)).astype(np.uint64)
    x = ((x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)).astype(np.uint64)
    return x ^ (x >> np.uint64(31))


def planted_logit(ids64: np.ndarray, *, seed: int = 0,
                  scale: float = 8.0) -> np.ndarray:
    """The TRUE logit of a planted-signal batch: each id contributes a fixed
    hash-derived weight in (-1, 1); the logit is `scale * mean_over_fields`.
    Deterministic in (id, seed) — this is the generative model's own scoring
    function, so its held-out AUC is the Bayes-optimal target a trained model
    is graded against."""
    h = _splitmix64(ids64.astype(np.uint64) ^ np.uint64(0xA5A5_0000 + seed))
    w = (h >> np.uint64(11)).astype(np.float64) / float(1 << 53) * 2.0 - 1.0
    return (scale * w.mean(axis=-1)).astype(np.float32)


def planted_criteo(batch_size: int, *, id_space: int = 1 << 15,
                   num_fields: int = 8, seed: int = 0, alpha: float = 1.05,
                   steps: Optional[int] = None, scale: float = 8.0,
                   label_seed: int = 1, ids_dtype=np.int32) -> Iterator[Dict]:
    """Criteo-like stream with a PLANTED id-conditional signal (the reference
    validates its benchmark models by AUC on real Criteo,
    `test/benchmark/criteo_deepctr.py`; real terabytes don't fit a test
    battery, so this generator makes held-out AUC a regression metric with a
    KNOWN optimum): ids are Zipfian like `synthetic_criteo`, labels are
    Bernoulli(sigmoid(planted_logit(ids))). Any model containing a per-id
    linear term (LR, W&D, DeepFM first order) can represent the true scorer
    exactly, so its held-out AUC must approach `planted_logit`'s own — see
    `tests/test_planted_auc.py`."""
    rng = np.random.default_rng(seed)
    it = itertools.count() if steps is None else range(steps)
    for _ in it:
        u = rng.random((batch_size, num_fields))
        raw = np.floor(np.clip(u ** (-1.0 / (alpha - 1.0)), 1.0, 2.0 ** 62)
                       ).astype(np.int64)
        fields = np.broadcast_to(np.arange(num_fields, dtype=np.uint64),
                                 (batch_size, num_fields))
        ids64 = hash_category(raw.astype(np.uint64), fields, id_space)
        logit = planted_logit(ids64, seed=label_seed, scale=scale)
        labels = (rng.random(batch_size) < 1.0 / (1.0 + np.exp(-logit))
                  ).astype(np.float32)
        if ids_dtype == "pair":
            from ..ops.id64 import np_split_ids
            ids = np_split_ids(ids64)
        else:
            ids = ids64.astype(ids_dtype)
        yield {"sparse": {"categorical": ids}, "dense": None, "label": labels}


def _rows_concat(a: Dict, b: Dict) -> Dict:
    out = {"sparse": {k: np.concatenate([a["sparse"][k], b["sparse"][k]])
                      for k in a["sparse"]},
           "label": np.concatenate([a["label"], b["label"]])}
    if a.get("dense") is not None:
        out["dense"] = np.concatenate([a["dense"], b["dense"]])
    if "weight" in a or "weight" in b:
        wa = a.get("weight", np.ones_like(a["label"]))
        wb = b.get("weight", np.ones_like(b["label"]))
        out["weight"] = np.concatenate([wa, wb])
    return out


def _rows_slice(batch: Dict, lo: int, hi: int) -> Dict:
    return {k: ({k2: v2[lo:hi] for k2, v2 in v.items()} if k == "sparse"
                else v[lo:hi])
            for k, v in batch.items() if v is not None}


class CriteoBatcher:
    """Rebatches any row iterator to a fixed batch size: splits oversized incoming
    batches, carries remainders across batches, and pads the final partial batch.
    Padded rows get id -1 (pulls zeros, grads dropped) and a `weight` of 0 — the
    loss fns weight samples so pad rows contribute nothing (unlike the reference,
    whose tf.data `drop_remainder` just discards the tail)."""

    def __init__(self, it: Iterator[Dict], batch_size: int):
        self.it = it
        self.batch_size = batch_size

    def __iter__(self):
        B = self.batch_size
        buf: Optional[Dict] = None
        for batch in self.it:
            buf = batch if buf is None else _rows_concat(buf, batch)
            n = buf["label"].shape[0]
            lo = 0
            while n - lo >= B:
                yield _rows_slice(buf, lo, lo + B)
                lo += B
            buf = _rows_slice(buf, lo, n) if lo else buf
            if buf["label"].shape[0] == 0:
                buf = None
        if buf is not None and buf["label"].shape[0] > 0:
            n = buf["label"].shape[0]
            pad = B - n
            out = {
                "sparse": {k: np.concatenate(
                    [v, np.full((pad,) + v.shape[1:], -1, v.dtype)])
                    for k, v in buf["sparse"].items()},
                "label": np.concatenate(
                    [buf["label"], np.zeros((pad,), np.float32)]),
                "weight": np.concatenate(
                    [buf.get("weight", np.ones((n,), np.float32)),
                     np.zeros((pad,), np.float32)]),
            }
            if buf.get("dense") is not None:
                out["dense"] = np.concatenate(
                    [buf["dense"], np.zeros((pad,) + buf["dense"].shape[1:],
                                            buf["dense"].dtype)])
            yield out


def pad_ragged(seqs, width: Optional[int] = None, dtype=np.int64) -> np.ndarray:
    """Variable-length id lists -> a static (len(seqs), width) array padded
    with -1 (= invalid in every lookup path: pad slots pull zero rows, train
    nothing, and combiner pooling masks them out). The host-side half of the
    framework's RaggedTensor answer (reference `Variable.sparse_read` accepts
    ragged, `exb.py:308-327`; static TPU shapes make pad+mask the idiomatic
    equivalent — see `embedding.combine`).

    width=None uses the batch's own max length (min 1 so the array is never
    0-wide). A sequence LONGER than an explicit width is an error — silent
    truncation would drop features the caller thinks are training."""
    lens = [len(s) for s in seqs]
    w = max(lens, default=0) or 1 if width is None else width
    out = np.full((len(lens), w), -1, dtype)
    for r, s in enumerate(seqs):
        if len(s) > w:
            raise ValueError(
                f"pad_ragged: sequence {r} has {len(s)} ids > width {w}; "
                "raise `width` (truncate explicitly if that's what you want)")
        out[r, :len(s)] = np.asarray(s, dtype)
    return out


def is_ragged(ids) -> bool:
    """True for a list/tuple/object-array of variable-length id sequences —
    the inputs `pad_ragged` exists for. Rectangular nested lists and real
    ndarrays are NOT ragged (they coerce directly)."""
    if isinstance(ids, np.ndarray):
        return ids.dtype == object
    if not isinstance(ids, (list, tuple)) or not ids:
        return False
    if not all(isinstance(s, (list, tuple, np.ndarray)) for s in ids):
        return False
    return len({len(s) for s in ids}) > 1


def prefetch_to_device(it: Iterator, size: int = 2,
                       sharding=None) -> Iterator:
    """Background-thread device prefetch: overlaps host parsing + H2D transfer with
    device compute (the reference's `pulling()` dataset prefetch + tf.data
    AUTOTUNE, `exb.py:645-691`). With a NamedSharding, batches land pre-sharded.

    Telemetry (the `ingest.*` family, label `ring="prefetch"`): queue
    occupancy (`ingest.queue_depth`), time the producer spent blocked on a
    full queue (`ingest.producer_stall_ms` — nonzero stall = the consumer is
    the bottleneck, the healthy compute-bound state), and items discarded by
    an early consumer exit (`ingest.dropped`). The depth-D generalization
    with mesh staging, parse workers and window stacking lives in
    `data.ingest.FeedRing`; this stays the minimal single-stream path (and
    keeps the single-device_get discipline — gauges are host counters)."""
    import jax

    from ..utils import metrics

    _labels = {"ring": "prefetch"}
    q: queue_mod.Queue = queue_mod.Queue(maxsize=size)
    _END = object()
    stop = threading.Event()

    def _put(item) -> bool:
        """Bounded put: gives up once the consumer has left (a consumer that
        abandons the generator would otherwise strand the producer blocked
        forever on the full queue — the thread leak this replaces). Any put
        that could not land immediately counts its whole blocked time into
        the stall counter (including the final, possibly-successful wait —
        a put that waits 49ms then lands is still a 49ms stall)."""
        try:
            q.put_nowait(item)
            return True
        except queue_mod.Full:
            pass
        t0 = time.perf_counter()
        try:
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.05)
                    return True
                except queue_mod.Full:
                    continue
            return False
        finally:
            metrics.observe("ingest.producer_stall_ms",
                            (time.perf_counter() - t0) * 1e3, "sum",
                            labels=_labels)

    def producer():
        try:
            for item in it:
                if sharding is not None:
                    item = jax.device_put(item, sharding)
                else:
                    item = jax.tree_util.tree_map(jax.numpy.asarray, item)
                if not _put(item):
                    return
                metrics.observe("ingest.queue_depth", float(q.qsize()),
                                "gauge", labels=_labels)
            _put(_END)
        except BaseException as e:  # propagate to the consumer, don't fake EOF
            _put(e)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        dropped = 0
        while True:  # unblock a producer mid-put, then reap it
            try:
                item = q.get_nowait()
            except queue_mod.Empty:
                break
            if item is not _END and not isinstance(item, BaseException):
                dropped += 1
        if dropped:
            metrics.observe("ingest.dropped", float(dropped), "sum",
                            labels=_labels)
        t.join(timeout=5)
