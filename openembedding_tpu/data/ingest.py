"""Line-rate ingest: per-host file-sharded streaming input, a bounded
multi-worker parse pool with a deterministic reorder stage, and a depth-D
device feed ring — the composed, instrumented feed path the reference gets
from tf.data interleave+prefetch and the `pulling()` dataset (PAPER.md data
path; `test/benchmark/criteo_deepctr.py:168-240`).

Round 18 software-pipelined the train loop so the sparse exchange overlaps
dense compute; this module is the other half of the ROADMAP "ingest at line
rate" item — nothing upstream of `train_many` should sit on the critical
path either, and when it does, it must be MEASURED, not guessed:

- `sharded_files` / `sharded_reader`: each host reads only its slice of the
  FILE list (no global shuffle barrier, no coordinator — the per-worker file
  sharding the reference gets from tf.data `shard()`, lifted from rows to
  files so hosts never touch each other's bytes). Epochs re-shard by RING
  ROTATION: epoch e assigns file i to host (i + e) % num_hosts, so every
  epoch covers every file exactly once and each host's working set rotates
  deterministically. Batches never span files — that is the invariant that
  makes per-host sharded reading bit-identical to the single-global-reader
  control (`sharded_reader(num_hosts=1)`), file by file.
- Pluggable SOURCES: "tsv" (native C++/Python Criteo TSV/.gz), "tfrecord"
  (tf or native engines), "synthetic" (spec-string generator for line-rate
  soaks) — or any callable `(path, batch_size, **kw) -> iterator of batch
  dicts`.
- `ParsePool`: a bounded multi-worker parse pool. Work items carry sequence
  numbers end-to-end and a reorder stage re-emits results in dispatch order,
  so batch order is deterministic regardless of worker scheduling — the
  determinism tf.data's AUTOTUNE interleave silently gives up (see the
  cycle_length=1 note in `criteo.read_criteo_tfrecord`).
- `FeedRing`: `prefetch_to_device` generalized to depth D with the mesh
  batch sharding from `parallel/multihost` — host parse -> staging
  `device_put` -> a bounded ring of already-resident (optionally stacked
  K-step window) batches, so H2D copies overlap the scan the same way round
  18 overlapped the collectives. The round-19 lifecycle hardening carries
  over: bounded stop-aware puts (an abandoned consumer can never strand the
  producer), exceptions propagate through the ring instead of faking EOF,
  and `close()` drains and joins every thread.
- Attribution: the ring publishes `ingest.*` gauges/counters (examples/s,
  bytes/s, queue depth per ring slot, parse/stage ms, producer stall time,
  dropped items); the trainer side times how long it blocks on the next
  batch into the StepWatch `trainer.input_wait_ms` lane
  (`utils/stepwatch.timed_batches`, wired by `Trainer.input_timed` /
  `MeshTrainer.train_stream`), and `input_wait_share()` folds the two into
  the single number an SLO can gate (tools/ingest_slo.json: input-bound vs
  compute-bound is a verdict, not a vibe).

Everything here is HOST-side: no jitted program changes, no new
collectives — the hlo-budget pins are delta 0 by construction.
"""

from __future__ import annotations

import queue as queue_mod
import threading
import time
from typing import Callable, Dict, Iterable, Iterator, List, Optional

import numpy as np

from ..utils import metrics

__all__ = ["FeedRing", "ParsePool", "SOURCES", "feed", "input_wait_share",
           "register_source", "ring_shard", "sharded_files", "sharded_reader"]

_END = object()          # producer -> consumer: clean end of stream
_TASK_END = object()     # dispatcher -> worker: no more tasks
_WORKER_EXIT = object()  # worker -> reorder stage: this worker is done


# ---------------------------------------------------------------------------
# per-host file sharding with ring-rotation epoch re-sharding
# ---------------------------------------------------------------------------


def ring_shard(num_files: int, host_id: int, num_hosts: int,
               epoch: int = 0) -> List[int]:
    """File indices host `host_id` owns in `epoch`: i with
    (i + epoch) % num_hosts == host_id, ascending. The union over hosts is
    every file exactly once; bumping the epoch rotates the assignment by one
    host, so across num_hosts epochs every host has read every file."""
    if not 0 <= host_id < num_hosts:
        raise ValueError(f"host_id {host_id} not in [0, {num_hosts})")
    return [i for i in range(num_files)
            if (i + epoch) % num_hosts == host_id]


def sharded_files(files, *, host_id: Optional[int] = None,
                  num_hosts: Optional[int] = None,
                  epochs: Optional[int] = 1,
                  start_epoch: int = 0) -> Iterator[tuple]:
    """-> (epoch, file_index, path) for this host's slice, epoch-major then
    ascending file index — the deterministic work list `sharded_reader`
    (and its ParsePool) consumes. `epochs=None` streams forever; host
    identity defaults to the live process (`multihost.host_id()`)."""
    if isinstance(files, str):
        files = [files]
    files = list(files)
    if host_id is None or num_hosts is None:
        from ..parallel import multihost
        host_id = multihost.host_id() if host_id is None else host_id
        num_hosts = multihost.num_hosts() if num_hosts is None else num_hosts
    epoch = start_epoch
    while epochs is None or epoch < start_epoch + epochs:
        for i in ring_shard(len(files), host_id, num_hosts, epoch):
            yield (epoch, i, files[i])
        epoch += 1


# ---------------------------------------------------------------------------
# pluggable per-file sources
# ---------------------------------------------------------------------------


def _tsv_source(path: str, batch_size: int, **kw) -> Iterator[Dict]:
    """One Criteo TSV/.gz file -> batches (native C++ parser when it builds;
    `kw` passes through to `criteo.read_criteo_tsv`). Host sharding is NOT
    applied here — the file list is already sharded."""
    from .criteo import read_criteo_tsv
    return read_criteo_tsv([path], batch_size, host_id=0, num_hosts=1, **kw)


def _tfrecord_source(path: str, batch_size: int, **kw) -> Iterator[Dict]:
    """One TFRecord file -> batches (`engine="tf"` or `"native"`)."""
    from .criteo import read_criteo_tfrecord
    return read_criteo_tfrecord([path], batch_size, host_id=0, num_hosts=1,
                                **kw)


def _synthetic_source(path: str, batch_size: int, **kw) -> Iterator[Dict]:
    """A `synthetic://k=v&k=v` spec string -> `criteo.synthetic_criteo`
    batches. Understood keys: steps, seed, id_space, fields, dense, alpha —
    e.g. `synthetic://steps=8&seed=3&id_space=4096`. A list of spec strings
    with distinct seeds is the saturating no-IO "file set" the line-rate
    soak shards exactly like real days."""
    from .criteo import synthetic_criteo
    spec = dict(kw)
    body = str(path).split("://", 1)[1] if "://" in str(path) else ""
    for part in body.split("&"):
        if not part:
            continue
        k, _, v = part.partition("=")
        spec[k] = v
    return synthetic_criteo(
        batch_size,
        id_space=int(spec.get("id_space", 1 << 25)),
        num_fields=int(spec.get("fields", 26)),
        dense_dim=int(spec.get("dense", 13)),
        seed=int(spec.get("seed", 0)),
        alpha=float(spec.get("alpha", 1.05)),
        steps=int(spec.get("steps", 1)))


SOURCES: Dict[str, Callable[..., Iterator[Dict]]] = {
    "tsv": _tsv_source,
    "tfrecord": _tfrecord_source,
    "synthetic": _synthetic_source,
}


def register_source(name: str, fn: Callable[..., Iterator[Dict]]) -> None:
    """Register a custom source: `fn(path, batch_size, **kw)` -> iterator of
    batch dicts for ONE file (batches must not span files — the sharding
    bit-identity invariant)."""
    SOURCES[name] = fn


def _batch_rows(batch: Dict) -> int:
    leaf = batch.get("label")
    if leaf is None:
        leaf = next(iter(batch["sparse"].values()))
    return int(np.asarray(leaf).shape[0])


def _batch_bytes(batch) -> int:
    total = 0
    for leaf in _np_leaves(batch):
        total += getattr(np.asarray(leaf), "nbytes", 0)
    return total


def _np_leaves(tree) -> Iterator:
    if isinstance(tree, dict):
        for v in tree.values():
            yield from _np_leaves(v)
    elif tree is not None:
        yield tree


def _bounded_put(q: queue_mod.Queue, item, stop: threading.Event,
                 stall_ms: Optional[List[float]] = None) -> bool:
    """Stop-aware bounded put (the round-19 `prefetch_to_device` idiom): a
    consumer that abandons the stream can never strand a producer blocked
    forever on a full queue. Returns False once `stop` is set. Any put that
    could not land immediately accumulates its whole blocked time into
    `stall_ms[0]` (including the final, possibly-successful wait)."""
    try:
        q.put_nowait(item)
        return True
    except queue_mod.Full:
        pass
    t0 = time.perf_counter() if stall_ms is not None else 0.0
    try:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue_mod.Full:
                continue
        return False
    finally:
        if stall_ms is not None:
            stall_ms[0] += (time.perf_counter() - t0) * 1e3


# ---------------------------------------------------------------------------
# the bounded multi-worker parse pool with a sequence-numbered reorder stage
# ---------------------------------------------------------------------------


class ParsePool:
    """Parse work items on `workers` threads; emit results in DISPATCH order.

    Every task is numbered when dispatched; workers tag their result (or the
    exception the parse raised) with that number and the consuming iterator
    holds out-of-order results in a reorder buffer until the next sequence
    number arrives — output order is a pure function of the input order, not
    of worker scheduling. The buffer is bounded in practice by the tasks in
    flight (task queue + workers + result queue), never by luck.

    `parse_fn(task)` returns an arbitrary payload (for file ingest: the
    file's full batch list — files here are shards, sized to fit in host
    memory many times over). A parse failure is delivered AT ITS SEQUENCE
    POSITION: everything parsed before the bad file still comes out, in
    order, then the exception raises.

    Lifecycle: `close()` (idempotent, also the iterator's exhaustion/abandon
    path and `__exit__`) stops dispatch, drains both queues, counts undelivered
    results into `ingest.dropped`, and joins every thread."""

    def __init__(self, tasks: Iterable, parse_fn: Callable, *,
                 workers: int = 2, depth: Optional[int] = None,
                 label: str = "pool"):
        if workers < 1:
            raise ValueError(f"ParsePool(workers={workers}): need >= 1")
        self._parse_fn = parse_fn
        self._tasks_it = iter(tasks)
        self._labels = {"pool": label}
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._tasks_q: queue_mod.Queue = queue_mod.Queue(maxsize=workers)
        self._out_q: queue_mod.Queue = queue_mod.Queue(
            maxsize=depth if depth else 2 * workers)
        # guarded-by: self._lock (close() swaps them out before joining)
        self._workers = [
            threading.Thread(target=self._work, name=f"ingest-parse-{i}",
                             daemon=True)
            for i in range(workers)]
        self._num_workers = workers
        # guarded-by: self._lock
        self._dispatcher = threading.Thread(
            target=self._dispatch, name="ingest-dispatch", daemon=True)
        for t in self._workers:
            t.start()
        self._dispatcher.start()

    # -- producer side --------------------------------------------------------

    def _dispatch(self) -> None:
        seq = 0
        try:
            for task in self._tasks_it:
                if not _bounded_put(self._tasks_q, (seq, task), self._stop):
                    return
                seq += 1
        except BaseException as e:  # the task ITERATOR failed: deliver the
            # fault at its sequence position (after every dispatched task's
            # result), don't fake end-of-stream
            _bounded_put(self._out_q, (seq, e), self._stop)
        finally:
            for _ in range(self._num_workers):
                if not _bounded_put(self._tasks_q, _TASK_END, self._stop):
                    return

    def _work(self) -> None:
        while not self._stop.is_set():
            try:
                item = self._tasks_q.get(timeout=0.05)
            except queue_mod.Empty:
                continue
            if item is _TASK_END:
                _bounded_put(self._out_q, _WORKER_EXIT, self._stop)
                return
            seq, task = item
            t0 = time.perf_counter()
            try:
                payload = self._parse_fn(task)
            except BaseException as e:  # deliver at seq position
                payload = e
            metrics.observe("ingest.parse_ms",
                            (time.perf_counter() - t0) * 1e3, "hist",
                            labels=self._labels)
            if not _bounded_put(self._out_q, (seq, payload), self._stop):
                return

    # -- consumer side: the reorder stage -------------------------------------

    def __iter__(self) -> Iterator:
        buf: Dict[int, object] = {}
        next_seq = 0
        exited = 0
        try:
            while True:
                if next_seq in buf:
                    payload = buf.pop(next_seq)
                    metrics.observe("ingest.reorder_depth", float(len(buf)),
                                    "gauge", labels=self._labels)
                    next_seq += 1
                    if isinstance(payload, BaseException):
                        raise payload
                    yield payload
                    continue
                if exited == self._num_workers and not buf:
                    return  # every worker done, everything emitted in order
                try:
                    item = self._out_q.get(timeout=0.05)
                except queue_mod.Empty:
                    if self._stop.is_set():
                        return  # closed from another thread
                    continue
                if item is _WORKER_EXIT:
                    exited += 1
                    continue
                seq, payload = item
                buf[seq] = payload
        finally:
            self.close()

    def close(self) -> None:
        """Stop + drain + join (idempotent; safe to race)."""
        self._stop.set()
        dropped = 0
        for q in (self._tasks_q, self._out_q):
            while True:
                try:
                    item = q.get_nowait()
                except queue_mod.Empty:
                    break
                if q is self._out_q and isinstance(item, tuple):
                    dropped += 1
        if dropped:
            metrics.observe("ingest.dropped", float(dropped), "sum",
                            labels=self._labels)
        with self._lock:
            t, self._dispatcher = self._dispatcher, None
            ws, self._workers = self._workers, []
        if t is not None:
            t.join(timeout=5)
        for w in ws:
            w.join(timeout=5)

    def __enter__(self) -> "ParsePool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# ---------------------------------------------------------------------------
# the sharded streaming reader (files -> deterministic batch stream)
# ---------------------------------------------------------------------------


def sharded_reader(files, batch_size: int, *,
                   source="tsv",
                   host_id: Optional[int] = None,
                   num_hosts: Optional[int] = None,
                   epochs: Optional[int] = 1, start_epoch: int = 0,
                   workers: int = 0, pool_depth: Optional[int] = None,
                   label: str = "reader",
                   **source_kw) -> Iterator[Dict]:
    """Stream this host's file slice into batches, epoch by epoch.

    `source` names a `SOURCES` entry (or is the callable itself); extra
    keyword arguments pass through to it. `workers=0` parses inline (the
    depth-1 synchronous control); `workers>0` parses files on a `ParsePool`,
    whose reorder stage keeps the batch order bit-identical to the inline
    path. Batches never span files, so the union of every host's stream is
    bit-identical (file by file) to the `num_hosts=1` global reader."""
    if isinstance(source, str):
        if source not in SOURCES:
            raise ValueError(
                f"unknown source {source!r} (known: {sorted(SOURCES)}; "
                "register_source extends)")
        src = SOURCES[source]
    else:
        src = source
    return _sharded_reader(src, files, batch_size, host_id=host_id,
                           num_hosts=num_hosts, epochs=epochs,
                           start_epoch=start_epoch, workers=workers,
                           pool_depth=pool_depth, label=label, **source_kw)


def _sharded_reader(src, files, batch_size, *, host_id, num_hosts, epochs,
                    start_epoch, workers, pool_depth, label, **source_kw):
    tasks = sharded_files(files, host_id=host_id, num_hosts=num_hosts,
                          epochs=epochs, start_epoch=start_epoch)
    if workers <= 0:
        for _epoch, _idx, path in tasks:
            yield from src(path, batch_size, **source_kw)
        return

    def parse_file(task):
        _epoch, _idx, path = task
        return list(src(path, batch_size, **source_kw))

    pool = ParsePool(tasks, parse_file, workers=workers, depth=pool_depth,
                     label=label)
    with pool:
        for batches in pool:
            yield from batches


# ---------------------------------------------------------------------------
# the depth-D device feed ring
# ---------------------------------------------------------------------------


class FeedRing:
    """Depth-D device feed ring: host batches -> already-resident batches.

    A producer thread pulls host batches from `it`, optionally groups them
    into stacked K-step `window`s (leading dim K — the shape
    `MeshTrainer.train_many` scans), stages them onto devices, and parks
    them in a bounded ring of `depth` slots; the consuming thread's
    `next()` returns resident arrays, so the H2D copy of batch/window t+1
    overlaps the device compute of window t. Staging:

    - `mesh`: `multihost.global_batch` (batch dim sharded over `axis`;
      windows use `multihost.window_batch` — leading K replicated for the
      scan). This is the production path.
    - `sharding`: plain `jax.device_put(item, sharding)`.
    - `device=False`: host arrays pass through untouched (pure-host tests,
      the oeweave harness).
    - otherwise: `jnp.asarray` per leaf (default-device staging).

    Telemetry (the attribution lane): `ingest.examples`/`ingest.bytes`
    counters, `ingest.examples_per_sec`/`ingest.bytes_per_sec` gauges,
    `ingest.stage_ms` hist (device_put time), `ingest.queue_depth` +
    per-slot `ingest.slot_fill{slot=}` gauges, `ingest.producer_stall_ms`
    (time the producer spent blocked on a full ring — a nonzero stall with
    zero consumer wait means compute-bound, the healthy state),
    `ingest.consumer_wait_ms` hist (time `next()` blocked — the ring-side
    twin of the trainer's `trainer.input_wait_ms` lane), and
    `ingest.dropped` (staged items discarded by an early `close()`).

    `throttle_s` sleeps the producer per host batch — the deliberately
    input-bound control the soak uses to prove the attribution points the
    right way.

    Lifecycle: same contract as `ParsePool.close` — stop, drain (counting
    drops), join; exceptions from the source propagate through the ring."""

    def __init__(self, it: Iterator, *, depth: int = 2,
                 mesh=None, axis: Optional[str] = None, sharding=None,
                 window: Optional[int] = None, device: bool = True,
                 label: str = "ring", rate_every: int = 8,
                 throttle_s: float = 0.0):
        if depth < 1:
            raise ValueError(f"FeedRing(depth={depth}): need >= 1")
        if window is not None and window < 1:
            raise ValueError(f"FeedRing(window={window}): need >= 1")
        self._it = iter(it)
        self.depth = int(depth)
        self._mesh = mesh
        self._axis = axis
        self._sharding = sharding
        self._window = window
        self._device = device
        self._labels = {"ring": label}
        self._rate_every = max(1, int(rate_every))
        self._throttle_s = float(throttle_s)
        self._q: queue_mod.Queue = queue_mod.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._stall_ms = [0.0]  # [total ms the producer blocked on the ring]
        self.examples = 0       # host rows staged (producer thread only)
        self.bytes = 0          # host bytes staged (producer thread only)
        # guarded-by: self._lock (close() tuple-swaps before joining)
        self._thread = threading.Thread(
            target=self._produce, name=f"ingest-{label}", daemon=True)
        self._thread.start()

    # -- staging --------------------------------------------------------------

    def _axis_name(self) -> str:
        if self._axis is not None:
            return self._axis
        from ..parallel.mesh import DATA_AXIS
        return DATA_AXIS

    def _stage(self, item):
        if not self._device:
            return item
        import jax
        if self._mesh is not None:
            from ..parallel import multihost
            if self._window is not None:
                return multihost.window_batch(item, self._mesh,
                                              self._axis_name())
            return multihost.global_batch(item, self._mesh,
                                          self._axis_name())
        if self._sharding is not None:
            return jax.device_put(item, self._sharding)
        return jax.tree_util.tree_map(jax.numpy.asarray, item)

    def _produce(self) -> None:
        seq = 0
        t_start = time.perf_counter()
        pending: List[Dict] = []
        try:
            for host_item in self._it:
                if self._stop.is_set():
                    return
                if self._throttle_s > 0:
                    time.sleep(self._throttle_s)
                rows = _batch_rows(host_item)
                nbytes = _batch_bytes(host_item)
                if self._window is not None:
                    pending.append(host_item)
                    if len(pending) < self._window:
                        self.examples += rows
                        self.bytes += nbytes
                        continue
                    host_item = _stack_window(pending)
                    pending = []
                t0 = time.perf_counter()
                staged = self._stage(host_item)
                metrics.observe("ingest.stage_ms",
                                (time.perf_counter() - t0) * 1e3, "hist",
                                labels=self._labels)
                if seq == 0:
                    # ring's worth of staged batches = this ring's share of
                    # device memory; shapes are static per ring, so the
                    # first batch prices all depth slots (memwatch ledger)
                    from ..utils import memwatch
                    memwatch.WATCH.set_component(
                        "feed_ring",
                        self.depth * memwatch.tree_device_bytes(staged),
                        labels=self._labels)
                if not _bounded_put(self._q, staged, self._stop,
                                    self._stall_ms):
                    return
                self.examples += rows
                self.bytes += nbytes
                seq += 1
                self._publish(seq, t_start)
            if pending:
                # a trailing partial window can't be scanned; account for it
                metrics.observe("ingest.dropped", float(len(pending)), "sum",
                                labels=self._labels)
            _bounded_put(self._q, _END, self._stop)
        except BaseException as e:  # propagate to the consumer, never fake EOF
            _bounded_put(self._q, e, self._stop)

    def _publish(self, seq: int, t_start: float) -> None:
        depth_now = self._q.qsize()
        metrics.observe("ingest.queue_depth", float(depth_now), "gauge",
                        labels=self._labels)
        slot = dict(self._labels)
        slot["slot"] = str((seq - 1) % self.depth)
        metrics.observe("ingest.slot_fill", float(depth_now), "gauge",
                        labels=slot)
        metrics.observe("ingest.producer_stall_ms", 0.0, "sum",
                        labels=self._labels)  # register the series at 0
        if seq % self._rate_every == 0:
            elapsed = max(time.perf_counter() - t_start, 1e-9)
            metrics.observe("ingest.examples_per_sec",
                            self.examples / elapsed, "gauge",
                            labels=self._labels)
            metrics.observe("ingest.bytes_per_sec", self.bytes / elapsed,
                            "gauge", labels=self._labels)
            stall, self._stall_ms[0] = self._stall_ms[0], 0.0
            if stall:
                metrics.observe("ingest.producer_stall_ms", stall, "sum",
                                labels=self._labels)

    # -- consumer -------------------------------------------------------------

    def __iter__(self) -> "FeedRing":
        return self

    def __next__(self):
        t0 = time.perf_counter()
        while True:
            if self._stop.is_set():
                raise StopIteration
            try:
                item = self._q.get(timeout=0.05)
                break
            except queue_mod.Empty:
                continue
        metrics.observe("ingest.consumer_wait_ms",
                        (time.perf_counter() - t0) * 1e3, "hist",
                        labels=self._labels)
        if item is _END:
            self.close()  # producer already exited: reap it now, not at GC
            raise StopIteration
        if isinstance(item, BaseException):
            self.close()
            raise item
        return item

    def close(self) -> None:
        """Stop + drain (counting staged-but-undelivered items into
        `ingest.dropped`) + join the producer. Idempotent, race-safe; the
        early-exit path every consumer `break` must reach (the round-19
        thread-leak regression class)."""
        self._stop.set()
        dropped = 0
        while True:
            try:
                item = self._q.get_nowait()
            except queue_mod.Empty:
                break
            if item is not _END and not isinstance(item, BaseException):
                dropped += 1
        if dropped:
            metrics.observe("ingest.dropped", float(dropped), "sum",
                            labels=self._labels)
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)

    def __enter__(self) -> "FeedRing":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _stack_window(batches: List[Dict]) -> Dict:
    """K host batches -> one stacked window (leading dim K on every leaf)."""
    def stack(*leaves):
        if leaves[0] is None:
            return None
        return np.stack([np.asarray(x) for x in leaves])
    out: Dict = {}
    for k in batches[0]:
        if k == "sparse":
            out[k] = {f: stack(*[b[k][f] for b in batches])
                      for f in batches[0][k]}
        else:
            out[k] = stack(*[b[k] for b in batches])
    return out


# ---------------------------------------------------------------------------
# the composed feed path + the attribution fold
# ---------------------------------------------------------------------------


def feed(files, batch_size: int, *, mesh=None, axis: Optional[str] = None,
         sharding=None, source="tsv", depth: int = 2,
         window: Optional[int] = None, workers: int = 0,
         epochs: Optional[int] = 1, start_epoch: int = 0,
         host_id: Optional[int] = None, num_hosts: Optional[int] = None,
         device: bool = True, label: str = "feed",
         throttle_s: float = 0.0, **source_kw) -> FeedRing:
    """The whole ingest path in one call: per-host file-sharded streaming
    (`sharded_reader`, with a ParsePool when `workers > 0`) into a depth-D
    `FeedRing` staging onto the mesh. Returns the ring; iterate it for
    already-resident batches (or stacked `window`-step windows for
    `MeshTrainer.train_stream`), and `close()` it (or exhaust it) when done.

        ring = ingest.feed(days, 4096, mesh=mesh, workers=4, depth=3,
                           window=8, epochs=None)
        state, rep = trainer.train_stream(state, ring)
    """
    it = sharded_reader(files, batch_size, source=source, host_id=host_id,
                        num_hosts=num_hosts, epochs=epochs,
                        start_epoch=start_epoch, workers=workers,
                        label=label, **source_kw)
    return FeedRing(it, depth=depth, mesh=mesh, axis=axis, sharding=sharding,
                    window=window, device=device, label=label,
                    throttle_s=throttle_s)


def _peek_hist(name: str) -> tuple:
    """(sum, count) over every label set of one spine metric — a PEEK (never
    creates the accumulator), summed so labeled lanes fold together."""
    with metrics._LOCK:
        accs = [a for a in metrics._REGISTRY.values() if a.name == name]
    total, count = 0.0, 0
    for a in accs:
        if a.kind == "hist":
            snap = a.hist_snapshot()
            total += snap[1]
            count += snap[2]
        else:
            total += a.value()
            count += a.count
    return total, count


def input_wait_share(*, wait_metric: str = "trainer.input_wait_ms",
                     step_metric: str = "auto",
                     publish: bool = True) -> Optional[float]:
    """The attribution number: mean host input-wait per window over mean
    total window wall time, from the metrics spine. `step_metric="auto"`
    prefers the window-cadence lane (`trainer.window_ms`, recorded by
    `MeshTrainer.train_stream`) and falls back to the sampled step lane
    (`trainer.step_ms`). Publishes `ingest.input_wait_share` (the gauge
    tools/ingest_slo.json gates: < 5% = compute-bound) and returns it;
    returns None (publishing nothing) until both lanes have samples."""
    wait_sum, wait_n = _peek_hist(wait_metric)
    if step_metric == "auto":
        step_sum, step_n = _peek_hist("trainer.window_ms")
        if step_n == 0:
            step_sum, step_n = _peek_hist("trainer.step_ms")
    else:
        step_sum, step_n = _peek_hist(step_metric)
    if wait_n == 0 or step_n == 0:
        return None
    wait_mean = wait_sum / wait_n
    step_mean = step_sum / step_n
    denom = wait_mean + step_mean
    if denom <= 0:
        return None
    share = wait_mean / denom
    if publish:
        metrics.observe("ingest.input_wait_share", share, "gauge")
    return share
