"""Input pipelines: Criteo readers, synthetic generators, device prefetch,
and the line-rate ingest subsystem (per-host file-sharded streaming +
parse pool + depth-D device feed ring, `data/ingest.py`).

reference: the benchmark readers in `test/benchmark/criteo_deepctr.py:168-240`
(CSV / TFRecord / Criteo-1TB TSV interleaved readers) and the preprocessors
(`examples/criteo_preprocess.py`, `test/criteo_preprocess.cpp`).
"""

from .criteo import (CriteoBatcher, criteo_fold_offsets, hash_category,
                     is_ragged, pad_ragged, planted_criteo, planted_logit,
                     read_criteo_tsv, synthetic_criteo, prefetch_to_device)
from .ingest import (FeedRing, ParsePool, feed, input_wait_share,
                     register_source, ring_shard, sharded_files,
                     sharded_reader)

__all__ = ["CriteoBatcher", "criteo_fold_offsets", "hash_category",
           "is_ragged", "pad_ragged", "planted_criteo", "planted_logit",
           "read_criteo_tsv", "synthetic_criteo", "prefetch_to_device",
           "FeedRing", "ParsePool", "feed", "input_wait_share",
           "register_source", "ring_shard", "sharded_files",
           "sharded_reader"]
