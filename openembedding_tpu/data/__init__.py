"""Input pipelines: Criteo readers, synthetic generators, device prefetch.

reference: the benchmark readers in `test/benchmark/criteo_deepctr.py:168-240`
(CSV / TFRecord / Criteo-1TB TSV interleaved readers) and the preprocessors
(`examples/criteo_preprocess.py`, `test/criteo_preprocess.cpp`).
"""

from .criteo import (CriteoBatcher, criteo_fold_offsets, hash_category,
                     is_ragged, pad_ragged, planted_criteo, planted_logit,
                     read_criteo_tsv, synthetic_criteo, prefetch_to_device)

__all__ = ["CriteoBatcher", "criteo_fold_offsets", "hash_category",
           "is_ragged", "pad_ragged", "planted_criteo", "planted_logit",
           "read_criteo_tsv", "synthetic_criteo", "prefetch_to_device"]
