"""Embedding table state + the user-facing `Embedding` layer spec.

Counterpart of the reference's user API surface (`tensorflow/exb.py`):
- `EmbeddingSpec` ~ the layer config (`Embedding.__init__`, `exb.py:388-419`):
  input_dim (-1 = 2^63 hashed), output_dim, dtype, initializer, per-variable optimizer,
  num_shards, sparse_as_dense.
- `EmbeddingTableState` ~ the server-side storage for one variable
  (`variable/EmbeddingTable.h` array table + optimizer slots from
  `EmbeddingOptimizerVariable.h`) — here a pytree of jax.Arrays so it shards,
  checkpoints and donates like any other train state.

Row-sharding layout (matches the reference so checkpoints stay resharding-friendly,
`EmbeddingPullOperator.cpp:74-84`): global id `i` lives on shard `i % S`, local row
`i // S`. A single-device table is the S=1 special case.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from flax import struct

from .initializers import Initializer, Uniform, make_initializer
from .meta import EmbeddingVariableMeta, HASH_VOCABULARY_THRESHOLD
from .optimizers import SparseOptimizer, make_optimizer
from .ops.sparse import lookup_rows, sparse_apply_dense_table


class HotRows(struct.PyTreeNode):
    """Replicated hot-row cache for one table (Parallax-style hybrid placement,
    `parallel/sharded.py`): a small trace-time-static set of H heavy-hitter rows
    held IDENTICALLY on every device, so their pulls gather locally (zero
    exchange bytes, zero owner-shard load) and their gradients reduce over the
    data axis like dense params. Chosen/refreshed off the hot path from the
    heavy-hitter sketches (`MeshTrainer.refresh_hot_rows`); persisted never —
    `hot_sync` writes the rows back into their owner shards at snapshot time so
    checkpoints/export/sync stay byte-identical to the hot-off world.

    Membership is a mini open-addressing probe table (`tables/hash_table.py`
    machinery, built host-side by `parallel/sharded.build_hot_identity`):
    `keys` holds the hot ids in the table's key layout at ~2x load headroom,
    `rank` maps a probe slot to its compact hot row in [0, H); empty slots
    carry rank H. `ids` lists the hot ids by rank (padding -1 / PAIR_EMPTY)
    for writeback/refresh bookkeeping."""

    keys: jax.Array               # (C,) or (C, 2) — probe table, table key layout
    rank: jax.Array               # (C,) int32 — probe slot -> hot row; H = empty
    ids: jax.Array                # (H,) or (H, 2) — hot ids by rank
    weights: jax.Array            # (H, dim) — table dtype
    slots: Dict[str, jax.Array]   # name -> (H, k) f32 (replicated optimizer state)


class MigRows(struct.PyTreeNode):
    """Cold-tail re-sharding state for one table (the other half of Parallax-
    style hybrid placement, `parallel/sharded.py` "COLD-TAIL RE-SHARDING"):
    a trace-time-static set of M measured-heavy COLD rows whose owner shard is
    overridden away from the `id % S` hash home, so a hot home shard sheds
    load it cannot shed through replication alone. Unlike `HotRows` the rows
    are NOT replicated — each keeps exactly one owner; only the id -> owner
    DIRECTORY is replicated so every client routes identically.

    The directory is a mini open-addressing probe table (same machinery as
    the hot probe, built host-side by `parallel/sharded.build_mig_identity`):
    `keys` holds the migrated ids at ~2x load headroom, `rank` maps a probe
    slot to the id's compact migration rank in [0, M) (M = empty), `ids` /
    `owners` list the migrated ids and their assigned owner shard by rank.
    `weights`/`slots` are each shard's ANNEX — M spare rows per shard; only
    the assigned owner's copy of a rank is live (the home-shard main-table
    row goes stale while migrated, exactly like a hot row's). `mig_writeback`
    restores the home copies at snapshot/refresh time so checkpoints, export
    and the sync delta feed stay byte-identical to an unmigrated run.
    Chosen/refreshed off the hot path by `MeshTrainer.migrate_rows` (driven
    by `placement.PlacementController`); persisted never."""

    keys: jax.Array               # (C,) or (C, 2) — directory probe, replicated
    rank: jax.Array               # (C,) int32 — probe slot -> rank; M = empty
    ids: jax.Array                # (M,) or (M, 2) — migrated ids by rank
    owners: jax.Array             # (M,) int32 — assigned owner shard; -1 = pad
    weights: jax.Array            # (M, dim) per shard — the annex (SHARDED)
    slots: Dict[str, jax.Array]   # name -> (M, k) per shard (SHARDED)


class EmbeddingTableState(struct.PyTreeNode):
    """One variable's shard-local storage: weights + optimizer slots.

    For `kind == "hash"` tables, `keys` maps slot -> global id (EMPTY sentinel = -1) and
    lookups go through the open-addressing probe (`tables/hash_table.py`).
    """

    weights: jax.Array                    # (rows, dim)
    slots: Dict[str, jax.Array]           # name -> (rows, k)
    keys: Optional[jax.Array] = None      # (rows,) int64, hash tables only
    # cumulative count of ids that failed to insert (hash tables only; the static-
    # capacity divergence from the reference's unbounded table must be observable)
    overflow: Optional[jax.Array] = None  # () int32
    # replicated hot-row cache (MeshTrainer(hot_rows=...); None = off). NOT
    # serialized: checkpoint/persist/export writers see owner-shard rows only,
    # after the trainer's hot_sync writeback.
    hot: Optional[HotRows] = None
    # cold-tail re-sharding directory + annex (MeshTrainer(mig_rows=...);
    # None = off). NOT serialized either: `hot_sync` writes migrated rows
    # back into their home shards before any snapshot/export/delta reader.
    mig: Optional[MigRows] = None
    # per-row error-feedback residuals for the quantized pull wire
    # (MeshTrainer(error_feedback=...); None = off). Sharded and laid out
    # exactly like `weights`, SERIALIZED like an optimizer slot (reserved
    # slot name "__ef__" in sharded checkpoints and persist deltas): the
    # residual is training state — dropping it at restore would re-bias the
    # int8 wire for every row mid-stream.
    ef: Optional[jax.Array] = None        # (rows, dim) f32


@dataclasses.dataclass(frozen=True)
class EmbeddingSpec:
    """Static description of one embedding variable (hashable; safe as a jit static).

    reference parity: `exb.py:388-443` (layer args) + `variable/Meta.h` (variable meta).
    """

    name: str
    input_dim: int                       # -1 -> hashed 63-bit id space (hash table)
    output_dim: int
    datatype: str = "float32"
    initializer: Initializer = dataclasses.field(default_factory=Uniform)
    optimizer: Optional[SparseOptimizer] = None   # None -> use model default
    num_shards: int = -1                 # -1 -> all mesh devices
    sparse_as_dense: bool = False        # small tables: dense mirrored param instead
    capacity: int = 0                    # hash tables: slots per build; 0 = auto
    # "hbm": the whole table lives in device memory. "host_cached": HBM holds a
    # fixed-capacity cache (`capacity` slots) and the full table lives in host RAM
    # (`tables/host_offload.py`) — tables larger than HBM, the reference's per-
    # variable PMem table selection (`EmbeddingInitOperator.cpp:146-168`).
    storage: str = "hbm"
    variable_id: int = -1
    # batch feature this variable reads its ids from; "" = the variable's own
    # name. Lets two variables share one id stream (e.g. a CTR model's
    # first-order dim-1 table beside the latent table — the reference's
    # DeepCTR linear feature columns likewise re-read the same input,
    # `test/benchmark/criteo_deepctr.py`).
    feature: str = ""
    # multivalent-feature pooling over the trailing id axis: "" (no pooling,
    # the layer emits per-slot rows), "sum", "mean" or "sqrtn". The framework's
    # answer to the reference's RaggedTensor `sparse_read` (`exb.py:308-327`,
    # whose downstream Keras graphs pool the ragged rows): variable-length id
    # lists pad to the static field width with -1 (`data.pad_ragged`) and the
    # pooling masks the pad slots out of both the value and the gradient, so
    # the result equals true varlen pooling (TF's safe_embedding_lookup_sparse
    # combiners) with static TPU-friendly shapes.
    combiner: str = ""

    def __post_init__(self):
        if self.input_dim == 0 or self.input_dim < -1:
            raise ValueError(f"invalid input_dim {self.input_dim}")
        if self.output_dim <= 0:
            raise ValueError(f"invalid output_dim {self.output_dim}")
        if self.storage not in ("hbm", "host_cached"):
            raise ValueError(f"invalid storage {self.storage!r} "
                             "(expected 'hbm' or 'host_cached')")
        if self.storage == "host_cached" and not self.use_hash_table:
            raise ValueError(
                f"embedding {self.name!r}: storage='host_cached' needs a "
                "hash-table variable (input_dim=-1 + capacity) — the device "
                "cache is keyed by id, not by dense row position")
        if self.combiner not in ("", "sum", "mean", "sqrtn"):
            raise ValueError(
                f"embedding {self.name!r}: unknown combiner "
                f"{self.combiner!r} (expected '', 'sum', 'mean' or 'sqrtn')")
        if self.storage == "host_cached" and self.sparse_as_dense:
            raise ValueError(
                f"embedding {self.name!r}: sparse_as_dense (dense-mirrored "
                "'Cache' mode) and storage='host_cached' are mutually "
                "exclusive — a dense mirror bypasses the two-tier table")

    @property
    def use_hash_table(self) -> bool:
        return self.input_dim == -1 or self.input_dim >= HASH_VOCABULARY_THRESHOLD

    @property
    def vocabulary_size(self) -> int:
        return HASH_VOCABULARY_THRESHOLD if self.use_hash_table else self.input_dim

    @property
    def feature_name(self) -> str:
        """The batch["sparse"] key this variable's ids come from."""
        return self.feature or self.name

    @property
    def meta(self) -> EmbeddingVariableMeta:
        return EmbeddingVariableMeta(
            datatype=self.datatype,
            embedding_dim=self.output_dim,
            vocabulary_size=-1 if self.use_hash_table else self.input_dim,
        )

    @property
    def dtype(self):
        return jnp.dtype(self.datatype) if self.datatype != "bfloat16" else jnp.bfloat16

    def rows_per_shard(self, num_shards: int) -> int:
        """ceil(vocab / S), the reference's `reserve_items`
        (`EmbeddingInitOperator.cpp:146-168`)."""
        if self.use_hash_table:
            if self.capacity <= 0:
                raise ValueError(
                    f"hash-table variable {self.name!r} needs an explicit capacity")
            return -(-self.capacity // num_shards)
        return -(-self.input_dim // num_shards)

    def device_bytes(self, optimizer: SparseOptimizer, num_shards: int, *,
                     need_ef: bool = False) -> Dict[str, int]:
        """Analytic PER-DEVICE byte model of this table's base state at
        shard count S, by subcomponent — the shapes `MeshTrainer.
        init_tables` materializes, priced without materializing them
        (utils/memwatch ledger; pinned exact against the live arrays by
        tests). Key lanes cost 8 bytes/row in BOTH layouts (one int64 or a
        uint32 pair); the replicated overflow scalar rides `keys`."""
        rows = self.rows_per_shard(num_shards)
        item = jnp.dtype(self.dtype).itemsize
        out = {
            "weights": rows * self.output_dim * item,
            "slots": rows * 4 * sum(
                optimizer.slot_shapes(self.output_dim).values()),
        }
        if self.use_hash_table:
            out["keys"] = rows * 8 + 4
        if need_ef:
            out["ef"] = rows * self.output_dim * 4
        return out

    def to_config(self) -> dict:
        return {
            "name": self.name,
            "input_dim": self.input_dim,
            "output_dim": self.output_dim,
            "datatype": self.datatype,
            "initializer": self.initializer.to_config(),
            "optimizer": self.optimizer.to_config() if self.optimizer else None,
            "num_shards": self.num_shards,
            "sparse_as_dense": self.sparse_as_dense,
            "capacity": self.capacity,
            "storage": self.storage,
            "variable_id": self.variable_id,
            "feature": self.feature,
            "combiner": self.combiner,
        }

    @classmethod
    def from_config(cls, d: dict) -> "EmbeddingSpec":
        d = dict(d)
        d["initializer"] = make_initializer(d["initializer"])
        d["optimizer"] = make_optimizer(d["optimizer"]) if d.get("optimizer") else None
        return cls(**d)


# ---------------------------------------------------------------------------
# Functional table ops (single shard / single device).  The sharded versions in
# `parallel/sharded.py` run these on each device's shard under shard_map.
# ---------------------------------------------------------------------------


def init_table_state(spec: EmbeddingSpec, optimizer: SparseOptimizer,
                     seed: int = 0, num_shards: int = 1,
                     shard_id: int = 0,
                     error_feedback: bool = False) -> EmbeddingTableState:
    """Materialize one shard's table (reference: lazy `_new_weights` init on first pull,
    `EmbeddingOptimizerVariable.h:242-266`; we init rows eagerly — deterministic per
    (seed, shard), documented divergence: RNG stream differs from lazy order).
    `error_feedback` adds the zero-initialized per-row residual array the
    quantized pull wire accumulates into (`parallel/sharded._serve_rows`)."""
    rows = spec.rows_per_shard(num_shards)
    # fold_in needs uint32 data; the unassigned sentinel (-1, specs built
    # outside an EmbeddingModel, e.g. a bare EmbeddingVariable) maps to a slot
    # no real variable_id reaches (2^15: 131071 * 2^15 still fits uint32)
    # instead of raising OverflowError. Streams of assigned ids are unchanged.
    vid = spec.variable_id if spec.variable_id >= 0 else (1 << 15)
    key = jax.random.fold_in(jax.random.PRNGKey(seed),
                             vid * 131071 + shard_id)
    weights = spec.initializer(key, (rows, spec.output_dim), spec.dtype)
    slots = optimizer.init_slots(rows, spec.output_dim, spec.dtype)
    keys = None
    overflow = None
    if spec.use_hash_table:
        # x64 on: int64 single-lane keys; x64 off (the default): uint32
        # split-pair keys — 63-bit ids in EITHER config (ops/id64.py)
        from .tables.hash_table import fresh_keys
        keys = fresh_keys(rows)
        overflow = jnp.zeros((), jnp.int32)
    ef = (jnp.zeros((rows, spec.output_dim), jnp.float32)
          if error_feedback else None)
    return EmbeddingTableState(weights=weights, slots=slots, keys=keys,
                               overflow=overflow, ef=ef)


def _flat_ids(spec: EmbeddingSpec, ids: jax.Array):
    """-> (flat ids, row-output shape): split-pair ids ((..., 2) uint32,
    `ops/id64.py`) keep their lane dim flat and drop it from the output.
    Pair dispatch is gated on `use_hash_table`: a uint32 two-field batch on an
    array table must NOT be misread as one 63-bit id per row."""
    from .ops.id64 import is_pair
    if spec.use_hash_table and is_pair(ids):
        return ids.reshape(-1, 2), ids.shape[:-1]
    return ids.reshape(-1), ids.shape


def lookup(spec: EmbeddingSpec, state: EmbeddingTableState,
           ids: jax.Array) -> jax.Array:
    """Single-shard pull: ids (any shape) -> rows (ids.shape + (dim,)).
    reference: `Variable.sparse_read`/`pull_weights` (`exb.py:308-327`)."""
    flat, out_shape = _flat_ids(spec, ids)
    if spec.use_hash_table:
        from .tables.hash_table import hash_lookup
        rows = hash_lookup(state, flat)
    else:
        rows = lookup_rows(state.weights, flat)
    return rows.reshape(out_shape + (spec.output_dim,))


def lookup_train(spec: EmbeddingSpec, state: EmbeddingTableState,
                 ids: jax.Array):
    """Training pull: like `lookup` but hash tables insert unseen ids (lazy init).
    Returns (new_state, rows). Array tables never mutate on pull."""
    flat, out_shape = _flat_ids(spec, ids)
    if spec.use_hash_table:
        from .tables.hash_table import hash_lookup_train
        state, rows = hash_lookup_train(state, flat)
    else:
        rows = lookup_rows(state.weights, flat)
    return state, rows.reshape(out_shape + (spec.output_dim,))


def valid_mask(spec: EmbeddingSpec, ids: jax.Array) -> jax.Array:
    """True where an id slot holds a real id — single-lane ids >= 0, split
    pairs via `pair_valid` (`ops/id64.py`). Shape = `lookup`'s row-output
    shape (the pair lane dim is dropped), so it broadcasts against rows."""
    from .ops.id64 import is_pair, pair_valid
    ids = jnp.asarray(ids)
    if spec.use_hash_table and is_pair(ids):
        return pair_valid(ids)
    return ids >= 0


def np_valid_mask(spec: EmbeddingSpec, ids) -> "np.ndarray":
    """Host-side twin of `valid_mask` for serving paths that hold the ORIGINAL
    numpy ids. They must mask from the numpy array, not from `jnp.asarray(ids)`:
    with x64 off that conversion truncates 63-bit int64 ids to int32, flipping
    real ids whose bit 31 is set to negative — `valid_mask` would silently
    mark them padding and drop their (correctly fetched) rows from the pool."""
    import numpy as np
    ids = np.asarray(ids)
    from .ops.id64 import HI_INVALID, is_pair
    if spec.use_hash_table and is_pair(ids):
        return ids[..., 0] < HI_INVALID
    return ids >= 0


def combine(spec: EmbeddingSpec, ids, rows: jax.Array,
            mask=None) -> jax.Array:
    """Pool multivalent rows (..., F, dim) over the id axis F per
    `spec.combiner`; identity when no combiner is set. Pad slots (-1 /
    EMPTY-pair ids) contribute zero to the pooled value AND receive zero
    gradient through the mask multiply — independent of the separate
    negative-ids-never-train row guarantee. mean/sqrtn divide by the VALID
    count (clamped >= 1: an all-pad row pools to zeros instead of NaN), which
    is exactly TF's safe_embedding_lookup_sparse combiner semantics — the op
    the reference's ragged `sparse_read` consumers feed (`exb.py:308-327`).

    `mask` overrides the id-derived validity — serving paths pass
    `np_valid_mask` computed on the original host int64 ids, which a device
    conversion could truncate (see np_valid_mask)."""
    if not spec.combiner:
        return rows
    m = jnp.asarray(mask) if mask is not None else valid_mask(spec, ids)
    if m.ndim < 2:
        raise ValueError(
            f"embedding {spec.name!r}: combiner={spec.combiner!r} needs ids "
            f"of shape (batch, fields), got rank {m.ndim}")
    mf = m.astype(rows.dtype)[..., None]
    s = jnp.sum(rows * mf, axis=-2)
    if spec.combiner == "sum":
        return s
    cnt = jnp.maximum(jnp.sum(mf, axis=-2), jnp.asarray(1, rows.dtype))
    if spec.combiner == "mean":
        return s / cnt
    return s / jnp.sqrt(cnt)


def serve_rows(spec: EmbeddingSpec, ids, lookup_fn) -> jax.Array:
    """The ONE serving-side embed: `lookup_fn(ids)` + combiner pooling with
    the validity mask taken from the ORIGINAL host ids (np_valid_mask — a
    device conversion would truncate 63-bit int64 ids under x64-off). Both
    `StandaloneModel.predict` and `parallel.ShardedModel.predict` route
    through here so the mask invariant lives in one place."""
    rows = lookup_fn(ids)
    if spec.combiner:
        rows = combine(spec, None, rows, mask=np_valid_mask(spec, ids))
    return rows


def apply_gradients(spec: EmbeddingSpec, state: EmbeddingTableState,
                    optimizer: SparseOptimizer, ids: jax.Array,
                    grads: jax.Array) -> EmbeddingTableState:
    """Single-shard push+update fused: duplicate grads summed, optimizer applied once
    per unique id (reference: push `EmbeddingPushOperator.cpp` + store
    `EmbeddingStoreOperator.cpp` collapsed into one step — SPMD needs no batch gate)."""
    flat_ids, _ = _flat_ids(spec, ids)
    flat_grads = grads.reshape(-1, spec.output_dim)
    if spec.use_hash_table:
        from .tables.hash_table import hash_apply_gradients
        return hash_apply_gradients(state, optimizer, flat_ids, flat_grads)
    weights, slots = sparse_apply_dense_table(
        optimizer, state.weights, state.slots, flat_ids, flat_grads)
    return state.replace(weights=weights, slots=slots)


class Embedding:
    """Drop-in layer handle, mirroring `exb.Embedding` (`exb.py:388-443`).

    Collects itself into the enclosing `EmbeddingModel`'s variable list; the actual
    compute is functional (lookup / apply_gradients) driven by the Trainer.
    """

    def __init__(self, input_dim: int, output_dim: int, *, name: str,
                 datatype: str = "float32",
                 embeddings_initializer: Optional[Initializer] = None,
                 optimizer: Optional[SparseOptimizer] = None,
                 num_shards: int = -1,
                 sparse_as_dense: bool = False,
                 capacity: int = 0,
                 storage: str = "hbm",
                 feature: str = "",
                 combiner: str = ""):
        self.spec = EmbeddingSpec(
            name=name,
            input_dim=input_dim,
            output_dim=output_dim,
            datatype=datatype,
            initializer=embeddings_initializer or Uniform(),
            optimizer=optimizer,
            num_shards=num_shards,
            sparse_as_dense=sparse_as_dense,
            capacity=capacity,
            storage=storage,
            feature=feature,
            combiner=combiner,
        )

    def __repr__(self):
        return f"Embedding({self.spec.name}: {self.spec.input_dim}x{self.spec.output_dim})"
