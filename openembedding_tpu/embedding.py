"""Embedding table state + the user-facing `Embedding` layer spec.

Counterpart of the reference's user API surface (`tensorflow/exb.py`):
- `EmbeddingSpec` ~ the layer config (`Embedding.__init__`, `exb.py:388-419`):
  input_dim (-1 = 2^63 hashed), output_dim, dtype, initializer, per-variable optimizer,
  num_shards, sparse_as_dense.
- `EmbeddingTableState` ~ the server-side storage for one variable
  (`variable/EmbeddingTable.h` array table + optimizer slots from
  `EmbeddingOptimizerVariable.h`) — here a pytree of jax.Arrays so it shards,
  checkpoints and donates like any other train state.

Row-sharding layout (matches the reference so checkpoints stay resharding-friendly,
`EmbeddingPullOperator.cpp:74-84`): global id `i` lives on shard `i % S`, local row
`i // S`. A single-device table is the S=1 special case.
"""

from __future__ import annotations

import dataclasses
import warnings
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from flax import struct

from .initializers import Initializer, Uniform, make_initializer
from .meta import EmbeddingVariableMeta, HASH_VOCABULARY_THRESHOLD
from .optimizers import SparseOptimizer, make_optimizer
from .ops.sparse import lookup_rows, sparse_apply_dense_table


class EmbeddingTableState(struct.PyTreeNode):
    """One variable's shard-local storage: weights + optimizer slots.

    For `kind == "hash"` tables, `keys` maps slot -> global id (EMPTY sentinel = -1) and
    lookups go through the open-addressing probe (`tables/hash_table.py`).
    """

    weights: jax.Array                    # (rows, dim)
    slots: Dict[str, jax.Array]           # name -> (rows, k)
    keys: Optional[jax.Array] = None      # (rows,) int64, hash tables only
    # cumulative count of ids that failed to insert (hash tables only; the static-
    # capacity divergence from the reference's unbounded table must be observable)
    overflow: Optional[jax.Array] = None  # () int32


@dataclasses.dataclass(frozen=True)
class EmbeddingSpec:
    """Static description of one embedding variable (hashable; safe as a jit static).

    reference parity: `exb.py:388-443` (layer args) + `variable/Meta.h` (variable meta).
    """

    name: str
    input_dim: int                       # -1 -> hashed 63-bit id space (hash table)
    output_dim: int
    datatype: str = "float32"
    initializer: Initializer = dataclasses.field(default_factory=Uniform)
    optimizer: Optional[SparseOptimizer] = None   # None -> use model default
    num_shards: int = -1                 # -1 -> all mesh devices
    sparse_as_dense: bool = False        # small tables: dense mirrored param instead
    capacity: int = 0                    # hash tables: slots per build; 0 = auto
    # "hbm": the whole table lives in device memory. "host_cached": HBM holds a
    # fixed-capacity cache (`capacity` slots) and the full table lives in host RAM
    # (`tables/host_offload.py`) — tables larger than HBM, the reference's per-
    # variable PMem table selection (`EmbeddingInitOperator.cpp:146-168`).
    storage: str = "hbm"
    variable_id: int = -1
    # batch feature this variable reads its ids from; "" = the variable's own
    # name. Lets two variables share one id stream (e.g. a CTR model's
    # first-order dim-1 table beside the latent table — the reference's
    # DeepCTR linear feature columns likewise re-read the same input,
    # `test/benchmark/criteo_deepctr.py`).
    feature: str = ""

    def __post_init__(self):
        if self.input_dim == 0 or self.input_dim < -1:
            raise ValueError(f"invalid input_dim {self.input_dim}")
        if self.output_dim <= 0:
            raise ValueError(f"invalid output_dim {self.output_dim}")
        if self.storage not in ("hbm", "host_cached"):
            raise ValueError(f"invalid storage {self.storage!r} "
                             "(expected 'hbm' or 'host_cached')")
        if self.storage == "host_cached" and not self.use_hash_table:
            raise ValueError(
                f"embedding {self.name!r}: storage='host_cached' needs a "
                "hash-table variable (input_dim=-1 + capacity) — the device "
                "cache is keyed by id, not by dense row position")
        if self.storage == "host_cached" and self.sparse_as_dense:
            raise ValueError(
                f"embedding {self.name!r}: sparse_as_dense (dense-mirrored "
                "'Cache' mode) and storage='host_cached' are mutually "
                "exclusive — a dense mirror bypasses the two-tier table")

    @property
    def use_hash_table(self) -> bool:
        return self.input_dim == -1 or self.input_dim >= HASH_VOCABULARY_THRESHOLD

    @property
    def vocabulary_size(self) -> int:
        return HASH_VOCABULARY_THRESHOLD if self.use_hash_table else self.input_dim

    @property
    def feature_name(self) -> str:
        """The batch["sparse"] key this variable's ids come from."""
        return self.feature or self.name

    @property
    def meta(self) -> EmbeddingVariableMeta:
        return EmbeddingVariableMeta(
            datatype=self.datatype,
            embedding_dim=self.output_dim,
            vocabulary_size=-1 if self.use_hash_table else self.input_dim,
        )

    @property
    def dtype(self):
        return jnp.dtype(self.datatype) if self.datatype != "bfloat16" else jnp.bfloat16

    def rows_per_shard(self, num_shards: int) -> int:
        """ceil(vocab / S), the reference's `reserve_items`
        (`EmbeddingInitOperator.cpp:146-168`)."""
        if self.use_hash_table:
            if self.capacity <= 0:
                raise ValueError(
                    f"hash-table variable {self.name!r} needs an explicit capacity")
            return -(-self.capacity // num_shards)
        return -(-self.input_dim // num_shards)

    def to_config(self) -> dict:
        return {
            "name": self.name,
            "input_dim": self.input_dim,
            "output_dim": self.output_dim,
            "datatype": self.datatype,
            "initializer": self.initializer.to_config(),
            "optimizer": self.optimizer.to_config() if self.optimizer else None,
            "num_shards": self.num_shards,
            "sparse_as_dense": self.sparse_as_dense,
            "capacity": self.capacity,
            "storage": self.storage,
            "variable_id": self.variable_id,
            "feature": self.feature,
        }

    @classmethod
    def from_config(cls, d: dict) -> "EmbeddingSpec":
        d = dict(d)
        d["initializer"] = make_initializer(d["initializer"])
        d["optimizer"] = make_optimizer(d["optimizer"]) if d.get("optimizer") else None
        return cls(**d)


# ---------------------------------------------------------------------------
# Functional table ops (single shard / single device).  The sharded versions in
# `parallel/sharded.py` run these on each device's shard under shard_map.
# ---------------------------------------------------------------------------


def init_table_state(spec: EmbeddingSpec, optimizer: SparseOptimizer,
                     seed: int = 0, num_shards: int = 1,
                     shard_id: int = 0) -> EmbeddingTableState:
    """Materialize one shard's table (reference: lazy `_new_weights` init on first pull,
    `EmbeddingOptimizerVariable.h:242-266`; we init rows eagerly — deterministic per
    (seed, shard), documented divergence: RNG stream differs from lazy order)."""
    rows = spec.rows_per_shard(num_shards)
    # fold_in needs uint32 data; the unassigned sentinel (-1, specs built
    # outside an EmbeddingModel, e.g. a bare EmbeddingVariable) maps to a slot
    # no real variable_id reaches (2^15: 131071 * 2^15 still fits uint32)
    # instead of raising OverflowError. Streams of assigned ids are unchanged.
    vid = spec.variable_id if spec.variable_id >= 0 else (1 << 15)
    key = jax.random.fold_in(jax.random.PRNGKey(seed),
                             vid * 131071 + shard_id)
    weights = spec.initializer(key, (rows, spec.output_dim), spec.dtype)
    slots = optimizer.init_slots(rows, spec.output_dim, spec.dtype)
    keys = None
    overflow = None
    if spec.use_hash_table:
        # x64 on: int64 single-lane keys; x64 off (the default): uint32
        # split-pair keys — 63-bit ids in EITHER config (ops/id64.py)
        from .tables.hash_table import fresh_keys
        keys = fresh_keys(rows)
        overflow = jnp.zeros((), jnp.int32)
    return EmbeddingTableState(weights=weights, slots=slots, keys=keys,
                               overflow=overflow)


def _flat_ids(spec: EmbeddingSpec, ids: jax.Array):
    """-> (flat ids, row-output shape): split-pair ids ((..., 2) uint32,
    `ops/id64.py`) keep their lane dim flat and drop it from the output.
    Pair dispatch is gated on `use_hash_table`: a uint32 two-field batch on an
    array table must NOT be misread as one 63-bit id per row."""
    from .ops.id64 import is_pair
    if spec.use_hash_table and is_pair(ids):
        return ids.reshape(-1, 2), ids.shape[:-1]
    return ids.reshape(-1), ids.shape


def lookup(spec: EmbeddingSpec, state: EmbeddingTableState,
           ids: jax.Array) -> jax.Array:
    """Single-shard pull: ids (any shape) -> rows (ids.shape + (dim,)).
    reference: `Variable.sparse_read`/`pull_weights` (`exb.py:308-327`)."""
    flat, out_shape = _flat_ids(spec, ids)
    if spec.use_hash_table:
        from .tables.hash_table import hash_lookup
        rows = hash_lookup(state, flat)
    else:
        rows = lookup_rows(state.weights, flat)
    return rows.reshape(out_shape + (spec.output_dim,))


def lookup_train(spec: EmbeddingSpec, state: EmbeddingTableState,
                 ids: jax.Array):
    """Training pull: like `lookup` but hash tables insert unseen ids (lazy init).
    Returns (new_state, rows). Array tables never mutate on pull."""
    flat, out_shape = _flat_ids(spec, ids)
    if spec.use_hash_table:
        from .tables.hash_table import hash_lookup_train
        state, rows = hash_lookup_train(state, flat)
    else:
        rows = lookup_rows(state.weights, flat)
    return state, rows.reshape(out_shape + (spec.output_dim,))


def apply_gradients(spec: EmbeddingSpec, state: EmbeddingTableState,
                    optimizer: SparseOptimizer, ids: jax.Array,
                    grads: jax.Array) -> EmbeddingTableState:
    """Single-shard push+update fused: duplicate grads summed, optimizer applied once
    per unique id (reference: push `EmbeddingPushOperator.cpp` + store
    `EmbeddingStoreOperator.cpp` collapsed into one step — SPMD needs no batch gate)."""
    flat_ids, _ = _flat_ids(spec, ids)
    flat_grads = grads.reshape(-1, spec.output_dim)
    if spec.use_hash_table:
        from .tables.hash_table import hash_apply_gradients
        return hash_apply_gradients(state, optimizer, flat_ids, flat_grads)
    weights, slots = sparse_apply_dense_table(
        optimizer, state.weights, state.slots, flat_ids, flat_grads)
    return state.replace(weights=weights, slots=slots)


class Embedding:
    """Drop-in layer handle, mirroring `exb.Embedding` (`exb.py:388-443`).

    Collects itself into the enclosing `EmbeddingModel`'s variable list; the actual
    compute is functional (lookup / apply_gradients) driven by the Trainer.
    """

    def __init__(self, input_dim: int, output_dim: int, *, name: str,
                 datatype: str = "float32",
                 embeddings_initializer: Optional[Initializer] = None,
                 optimizer: Optional[SparseOptimizer] = None,
                 num_shards: int = -1,
                 sparse_as_dense: bool = False,
                 capacity: int = 0,
                 storage: str = "hbm",
                 feature: str = ""):
        self.spec = EmbeddingSpec(
            name=name,
            input_dim=input_dim,
            output_dim=output_dim,
            datatype=datatype,
            initializer=embeddings_initializer or Uniform(),
            optimizer=optimizer,
            num_shards=num_shards,
            sparse_as_dense=sparse_as_dense,
            capacity=capacity,
            storage=storage,
            feature=feature,
        )

    def __repr__(self):
        return f"Embedding({self.spec.name}: {self.spec.input_dim}x{self.spec.output_dim})"
