"""Online model sync — stream committed embedding deltas from a training run
into live serving replicas, no restart, no full reload.

The reference keeps serving replicas fresh by replicating models on the PS and
restoring dead nodes from live peers (HA serving mode,
`server/EmbeddingRestoreOperator.cpp`); its TF-Serving surface still reloads a
full SavedModel per version. Here the training side already commits exactly
the right artifact — `persist.IncrementalPersister`'s `delta_<step>`
directories hold only the rows touched since the previous persist, chained by
parent pointers (a sparse row-update stream in the SparCML sense,
arxiv 1802.08021) — so serving freshness becomes a transport problem:

- `publisher.SyncPublisher` exposes a persist root's committed base/delta
  chain as a versioned HTTP feed on the existing serving surface
  (`GET /models/<sign>:versions`, `GET /models/<sign>/delta/<step>/...`),
  with optional bf16/int8 row encoding on the wire (`ops/wire` numpy codecs;
  EQuARX-style quantized transport, arxiv 2506.17615);
- `subscriber.SyncSubscriber` runs inside a serving node: negotiates its
  servable's version against the feed, fetches only the missing delta suffix,
  validates the parent-pointer chain (`persist.delta_chain` semantics: apply
  a consistent prefix, never a torn mix), applies rows off the predict path
  and atomically swaps the servable in `ModelRegistry`'s manager (RCU:
  in-flight predicts finish on the old version), rolling back to the last
  good version on any failed fetch/validate/apply;
- both halves publish `sync.*` metrics through the existing `/metrics`
  Prometheus text (version lag, staleness, bytes fetched, apply ms,
  rollbacks), and the subscriber carries a deliberate fault-injection hook
  (`FaultInjector`: drop/duplicate/reorder/truncate a delta) so graceful
  degradation is testable, not aspirational.
"""

from . import lineage
from .publisher import SyncPublisher
from .subscriber import (FaultInjector, SyncChainError, SyncError,
                         SyncSubscriber)

__all__ = ["SyncPublisher", "SyncSubscriber", "SyncError", "SyncChainError",
           "FaultInjector", "lineage"]
