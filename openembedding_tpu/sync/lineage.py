"""Delta lineage: per-delta birth→commit→publish→fetch→apply→swap→serve book.

The subscriber state machine (`sync/subscriber.py`) measures each hop of a
delta's journey; the predict path (`serving.py`) closes the chain with the
first request served at that version. This module is the shared ledger both
write and every surface reads: `/timelinez` exports it, `tools/
fleet_timeline.py` renders the chain across nodes, capsules bundle it so a
postmortem shows where a stale delta stalled, and `/fleetz` prints the last
hop breakdown.

One record per (model sign, step). All stamps are WALL times in the clock
domain of the process that wrote them; `offset_s` is the writer's estimated
offset to the publisher's clock (Cristian-style, from request round-trips)
so a reader can translate publisher-domain stamps (birth, commit) into the
local domain. Hop durations (`hops`, milliseconds) are computed by the
subscriber at swap time and stored alongside — they are clock-domain-safe by
construction (each hop is a difference within one domain, or skew-corrected
across the boundary).

The book is bounded (oldest (sign, step) evicted first) and every method is
O(1), lock-cheap, and no-throw — it sits on the predict hot path.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from ..utils import metrics, trace


class LineageBook:
    """Bounded ledger of per-delta lineage records keyed by (sign, step)."""

    def __init__(self, capacity: int = 256):
        self._lock = threading.Lock()
        self._capacity = int(capacity)
        # (sign, step) -> record dict; insertion-ordered for eviction
        self._records: "OrderedDict[tuple, Dict[str, Any]]" = \
            OrderedDict()  # guarded-by: self._lock

    def record(self, sign: str, step: int, **stamps) -> None:
        """Merge stamps into the (sign, step) record, creating it if new.
        Known stamps: trace_id, birth, commit, seen, fetched, applied,
        swapped, first_serve (wall times), hops (dict of hop->ms),
        offset_s (estimated publisher-clock offset). Later writes win for
        scalar stamps; `hops` dicts are merged key-wise."""
        key = (str(sign), int(step))
        with self._lock:
            rec = self._records.get(key)
            if rec is None:
                rec = {"sign": key[0], "step": key[1]}
                self._records[key] = rec
                while len(self._records) > self._capacity:
                    self._records.popitem(last=False)
            else:
                self._records.move_to_end(key)
            for k, v in stamps.items():
                if v is None:
                    continue
                if k == "hops" and isinstance(rec.get("hops"), dict) \
                        and isinstance(v, dict):
                    rec["hops"].update(v)
                else:
                    rec[k] = dict(v) if k == "hops" and isinstance(v, dict) \
                        else v

    def note_serve(self, sign: str, step: int,
                   now: Optional[float] = None) -> None:
        """Close a delta's chain with its FIRST predict at that version:
        idempotent (only the first call per (sign, step) lands), O(1), and
        no-throw — it runs inside the predict handler."""
        try:
            import time
            key = (str(sign), int(step))
            now = time.time() if now is None else float(now)
            with self._lock:
                rec = self._records.get(key)
                if rec is None or rec.get("first_serve") is not None:
                    return
                rec["first_serve"] = now
                swapped = rec.get("swapped")
                hops = rec.setdefault("hops", {})
                serve_ms = None
                if swapped is not None:
                    serve_ms = max(0.0, (now - float(swapped)) * 1e3)
                    hops["serve"] = serve_ms
            if serve_ms is not None:
                metrics.observe("sync.hop_ms", serve_ms, "hist",
                                labels={"hop": "serve"})
            trace.event("sync", "first_serve", model=sign, step=int(step))
        except Exception:
            pass

    def get(self, sign: str, step: int) -> Optional[Dict[str, Any]]:
        with self._lock:
            rec = self._records.get((str(sign), int(step)))
            return dict(rec) if rec is not None else None

    def last(self, sign: Optional[str] = None) -> Optional[Dict[str, Any]]:
        """The most recently touched record (optionally for one sign)."""
        with self._lock:
            for key in reversed(self._records):
                if sign is None or key[0] == str(sign):
                    return dict(self._records[key])
        return None

    def export(self, sign: Optional[str] = None) -> List[Dict[str, Any]]:
        """All records oldest-first (the /timelinez + capsule payload)."""
        with self._lock:
            return [dict(rec) for key, rec in self._records.items()
                    if sign is None or key[0] == str(sign)]

    def clear(self) -> None:
        with self._lock:
            self._records.clear()


BOOK = LineageBook()

# canonical hop order of a delta's journey: publisher-side commit, feed
# publication, subscriber fetch/apply/swap, first predict at the version
HOP_ORDER = ("commit", "publish", "fetch", "apply", "swap", "serve")


def note_serve(sign: str, step: int) -> None:
    """Module-level convenience for the predict path."""
    BOOK.note_serve(sign, step)
