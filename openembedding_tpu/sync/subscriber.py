"""Subscriber half of online model sync: live delta apply inside a serving node.

Drives a per-model state machine against a publisher feed:

    IDLE ──poll──> FETCHING ──payload ok──> APPLYING ──swap──> IDLE
      ^                │                        │
      └── backoff ── DEGRADED <── chain/validate/apply failure ──┘

Every successfully applied delta is published with an ATOMIC servable swap
(`ModelManager.swap`): predicts that already resolved the old servable finish
on it untouched (RCU), the next request sees the new version. Because the
swap happens only after a delta fully validates and applies, "rollback" is
structural — a failure at ANY point leaves the node serving the last good
version; `sync.rollbacks` counts those abandonments and the machine enters
DEGRADED with exponential backoff until the feed yields a consistent chain
again. A subscriber that has fallen behind the feed's base (its deltas GC'd
under `persist` retention) cannot catch up incrementally and stays DEGRADED —
the operator reloads the model (POST /models) to resume; size
`IncrementalPersister(full_every=..., keep=...)` (or opt out of delta pruning)
so the retained chain covers the worst-case subscriber lag.

`FaultInjector` is a deliberate chaos hook for tests and soak tooling: it can
drop, duplicate, reorder or truncate deltas between fetch and apply to prove
the degradation above is graceful (DEGRADED + rollback + zero failed
predicts), not theoretical.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional
from urllib.parse import quote

import numpy as np

from ..ops import wire as wire_mod
from ..persist import DELTA_FORMAT
from ..utils import metrics, trace
from . import lineage

IDLE, FETCHING, APPLYING, DEGRADED = "IDLE", "FETCHING", "APPLYING", "DEGRADED"
_STATE_CODE = {IDLE: 0, FETCHING: 1, APPLYING: 2, DEGRADED: 3}


class SyncError(RuntimeError):
    """A sync attempt failed; the node keeps serving the last good version."""


class SyncChainError(SyncError):
    """The fetched delta does not extend the applied chain (torn, reordered,
    duplicated, foreign-format, or parent-mismatched payload)."""


class FaultInjector:
    """Deliberate fault injection between fetch and apply. Subclass and
    override either method; the default is a no-op. `plan` may drop,
    duplicate or reorder the pending step list; `payload` may corrupt or
    truncate one fetched delta (return the payload dict, mutated or not)."""

    def plan(self, steps: List[int]) -> List[int]:
        return steps

    def payload(self, step: int, payload: dict) -> dict:
        return payload


class SyncSubscriber:
    """Keep one model in a `ModelManager` fresh against a publisher feed.

    Drive it either deterministically — `poll()` per tick (tests, soak) — or
    with `start()`/`stop()` for the background thread the serving node CLI
    uses. `feed` is the publisher node's base URL; the model must already be
    loaded on THIS node (POST /models) before the first poll, and its export
    step must sit on the feed's chain (export the base persist's state).
    """

    def __init__(self, manager, model_sign: str, feed: str, *,
                 wire: Optional[str] = None, interval_s: float = 1.0,
                 wait_s: float = 0.0, max_backoff_s: float = 30.0,
                 timeout: float = 30.0, faults: Optional[FaultInjector] = None):
        self.manager = manager
        self.model_sign = model_sign
        self.feed = feed.rstrip("/")
        self.wire = wire_mod.wire_format(wire or "fp32")
        self.interval_s = interval_s
        self.wait_s = wait_s
        self.max_backoff_s = max_backoff_s
        self.timeout = timeout
        self.faults = faults
        # `_mu` guards the machine state the worker thread WRITES and the
        # serving threads READ (`status()` on GET :syncstate / statusz):
        # without it a reader can see a half-updated (state, version,
        # last_degraded_reason) triple mid-transition. The `# guarded-by:`
        # annotations are enforced by `make lint` (tools/oelint lockset
        # pass): any write outside `with self._mu:` fails CI.
        self._mu = threading.Lock()
        self.state = IDLE                       # guarded-by: self._mu
        self.version: Optional[int] = None      # guarded-by: self._mu
        self.applied = 0                        # guarded-by: self._mu
        self.last_error: Optional[str] = None   # guarded-by: self._mu
        # survives recovery: the reason the machine LAST entered DEGRADED
        # (shown on /statusz and :syncstate — `last_error` clears on the next
        # clean round, this stays for the post-mortem)
        # guarded-by: self._mu
        self.last_degraded_reason: Optional[str] = None
        self._backoff = 0.0                     # guarded-by: self._mu
        self._head_times: Dict[int, float] = {}  # guarded-by: self._mu
        # delta lineage bookkeeping: per-step birth stamps off the feed
        # (publisher clock) and first-seen times (local clock), the
        # Cristian-style clock-offset estimate to the publisher, and the
        # last applied delta's hop decomposition / end-to-end freshness
        self._births: Dict[int, float] = {}      # guarded-by: self._mu
        self._feed_seen: Dict[int, float] = {}   # guarded-by: self._mu
        self._clock_offset_s = 0.0               # guarded-by: self._mu
        self._offset_samples = 0                 # guarded-by: self._mu
        self._last_hops: Optional[dict] = None   # guarded-by: self._mu
        # guarded-by: self._mu
        self._last_freshness_ms: Optional[float] = None
        self._stop = threading.Event()
        # guarded-by: self._mu
        self._thread: Optional[threading.Thread] = None

    # -- wire ----------------------------------------------------------------

    def _get(self, path: str):
        # each sync round binds a request id (`sync_once`); injecting the
        # full trace context (request id + X-OETPU-Trace parent span) onto
        # every feed fetch means the PUBLISHER node's handler spans link
        # back to this subscriber's fetch span as ONE cross-process tree
        headers = trace.inject_headers()
        req = urllib.request.Request(f"{self.feed}{path}", headers=headers)
        t0 = time.time()
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as r:
                raw = r.read()
                server_time = r.headers.get(trace.SERVER_TIME_HEADER)
        except urllib.error.HTTPError as e:
            if e.code == 304:
                return None
            raise SyncError(f"feed {path}: HTTP {e.code}") from e
        except (urllib.error.URLError, ConnectionError, OSError) as e:
            raise SyncError(f"feed {path}: {e}") from e
        if server_time:
            try:
                self._note_clock(float(server_time), t0, time.time())
            except (TypeError, ValueError):
                pass
        metrics.observe("sync.bytes_fetched", len(raw))
        return raw

    def _note_clock(self, t_server: float, t0: float, t2: float) -> None:
        """Cristian-style clock-offset estimate from one round-trip: the
        publisher stamped `t_server` somewhere inside [t0, t2] of OUR clock,
        so offset ~= t_server - (t0 + t2)/2, error bounded by RTT/2. EWMA
        over rounds smooths network jitter; `status()` and the lineage book
        expose the estimate so merged timelines can de-skew our stamps."""
        offset = t_server - (t0 + t2) / 2.0
        with self._mu:
            if self._offset_samples == 0:
                self._clock_offset_s = offset
            else:
                self._clock_offset_s += 0.3 * (offset - self._clock_offset_s)
            self._offset_samples += 1

    def _get_json(self, path: str):
        raw = self._get(path)
        return None if raw is None else json.loads(raw)

    def _get_npz(self, path: str) -> dict:
        import io
        raw = self._get(path)
        with np.load(io.BytesIO(raw)) as z:
            return {k: z[k] for k in z.files}

    def _fetch_delta(self, step: int) -> dict:
        """-> {"meta", "tables": {name: (ids, rows_f32)}, "dense": flat}."""
        sign = quote(self.model_sign, safe="")
        meta = self._get_json(f"/models/{sign}/delta/{step}/meta")
        tables = {}
        for name in meta.get("tables", []):
            z = self._get_npz(
                f"/models/{sign}/delta/{step}/table/{quote(name, safe='')}"
                f"?wire={self.wire}")
            fmt = str(z["fmt"])
            rows = wire_mod.np_decode_rows(z["wire"], int(z["dim"]), fmt)
            tables[name] = (np.asarray(z["ids"], np.int64), rows)
        dense = self._get_npz(f"/models/{sign}/delta/{step}/dense")
        return {"meta": meta, "tables": tables, "dense": dense}

    # -- state machine -------------------------------------------------------

    def _set_state(self, state: str, reason: Optional[str] = None) -> None:
        with self._mu:
            prev, self.state = self.state, state
            if state == DEGRADED and reason:
                self.last_degraded_reason = reason
        metrics.observe("sync.state", _STATE_CODE[state], "gauge")
        if state != prev:
            # discrete transition -> flight recorder (the /statusz tail that
            # explains a DEGRADED spike after the fact)
            attrs = {"model": self.model_sign, "from": prev, "to": state}
            if reason:
                attrs["reason"] = reason
            trace.event("sync", "state", **attrs)

    def _observe_lag(self, head: Optional[int]) -> None:
        if head is None or self.version is None:
            return
        metrics.observe("sync.version_lag_steps",
                        max(0, head - self.version), "gauge")
        metrics.observe("sync.head_version", float(head), "gauge")
        metrics.observe("sync.applied_version", float(self.version), "gauge")
        t = self._head_times.get(self.version)
        if t is not None:
            metrics.observe("sync.staleness_seconds",
                            max(0.0, time.time() - t), "gauge")
        f = self._freshness_ms(head)
        if f is not None:
            metrics.observe("sync.freshness_ms", f, "gauge")

    def _freshness_ms(self, head: Optional[int]) -> Optional[float]:
        """End-to-end freshness of what THIS node serves: while the feed
        head is ahead of the applied version, the skew-corrected age of the
        head delta's BIRTH (it grows every poll a stalled delta stays
        unapplied — the SLO trip wire); once caught up, frozen at the last
        applied delta's measured birth->swap latency."""
        with self._mu:
            offset = self._clock_offset_s
            last = self._last_freshness_ms
            birth = None
            if (head is not None and self.version is not None
                    and head > self.version):
                birth = self._births.get(head)
        if birth is not None:
            return max(0.0, (time.time() + offset - birth) * 1e3)
        return last

    def _record_lineage(self, step: int, fetched: float, applied_t: float,
                        swapped: float) -> None:
        """Fold one applied delta's hop decomposition into `sync.hop_ms`
        hists, the shared lineage book, and the freshness snapshot. `birth`/
        `commit` stamps are publisher-domain, `seen`/`fetched`/`applied`/
        `swapped` local-domain; the publish hop and the end-to-end number
        cross domains via the Cristian offset estimate. The `fetch` hop runs
        first-seen-on-feed -> fetched, so DEGRADED retry time during a
        payload stall lands on it — the soak's stalled-hop attribution."""
        with self._mu:
            offset = self._clock_offset_s
            birth = self._births.get(step)
            seen = self._feed_seen.get(step)
            commit_t = self._head_times.get(step)
            hops: Dict[str, float] = {}
            if birth is not None and commit_t is not None:
                hops["commit"] = max(0.0, (commit_t - birth) * 1e3)
            if commit_t is not None and seen is not None:
                hops["publish"] = max(0.0, (seen + offset - commit_t) * 1e3)
            if seen is not None:
                hops["fetch"] = max(0.0, (fetched - seen) * 1e3)
            hops["apply"] = max(0.0, (applied_t - fetched) * 1e3)
            hops["swap"] = max(0.0, (swapped - applied_t) * 1e3)
            e2e = None
            if birth is not None:
                e2e = max(0.0, (swapped + offset - birth) * 1e3)
                self._last_freshness_ms = e2e
            self._last_hops = {"step": step, "hops": dict(hops)}
            # stamps for this and older steps are consumed: bound the maps
            self._births = {k: v for k, v in self._births.items()
                            if k > step}
            self._feed_seen = {k: v for k, v in self._feed_seen.items()
                               if k > step}
        for h, v in hops.items():
            metrics.observe("sync.hop_ms", v, "hist", labels={"hop": h})
        if e2e is not None:
            metrics.observe("sync.freshness_ms", e2e, "gauge")
        lineage.BOOK.record(
            self.model_sign, step, trace_id=trace.get_request_id(),
            birth=birth, commit=commit_t, seen=seen, fetched=fetched,
            applied=applied_t, swapped=swapped, hops=hops, offset_s=offset)

    def sync_once(self) -> int:
        """One negotiation round; returns deltas applied. Raises SyncError on
        any failure — state/metrics handling lives in `poll()`. The round
        runs under one request id, propagated to the publisher on every
        fetch (`X-OETPU-Request-Id`)."""
        with trace.request():
            return self._sync_once()

    def _sync_once(self) -> int:
        servable = self.manager.find_model(self.model_sign)
        with self._mu:
            # check and seed under one lock: a poll racing a manual
            # sync_once() must not both observe None and double-seed
            if self.version is None:
                self.version = int(getattr(servable, "step", 0))
        sign = quote(self.model_sign, safe="")
        q = (f"?after={self.version}&wait_s={self.wait_s}"
             if self.wait_s > 0 else "")
        feed = self._get_json(f"/models/{sign}:versions{q}")
        if feed is None:  # 304: nothing newer inside the poll window
            self._observe_lag(self.version)
            return 0
        if feed.get("format") != "oetpu-sync-v1":
            raise SyncError(f"foreign feed format {feed.get('format')!r}")
        head = feed.get("head_step")
        now = time.time()
        with self._mu:
            for d in feed.get("deltas", []):
                self._head_times[d["step"]] = d["commit_time"]
                if d.get("birth_time") is not None:
                    self._births[d["step"]] = float(d["birth_time"])
                # first time THIS node saw the delta on the feed (local
                # clock) — the fetch hop's start, kept across retries
                self._feed_seen.setdefault(d["step"], now)
        self._observe_lag(head)
        if head is None or head <= self.version:
            return 0
        base = feed.get("base_step")
        chain_steps = [d["step"] for d in feed.get("deltas", [])]
        if self.version != base and self.version not in chain_steps:
            raise SyncChainError(
                f"servable version {self.version} is not on the feed chain "
                f"(base {base}, deltas {chain_steps[:8]}...): fell behind "
                "retention — reload the model to resume")
        pending = [s for s in chain_steps if s > self.version]
        if self.faults is not None:
            pending = self.faults.plan(list(pending))

        self._set_state(FETCHING)
        applied = 0
        for step in pending:
            with trace.span("sync", "fetch", step=int(step)):
                payload = self._fetch_delta(step)
            t_fetched = time.time()
            if self.faults is not None:
                payload = self.faults.payload(step, payload)
            meta = payload.get("meta") or {}
            if (meta.get("format") != DELTA_FORMAT
                    or int(meta.get("step", -1)) != int(step)
                    or int(meta.get("parent", -1)) != int(self.version)):
                raise SyncChainError(
                    f"delta {step} does not extend version {self.version} "
                    f"(parent={meta.get('parent')}, "
                    f"format={meta.get('format')!r})")
            self._set_state(APPLYING)
            with trace.span("sync", "apply", step=int(step)):
                new_servable = servable.apply_update(
                    payload["tables"], payload["dense"], step=int(step),
                    model_version=meta.get("model_version"))
            t_applied = time.time()
            with trace.span("sync", "swap", step=int(step)):
                self.manager.swap(self.model_sign, new_servable,
                                  expected=servable)
            t_swapped = time.time()
            servable = new_servable
            with self._mu:
                self.version = int(step)
                self.applied += 1
            applied += 1
            metrics.observe("sync.applied_deltas", 1)
            self._record_lineage(int(step), t_fetched, t_applied, t_swapped)
            self._observe_lag(head)
            self._set_state(FETCHING)
        self._set_state(IDLE)
        return applied

    def _degrade(self, reason: str) -> None:
        with self._mu:
            self.last_error = reason
        metrics.observe("sync.rollbacks", 1)
        trace.event("sync", "rollback", model=self.model_sign,
                    version=self.version, reason=reason)
        self._set_state(DEGRADED, reason=reason)
        with self._mu:
            self._backoff = min(max(self._backoff * 2, self.interval_s),
                                self.max_backoff_s)

    def poll(self) -> int:
        """One guarded tick: sync, or record the failure and degrade.
        Returns deltas applied (0 on failure — check `.state`/`.last_error`)."""
        try:
            applied = self.sync_once()
        except SyncError as e:
            self._degrade(str(e))
            return 0
        except Exception as e:  # noqa: BLE001 — a bug must not kill the loop
            self._degrade(f"{type(e).__name__}: {e}")
            return 0
        with self._mu:
            self.last_error = None
            self._backoff = 0.0
        return applied

    def status(self) -> dict:
        # one consistent snapshot: serving threads render this on
        # :syncstate / statusz while the worker is mid-transition
        with self._mu:
            return {"model_sign": self.model_sign, "feed": self.feed,
                    "state": self.state, "version": self.version,
                    "applied": self.applied, "wire": self.wire,
                    "last_error": self.last_error,
                    "last_degraded_reason": self.last_degraded_reason,
                    "freshness_ms": self._last_freshness_ms,
                    "clock_offset_ms": self._clock_offset_s * 1e3,
                    "last_hops": dict(self._last_hops)
                    if self._last_hops is not None else None}

    # -- background loop -----------------------------------------------------

    def start(self) -> "SyncSubscriber":
        # two racing start()s (CLI + a POST /sync) must not leak a thread
        with self._mu:
            if self._thread is None:
                self._stop.clear()
                self._thread = threading.Thread(target=self._run,
                                                daemon=True)
                self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            self.poll()
            delay = self._backoff if self.state == DEGRADED else self.interval_s
            if self._stop.wait(max(delay, 0.01)):
                return

    def stop(self) -> None:
        self._stop.set()
        with self._mu:
            t, self._thread = self._thread, None
        if t is not None:  # join OUTSIDE the lock: _run takes no lock, but
            t.join(timeout=10)  # a slow join must not block status() readers
