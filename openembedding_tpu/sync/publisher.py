"""Publisher half of online model sync: a persist root as a versioned feed.

The trainer process (or any process that can read the persist root) runs the
ordinary serving HTTP server with this publisher registered for a model sign;
the feed then rides the existing REST surface (`serving.ServingHandler`):

    GET /models/<sign>:versions[?after=<step>&wait_s=<s>]
        -> {"format": "oetpu-sync-v1", "base_step", "head_step",
            "deltas": [{"step", "parent", "commit_time", "tables"}, ...],
            "wire_formats": [...]}   (ETag = head commit step; with `after`,
            a bounded long-poll that 304s if nothing newer commits in time)
    GET /models/<sign>/delta/<step>/meta           -> the delta's meta.json
    GET /models/<sign>/delta/<step>/dense          -> npz, dense params only
    GET /models/<sign>/delta/<step>/table/<name>[?wire=fp32|bf16|int8]
        -> npz {ids (int64, exact), wire (encoded rows), fmt, dim}
        (ETag = commit step on every delta file: committed deltas are
        immutable, so any cache layer may hold them forever)

Only the COMMITTED consistent chain is ever served (`persist.delta_chain`):
an uncommitted or orphaned delta directory is invisible to subscribers, the
same crash-consistency restore relies on. Optimizer slots never enter the
feed — a serving replica has no use for them, which alone halves the wire
bytes before any quantization (`ops/wire.sync_delta_cost`).
"""

from __future__ import annotations

import io
import json
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..ops import wire as wire_mod
from ..persist import COMMIT_FILE, DELTA_FORMAT, delta_chain, list_persists
from ..utils import metrics, trace

# a bounded poll may park a handler thread at most this long
MAX_WAIT_S = 30.0
FEED_FORMAT = "oetpu-sync-v1"


class SyncPublisher:
    """Read-only view of one persist root for the serving HTTP surface.

    Stateless between requests except a meta.json cache — the feed is
    recomputed from the directory listing per call (the same `delta_chain`
    walk restore uses; cheap at serving-feed rates), so a publisher never
    needs to be told when the trainer commits.
    """

    def __init__(self, root: str, *, wire: Optional[str] = None):
        self.root = root
        # default row encoding when the subscriber doesn't pick one
        self.wire = wire_mod.wire_format(wire or "fp32")
        self._meta_cache: Dict[str, dict] = {}
        self._lock = threading.Lock()

    # -- feed ----------------------------------------------------------------

    def _delta_meta(self, path: str) -> dict:
        with self._lock:
            cached = self._meta_cache.get(path)
        if cached is not None:
            return cached
        with open(os.path.join(path, "meta.json")) as f:
            meta = json.load(f)
        with self._lock:
            self._meta_cache[path] = meta  # committed deltas are immutable
        return meta

    def versions(self) -> dict:
        """The committed chain as one JSON document (see module doc)."""
        base, chain = delta_chain(self.root)
        if base is None:
            return {"format": FEED_FORMAT, "base_step": None,
                    "head_step": None, "deltas": [],
                    "wire_formats": list(wire_mod.FORMATS)}
        base_step = list_persists(self.root)[-1][0]
        head = base_step
        deltas: List[dict] = []
        for path in chain:
            meta = self._delta_meta(path)
            step = int(meta["step"])
            try:
                commit_time = os.path.getmtime(
                    os.path.join(path, COMMIT_FILE))
            except OSError:
                continue  # GC'd between the chain walk and here: feed shrinks
            # birth_time: when the trainer CAPTURED the delta's state
            # (persist.py stamps it into meta) — the zero point of the
            # subscriber's end-to-end freshness chain; absent on deltas
            # written before the stamp existed
            deltas.append({"step": step, "parent": int(meta["parent"]),
                           "commit_time": commit_time,
                           "birth_time": meta.get("birth_time"),
                           "tables": list(meta.get("tables", []))})
            head = step
        return {"format": FEED_FORMAT, "base_step": base_step,
                "head_step": head, "deltas": deltas,
                "wire_formats": list(wire_mod.FORMATS)}

    def wait_versions(self, after: Optional[int],
                      wait_s: float = 0.0) -> Tuple[dict, bool]:
        """-> (feed, changed). With `after`, park up to `wait_s` (capped at
        MAX_WAIT_S) until the head advances past it — the handler turns
        changed=False into 304 Not Modified."""
        feed = self.versions()
        if after is None:
            return feed, True
        deadline = time.monotonic() + min(max(wait_s, 0.0), MAX_WAIT_S)
        while (feed["head_step"] is None or feed["head_step"] <= after):
            if time.monotonic() >= deadline:
                return feed, (feed["head_step"] or 0) > after
            time.sleep(0.05)
            feed = self.versions()
        return feed, True

    # -- delta payloads ------------------------------------------------------

    def _delta_path(self, step: int) -> str:
        path = os.path.join(self.root, f"delta_{int(step):012d}")
        if not os.path.exists(os.path.join(path, COMMIT_FILE)):
            raise KeyError(f"no committed delta at step {step}")
        return path

    def delta_meta(self, step: int) -> dict:
        meta = self._delta_meta(self._delta_path(step))
        if meta.get("format") != DELTA_FORMAT:
            raise KeyError(f"delta at step {step} has foreign format "
                           f"{meta.get('format')!r}")
        return meta

    def delta_table(self, step: int, name: str,
                    fmt: Optional[str] = None) -> bytes:
        """One table's touched rows as an npz body: exact int64 ids beside
        the wire-encoded rows (the sync cost gauges update per serve)."""
        from ..persist import _load_delta_table
        fmt = wire_mod.wire_format(fmt or self.wire)
        path = self._delta_path(step)
        if name not in self.delta_meta(step).get("tables", []):
            raise KeyError(f"delta {step} carries no table {name!r}")
        # the fetch-side half of the sync trace: a subscriber's request id
        # (stamped by the serving handler) correlates this serve with the
        # subscriber's sync.fetch span of the same round
        with trace.span("sync", "serve_delta", step=int(step), table=name,
                        wire=fmt):
            ids, weights, _slots = _load_delta_table(path, name)
            dim = int(weights.shape[1]) if weights.ndim == 2 else 0
            payload = wire_mod.np_encode_rows(weights, fmt)
            metrics.observe_sync_cost(
                wire_mod.sync_delta_cost({name: (int(ids.size), dim)}, fmt))
            buf = io.BytesIO()
            np.savez(buf, ids=np.asarray(ids, np.int64), wire=payload,
                     fmt=np.asarray(fmt), dim=np.asarray(dim, np.int64))
            return buf.getvalue()

    def delta_dense(self, step: int) -> bytes:
        """The delta's dense params (npz; optimizer slot entries dropped)."""
        path = self._delta_path(step)
        with np.load(os.path.join(path, "dense.npz")) as z:
            params = {k[len("params/"):]: z[k] for k in z.files
                      if k.startswith("params/")}
        buf = io.BytesIO()
        np.savez(buf, **params)
        return buf.getvalue()
