# CI entry points (reference ships build+test automation,
# /root/reference/.github/workflows/build.yml; this is the TPU-native repo's
# equivalent — `.github/workflows/ci.yml` calls these same targets).
#
# Everything runs on an 8-virtual-device CPU mesh: the root conftest.py flips
# JAX to the cpu backend before it initializes, so no TPU (or axon relay) is
# needed. `make ci` is the one command that must stay green.

PY ?= python
# `-u PALLAS_AXON_POOL_IPS`: on hosts with a tunneled TPU (this image), every
# interpreter otherwise performs the accelerator handshake at startup — CPU
# targets must never touch it (tests/conftest.py does the same for pytest).
CPU_ENV = env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
	XLA_FLAGS=--xla_force_host_platform_device_count=8

.PHONY: ci test dryrun bench-smoke native lint lint-fast lint-budget \
	lint-metrics weave capsule-smoke timeline-smoke

ci: lint test dryrun bench-smoke weave capsule-smoke timeline-smoke

# the full static-analysis + invariant-guard suite (tools/oelint): eleven
# passes — trace-hazard (recompile hazards in jit-reachable code), host-sync
# (device_get discipline in `# oelint: hot-path` fns), sharding
# (PartitionSpec placement-flow consistency), spmd-divergence (per-process
# host control flow upstream of collectives), hlo-budget (compiled
# collective counts vs tools/oelint/hlo_budget.json), implicit-reshard
# (GSPMD-inserted collectives with no traced-op attribution), lockset
# (`# guarded-by:` discipline + lock-ordering cycles), atomicity
# (check-then-act split across a lock release), cond-wait (Condition.wait
# predicate loops, notify under the lock), thread-lifecycle (every thread
# has a reachable join), metrics (name hygiene). CPU-only, no chip; passes
# run concurrently and the compiles are cached on a source digest — warm
# runs finish in seconds (<= 25 s budget).
lint:
	$(CPU_ENV) $(PY) -m tools.oelint

# fast local iteration: lint only files changed vs HEAD (skips the
# hlo-budget/implicit-reshard compile unless exchange/trainer/ops paths
# changed)
lint-fast:
	$(CPU_ENV) $(PY) -m tools.oelint --changed-only

# regenerate the pinned HLO collective budget after an INTENTIONAL
# collective change; commit the resulting json diff
lint-budget:
	$(CPU_ENV) $(PY) -m tools.oelint --update-budget

# metric-name hygiene only (back-compat alias; the check is oelint's
# metrics pass and runs as part of `make lint`)
lint-metrics:
	$(PY) tools/lint_metrics.py

# deterministic concurrency testing (tools/oeweave): explore seeded-random +
# preemption-bounded interleavings of the threaded control plane (subscriber
# state machine, micro-batcher, persister, placement watcher, offload store,
# sketch worker, reporter, SLO evaluator) on a cooperative scheduler; any
# failing schedule prints a replay token that reproduces it bit-for-bit.
# ~60 s budget; typical full run is a few seconds.
weave:
	$(CPU_ENV) $(PY) -m tools.oeweave --budget-s 60

# the full battery (mesh collectives, serving HA processes, persist crash
# consistency, planted-signal AUC regression, keras parity, ...)
test:
	$(PY) -m pytest tests/ -q

# the driver's multi-chip validation: jit + execute full train steps (DP +
# row-sharded tables + all_to_all, packed scan, 63-bit ids, host-cached scan,
# ring-attention CP) over an 8-device mesh
dryrun:
	$(CPU_ENV) $(PY) -c "import __graft_entry__ as g; \
	fn, args = g.entry(); import jax; out = jax.jit(fn)(*args); \
	print('entry OK, loss', float(out['loss'])); g.dryrun_multichip(8)"

# the benchmark harness end to end on tiny shapes (measures nothing — proves
# the suite runs and emits its one-line JSON contract)
bench-smoke:
	$(CPU_ENV) OETPU_BENCH_SCAN_STEPS=3 OETPU_BENCH_REPEATS=1 \
	OETPU_BENCH_VOCAB=65536 OETPU_BENCH_BUDGET_S=480 $(PY) bench.py

# the flight-data layer end to end: arm capsules in a temp dir, force one
# trigger, and round-trip it through the offline renderer — proves the
# failure path (capsule assembly + atomic write + report) stays importable
# and renderable without a live process
capsule-smoke:
	$(CPU_ENV) $(PY) -c "import tempfile, glob, os; \
	from openembedding_tpu.utils import capsule, metrics, history, trace; \
	d = tempfile.mkdtemp(prefix='capsmoke'); capsule.configure(d); \
	metrics.observe('train.steps', 3.0); \
	history.HISTORY.sample_registry(); \
	trace.event('health', 'nonfinite', source='smoke'); \
	p = capsule.trigger('smoke', origin='make capsule-smoke'); \
	assert p and os.path.exists(p), 'capsule not written'; \
	import tools.capsule_report as cr; \
	text = cr.render(cr.load(p)); \
	assert 'reason=smoke' in text and 'train.steps' in text, text; \
	print('capsule smoke OK:', os.path.basename(p))"

# the fleet-causality surface end to end: two in-process serving nodes,
# Cristian clock probes against both /timelinez endpoints, one merged
# skew-corrected timeline — proves the scrape+merge path stays green without
# a real fleet
timeline-smoke:
	$(CPU_ENV) $(PY) -c "import tempfile, threading; \
	from openembedding_tpu.serving import make_server; \
	from openembedding_tpu.utils import trace; \
	from tools import fleet_timeline as ftl; \
	srvs = [make_server(tempfile.mkdtemp(prefix='tlsmoke')) \
	        for _ in range(2)]; \
	[threading.Thread(target=s.serve_forever, daemon=True).start() \
	 for s in srvs]; \
	urls = ['http://127.0.0.1:%d' % s.server_address[1] for s in srvs]; \
	trace.event('serving', 'smoke', source='make timeline-smoke'); \
	nodes = []; \
	[nodes.append((u, *ftl.probe(u, probes=2))) for u in urls]; \
	items = ftl.merge([(n, d, o) for n, d, o in nodes]); \
	assert items, 'merged fleet timeline is empty'; \
	print(ftl.render(items, limit=5)); \
	[s.shutdown() for s in srvs]; \
	print('timeline smoke OK: %d merged items' % len(items))"

# build the native data-path extension explicitly (the package also builds it
# on demand at import; this target surfaces compiler errors directly)
native:
	$(CPU_ENV) $(PY) -c "from openembedding_tpu import native; \
	native.build(); print('native extension OK')"
