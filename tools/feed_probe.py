"""Input-path headroom: reader -> batcher -> prefetch_to_device, NO train step.

VERDICT r4 item 8: at the 1M-examples/s north star each of 16 hosts must
parse ~62.5k rows/s; the native readers were measured in isolation (169k
rows/s TFRecord @4 threads) but the end-to-end feed — parse + batch +
device placement + the prefetch queue — was never pinned. This probe:

  1. generates a synthetic Criteo TSV (and .gz) once,
  2. streams it through `read_criteo_tsv(native=...)` + `prefetch_to_device`,
  3. reports rows/s for a thread-count curve, and
  4. reports the STALL FRACTION against a simulated device consuming at the
     chip step rate (--device-ms per batch; default 23.4 ms = 4096 rows at
     the measured 175k ex/s/chip): the fraction of wall time the "device"
     loop spends blocked on the feed. 0 = input fully off the critical path.

Usage:  python tools/feed_probe.py [--rows 400000] [--batch 4096]
                                   [--threads 1,2,4,8] [--device-ms 23.4]
One JSON line per configuration on stdout.
"""

import argparse
import gzip
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NUM_DENSE, NUM_SPARSE = 13, 26


def synth_tsv(path: str, rows: int, seed: int = 0) -> str:
    rng = np.random.default_rng(seed)
    label = rng.integers(0, 2, rows)
    dense = rng.integers(-5, 1000, (rows, NUM_DENSE))
    dense_miss = rng.random((rows, NUM_DENSE)) < 0.1
    cats = rng.integers(0, 1 << 32, (rows, NUM_SPARSE), dtype=np.int64)
    cat_miss = rng.random((rows, NUM_SPARSE)) < 0.1
    with open(path, "w") as f:
        for r in range(rows):
            cols = [str(label[r])]
            cols += ["" if dense_miss[r, i] else str(dense[r, i])
                     for i in range(NUM_DENSE)]
            cols += ["" if cat_miss[r, i] else f"{cats[r, i]:08x}"
                     for i in range(NUM_SPARSE)]
            f.write("\t".join(cols) + "\n")
    return path


def run_one(paths, batch, threads, device_ms, native, repeat_rows):
    from openembedding_tpu.data import prefetch_to_device, read_criteo_tsv

    it = read_criteo_tsv(paths, batch, id_space=1 << 25, native=native,
                         native_threads=threads, repeat=True)
    it = prefetch_to_device(it, size=4)
    target_batches = max(1, repeat_rows // batch)
    # warm: first batch pays reader spin-up + device transfer compile
    next(it)
    t_start = time.perf_counter()
    stalled = 0.0
    n = 0
    for _ in range(target_batches):
        t0 = time.perf_counter()
        b = next(it)
        stalled += time.perf_counter() - t0
        n += int(b["label"].shape[0])
        if device_ms > 0:
            time.sleep(device_ms / 1e3)  # the simulated device step
    total = time.perf_counter() - t_start
    feed_only_rows_s = n / max(1e-9, stalled) if device_ms == 0 else None
    return {"threads": threads, "native": native, "rows": n,
            "rows_per_s": round(n / total, 1),
            "stall_fraction": round(stalled / total, 4),
            "device_ms": device_ms,
            **({"feed_only_rows_per_s": round(feed_only_rows_s, 1)}
               if feed_only_rows_s is not None else {})}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=400_000)
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--threads", default="1,2,4,8")
    ap.add_argument("--device-ms", type=float, default=23.4)
    ap.add_argument("--measure-rows", type=int, default=400_000)
    ap.add_argument("--gz", action="store_true", help="also probe .gz input")
    args = ap.parse_args()

    tmp = tempfile.mkdtemp(prefix="feed_probe_")
    base = synth_tsv(os.path.join(tmp, "a.tsv"), args.rows)
    paths = [base]
    if args.gz:
        gz = os.path.join(tmp, "a.tsv.gz")
        with open(base, "rb") as fin, gzip.open(gz, "wb", 1) as fout:
            fout.write(fin.read())

    for threads in [int(t) for t in args.threads.split(",")]:
        # pure feed rate (no device consumer)
        out = run_one(paths, args.batch, threads, 0.0, "on",
                      args.measure_rows)
        print(json.dumps({"case": "feed", **out}), flush=True)
        # behind a simulated chip-rate consumer
        out = run_one(paths, args.batch, threads, args.device_ms, "on",
                      args.measure_rows)
        print(json.dumps({"case": "feed+device", **out}), flush=True)
    # the Python fallback parser, for the curve's floor
    out = run_one(paths, args.batch, 1, 0.0, "off",
                  min(args.measure_rows, 100_000))
    print(json.dumps({"case": "feed-python", **out}), flush=True)
    if args.gz:
        out = run_one([gz], args.batch, 4, 0.0, "on", args.measure_rows)
        print(json.dumps({"case": "feed-gz", **out}), flush=True)


if __name__ == "__main__":
    main()
