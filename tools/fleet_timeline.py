"""Skew-corrected merged fleet timeline from N nodes' `GET /timelinez`.

    python tools/fleet_timeline.py http://trainer:8501 http://replica1:8501
    python tools/fleet_timeline.py node1:8501 node2:8501 --request <rid>
    python tools/fleet_timeline.py node1:8501 node2:8501 --version 42

Each node's `/timelinez` returns its flight-recorder events/spans (every
item carries a (wall, monotonic) timestamp pair and the node's process id),
its delta lineage book, and `wall_time` — the node's clock at serve time.
Raw wall clocks across hosts are NOT comparable (NTP drift, VMs, clock
steps), so the CLI estimates each node's clock offset Cristian-style: for
each of `--probes` round-trips it records (t0, node wall_time, t2) and takes
offset = wall_time - (t0+t2)/2 from the MINIMUM-RTT probe (tightest error
bound, RTT/2). Every item's wall stamp is then shifted into the scraper's
clock domain before the merge sorts them into one causally-ordered timeline.

Delta lineage records render as DELTA chain lines
(commit→publish→fetch→apply→swap→first-predict with per-hop milliseconds);
their publisher-domain stamps (birth/commit) are translated through the
RECORDING node's own offset estimate (`offset_s` in the record) before the
node→CLI correction, so all three clock domains land on one axis. Within one
record the chain is additionally clamped non-decreasing in hop order —
cross-domain correction is an estimate, and a merged timeline whose fetch
precedes its publish by 3ms of residual skew reads as causal nonsense.

Filters: `--request <rid>` keeps one trace's items; `--version <step>` keeps
one delta's chain + items stamped with that step.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# lineage stamp -> (chain position, display name); publisher-domain stamps
# (birth, commit) carry the record's own offset on top of the node offset
_CHAIN = (("birth", "birth", True), ("commit", "commit", True),
          ("seen", "publish", False), ("fetched", "fetch", False),
          ("applied", "apply", False), ("swapped", "swap", False),
          ("first_serve", "first_predict", False))


def probe(node: str, timeout: float = 10.0, probes: int = 3):
    """-> (doc, offset_s) for one node: scrape /timelinez `probes` times,
    estimate the node->local clock offset from the min-RTT round-trip
    (Cristian), keep the last full document."""
    url = node.rstrip("/")
    if not url.startswith("http"):
        url = f"http://{url}"
    doc, best_rtt, offset = None, None, 0.0
    for _ in range(max(1, int(probes))):
        t0 = time.time()
        with urllib.request.urlopen(f"{url}/timelinez",
                                    timeout=timeout) as r:
            doc = json.loads(r.read())
        t2 = time.time()
        rtt = t2 - t0
        if best_rtt is None or rtt < best_rtt:
            best_rtt = rtt
            offset = float(doc.get("wall_time", (t0 + t2) / 2)) \
                - (t0 + t2) / 2.0
    # invert: doc stamps are in the NODE's domain; local = stamp - offset
    return doc, -offset


def _lineage_items(name: str, rec: dict, node_offset: float) -> list:
    """One lineage record -> DELTA chain items in the CLI clock domain,
    clamped non-decreasing along hop order."""
    rec_off = float(rec.get("offset_s") or 0.0)
    items, floor = [], None
    hops = rec.get("hops") or {}
    for stamp, label, publisher_domain in _CHAIN:
        t = rec.get(stamp)
        if t is None:
            continue
        t = float(t)
        if publisher_domain:
            # publisher clock -> recording node's clock -> CLI clock
            t = t - rec_off
        t = t + node_offset
        if floor is not None and t < floor:
            t = floor  # causal clamp: residual skew must not reorder a chain
        floor = t
        hop_key = {"publish": "publish", "fetch": "fetch", "apply": "apply",
                   "swap": "swap", "first_predict": "serve",
                   "commit": "commit"}.get(label)
        ms = hops.get(hop_key) if hop_key else None
        detail = f" ({ms:.1f}ms)" if isinstance(ms, (int, float)) else ""
        items.append({
            "ts": t, "node": name, "kind": "DELTA",
            "what": f"{rec.get('sign')}#{rec.get('step')} {label}{detail}",
            "request_id": rec.get("trace_id"), "step": rec.get("step")})
    return items


def merge(nodes_data) -> list:
    """[(name, doc, offset_s_to_local), ...] -> one merged, skew-corrected,
    time-sorted item list. Pure function — the tests drive it with fake
    docs and deliberately skewed clocks."""
    items = []
    for name, doc, offset in nodes_data:
        for e in doc.get("events", []):
            items.append({"ts": float(e["ts"]) + offset, "node": name,
                          "kind": "EVT",
                          "what": f"{e['group']}.{e['name']}",
                          "request_id": e.get("request_id"),
                          "step": (e.get("attrs") or {}).get("step"),
                          "attrs": e.get("attrs") or {}})
        for s in doc.get("spans", []):
            items.append({"ts": float(s["start"]) + offset, "node": name,
                          "kind": "SPAN",
                          "what": f"{s['group']}.{s['name']} "
                                  f"{(s.get('duration_ms') or 0.0):.1f}ms",
                          "request_id": s.get("request_id"),
                          "step": (s.get("attrs") or {}).get("step"),
                          "attrs": s.get("attrs") or {}})
        for rec in doc.get("lineage", []):
            items.extend(_lineage_items(name, rec, offset))
    items.sort(key=lambda it: it["ts"])
    return items


def filter_items(items, request=None, version=None):
    if request is not None:
        items = [it for it in items if it.get("request_id") == request]
    if version is not None:
        items = [it for it in items if it.get("step") == int(version)]
    return items


def render(items, limit=None) -> str:
    if limit is not None:
        items = items[-int(limit):]
    width = max((len(it["node"]) for it in items), default=4)
    lines = []
    for it in items:
        ts = it["ts"]
        stamp = time.strftime("%H:%M:%S", time.localtime(ts)) \
            + f".{int((ts % 1) * 1e3):03d}"
        rid = f" rid={it['request_id']}" if it.get("request_id") else ""
        lines.append(f"[{stamp}] {it['node'].ljust(width)}  "
                     f"{it['kind']:<5} {it['what']}{rid}")
    return "\n".join(lines) if lines else "(no matching timeline items)"


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="scrape N nodes' /timelinez and print one "
                    "skew-corrected merged fleet timeline")
    ap.add_argument("nodes", nargs="+", help="node base URLs (or host:port)")
    ap.add_argument("--probes", type=int, default=3,
                    help="clock-offset round-trips per node (min-RTT wins)")
    ap.add_argument("--request", default=None,
                    help="keep only items of one trace/request id")
    ap.add_argument("--version", type=int, default=None,
                    help="keep only one delta version's chain + items")
    ap.add_argument("--limit", type=int, default=None,
                    help="print only the newest N items")
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args(argv)
    nodes_data, dead = [], []
    for node in args.nodes:
        try:
            doc, offset = probe(node, timeout=args.timeout,
                                probes=args.probes)
            name = doc.get("node") or node
            nodes_data.append((name, doc, offset))
            print(f"# node {name} ({node}): clock offset "
                  f"{offset * 1e3:+.2f}ms vs local")
        except Exception as e:  # noqa: BLE001 — a dead node degrades
            dead.append(f"# node {node} unreachable: {e}")
    for line in dead:
        print(line)
    if not nodes_data:
        print("# no node answered", file=sys.stderr)
        return 1
    items = filter_items(merge(nodes_data), request=args.request,
                         version=args.version)
    print(render(items, limit=args.limit))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
