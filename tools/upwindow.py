"""Prioritized chip-evidence battery for a relay up-window.

The axon relay to the one real v5e chip goes down for hours at a time; every
builder-side perf claim since round 1 is CPU-relative because no up-window
coincided with a measurement session (VERDICT r4 "What's missing" #1). This
script spends an up-window in strict priority order so that even a 10-minute
window yields permanent evidence:

  1. bench dim9        — the headline number (vs 86.5k/chip baseline)
  2. bench dim64       — packed (V,128)+(V,2) layout, first chip number
  3. dim64_probe       — memory_analysis(): is the padded table copy gone?
  4. bench mesh1+mesh1f— the fused exchange route on-chip (r3 chip datum 0.854x
                         predates the fused route; CPU says ~1.25x)
  5. bench pull        — p50 latency
  6. step_bisect       — stage times incl. fused vs split route (feeds the
                         v5e-64 projection arithmetic, VERDICT item 7)
  7. offload           — scan-fused offload_train_many ex/s at a >HBM table

After EACH case the raw output is appended to PERF_CHIP_R5.md and committed,
so a window that dies mid-battery still leaves everything it measured in the
repo history. Pure-Python orchestrator: jax is only imported in child
processes (a hung backend claim is uninterruptible in-process — see
bench.py's orchestrator and the same lesson in PERF.md).

Usage: python tools/upwindow.py [--skip CASE,CASE] [--no-commit]
Typically invoked by tools/chip_watcher.sh when a probe succeeds.
"""

import argparse
import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "PERF_CHIP_R5.md")
DONE = "/tmp/upwindow_r5_done.json"  # cases already green (watcher re-entry)

# (name, argv, env overrides, timeout_s). bench.py cases reuse its watchdogs/
# retries. The orchestrator's TOTAL budget must EXCEED the per-run case budget
# (the child's deadline is computed from TOTAL's remainder); +240s leaves one
# probe cycle + margin, while still failing fast if the relay drops mid-battery
# instead of eating the window's remainder on doomed retries.


def bench_case(cases, budget):
    env = {"OETPU_BENCH_CASES": cases,
           "OETPU_BENCH_BUDGET_S": str(budget),
           "OETPU_BENCH_TOTAL_BUDGET_S": str(budget + 240),
           "OETPU_BENCH_PROBE_TIMEOUT_S": "75"}
    return ([sys.executable, os.path.join(REPO, "bench.py")], env,
            budget + 300)


CASES = [
    ("bench_dim9", *bench_case("dim9", 420)),
    # dim64 may need TWO compiles now (packed attempt -> unpacked fallback,
    # r5 chip finding in PERF_CHIP_R5.md), and mesh1's fused-exchange compile
    # blew the old 420s watchdog — budgets sized for the slow path
    ("bench_dim64", *bench_case("dim64", 700)),
    ("dim64_probe",
     [sys.executable, os.path.join(REPO, "tools", "dim64_probe.py")], {}, 900),
    # one mesh case per battery entry: each is allowed a 700s first compile
    # (bench.py case_mesh1), so sharing one budget would starve the second
    # case exactly when the allowance is used; separate entries also mean a
    # relay drop loses at most one case
    ("bench_mesh1", *bench_case("mesh1", 1000)),
    ("bench_mesh1f", *bench_case("mesh1f", 1000)),
    ("bench_pull", *bench_case("pull", 300)),
    ("step_bisect",
     [sys.executable, os.path.join(REPO, "tools", "step_bisect.py")], {}, 900),
    ("offload",
     [sys.executable, os.path.join(REPO, "examples", "criteo_deepctr.py"),
      "--model", "deepfm", "--dim", "64", "--synthetic",
      "--batch-size", "4096", "--steps", "64", "--scan", "16",
      "--vocabulary", str(1 << 24), "--offload", str(1 << 20)], {}, 900),
    # 8. wire codec on-chip (bench 'wire' case: quant/dequant compute cost;
    #    S>1 byte savings are CPU-mesh-measured by tools/wire_microbench.py,
    #    whose stanza is committed here too — it needs no relay, but riding
    #    the battery keeps all BENCH stanzas in one capture file)
    ("bench_wire", *bench_case("wire", 300)),
    # 8b. round-13 in-collective codec (bench 'wire_inband' case: in-band
    #     scale pack/unpack, stochastic rounding, and the error-feedback
    #     serve overhead — the compute the quantized a2as add on-chip)
    ("bench_wire_inband", *bench_case("wire_inband", 300)),
    ("wire_microbench",
     [sys.executable, os.path.join(REPO, "tools", "wire_microbench.py")],
     {"JAX_PLATFORMS": "cpu"}, 600),
    # 9. online-sync delta pipeline (bench 'sync' case: per-delta latency /
    #    rows/s / bytes per wire format) + the soak's zero-failed-predicts
    #    invariant under live traffic. Both are host-dominated and already
    #    measured on CPU (PERF.md sync stanza); the chip entries pin that the
    #    on-device apply scatter doesn't change the story.
    ("bench_sync", *bench_case("sync", 300)),
    ("sync_soak",
     [sys.executable, os.path.join(REPO, "tools", "sync_soak.py"),
      "--steps", "24", "--persist-every", "2", "--step-delay-s", "0.2",
      "--lag-bound-steps", "12"],
     {"JAX_PLATFORMS": "cpu"}, 600),
    # 10. workload-skew telemetry overhead (bench 'skew' case: per-shard
    #     load accounting on/off + sketch ms/batch). TWO fused-exchange
    #     compiles at the mesh1 700s allowance each — budget sized for both.
    ("bench_skew", *bench_case("skew", 1700)),
    # 11. hot-row replication (bench 'hot' case: Zipf vs uniform, cache
    #     on/off — hit ratio, imbalance drop, min zero-drop capacity + the
    #     exchange-bytes model at it). The byte/imbalance wins need S >= 2
    #     shards, so like wire_microbench this entry runs on the 8-virtual-
    #     device CPU mesh (no relay needed; riding the battery keeps all
    #     BENCH stanzas in one capture file).
    ("bench_hot",
     [sys.executable, os.path.join(REPO, "bench.py")],
     {"OETPU_BENCH_CASES": "hot",
      "OETPU_BENCH_BUDGET_S": "900",
      "OETPU_BENCH_TOTAL_BUDGET_S": "1140",
      "OETPU_BENCH_PROBE_TIMEOUT_S": "75",
      "JAX_PLATFORMS": "cpu",
      "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}, 1200),
    # 12. self-driving placement (bench 'placement' case: drifting-Zipf hot
    #     set rotated mid-run, PlacementController on/off — pre/post-drift
    #     steady imbalance, hit ratio, refresh + migration counts, annex
    #     all_gather bytes). Like bench_hot this needs S >= 2, so it rides
    #     the 8-virtual-device CPU mesh; the controller itself is host-side.
    ("bench_placement",
     [sys.executable, os.path.join(REPO, "bench.py")],
     {"OETPU_BENCH_CASES": "placement",
      "OETPU_BENCH_BUDGET_S": "1100",
      "OETPU_BENCH_TOTAL_BUDGET_S": "1340",
      "OETPU_BENCH_PROBE_TIMEOUT_S": "75",
      "JAX_PLATFORMS": "cpu",
      "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}, 1400),
    # 13. round-14 ZeRO dense sharding (bench 'zero' case: dense_shard
    #     on/off — opt-state bytes per replica, ms/step). The S-fold memory
    #     win needs S >= 2 shards, so like bench_hot it rides the
    #     8-virtual-device CPU mesh; an up-window re-run pins the chip's
    #     reduce_scatter/all_gather timings on top.
    ("bench_zero",
     [sys.executable, os.path.join(REPO, "bench.py")],
     {"OETPU_BENCH_CASES": "zero",
      "OETPU_BENCH_BUDGET_S": "900",
      "OETPU_BENCH_TOTAL_BUDGET_S": "1140",
      "OETPU_BENCH_PROBE_TIMEOUT_S": "75",
      "JAX_PLATFORMS": "cpu",
      "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}, 1200),
    # 13a. round-23 sparsity-aware dense collectives (bench 'zero_sparse'
    #     case: dense_wire sparse_topk vs int8 vs fp32 grad wire bytes from
    #     the compiled HLO across a planted gradient-density sweep, with
    #     the measured-density gauge and the policy's crossover verdict at
    #     each point; loss parity asserted). NINE small compiles on the
    #     8-virtual-device CPU mesh; a chip re-run prices the sparse a2a's
    #     actual link time on top of the byte accounting.
    ("bench_zero_sparse",
     [sys.executable, os.path.join(REPO, "bench.py")],
     {"OETPU_BENCH_CASES": "zero_sparse",
      "OETPU_BENCH_BUDGET_S": "1100",
      "OETPU_BENCH_TOTAL_BUDGET_S": "1340",
      "OETPU_BENCH_PROBE_TIMEOUT_S": "75",
      "JAX_PLATFORMS": "cpu",
      "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}, 1400),
    # 13b. round-17 bytes endgame (bench 'wire_total' case: total compiled
    #     wire bytes per step — sparse a2as + hot reduce + dense collectives
    #     — round-12 fp32 system vs global-int8 vs policy-mixed wire with
    #     dense_wire="int8"; result-byte and link-accounted cuts). Needs
    #     S >= 2, so like bench_zero it rides the 8-virtual-device CPU mesh;
    #     THREE fused-exchange compiles, budget sized for them.
    ("bench_wire_total",
     [sys.executable, os.path.join(REPO, "bench.py")],
     {"OETPU_BENCH_CASES": "wire_total",
      "OETPU_BENCH_BUDGET_S": "1100",
      "OETPU_BENCH_TOTAL_BUDGET_S": "1340",
      "OETPU_BENCH_PROBE_TIMEOUT_S": "75",
      "JAX_PLATFORMS": "cpu",
      "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}, 1400),
    # 14. round-14 offload staging pipeline (bench 'offload_pipe' case:
    #     pipeline on/off x densify K in {1,4,16} — ms/round, pipeline
    #     occupancy, drained rows). Host-side two-tier cache work; no mesh
    #     or relay needed, riding the battery keeps the stanzas together.
    ("bench_offload_pipe",
     [sys.executable, os.path.join(REPO, "bench.py")],
     {"OETPU_BENCH_CASES": "offload_pipe",
      "OETPU_BENCH_BUDGET_S": "600",
      "OETPU_BENCH_TOTAL_BUDGET_S": "840",
      "OETPU_BENCH_PROBE_TIMEOUT_S": "75",
      "JAX_PLATFORMS": "cpu"}, 900),
    # 14b. round-18 software-pipelined train loop (bench 'pipeline' case:
    #     pipeline_steps on/off over K=8 scan windows — ms/step, loss bit
    #     parity, conflict-patch vs overlapped bytes). CPU pins the structure
    #     (bit-exactness + patch-byte accounting); a chip re-run pins the
    #     actual overlap speedup. TWO fused-exchange train_many compiles on
    #     the 8-virtual-device CPU mesh, budget sized like bench_wire_total.
    ("bench_pipeline",
     [sys.executable, os.path.join(REPO, "bench.py")],
     {"OETPU_BENCH_CASES": "pipeline",
      "OETPU_BENCH_BUDGET_S": "1100",
      "OETPU_BENCH_TOTAL_BUDGET_S": "1340",
      "OETPU_BENCH_PROBE_TIMEOUT_S": "75",
      "JAX_PLATFORMS": "cpu",
      "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}, 1400),
    # 14c. round-20 line-rate ingest (bench 'ingest' case: compute ceiling
    #     from pre-staged windows, then the depth-D feed-ring-fed
    #     train_stream — examples/s/chip + measured input-wait share, plus
    #     the throttled-producer attribution control). CPU pins the
    #     attribution structure (share ~0 at line rate, high when
    #     throttled); a chip re-run pins the real examples/s/chip ceiling
    #     the v5e-64 target is judged against. One fused-exchange
    #     train_many compile on the 8-virtual-device CPU mesh.
    ("bench_ingest",
     [sys.executable, os.path.join(REPO, "bench.py")],
     {"OETPU_BENCH_CASES": "ingest",
      "OETPU_BENCH_BUDGET_S": "900",
      "OETPU_BENCH_TOTAL_BUDGET_S": "1140",
      "OETPU_BENCH_PROBE_TIMEOUT_S": "75",
      "JAX_PLATFORMS": "cpu",
      "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}, 1200),
    # 15. round-16 numerics sentinel + step watch (bench 'health' case:
    #     per-step loop with sentinel+measure_every on vs off — the <= 2%
    #     overhead acceptance bound). Single-chip relay case like bench_dim9;
    #     two compiles of the dim9 step (sentinel on/off), budget sized so.
    ("bench_health", *bench_case("health", 700)),
    # 16. round-21 flight-data layer (bench 'obs2' case: per-step loop with
    #     capsules armed + history sampling + memwatch publish every 8 steps
    #     vs all off — the <= 2% overhead acceptance bound). Two compiles of
    #     the 1-device mesh step (obs on/off), budget sized like health.
    ("bench_obs2", *bench_case("obs2", 700)),
    # 17. round-22 fleet-causality layer (bench 'causality' case: per-step
    #     loop with trace-context inject/extract + lineage bookkeeping vs
    #     off — the <= 2% overhead acceptance bound). Two compiles of the
    #     1-device mesh step (on/off), budget sized like health/obs2.
    ("bench_causality", *bench_case("causality", 700)),
]


def log(msg):
    print(f"[upwindow t={time.time() - T0:7.1f}s] {msg}", flush=True)


T0 = time.time()


def append_and_commit(name, text, commit=True):
    with open(OUT, "a") as f:
        f.write(text)
    if not commit:
        return
    for attempt in range(5):
        try:
            # add (the file starts untracked) + pathspec-scoped commit: must
            # not sweep up files the interactive session staged concurrently
            subprocess.run(["git", "add", "PERF_CHIP_R5.md"], cwd=REPO,
                           check=True, capture_output=True, timeout=60)
            subprocess.run(
                ["git", "commit", "-m",
                 f"Chip evidence: {name} (upwindow battery)",
                 "--", "PERF_CHIP_R5.md"],
                cwd=REPO, check=True, capture_output=True, timeout=60)
            return
        except subprocess.CalledProcessError as e:
            # index.lock contention with the interactive session is expected;
            # "nothing to commit" means a concurrent commit already took it
            err = (e.stdout or b"").decode() + (e.stderr or b"").decode()
            if "nothing to commit" in err:
                return
            time.sleep(3 + 2 * attempt)
        except subprocess.TimeoutExpired:
            time.sleep(3)
    log(f"WARNING: could not commit {name} (left in working tree)")


def run_case(name, argv, env_over, timeout):
    log(f"case {name}: starting (timeout {timeout}s)")
    env = dict(os.environ, **env_over)
    t0 = time.time()
    try:
        p = subprocess.run(argv, cwd=REPO, env=env, capture_output=True,
                           text=True, timeout=timeout)
        rc, out, err = p.returncode, p.stdout, p.stderr
    except subprocess.TimeoutExpired as e:
        rc = 124
        out = (e.stdout or b"").decode(errors="replace") if isinstance(
            e.stdout, bytes) else (e.stdout or "")
        err = (e.stderr or b"").decode(errors="replace") if isinstance(
            e.stderr, bytes) else (e.stderr or "")
    dt = time.time() - t0
    log(f"case {name}: rc={rc} in {dt:.0f}s")
    stamp = datetime.datetime.utcnow().strftime("%Y-%m-%d %H:%M:%S UTC")
    tail = lambda s, n: "\n".join(s.strip().splitlines()[-n:])
    text = (f"\n## {name} — {stamp} (rc={rc}, {dt:.0f}s)\n\n"
            f"```\n{tail(out, 60)}\n```\n")
    if rc != 0 or not out.strip():
        text += f"\nstderr tail:\n```\n{tail(err, 40)}\n```\n"
    return rc, out, text


def probe(timeout=75):
    """One throwaway-subprocess chip probe; True iff the relay answered."""
    try:
        p = subprocess.run(
            [sys.executable, "-c",
             "import jax; d=jax.devices(); print(d); "
             "assert d[0].platform != 'cpu'"],
            capture_output=True, timeout=timeout, cwd=REPO)
        return p.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip", default="", help="comma-separated case names")
    ap.add_argument("--no-commit", action="store_true")
    ap.add_argument("--no-probe", action="store_true",
                    help="assume the relay is up (caller already probed)")
    ap.add_argument("--force", action="store_true",
                    help="re-run cases already green in a prior invocation")
    ap.add_argument("--dry-run", action="store_true",
                    help="print the battery plan (name, argv, env, timeout) "
                         "and exit without probing or running anything")
    args = ap.parse_args()
    skip = set(filter(None, args.skip.split(",")))
    if args.dry_run:
        for name, argv, env_over, timeout in CASES:
            mark = "skip" if name in skip else "run "
            env = " ".join(f"{k}={v}" for k, v in sorted(env_over.items()))
            print(f"[{mark}] {name}: timeout={timeout}s "
                  f"{env + ' ' if env else ''}{' '.join(argv)}")
        return 0
    done = set()
    if not args.force and os.path.exists(DONE):
        with open(DONE) as f:
            done = set(json.load(f))
        if done:
            log(f"prior green cases (skipping): {sorted(done)}")

    if not args.no_probe:
        log("probing relay before spending the window")
        if not probe():
            log("relay DOWN — exiting without touching PERF_CHIP_R5.md")
            return 3

    if not os.path.exists(OUT):
        append_and_commit("init", (
            "# PERF_CHIP_R5 — on-chip evidence battery (round 5)\n\n"
            "Raw per-case output from tools/upwindow.py, appended and\n"
            "committed after each case during relay up-windows. Analysis\n"
            "is folded into PERF.md; this file is the primary record.\n"),
            commit=not args.no_commit)

    results = {}
    for name, argv, env_over, timeout in CASES:
        if name in skip or name in done:
            continue
        rc, out, text = run_case(name, argv, env_over, timeout)
        append_and_commit(name, text, commit=not args.no_commit)
        results[name] = rc
        if rc == 0:
            done.add(name)
            with open(DONE, "w") as f:
                json.dump(sorted(done), f)
        if rc != 0 and not probe():
            log("relay dropped mid-battery — stopping (evidence so far is "
                "committed); rerun when it returns")
            break
    log(f"battery done: {results}")
    return 0 if all(v == 0 for v in results.values()) else 1


if __name__ == "__main__":
    sys.exit(main())
