"""On-chip HBM probe for the dim-64 packed benchmark configuration.

Round-3 finding (PERF.md "dim-64 single-chip HBM budget"): XLA's TPU gather
lowering for row widths in (32, 128) materializes a 128-lane-padded 2.0x temp
copy of the whole table, which is why the dim64 bench case runs 2^23 rows.
The split first-order layout makes the packed categorical table exactly
(V, 64+64=128) — lane-exact, so the padded copy should vanish. This probe
compiles the REAL bench program (train_many on make_deepfm(dim=64)) for the
attached TPU and prints `memory_analysis()`: run it in a relay up-window and
record temp_size vs table size in PERF.md.

Usage (needs the real chip):  python tools/dim64_probe.py [--vocab LOG2]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab", type=int, default=23,
                    help="log2 table rows (default 23 = the bench case)")
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--steps", type=int, default=8)
    args = ap.parse_args()

    import jax

    import openembedding_tpu as embed
    from openembedding_tpu.data import synthetic_criteo
    from openembedding_tpu.model import Trainer
    from openembedding_tpu.models import make_deepfm

    V = 1 << args.vocab
    print(f"platform={jax.devices()[0].platform} vocab=2^{args.vocab}",
          flush=True)
    model = make_deepfm(vocabulary=V, dim=64)
    tr = Trainer(model, embed.Adagrad(learning_rate=0.05))
    batches = list(synthetic_criteo(args.batch, id_space=V, steps=args.steps,
                                    seed=1, ids_dtype=np.int32))
    stacked = jax.device_put(jax.tree_util.tree_map(
        lambda *xs: np.stack(xs), *batches))
    state = tr.init(batches[0])
    layouts = tr._packed_layouts(state)
    print(f"packed layouts: { {k: v for k, v in layouts.items()} }", flush=True)
    compiled = jax.jit(tr.train_many, donate_argnums=(0,)).lower(
        state, stacked).compile()
    ma = compiled.memory_analysis()
    table_bytes = V * 128 * 4
    print(f"table (packed, V x 128 f32): {table_bytes / 2**30:.2f} GiB")
    if ma is None:
        print("memory_analysis() unavailable on this backend", flush=True)
        return 1
    for f in ("temp_size_in_bytes", "argument_size_in_bytes",
              "output_size_in_bytes", "alias_size_in_bytes"):
        v = getattr(ma, f, None)
        if v is not None:
            print(f"{f}: {v / 2**30:.3f} GiB")
    temp = getattr(ma, "temp_size_in_bytes", None)
    if temp is None:
        print("temp_size_in_bytes unavailable on this backend", flush=True)
    else:
        ratio = temp / table_bytes
        print(f"temp/table ratio: {ratio:.2f} "
              f"({'NO padded table copy' if ratio < 1.0 else 'TABLE-SIZED TEMP PRESENT'})")
    # run one dispatch so the number is a real program, not just a compile
    state, m = compiled(state, stacked)
    print(f"executed: loss={float(np.asarray(m['loss'])[-1]):.4f}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
