"""On-chip HBM probe for the dim-64 packed benchmark configuration.

Round-3 finding (PERF.md "dim-64 single-chip HBM budget"): XLA's TPU gather
lowering for row widths in (32, 128) materializes a 128-lane-padded 2.0x temp
copy of the whole table, which is why the dim64 bench case runs 2^23 rows.
The split first-order layout makes the packed categorical table exactly
(V, 64+64=128) — lane-exact, so the padded copy should vanish. This probe
compiles the REAL bench program (train_many on make_deepfm(dim=64)) for the
attached TPU and prints `memory_analysis()`: run it in a relay up-window and
record temp_size vs table size in PERF.md.

Usage (needs the real chip):  python tools/dim64_probe.py [--vocab LOG2]
"""

import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _probe_one(log2_vocab, batch, steps, packed):
    """Compile (and once-execute) the bench dim64 program at one config.
    -> (ok, report dict). Never raises: the error HEAD (the part XLA's
    allocation dump buries) is captured into the report."""
    import jax

    import openembedding_tpu as embed
    from openembedding_tpu.data import synthetic_criteo
    from openembedding_tpu.model import Trainer
    from openembedding_tpu.models import make_deepfm
    from openembedding_tpu.ops import sparse as sparse_ops

    sparse_ops.PACKED_MAX_BYTES = (4 << 30) if packed else 0
    V = 1 << log2_vocab
    rep = {"vocab_log2": log2_vocab, "packed": packed}
    model = make_deepfm(vocabulary=V, dim=64)
    tr = Trainer(model, embed.Adagrad(learning_rate=0.05))
    batches = list(synthetic_criteo(batch, id_space=V, steps=steps,
                                    seed=1, ids_dtype=np.int32))
    stacked = jax.device_put(jax.tree_util.tree_map(
        lambda *xs: np.stack(xs), *batches))
    state = tr.init(batches[0])
    layouts = tr._packed_layouts(state)
    rep["layouts"] = {k: v for k, v in layouts.items()}
    try:
        compiled = jax.jit(tr.train_many, donate_argnums=(0,)).lower(
            state, stacked).compile()
    except Exception as e:  # noqa: BLE001 — the failure IS the datum
        head = "\n".join(f"{type(e).__name__}: {e}".splitlines()[:12])
        rep["compile_error_head"] = head
        return False, rep
    ma = compiled.memory_analysis()
    table_bytes = V * (128 if packed else 64) * 4
    rep["table_gib"] = round(table_bytes / 2**30, 3)
    if ma is not None:
        for f in ("temp_size_in_bytes", "argument_size_in_bytes",
                  "output_size_in_bytes", "alias_size_in_bytes"):
            v = getattr(ma, f, None)
            if v is not None:
                rep[f] = round(v / 2**30, 3)
        temp = getattr(ma, "temp_size_in_bytes", None)
        if temp is not None:
            rep["temp_over_table"] = round(temp / table_bytes, 2)
    try:
        state, m = compiled(state, stacked)
        rep["loss"] = round(float(np.asarray(m["loss"])[-1]), 4)
    except Exception as e:  # noqa: BLE001
        rep["exec_error_head"] = "\n".join(
            f"{type(e).__name__}: {e}".splitlines()[:12])
        return False, rep
    return True, rep


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab", type=int, default=23,
                    help="log2 table rows (default 23 = the bench case)")
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--no-bisect", action="store_true",
                    help="single config only (the pre-r5 behavior)")
    args = ap.parse_args()

    import jax
    print(f"platform={jax.devices()[0].platform}", flush=True)

    # r5 chip finding (PERF_CHIP_R5.md): the packed program at 2^23 dies in
    # remote compile. Each probe runs in THIS process sequentially — the
    # packing knob is module state, reset per _probe_one call.
    ok, rep = _probe_one(args.vocab, args.batch, args.steps, packed=True)
    print(f"packed@2^{args.vocab}: {rep}", flush=True)
    if ok or args.no_bisect:
        return 0 if ok else 1

    # packed fails at the bench vocab: find the largest packed vocab that
    # compiles (the HBM headroom curve), then the unpacked control at the
    # ORIGINAL vocab — together they say whether the 4 GiB packing gate or
    # the packed program structure is what the chip rejects.
    for lv in range(args.vocab - 1, args.vocab - 4, -1):
        ok, rep = _probe_one(lv, args.batch, args.steps, packed=True)
        print(f"packed@2^{lv}: {rep}", flush=True)
        if ok:
            break
    ok_u, rep_u = _probe_one(args.vocab, args.batch, args.steps, packed=False)
    print(f"unpacked@2^{args.vocab}: {rep_u}", flush=True)
    return 0 if ok_u else 1


if __name__ == "__main__":
    sys.exit(main())
