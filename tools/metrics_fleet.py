"""Fleet-wide /metrics aggregation from the command line.

    python tools/metrics_fleet.py http://trainer:8501 http://replica1:8501 \
        http://replica2:8501                  # merged exposition on stdout
    python tools/metrics_fleet.py node1:8501 node2:8501 --summary

Scrapes each node's `GET /metrics` and merges them with
`utils/metrics.merge_prometheus`: counters and histogram bucket/sum/count
series SUM across nodes (bucket series are de-cumulated per node and
re-cumulated on the union `le` grid), gauges keep one series per node with
an added `instance` label. The same merge backs `GET /fleetz` on any serving
node started with `--peers` — this tool is the server-less twin for
operators and cron jobs. Unreachable nodes degrade to a `#` comment line
(exit stays 0 while at least one node answered).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from openembedding_tpu.utils.metrics import (merge_prometheus,  # noqa: E402
                                             parse_prometheus)


def scrape(node: str, timeout: float) -> str:
    import urllib.request
    url = node.rstrip("/")
    if not url.startswith("http"):
        url = f"http://{url}"
    if not url.endswith("/metrics"):
        url += "/metrics"
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def freshness_table(scrapes) -> str:
    """"Who is stale" table from PER-NODE (unmerged) scrapes: each node's
    `sync.freshness_ms` / head / applied version gauges side by side — the
    CLI twin of /fleetz's `# fleet freshness:` comment lines."""
    cols = {"oetpu_sync_freshness_ms": "freshness_ms",
            "oetpu_sync_head_version": "head",
            "oetpu_sync_applied_version": "applied",
            "oetpu_sync_version_lag_steps": "lag_steps"}
    rows = []
    for node, text in scrapes:
        vals = {}
        for name, _labels, value in parse_prometheus(text)["samples"]:
            if name in cols:
                vals[cols[name]] = value
        rows.append((node, vals))
    if not any(v for _, v in rows):
        return "(no sync freshness series on any node)"
    width = max(len(n) for n, _ in rows)
    order = ("freshness_ms", "head", "applied", "lag_steps")
    head = "node".ljust(width) + "".join(c.rjust(14) for c in order)
    lines = [head, "-" * len(head)]
    for node, vals in rows:
        cells = "".join(
            (f"{vals[c]:,.1f}" if c == "freshness_ms" else f"{vals[c]:,.0f}")
            .rjust(14) if c in vals else "-".rjust(14) for c in order)
        lines.append(node.ljust(width) + cells)
    return "\n".join(lines)


def summary(text: str) -> str:
    """Counter/sum table of the merged exposition (quick fleet health read)."""
    rows = []
    for name, labels, value in parse_prometheus(text)["samples"]:
        if name.endswith(("_total", "_count")):
            lab = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
            rows.append((f"{name}{{{lab}}}" if lab else name, value))
    if not rows:
        return "(no counter series)"
    width = max(len(k) for k, _ in rows)
    return "\n".join(f"{k.ljust(width)}  {v:,.0f}" for k, v in sorted(rows))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="scrape N nodes' /metrics and print the merged fleet "
                    "exposition")
    ap.add_argument("nodes", nargs="+", help="node base URLs (or host:port)")
    ap.add_argument("--timeout", type=float, default=10.0)
    ap.add_argument("--summary", action="store_true",
                    help="print a counter summary table instead of the full "
                         "merged exposition")
    ap.add_argument("--freshness", action="store_true",
                    help="print the per-node sync freshness / lineage table "
                         "(who is stale) instead of the merged exposition")
    args = ap.parse_args(argv)
    scrapes, dead = [], []
    for node in args.nodes:
        try:
            scrapes.append((node, scrape(node, args.timeout)))
        except Exception as e:  # noqa: BLE001 — a dead node degrades, not dies
            dead.append(f"# fleet: node {node} unreachable: {e}")
    for line in dead:
        print(line)
    if not scrapes:
        print("# fleet: no node answered", file=sys.stderr)
        return 1
    if args.freshness:
        print(freshness_table(scrapes))
        return 0
    merged = merge_prometheus(scrapes)
    print(summary(merged) if args.summary else merged, end="")
    if not args.summary:
        print()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
