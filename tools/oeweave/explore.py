"""oeweave exploration: policies, schedules, replay tokens.

A *schedule* is the sequence of choice indices the scheduler recorded
(`WeaveScheduler.choices`). Three policies produce schedules:

- `RandomPolicy(seed)` — seeded bounded-random: at every decision pick a
  uniformly random candidate. Same seed, same scenario → identical
  schedule (the seed-determinism pin in tests).
- `SweepPolicy(overrides)` — preemption-bounded sweep: run the baseline
  (always keep the current thread running when possible; else lowest tid)
  but at the decision indices in `overrides` force a specific alternative.
  `sweep()` enumerates all single-preemption schedules, then (budget
  permitting) pairs — a bounded systematic walk of "what if a context
  switch happened *here*".
- `ReplayPolicy(choices)` — replay a recorded schedule; past the recorded
  tail it always picks index 0, which is deterministic, so a token
  replays bit-for-bit even though teardown may take extra decisions.

A failing schedule is reported as a replay token:

    oeweave1:<base36 choice per decision>

`replay(scenario, token)` re-runs the exact interleaving and re-raises
the failure — the token is the bug report.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from .scheduler import (WeaveBudget, WeaveError, WeaveScheduler)

TOKEN_PREFIX = "oeweave1:"
_ALPHA = "0123456789abcdefghijklmnopqrstuvwxyz"


def encode_token(choices: List[int]) -> str:
    parts = []
    for c in choices:
        if c < 36:
            parts.append(_ALPHA[c])
        else:  # unreachably wide decision; escape it
            parts.append(f"({c})")
    return TOKEN_PREFIX + "".join(parts)


def decode_token(token: str) -> List[int]:
    if not token.startswith(TOKEN_PREFIX):
        raise ValueError(f"not an oeweave replay token: {token!r}")
    body = token[len(TOKEN_PREFIX):]
    out: List[int] = []
    i = 0
    while i < len(body):
        ch = body[i]
        if ch == "(":
            j = body.index(")", i)
            out.append(int(body[i + 1:j]))
            i = j + 1
        else:
            out.append(_ALPHA.index(ch))
            i += 1
    return out


class RandomPolicy:
    def __init__(self, seed: int):
        self._rng = random.Random(seed)

    def __call__(self, n: int, tids: List[int], runnables: List[bool],
                 cur_tid: int, decision: int) -> int:
        return self._rng.randrange(n)


class SweepPolicy:
    """Baseline run-to-completion order with forced preemptions.

    Default choice keeps the current thread running while it is RUNNABLE
    (no preemption), else runs the lowest-tid runnable candidate, and only
    fires a timeout when nothing is runnable — i.e. the schedule an
    uncontended real machine would produce. `overrides[d] = k` forces
    candidate k at decision d (the injected context switch).
    """

    def __init__(self, overrides: Optional[Dict[int, int]] = None):
        self.overrides = overrides or {}

    def __call__(self, n: int, tids: List[int], runnables: List[bool],
                 cur_tid: int, decision: int) -> int:
        if decision in self.overrides:
            return self.overrides[decision] % n
        if cur_tid in tids and runnables[tids.index(cur_tid)]:
            return tids.index(cur_tid)
        for i, r in enumerate(runnables):
            if r:
                return i
        return 0


class ReplayPolicy:
    def __init__(self, choices: List[int]):
        self.choices = choices

    def __call__(self, n: int, tids: List[int], runnables: List[bool],
                 cur_tid: int, decision: int) -> int:
        if decision < len(self.choices):
            return self.choices[decision] % n
        return 0


@dataclass
class Failure:
    token: str
    error: str
    kind: str  # exception | deadlock | leak


@dataclass
class Result:
    schedules_explored: int = 0
    truncated: int = 0
    failures: List[Failure] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.failures


def run_schedule(scenario: Callable[[], None], policy,
                 max_decisions: int = 20000):
    """One schedule. Returns (failure_or_None, scheduler)."""
    sched = WeaveScheduler(policy, max_decisions=max_decisions)
    try:
        sched.run(scenario)
    except WeaveBudget:
        return Failure(encode_token(sched.choices), "budget", "truncated"), sched
    except BaseException as e:  # noqa: BLE001 — every failure gets a token
        kind = type(e).__name__
        if "Deadlock" in kind:
            kind = "deadlock"
        elif "Leak" in kind:
            kind = "leak"
        else:
            kind = "exception"
        return Failure(encode_token(sched.choices), repr(e), kind), sched
    return None, sched


def explore(scenario: Callable[[], None], *,
            random_schedules: int = 20, seed: int = 0,
            preemption_schedules: int = 40, preemption_depth: int = 2,
            max_decisions: int = 20000,
            stop_on_first: bool = False) -> Result:
    """Random exploration + preemption-bounded sweep over one scenario."""
    res = Result()

    def record(fail: Optional[Failure]) -> bool:
        res.schedules_explored += 1
        if fail is None:
            return False
        if fail.kind == "truncated":
            res.truncated += 1
            return False
        res.failures.append(fail)
        return True

    # seeded bounded-random
    for i in range(random_schedules):
        fail, _ = run_schedule(scenario, RandomPolicy(seed + i), max_decisions)
        if record(fail) and stop_on_first:
            return res

    # preemption-bounded sweep: baseline, then forced alternatives at each
    # decision point, breadth-first up to `preemption_depth` preemptions.
    budget = preemption_schedules
    fail, base = run_schedule(scenario, SweepPolicy(), max_decisions)
    if record(fail) and stop_on_first:
        return res
    budget -= 1
    frontier: List[Dict[int, int]] = [{}]
    counts_for: Dict[str, List[int]] = {"": list(base.candidate_counts)}
    for depth in range(preemption_depth):
        nxt: List[Dict[int, int]] = []
        for ov in frontier:
            key = ",".join(f"{d}:{k}" for d, k in sorted(ov.items()))
            counts = counts_for.get(key)
            if counts is None:
                continue
            start = (max(ov) + 1) if ov else 0
            for d in range(start, len(counts)):
                for alt in range(1, counts[d]):
                    if budget <= 0:
                        return res
                    child = dict(ov)
                    child[d] = alt
                    fail, sched = run_schedule(
                        scenario, SweepPolicy(child), max_decisions)
                    budget -= 1
                    if record(fail) and stop_on_first:
                        return res
                    ckey = ",".join(
                        f"{dd}:{kk}" for dd, kk in sorted(child.items()))
                    counts_for[ckey] = list(sched.candidate_counts)
                    nxt.append(child)
        frontier = nxt
        if not frontier:
            break
    return res


def replay(scenario: Callable[[], None], token: str,
           max_decisions: int = 20000) -> Optional[Failure]:
    """Re-run the exact recorded interleaving; returns its Failure (or None
    if the schedule no longer fails — e.g. after a fix)."""
    choices = decode_token(token)
    fail, _ = run_schedule(scenario, ReplayPolicy(choices), max_decisions)
    return fail
