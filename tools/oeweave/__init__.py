"""oeweave: deterministic interleaving checker for the threaded control plane.

Run it:
    make weave                      # explore every scenario (CI budget)
    python -m tools.oeweave         # same, direct
    python -m tools.oeweave sync_subscriber --schedules 50
    python -m tools.oeweave --replay 'sync_subscriber:oeweave1:0121...'

Library surface:
    from tools.oeweave import explore, replay, scenarios
    result = explore.explore(scenarios.SCENARIOS["sync_subscriber"])

See `scheduler.py` for the execution model and `explore.py` for policies
and replay tokens.
"""

from . import explore, scheduler  # noqa: F401
from .explore import Failure, Result, decode_token, encode_token, replay  # noqa: F401
from .scheduler import (WeaveDeadlock, WeaveError, WeaveLeak,  # noqa: F401
                        WeaveScheduler, yield_point)
