"""oeweave scenarios: the threaded control-plane modules under the scheduler.

Each scenario is a zero-arg callable run under `WeaveScheduler` (primitives
patched): it constructs the object under test INSIDE the weave context (so
its locks/queues/threads are deterministic), drives it from several weave
threads, and asserts the invariants the module's docs promise — no torn
status, no lost wakeups, no double-apply, idempotent start/stop, clean
shutdown. Failures (assertion, deadlock, leak) surface through
`explore.Result` with a replay token.

Scenarios script the *wire/device* half (fake `sync_once`, stub model,
stubbed `decide`) — the point is the host-side locking, not the payloads.
`warm()` pre-imports the heavy modules: imports inside a weave run could
spawn real threads mid-schedule (jax pools) and must already be done.
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from types import SimpleNamespace
from typing import Callable, Dict

import numpy as np

_WARMED = False


def warm() -> None:
    """Import every module a scenario touches, before any weave run."""
    global _WARMED
    if _WARMED:
        return
    import openembedding_tpu.data.ingest        # noqa: F401
    import openembedding_tpu.persist            # noqa: F401
    import openembedding_tpu.serving            # noqa: F401
    import openembedding_tpu.sync.subscriber    # noqa: F401
    import openembedding_tpu.tables.host_offload  # noqa: F401
    import openembedding_tpu.placement.controller  # noqa: F401
    import openembedding_tpu.utils.metrics      # noqa: F401
    import openembedding_tpu.utils.sketch       # noqa: F401
    import openembedding_tpu.utils.slo          # noqa: F401
    import openembedding_tpu.export             # noqa: F401
    _WARMED = True


# -- SyncSubscriber: IDLE -> FETCHING -> APPLYING -> DEGRADED machine ---------


def sync_subscriber() -> None:
    """Racing start/start, concurrent status readers, fault injection,
    racing stop/stop. Invariants: (state=DEGRADED => reason set),
    applied == version (both bump under one lock hold), exactly one worker
    ever spawned, `_thread` None after stop, zero leaks."""
    from openembedding_tpu.sync import subscriber as sub
    s = sub.SyncSubscriber(manager=None, model_sign="m", feed="http://feed",
                           interval_s=0.01, max_backoff_s=0.05)
    script = ["ok", "fail", "ok", "ok"]

    def fake_sync_once() -> int:
        outcome = script.pop(0) if script else "ok"
        s._set_state(sub.FETCHING)
        if outcome == "fail":
            raise sub.SyncError("injected fault")
        s._set_state(sub.APPLYING)
        with s._mu:
            s.version = (s.version or 0) + 1
            s.applied += 1
        s._set_state(sub.IDLE)
        return 1

    s.sync_once = fake_sync_once
    runs = []
    orig_run = s._run

    def counted_run() -> None:
        runs.append(1)
        orig_run()

    s._run = counted_run

    def reader() -> None:
        for _ in range(3):
            st = s.status()
            if st["state"] == sub.DEGRADED:
                assert st["last_degraded_reason"], \
                    "torn status: DEGRADED without a reason"
            assert st["applied"] == (st["version"] or 0), \
                f"torn (version, applied): {st['version']}, {st['applied']}"
            time.sleep(0.005)

    starters = [threading.Thread(target=s.start, name=f"start{i}")
                for i in range(2)]
    readers = [threading.Thread(target=reader, name=f"read{i}")
               for i in range(2)]
    for t in starters + readers:
        t.start()
    for t in starters + readers:
        t.join()
    time.sleep(0.02)  # let the worker take some polls
    stoppers = [threading.Thread(target=s.stop, name=f"stop{i}")
                for i in range(2)]
    for t in stoppers:
        t.start()
    for t in stoppers:
        t.join()
    assert len(runs) <= 1, f"start() leaked {len(runs)} workers"
    with s._mu:
        assert s._thread is None, "stop() left _thread set"
    st = s.status()
    assert st["applied"] == (st["version"] or 0)


# -- Subscriber lineage bookkeeping: hop records vs readers vs note_serve -----


def sync_lineage() -> None:
    """Concurrent `_record_lineage`/`_note_clock` writers racing `status()`
    readers, duplicate `note_serve` calls, and a `LineageBook.export` reader.
    Invariants: the `last_hops` snapshot in status() is untorn (every hop in
    the snapshot encodes the snapshot's own step), `first_serve` is written
    exactly once under a duplicate-predict race (its serve hop agrees with
    whichever call won), the clock-offset EWMA of a constant sample stays at
    that constant, and export() never tears mid-record."""
    from openembedding_tpu.sync import lineage
    from openembedding_tpu.sync import subscriber as sub

    s = sub.SyncSubscriber(manager=None, model_sign="m", feed="http://feed",
                           interval_s=0.01)
    book = lineage.LineageBook(capacity=8)  # local: schedules must not share
    # pre-seed the served delta: note_serve on an unknown record is a no-op,
    # and the duplicate-serve race must not depend on beating the writer
    book.record("m", 2, swapped=2.0)

    def writer() -> None:
        for k in range(1, 5):
            b = 10.0 * k
            with s._mu:
                s._births[k] = b
                s._head_times[k] = b
                s._feed_seen[k] = b
            # every local-domain hop of step k is exactly k*10ms — a torn
            # snapshot mixing two steps' hops is mechanically detectable
            s._record_lineage(k, b + k * 0.01, b + 2 * k * 0.01,
                              b + 3 * k * 0.01)
            book.record("m", k, swapped=float(k))
            time.sleep(0.002)

    def clocker() -> None:
        for _ in range(6):
            s._note_clock(2000.5, 1999.9, 2000.1)  # offset exactly +0.5s
            time.sleep(0.002)

    def reader() -> None:
        for _ in range(6):
            st = s.status()
            lh = st.get("last_hops")
            if lh is not None:
                k = lh["step"]
                for hop in ("fetch", "apply", "swap"):
                    got = lh["hops"][hop]
                    assert abs(got - k * 10.0) < 0.5, \
                        f"torn last_hops: step {k} {hop}={got}"
            off = st.get("clock_offset_ms") or 0.0
            assert 0.0 <= off <= 500.0 + 1e-6, f"offset escaped EWMA: {off}"
            for rec in book.export():
                assert rec.get("step") is not None, f"torn export: {rec}"
            time.sleep(0.002)

    def server(now: float) -> None:
        book.note_serve("m", 2, now=now)

    threads = ([threading.Thread(target=writer, name="write")]
               + [threading.Thread(target=clocker, name="clock")]
               + [threading.Thread(target=reader, name=f"read{i}")
                  for i in range(2)]
               + [threading.Thread(target=server, args=(n,), name=f"srv{n}")
                  for n in (2.25, 9.0)])
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    rec = book.get("m", 2)
    assert rec is not None and rec.get("first_serve") in (2.25, 9.0), rec
    # the serve hop must agree with whichever duplicate won first_serve
    want = (rec["first_serve"] - 2.0) * 1e3
    assert abs(rec["hops"]["serve"] - want) < 1e-6, rec
    off_ms = s.status()["clock_offset_ms"]
    assert abs(off_ms - 500.0) < 1e-6, f"EWMA of constant drifted: {off_ms}"


# -- MicroBatcher: leader/follower window under the shared condition ----------


def micro_batcher() -> None:
    """N concurrent predicts through one group window. Invariants: every
    request gets exactly its own logits row back (no cross-wiring, no lost
    wakeup leaves a follower parked), groups map drains empty."""
    from openembedding_tpu import serving

    mb = serving.MicroBatcher(manager=None, window_ms=5.0, max_batch=4)

    class _Model:
        def predict(self, merged):
            return np.asarray(merged["sparse"]["f"], np.float32)

    model = _Model()
    outs: Dict[int, np.ndarray] = {}

    def req(i: int) -> None:
        batch = {"sparse": {"f": np.array([[float(i)]], np.float32)}}
        outs[i] = mb.predict(model, "m", batch)

    threads = [threading.Thread(target=req, args=(i,), name=f"req{i}")
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(4):
        assert outs[i].shape[0] == 1 and float(outs[i][0, 0]) == float(i), \
            f"request {i} got someone else's rows: {outs[i]!r}"
    assert not mb._groups, f"groups not drained: {mb._groups!r}"


# -- PeriodicReporter ---------------------------------------------------------


def periodic_reporter() -> None:
    """Racing start/start and stop/stop. Invariants: exactly one reporter
    thread, `_thread` None after stop, zero leaks."""
    from openembedding_tpu.utils import metrics as m

    rep = m.PeriodicReporter(interval=0.01, sink=lambda s: None, reset=False)
    runs = []
    orig_run = rep._run

    def counted_run() -> None:
        runs.append(1)
        orig_run()

    rep._run = counted_run
    starters = [threading.Thread(target=rep.start, name=f"start{i}")
                for i in range(2)]
    for t in starters:
        t.start()
    for t in starters:
        t.join()
    time.sleep(0.03)
    stoppers = [threading.Thread(target=rep.stop, name=f"stop{i}")
                for i in range(2)]
    for t in stoppers:
        t.start()
    for t in stoppers:
        t.join()
    assert len(runs) <= 1, f"start() leaked {len(runs)} reporter threads"
    with rep._lock:
        assert rep._thread is None, "stop() left _thread set"


# -- PlacementController watcher ----------------------------------------------


def placement_watcher() -> None:
    """Watcher parks decisions, on_step consumes them, racing start/stop.
    Invariants: a parked decision is applied at most once (no double-apply),
    idempotent start, clean stop."""
    from openembedding_tpu.placement.controller import PlacementController

    trainer = SimpleNamespace(mig_enabled=False, hot_enabled=False)
    policy = SimpleNamespace(hot_budget_bytes=0, imbalance_target=0.0)
    ctrl = PlacementController(trainer, policy, interval_steps=0)
    decision = SimpleNamespace(refresh=True, migrate=False, tables={},
                               reason="weave")
    decided = []

    def fake_decide(state=None):
        decided.append(1)
        return decision

    ctrl.decide = fake_decide
    starters = [threading.Thread(target=ctrl.start, args=(0.01,),
                                 name=f"start{i}") for i in range(2)]
    for t in starters:
        t.start()
    applied_rounds = 0
    for step in range(1, 5):
        with ctrl._lock:
            before = ctrl._pending
        ctrl.on_step(None, step=step)
        if before is not None:
            applied_rounds += 1
        time.sleep(0.008)
    stoppers = [threading.Thread(target=ctrl.stop, name=f"stop{i}")
                for i in range(2)]
    for t in stoppers:
        t.start()
    for t in starters + stoppers:
        t.join()
    ctrl.stop()
    with ctrl._lock:
        t = ctrl._thread
    if t is not None:
        # stop() joins with a timeout; under adversarial scheduling that can
        # expire with the watcher still runnable — the invariant is that it
        # EVENTUALLY exits (a stuck watcher fails as deadlock/leak)
        t.join()
    assert t is None or not t.is_alive(), "watcher still alive after stop"
    # on_step swapped _pending out atomically: a decision parked once is
    # never applied twice, so rounds applied <= rounds decided
    assert applied_rounds <= len(decided)


# -- HostOffloadTable's host store (the stage ring's shared state) ------------


def host_offload_store() -> None:
    """The staging worker's `lookup` racing the training thread's
    merge/defer/drain. Invariants: a reader only ever sees fully-merged
    values (monotone versions k=1..K for one id, never a torn row), and
    `snapshot()` is internally consistent."""
    from openembedding_tpu.tables.host_offload import HostStore

    store = HostStore(dim=2, slot_widths={"m": 1})
    rounds = 5

    def writer() -> None:
        for k in range(1, rounds + 1):
            ids = np.array([7], np.int64)
            w = np.full((1, 2), float(k), np.float32)
            sl = {"m": np.full((1, 1), float(k), np.float32)}
            if k % 2:
                store.merge(ids, w, sl)
            else:
                store.defer(ids, w, sl)
                store.drain()

    seen = []

    def reader() -> None:
        for _ in range(rounds):
            hit, w, sl = store.lookup(np.array([7], np.int64))
            if hit[0]:
                assert w[0, 0] == w[0, 1] == sl["m"][0, 0], \
                    f"torn row: weights {w[0]!r} slots {sl['m'][0]!r}"
                seen.append(float(w[0, 0]))
            time.sleep(0.001)

    def snapshotter() -> None:
        snap = store.snapshot()
        assert len(snap.ids) == len(snap.weights), "torn snapshot"

    threads = [threading.Thread(target=writer, name="writer"),
               threading.Thread(target=reader, name="reader"),
               threading.Thread(target=snapshotter, name="snap")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert seen == sorted(seen), f"non-monotone reads: {seen}"


# -- AsyncPersister / GC ------------------------------------------------------


def async_persister() -> None:
    """persist() from the training thread racing wait() and double close().
    Invariants: every submitted persist commits, close is idempotent (the
    double-close used to deadlock on the sentinel's task_done), writer
    thread joins, zero leaks."""
    from openembedding_tpu.persist import AsyncPersister, PersistPolicy

    root = tempfile.mkdtemp(prefix="oeweave-persist-")
    trainer = SimpleNamespace(num_shards=1,
                              externalize=lambda state: state)
    p = AsyncPersister(trainer, model=None, root=root, window=1, keep=10,
                       policy=PersistPolicy(every_steps=1))
    committed = []
    p._write_full_payload = (
        lambda snapshot, stores, tmp: (os.makedirs(tmp, exist_ok=True),
                                       committed.append(1)))

    def producer() -> None:
        for step in (1, 2, 3):
            p.persist(SimpleNamespace(step=step))

    def waiter() -> None:
        p.wait()

    threads = [threading.Thread(target=producer, name="producer"),
               threading.Thread(target=waiter, name="waiter")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    closers = [threading.Thread(target=p.close, name=f"close{i}")
               for i in range(2)]
    for t in closers:
        t.start()
    for t in closers:
        t.join()
    p.close()  # third, sequential: must stay a no-op
    assert len(committed) == 3, f"lost persists: {len(committed)}/3 written"
    # close() joins with a timeout, and an adversarial schedule may starve
    # the (runnable) writer past any timeout — "stopped" here means the
    # writer EVENTUALLY exits once scheduled, so join untimed before
    # asserting. A writer that never exits still fails: deadlock/leak.
    p._thread.join()
    assert not p._thread.is_alive(), "writer thread alive after close"


# -- SkewMonitor --------------------------------------------------------------


def skew_monitor() -> None:
    """Two producers feeding the bounded queue, drain, close. Invariants:
    every accepted batch is folded in, close() joins the worker (the leak
    the thread-lifecycle pass flagged), zero leaks."""
    from openembedding_tpu.utils.sketch import SkewMonitor

    mon = SkewMonitor(k=8, queue_size=16)
    accepted = []

    def producer(base: int) -> None:
        for i in range(3):
            if mon.observe("t", np.array([base + i, base], np.int64)):
                accepted.append(2)

    threads = [threading.Thread(target=producer, args=(b,), name=f"prod{b}")
               for b in (10, 20)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    mon.drain()
    total = sum(sk.total for sk in [mon.sketch(t) for t in mon.tables()])
    assert total == sum(accepted), \
        f"accepted {sum(accepted)} ids but folded {total}"
    mon.close()
    with mon._lock:
        t = mon._thread
    assert t is None or not t.is_alive(), "worker alive after close"


# -- SLOEvaluator -------------------------------------------------------------


def slo_evaluator() -> None:
    """Racing start/start, evaluate_now from a second thread mid-tick,
    racing stop/stop. Invariants: one evaluator thread, snapshot always a
    consistent list, `_thread` None after stop."""
    from openembedding_tpu.utils.slo import SLOEvaluator

    ev = SLOEvaluator(specs=[], interval_s=0.01)
    runs = []
    orig_run = ev._run

    def counted_run() -> None:
        runs.append(1)
        orig_run()

    ev._run = counted_run

    def evaluator() -> None:
        for _ in range(2):
            ev.evaluate_now()
            ev.snapshot()
            time.sleep(0.004)

    starters = [threading.Thread(target=ev.start, name=f"start{i}")
                for i in range(2)]
    side = threading.Thread(target=evaluator, name="eval")
    for t in starters + [side]:
        t.start()
    for t in starters + [side]:
        t.join()
    time.sleep(0.02)
    stoppers = [threading.Thread(target=ev.stop, name=f"stop{i}")
                for i in range(2)]
    for t in stoppers:
        t.start()
    for t in stoppers:
        t.join()
    assert len(runs) <= 1, f"start() leaked {len(runs)} evaluator threads"
    with ev._lock:
        assert ev._thread is None, "stop() left _thread set"


# -- ingest FeedRing (the depth-D device feed ring, host mode) ----------------


def feed_ring() -> None:
    """Producer staging into the bounded ring racing the consumer and a
    concurrent close (the early-exit path). Invariants: delivered batches
    are a PREFIX of the source in source order (the reorder/ring contract —
    no skips, no reordering, no duplicates), close() always joins the
    producer (`_thread` None — the round-19 leak class), delivered+dropped
    never exceeds what the source produced, and a second close is a no-op."""
    from openembedding_tpu.data.ingest import FeedRing

    src = [{"label": np.full((2,), float(i), np.float32)} for i in range(6)]
    ring = FeedRing(iter(src), depth=2, device=False, label="weave")
    got = []

    def consumer() -> None:
        for b in ring:
            got.append(int(b["label"][0]))
            if len(got) >= 3:
                break  # early exit: the drain path must reap the producer

    def closer() -> None:
        time.sleep(0.005)
        ring.close()

    threads = [threading.Thread(target=consumer, name="consume"),
               threading.Thread(target=closer, name="close")]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    ring.close()  # idempotent; also covers the consumer-broke-early case
    assert got == list(range(len(got))), \
        f"ring delivered out of order or with gaps: {got}"
    assert len(got) <= len(src)
    with ring._lock:
        assert ring._thread is None, "close() left the producer thread set"


# -- ingest ParsePool (bounded workers + sequence-numbered reorder) -----------


def parse_pool() -> None:
    """Adversarially-delayed workers racing the reorder stage and an early
    close. Invariants: emitted payloads are a prefix of the task sequence in
    DISPATCH order regardless of worker scheduling (the sequence-number
    contract), an injected parse fault surfaces at its sequence position
    (everything before it emitted first), and close() joins dispatcher and
    every worker."""
    from openembedding_tpu.data.ingest import ParsePool

    def parse(task):
        time.sleep(0.002 if task % 2 else 0.0)  # adversarial skew
        if task == 4:
            raise RuntimeError("injected parse fault")
        return task * 10

    pool = ParsePool(range(6), parse, workers=3, label="weave")
    got = []
    fault = []
    try:
        for payload in pool:
            got.append(payload)
    except RuntimeError:
        fault.append(1)
    assert got == [0, 10, 20, 30], \
        f"reorder stage broke dispatch order: {got}"
    assert fault, "injected parse fault never surfaced"
    pool.close()  # idempotent second close
    with pool._lock:
        assert pool._dispatcher is None and not pool._workers, \
            "close() left pool threads set"


SCENARIOS: Dict[str, Callable[[], None]] = {
    "sync_subscriber": sync_subscriber,
    "sync_lineage": sync_lineage,
    "micro_batcher": micro_batcher,
    "periodic_reporter": periodic_reporter,
    "placement_watcher": placement_watcher,
    "host_offload_store": host_offload_store,
    "async_persister": async_persister,
    "skew_monitor": skew_monitor,
    "slo_evaluator": slo_evaluator,
    "feed_ring": feed_ring,
    "parse_pool": parse_pool,
}
