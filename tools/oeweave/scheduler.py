"""oeweave scheduler: deterministic cooperative execution of threaded code.

The scheduler serializes a multi-threaded scenario onto ONE runnable thread
at a time. Real OS threads still exist (so ``threading.current_thread()``,
thread-locals and contextvars behave normally), but every instrumented
primitive — lock acquire/release, condition wait/notify, event wait/set,
queue put/get, thread start/join, ``time.sleep`` — is a *yield point* where
a scheduling **policy** chooses which thread runs next. The sequence of
choices IS the schedule; recording it gives a compact replay token that
reproduces any interleaving bit-for-bit (see `explore.py`).

Design notes:

- Instrumentation is context-manager patching (`patched()`): while active,
  ``threading.Thread/Lock/RLock/Condition/Event/Semaphore``,
  ``queue.Queue/SimpleQueue`` and ``time.sleep/monotonic/time`` resolve to
  weave implementations. Production modules are untouched; objects they
  construct *inside* the context pick up weave primitives.
- Threads not registered with the scheduler (jax internals, pytest
  machinery) fall through to real primitives — they are bystanders, not
  participants.
- Time is virtual: ``monotonic()`` returns ``base + now`` where ``now``
  only advances when the policy *chooses* to fire a pending timeout. A
  timed wait is therefore a scheduling choice like any other ("the timeout
  fires here"), which is how lost-wakeup bugs that hide behind generous
  timeouts become reachable in milliseconds.
- Deadlock (no runnable thread, no pending timeout) aborts the schedule
  with every thread's block reason — this is how a classic lost wakeup
  (``if not ready: cond.wait()``) actually manifests.
- At scenario end the scheduler *drains*: remaining threads are scheduled
  (timeouts fire) until they finish or only indefinitely-blocked threads
  remain; those are reported as **leaked threads**, the "clean shutdown"
  invariant.
"""

from __future__ import annotations

import queue as _queue_mod
import threading as _threading
import time as _time_mod
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Tuple

# Real primitives, captured at import so weave internals never recurse into
# patched versions.
_REAL_THREAD = _threading.Thread
_REAL_LOCK = _threading.Lock
_REAL_RLOCK = _threading.RLock
_REAL_CONDITION = _threading.Condition
_REAL_EVENT = _threading.Event
_REAL_SEMAPHORE = _threading.Semaphore
_REAL_QUEUE = _queue_mod.Queue
_REAL_SIMPLE_QUEUE = _queue_mod.SimpleQueue
_REAL_MONOTONIC = _time_mod.monotonic
_REAL_TIME = _time_mod.time
_REAL_SLEEP = _time_mod.sleep
_get_ident = _threading.get_ident

# The single active scheduler (one weave run at a time; runs are themselves
# serialized by the harness).
_ACTIVE: Optional["WeaveScheduler"] = None


@contextmanager
def _unpatched():
    """Momentarily restore real threading internals.

    CPython's Thread/Event constructors resolve `Event`/`Condition` through
    the threading module globals — constructing a REAL helper object while
    patched would hand it weave internals. Weave code constructing real
    primitives (the scheduler gate, thread bootstraps, event mirrors) wraps
    the construction in this.
    """
    cur = (_threading.Thread, _threading.Lock, _threading.RLock,
           _threading.Condition, _threading.Event, _threading.Semaphore,
           _queue_mod.Queue, _queue_mod.SimpleQueue,
           _time_mod.sleep, _time_mod.monotonic, _time_mod.time)
    _threading.Thread = _REAL_THREAD
    _threading.Lock = _REAL_LOCK
    _threading.RLock = _REAL_RLOCK
    _threading.Condition = _REAL_CONDITION
    _threading.Event = _REAL_EVENT
    _threading.Semaphore = _REAL_SEMAPHORE
    _queue_mod.Queue = _REAL_QUEUE
    _queue_mod.SimpleQueue = _REAL_SIMPLE_QUEUE
    _time_mod.sleep = _REAL_SLEEP
    _time_mod.monotonic = _REAL_MONOTONIC
    _time_mod.time = _REAL_TIME
    try:
        yield
    finally:
        (_threading.Thread, _threading.Lock, _threading.RLock,
         _threading.Condition, _threading.Event, _threading.Semaphore,
         _queue_mod.Queue, _queue_mod.SimpleQueue,
         _time_mod.sleep, _time_mod.monotonic, _time_mod.time) = cur

# How long a parked thread waits on its gate before declaring the harness
# itself wedged (a bug in the scheduler, not the scenario).
_GATE_TIMEOUT_S = 30.0

RUNNABLE = "runnable"
BLOCKED = "blocked"
FINISHED = "finished"


class WeaveError(Exception):
    """Base for scheduler-detected scenario failures."""


class WeaveDeadlock(WeaveError):
    """No runnable thread and no pending timeout."""


class WeaveLeak(WeaveError):
    """Threads still alive/blocked after the scenario body returned."""


class WeaveBudget(WeaveError):
    """Schedule exceeded max_decisions (treated as truncated, not failed)."""


class WeaveInternal(WeaveError):
    """The harness itself wedged (gate timeout) — a scheduler bug."""


class _WeaveKilled(BaseException):
    """Raised inside a weave thread at its next yield point to tear it down.

    BaseException so scenario code's ``except Exception`` does not swallow
    the teardown.
    """


class _ThreadState:
    __slots__ = ("tid", "name", "go", "kill", "status", "reason", "deadline",
                 "wake_flag", "ident", "weave_thread")

    def __init__(self, tid: int, name: str):
        self.tid = tid
        self.name = name
        self.go = False
        self.kill = False
        self.status = RUNNABLE
        self.reason: str = ""
        self.deadline: Optional[float] = None  # virtual-clock instant
        self.wake_flag: Optional[str] = None   # "signal" | "timeout"
        self.ident: Optional[int] = None
        self.weave_thread: Optional["WeaveThread"] = None

    def describe(self) -> str:
        if self.status == BLOCKED:
            dl = "" if self.deadline is None else f" (timeout@{self.deadline:.3f})"
            return f"{self.name}: blocked on {self.reason}{dl}"
        return f"{self.name}: {self.status}"


class WeaveScheduler:
    """Cooperative scheduler; see module docstring.

    `policy(n, tids, runnables, cur_tid, decision)` -> index in [0, n)
    choosing among the sorted-by-tid candidate threads (`runnables[i]`
    False means candidate i is a pending timeout, not a runnable thread).
    Every call is recorded in `choices`; `candidate_counts` records n for
    the preemption sweep.
    """

    def __init__(self,
                 policy: Callable[[int, List[int], List[bool], int, int], int],
                 max_decisions: int = 20000):
        self.policy = policy
        self.max_decisions = int(max_decisions)
        self.choices: List[int] = []
        self.candidate_counts: List[int] = []
        self.now = 0.0
        self.base = _REAL_MONOTONIC()
        self.threads: List[_ThreadState] = []
        self.by_ident: Dict[int, _ThreadState] = {}
        self.fatal: Optional[BaseException] = None
        self.thread_errors: List[Tuple[str, BaseException]] = []
        self._cv = _REAL_CONDITION()
        self._decision = 0
        self._next_tid = 0

    # -- registration ---------------------------------------------------------

    def _register(self, name: str) -> _ThreadState:
        st = _ThreadState(self._next_tid, name)
        self._next_tid += 1
        self.threads.append(st)
        return st

    def _bind(self, st: _ThreadState) -> None:
        st.ident = _get_ident()
        self.by_ident[st.ident] = st

    def current(self) -> Optional[_ThreadState]:
        return self.by_ident.get(_get_ident())

    # -- the decision core ----------------------------------------------------

    def _candidates(self) -> List[_ThreadState]:
        """Runnable threads, plus timed-blocked threads whose deadline is
        the EARLIEST pending one. Virtual time is monotone: a later timeout
        cannot fire before an earlier one still pending — without this
        restriction the explorer reaches schedules real time cannot (e.g. a
        10 s join timing out before a 10 ms tick)."""
        out = [t for t in self.threads if t.status == RUNNABLE]
        timed = [t for t in self.threads
                 if t.status == BLOCKED and t.deadline is not None]
        if timed:
            dmin = min(t.deadline for t in timed)
            out.extend(t for t in timed if t.deadline == dmin)
        out.sort(key=lambda t: t.tid)
        return out

    def _choose_and_transfer(self, st: _ThreadState, *, parked: bool) -> None:
        """Pick the next thread to run and hand control over.

        `parked`: st has just blocked (it is not runnable unless it has a
        deadline). Otherwise st stays a candidate and may keep running.
        """
        if self.fatal is not None:
            raise _WeaveKilled()
        cands = self._candidates()
        if not cands:
            self._abort(WeaveDeadlock(
                "deadlock: no runnable thread, no pending timeout\n  "
                + "\n  ".join(t.describe() for t in self.threads
                              if t.status != FINISHED)))
            raise _WeaveKilled()
        if self._decision >= self.max_decisions:
            self._abort(WeaveBudget(
                f"schedule exceeded {self.max_decisions} decisions; "
                "threads:\n  "
                + "\n  ".join(t.describe() for t in self.threads
                              if t.status != FINISHED)))
            raise _WeaveKilled()
        n = len(cands)
        idx = self.policy(n, [t.tid for t in cands],
                          [t.status == RUNNABLE for t in cands],
                          st.tid, self._decision)
        idx = max(0, min(n - 1, int(idx)))
        self.choices.append(idx)
        self.candidate_counts.append(n)
        self._decision += 1
        nxt = cands[idx]
        if nxt.status == BLOCKED:
            # the policy chose to fire this thread's timeout
            if nxt.deadline is not None and nxt.deadline > self.now:
                self.now = nxt.deadline
            nxt.status = RUNNABLE
            nxt.wake_flag = "timeout"
            nxt.reason = ""
            nxt.deadline = None
        if nxt is st:
            return  # keep running (or: own timeout fired immediately)
        self._switch_to(nxt, wait=True, me=st)
        if parked and st.status == BLOCKED:
            # woken gate but still marked blocked (shouldn't happen) — guard
            st.status = RUNNABLE

    def _switch_to(self, nxt: _ThreadState, *, wait: bool,
                   me: Optional[_ThreadState]) -> None:
        with self._cv:
            if me is not None:
                me.go = False
            nxt.go = True
            self._cv.notify_all()
            if not wait or me is None:
                return
            deadline = _REAL_MONOTONIC() + _GATE_TIMEOUT_S
            while not me.go and not me.kill:
                left = deadline - _REAL_MONOTONIC()
                if left <= 0:
                    raise WeaveInternal(
                        f"{me.name}: gate timeout — scheduler wedged")
                self._cv.wait(left)
        if me.kill:
            raise _WeaveKilled()

    def yield_point(self, op: str = "") -> None:
        """A preemption opportunity: the policy may switch threads here."""
        st = self.current()
        if st is None:
            return
        if st.kill:
            raise _WeaveKilled()
        self._choose_and_transfer(st, parked=False)

    def block(self, st: _ThreadState, reason: str,
              timeout: Optional[float] = None) -> bool:
        """Park st until `wake()` or (policy-chosen) timeout.

        Returns True when woken by signal, False on timeout.
        """
        st.status = BLOCKED
        st.reason = reason
        st.deadline = None if timeout is None else self.now + max(0.0, timeout)
        st.wake_flag = None
        self._choose_and_transfer(st, parked=True)
        # here st.go is True again and wake_flag says why
        flag = st.wake_flag
        st.wake_flag = None
        st.reason = ""
        st.deadline = None
        return flag == "signal"

    def wake(self, st: _ThreadState) -> None:
        """Mark a blocked thread runnable (it runs when the policy picks it)."""
        if st.status == BLOCKED:
            st.status = RUNNABLE
            st.wake_flag = "signal"
            st.reason = ""
            st.deadline = None

    def _abort(self, exc: BaseException) -> None:
        """Record a fatal failure and kill every weave thread."""
        if self.fatal is None:
            self.fatal = exc
            if isinstance(exc, WeaveLeak):
                try:  # oeweave runs standalone too — the package may be absent
                    from openembedding_tpu.utils import capsule as _capsule
                    _capsule.trigger("weave_leak", detail=str(exc))
                except Exception:  # noqa: BLE001 — diagnosis must not mask
                    pass           # the leak itself
        with self._cv:
            for t in self.threads:
                if t.status != FINISHED:
                    t.kill = True
            self._cv.notify_all()

    def finish(self, st: _ThreadState) -> None:
        """Thread body returned: wake joiners, pass control on, exit."""
        st.status = FINISHED
        st.go = False
        for t in self.threads:
            if t.status == BLOCKED and t.reason == f"join:{st.tid}":
                self.wake(t)
        cands = self._candidates()
        if cands:
            nxt = cands[0] if len(cands) == 1 else None
            if nxt is None:
                n = len(cands)
                idx = self.policy(n, [t.tid for t in cands],
                                  [t.status == RUNNABLE for t in cands],
                                  st.tid, self._decision)
                idx = max(0, min(n - 1, int(idx)))
                self.choices.append(idx)
                self.candidate_counts.append(n)
                self._decision += 1
                nxt = cands[idx]
            if nxt.status == BLOCKED:
                if nxt.deadline is not None and nxt.deadline > self.now:
                    self.now = nxt.deadline
                nxt.status = RUNNABLE
                nxt.wake_flag = "timeout"
                nxt.reason = ""
                nxt.deadline = None
            self._switch_to(nxt, wait=False, me=st)
        elif any(t.status == BLOCKED for t in self.threads):
            self._abort(WeaveDeadlock(
                "deadlock at thread exit: remaining threads blocked forever\n  "
                + "\n  ".join(t.describe() for t in self.threads
                              if t.status == BLOCKED)))

    # -- top level ------------------------------------------------------------

    def run(self, fn: Callable[[], None]) -> None:
        """Run `fn` as the scenario main thread under this scheduler.

        Raises the first failure: a thread exception, WeaveDeadlock,
        WeaveLeak, or WeaveBudget.
        """
        global _ACTIVE
        if _ACTIVE is not None:
            raise RuntimeError("nested weave runs are not supported")
        main = self._register("main")
        self._bind(main)
        main.go = True
        _ACTIVE = self
        try:
            with patched():
                try:
                    fn()
                    self._drain(main)
                except _WeaveKilled:
                    pass
        finally:
            main.status = FINISHED
            _ACTIVE = None
            self._teardown()
        if self.fatal is not None:
            raise self.fatal
        if self.thread_errors:
            name, err = self.thread_errors[0]
            raise WeaveError(f"thread {name!r} raised {err!r}") from err

    def _drain(self, main: _ThreadState) -> None:
        """After the scenario body: let finishing threads finish, then flag
        leaks. Timed waits are timed out; indefinite blocks are leaks."""
        if self.fatal is not None:
            return
        budget = self.max_decisions
        while budget > 0:
            others = [t for t in self.threads
                      if t is not main and t.status != FINISHED]
            if not others:
                break
            cands = [t for t in others
                     if t.status == RUNNABLE
                     or (t.status == BLOCKED and t.deadline is not None)]
            if not cands:
                leaked = ", ".join(t.describe() for t in others)
                self._abort(WeaveLeak(f"leaked threads after scenario: {leaked}"))
                return
            budget -= 1
            # pick the next NON-main thread ourselves (deterministically):
            # routing this through the policy lets prefer-current policies
            # keep choosing the idle main forever and never surface the leak
            nxt = min((t for t in cands if t.status == RUNNABLE),
                      key=lambda t: t.tid, default=None)
            if nxt is None:  # only timed waits left: fire the earliest
                nxt = min(cands, key=lambda t: (t.deadline, t.tid))
                if nxt.deadline is not None and nxt.deadline > self.now:
                    self.now = nxt.deadline
                nxt.status = RUNNABLE
                nxt.wake_flag = "timeout"
                nxt.reason = ""
                nxt.deadline = None
            self._switch_to(nxt, wait=True, me=main)
            if self.fatal is not None:
                return
        else:
            others = [t for t in self.threads
                      if t is not main and t.status != FINISHED]
            if others:
                self._abort(WeaveLeak(
                    "threads still running after drain budget: "
                    + ", ".join(t.describe() for t in others)))

    def _teardown(self) -> None:
        """Kill any still-alive weave thread and join its OS thread."""
        with self._cv:
            for t in self.threads:
                if t.status != FINISHED:
                    t.kill = True
                    t.status = RUNNABLE
            self._cv.notify_all()
        for t in self.threads:
            wt = t.weave_thread
            if wt is not None and wt._os_thread is not None:
                wt._os_thread.join(timeout=5.0)

    # virtual clock
    def monotonic(self) -> float:
        return self.base + self.now

    def sleep(self, st: _ThreadState, seconds: float) -> None:
        if seconds <= 0:
            self.yield_point("sleep0")
            return
        self.block(st, f"sleep:{seconds:g}", timeout=seconds)


# -- weave primitives ---------------------------------------------------------


def _sched_and_state() -> Tuple[Optional[WeaveScheduler], Optional[_ThreadState]]:
    s = _ACTIVE
    if s is None:
        return None, None
    return s, s.current()


class WeaveLock:
    """Deterministic Lock. Falls back to a real lock for unregistered
    threads (bystanders keep mutual exclusion against each other, not
    against weave threads — weave threads never run concurrently anyway)."""

    _reentrant = False

    def __init__(self):
        self._owner: Optional[int] = None   # tid
        self._count = 0
        self._waiters: List[int] = []
        self._real = _REAL_RLOCK()

    def _state_of(self, sched: WeaveScheduler,
                  tid: int) -> Optional[_ThreadState]:
        for t in sched.threads:
            if t.tid == tid:
                return t
        return None

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        sched, st = _sched_and_state()
        if sched is None or st is None:
            if timeout is not None and timeout > 0:
                return self._real.acquire(blocking, timeout)
            return self._real.acquire(blocking)
        sched.yield_point("lock.acquire")
        if self._owner == st.tid:
            if self._reentrant:
                self._count += 1
                return True
            raise RuntimeError(
                "deadlock: non-reentrant lock re-acquired by owner "
                f"{st.name}")
        tmo = None if timeout is None or timeout < 0 else float(timeout)
        while self._owner is not None:
            if not blocking:
                return False
            self._waiters.append(st.tid)
            signaled = sched.block(st, f"lock:{id(self):#x}", tmo)
            if st.tid in self._waiters:
                self._waiters.remove(st.tid)
            if not signaled and self._owner is not None:
                return False  # timed out
        self._owner = st.tid
        self._count = 1
        return True

    def release(self) -> None:
        sched, st = _sched_and_state()
        if sched is None or st is None:
            self._real.release()
            return
        if self._owner != st.tid:
            raise RuntimeError("release of un-acquired lock")
        self._count -= 1
        if self._count > 0:
            return
        self._owner = None
        for tid in list(self._waiters):
            t = self._state_of(sched, tid)
            if t is not None:
                sched.wake(t)
        self._waiters.clear()
        sched.yield_point("lock.release")

    def locked(self) -> bool:
        if _ACTIVE is None:
            # best effort on the real path
            got = self._real.acquire(False)
            if got:
                self._real.release()
            return not got
        return self._owner is not None

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    # condition support: fully release regardless of recursion, return count
    def _release_save(self) -> int:
        sched, st = _sched_and_state()
        if sched is None or st is None or self._owner != st.tid:
            raise RuntimeError("cannot wait on un-acquired lock")
        count = self._count
        self._count = 0
        self._owner = None
        for tid in list(self._waiters):
            t = self._state_of(sched, tid)
            if t is not None:
                sched.wake(t)
        self._waiters.clear()
        return count

    def _acquire_restore(self, count: int) -> None:
        sched, st = _sched_and_state()
        if sched is None or st is None:
            raise RuntimeError("weave lock restore outside scheduler")
        while self._owner is not None:
            self._waiters.append(st.tid)
            sched.block(st, f"lock:{id(self):#x}", None)
            if st.tid in self._waiters:
                self._waiters.remove(st.tid)
        self._owner = st.tid
        self._count = count

    def _is_owned(self) -> bool:
        _, st = _sched_and_state()
        return st is not None and self._owner == st.tid


class WeaveRLock(WeaveLock):
    _reentrant = True


class WeaveCondition:
    """Deterministic Condition over a WeaveLock.

    Matches threading semantics: wait/notify require the lock; a waiter
    fully releases the lock, parks, and re-acquires before returning.
    notify() marks waiters runnable — they still contend for the lock.
    """

    def __init__(self, lock=None):
        if lock is None:
            lock = WeaveRLock()
        self._lock = lock
        self._waiters: List[int] = []

    def __enter__(self):
        self._lock.acquire()
        return self

    def __exit__(self, *exc):
        self._lock.release()
        return False

    def acquire(self, *a, **kw):
        return self._lock.acquire(*a, **kw)

    def release(self):
        self._lock.release()

    def wait(self, timeout: Optional[float] = None) -> bool:
        sched, st = _sched_and_state()
        if sched is None or st is None:
            raise RuntimeError("weave condition used outside scheduler")
        if not self._lock._is_owned():
            raise RuntimeError("cannot wait on un-acquired lock")
        count = self._lock._release_save()
        self._waiters.append(st.tid)
        signaled = sched.block(st, f"cond:{id(self):#x}", timeout)
        if st.tid in self._waiters:
            self._waiters.remove(st.tid)
        self._lock._acquire_restore(count)
        return signaled

    def wait_for(self, predicate, timeout: Optional[float] = None) -> bool:
        sched, _ = _sched_and_state()
        endtime = None
        if timeout is not None and sched is not None:
            endtime = sched.now + timeout
        result = predicate()
        while not result:
            waittime = None
            if endtime is not None and sched is not None:
                waittime = endtime - sched.now
                if waittime <= 0:
                    break
            self.wait(waittime)
            result = predicate()
        return bool(result)

    def notify(self, n: int = 1) -> None:
        sched, st = _sched_and_state()
        if sched is None or st is None:
            raise RuntimeError("weave condition used outside scheduler")
        if not self._lock._is_owned():
            raise RuntimeError("cannot notify on un-acquired lock")
        woken = 0
        for tid in list(self._waiters):
            if woken >= n:
                break
            self._waiters.remove(tid)
            t = self._lock._state_of(sched, tid)
            if t is not None:
                sched.wake(t)
                woken += 1
        sched.yield_point("cond.notify")

    def notify_all(self) -> None:
        self.notify(len(self._waiters) or 1)


class WeaveEvent:
    """Deterministic Event; mirrors state into a real Event so bystander
    threads (or post-run stragglers) still see set()."""

    def __init__(self):
        self._flag = False
        with _unpatched():
            self._real = _REAL_EVENT()
        self._waiters: List[int] = []

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:
        self._flag = True
        self._real.set()
        sched, st = _sched_and_state()
        if sched is None or st is None:
            return
        for tid in list(self._waiters):
            for t in sched.threads:
                if t.tid == tid:
                    sched.wake(t)
        self._waiters.clear()
        sched.yield_point("event.set")

    def clear(self) -> None:
        self._flag = False
        self._real.clear()

    def wait(self, timeout: Optional[float] = None) -> bool:
        sched, st = _sched_and_state()
        if sched is None or st is None:
            return self._real.wait(timeout)
        sched.yield_point("event.wait")
        if self._flag:
            return True
        self._waiters.append(st.tid)
        sched.block(st, f"event:{id(self):#x}", timeout)
        if st.tid in self._waiters:
            self._waiters.remove(st.tid)
        return self._flag


class WeaveSemaphore:
    def __init__(self, value: int = 1):
        self._value = int(value)
        self._waiters: List[int] = []

    def acquire(self, blocking: bool = True,
                timeout: Optional[float] = None) -> bool:
        sched, st = _sched_and_state()
        if sched is None or st is None:
            raise RuntimeError("weave semaphore used outside scheduler")
        sched.yield_point("sem.acquire")
        while self._value <= 0:
            if not blocking:
                return False
            self._waiters.append(st.tid)
            signaled = sched.block(st, f"sem:{id(self):#x}", timeout)
            if st.tid in self._waiters:
                self._waiters.remove(st.tid)
            if not signaled and self._value <= 0:
                return False
        self._value -= 1
        return True

    def release(self, n: int = 1) -> None:
        sched, _ = _sched_and_state()
        self._value += int(n)
        if sched is None:
            return
        for tid in list(self._waiters):
            for t in sched.threads:
                if t.tid == tid:
                    sched.wake(t)
        self._waiters.clear()
        sched.yield_point("sem.release")

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False


class WeaveQueue:
    """Deterministic queue.Queue (put/get/join/task_done and the _nowait
    variants). Built on weave primitives so every op is a yield point."""

    def __init__(self, maxsize: int = 0):
        self.maxsize = int(maxsize)
        self._items: List[object] = []
        self._lock = WeaveLock()
        self._not_empty = WeaveCondition(self._lock)
        self._not_full = WeaveCondition(self._lock)
        self._all_done = WeaveCondition(self._lock)
        self._unfinished = 0

    def qsize(self) -> int:
        return len(self._items)

    def empty(self) -> bool:
        return not self._items

    def full(self) -> bool:
        return 0 < self.maxsize <= len(self._items)

    def put(self, item, block: bool = True,
            timeout: Optional[float] = None) -> None:
        with self._lock:
            while 0 < self.maxsize <= len(self._items):
                if not block:
                    raise _queue_mod.Full
                if not self._not_full.wait(timeout):
                    if 0 < self.maxsize <= len(self._items):
                        raise _queue_mod.Full
            self._items.append(item)
            self._unfinished += 1
            self._not_empty.notify()

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: Optional[float] = None):
        with self._lock:
            while not self._items:
                if not block:
                    raise _queue_mod.Empty
                if not self._not_empty.wait(timeout):
                    if not self._items:
                        raise _queue_mod.Empty
            item = self._items.pop(0)
            self._not_full.notify()
            return item

    def get_nowait(self):
        return self.get(block=False)

    def task_done(self) -> None:
        with self._lock:
            if self._unfinished <= 0:
                raise ValueError("task_done() called too many times")
            self._unfinished -= 1
            if self._unfinished == 0:
                self._all_done.notify_all()

    def join(self) -> None:
        with self._lock:
            while self._unfinished:
                self._all_done.wait()


class WeaveSimpleQueue(WeaveQueue):
    def __init__(self):
        super().__init__(0)


class WeaveThread:
    """Deterministic Thread: a real OS thread whose body only runs while the
    scheduler has scheduled it. Created outside an active scheduler (or by
    a bystander thread), it degrades to a plain real thread."""

    def __init__(self, group=None, target=None, name=None, args=(),
                 kwargs=None, *, daemon=None):
        self._target = target
        self._args = args
        self._kwargs = kwargs or {}
        self.daemon = bool(daemon) if daemon is not None else True
        sched, cur = _sched_and_state()
        self._sched = sched if (sched is not None and cur is not None) else None
        self._os_thread: Optional[_threading.Thread] = None
        if self._sched is not None:
            self._st = self._sched._register(
                name or f"weave-{self._sched._next_tid}")
            self._st.status = BLOCKED
            self._st.reason = "not-started"
            self._st.weave_thread = self
        else:
            self._st = None
        self.name = name or (self._st.name if self._st else "thread")

    def start(self) -> None:
        if self._os_thread is not None:
            raise RuntimeError("threads can only be started once")
        if self._sched is None:
            with _unpatched():
                self._os_thread = _REAL_THREAD(
                    target=self._target, args=self._args, kwargs=self._kwargs,
                    name=self.name, daemon=self.daemon)
            self._os_thread.start()
            return
        sched, st = self._sched, self._st
        with _unpatched():
            self._os_thread = _REAL_THREAD(
                target=self._bootstrap, name=self.name, daemon=True)
        # mark runnable before the OS thread exists so the starter's next
        # yield point can already choose it
        st.status = RUNNABLE
        st.reason = ""
        self._os_thread.start()
        sched.yield_point("thread.start")

    def _bootstrap(self) -> None:
        sched, st = self._sched, self._st
        sched._bind(st)
        # park until scheduled the first time
        with sched._cv:
            deadline = _REAL_MONOTONIC() + _GATE_TIMEOUT_S
            while not st.go and not st.kill:
                left = deadline - _REAL_MONOTONIC()
                if left <= 0:
                    return
                sched._cv.wait(left)
        if st.kill:
            sched.finish(st)
            return
        try:
            if self._target is not None:
                self._target(*self._args, **self._kwargs)
        except _WeaveKilled:
            pass
        except BaseException as e:  # noqa: BLE001 — report, don't swallow
            sched.thread_errors.append((st.name, e))
            sched._abort(WeaveError(f"thread {st.name!r} raised {e!r}"))
        finally:
            sched.finish(st)

    def run(self) -> None:
        if self._target is not None:
            self._target(*self._args, **self._kwargs)

    def is_alive(self) -> bool:
        if self._sched is None:
            return self._os_thread is not None and self._os_thread.is_alive()
        if self._os_thread is None:
            return False
        return self._st.status != FINISHED

    def join(self, timeout: Optional[float] = None) -> None:
        if self._sched is None:
            if self._os_thread is not None:
                self._os_thread.join(timeout)
            return
        sched, st = self._sched, self._st
        cur = sched.current()
        if cur is None:
            # bystander joining a weave thread: wait on the real thread
            if self._os_thread is not None:
                self._os_thread.join(timeout)
            return
        sched.yield_point("thread.join")
        if st.status == FINISHED or self._os_thread is None:
            return
        sched.block(cur, f"join:{st.tid}", timeout)

    @property
    def ident(self):
        return self._os_thread.ident if self._os_thread else None


# -- patching -----------------------------------------------------------------


def _weave_sleep(seconds: float) -> None:
    sched, st = _sched_and_state()
    if sched is None or st is None:
        _REAL_SLEEP(seconds)
        return
    sched.sleep(st, float(seconds))


def _weave_monotonic() -> float:
    sched = _ACTIVE
    if sched is None:
        return _REAL_MONOTONIC()
    return sched.monotonic()


_TIME_BASE = _REAL_TIME() - _REAL_MONOTONIC()


def _weave_time() -> float:
    sched = _ACTIVE
    if sched is None:
        return _REAL_TIME()
    return _TIME_BASE + sched.monotonic()


@contextmanager
def patched():
    """Swap threading/queue/time entry points for weave implementations."""
    saved = (_threading.Thread, _threading.Lock, _threading.RLock,
             _threading.Condition, _threading.Event, _threading.Semaphore,
             _queue_mod.Queue, _queue_mod.SimpleQueue,
             _time_mod.sleep, _time_mod.monotonic, _time_mod.time)
    _threading.Thread = WeaveThread
    _threading.Lock = WeaveLock
    _threading.RLock = WeaveRLock
    _threading.Condition = WeaveCondition
    _threading.Event = WeaveEvent
    _threading.Semaphore = WeaveSemaphore
    _queue_mod.Queue = WeaveQueue
    _queue_mod.SimpleQueue = WeaveSimpleQueue
    _time_mod.sleep = _weave_sleep
    _time_mod.monotonic = _weave_monotonic
    _time_mod.time = _weave_time
    try:
        yield
    finally:
        (_threading.Thread, _threading.Lock, _threading.RLock,
         _threading.Condition, _threading.Event, _threading.Semaphore,
         _queue_mod.Queue, _queue_mod.SimpleQueue,
         _time_mod.sleep, _time_mod.monotonic, _time_mod.time) = saved


def yield_point(op: str = "shared-state") -> None:
    """Optional explicit yield point for scenario code touching shared
    state outside any primitive. No-op outside a weave run."""
    sched = _ACTIVE
    if sched is not None:
        sched.yield_point(op)
