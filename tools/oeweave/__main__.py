"""CLI: explore the control-plane scenarios, or replay a failing token.

Exit 0 when every explored schedule passes; exit 1 with one replay token
per failure otherwise. `weave.schedules_explored` / `weave.failures` are
reported at the end of the run (the same accumulator surface `make ci`
tooling scrapes).
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def _cpu_env() -> None:
    # the scenarios never touch devices; keep jax off the TPU so `make
    # weave` can run next to a training job (same discipline as oelint)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None) -> int:
    _cpu_env()
    ap = argparse.ArgumentParser(prog="oeweave", description=__doc__)
    ap.add_argument("scenarios", nargs="*",
                    help="scenario names (default: all)")
    ap.add_argument("--schedules", type=int, default=25,
                    help="random schedules per scenario")
    ap.add_argument("--sweep", type=int, default=40,
                    help="preemption-sweep schedules per scenario")
    ap.add_argument("--depth", type=int, default=2,
                    help="preemption bound for the sweep")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--budget-s", type=float, default=60.0,
                    help="wall-clock budget over all scenarios")
    ap.add_argument("--replay", metavar="SCENARIO:TOKEN",
                    help="replay one recorded schedule and exit")
    ap.add_argument("--list", action="store_true", help="list scenarios")
    args = ap.parse_args(argv)

    from tools.oeweave import explore as ex
    from tools.oeweave import scenarios as sc

    if args.list:
        for name in sc.SCENARIOS:
            print(name)
        return 0

    if args.replay:
        name, _, token = args.replay.partition(":")
        if name not in sc.SCENARIOS:
            ap.error(f"unknown scenario {name!r}")
        sc.warm()
        fail = ex.replay(sc.SCENARIOS[name], token)
        if fail is None:
            print(f"{name}: schedule replays clean (fixed?)")
            return 0
        print(f"{name}: reproduced [{fail.kind}] {fail.error}")
        print(f"  token: {fail.token}")
        return 1

    names = args.scenarios or list(sc.SCENARIOS)
    for n in names:
        if n not in sc.SCENARIOS:
            ap.error(f"unknown scenario {n!r} (try --list)")
    sc.warm()

    from openembedding_tpu.utils import metrics

    t0 = time.monotonic()
    explored = 0
    failures = []
    rc = 0
    for name in names:
        left = args.budget_s - (time.monotonic() - t0)
        if left <= 0:
            print(f"budget exhausted; skipping {name} and later scenarios")
            break
        res = ex.explore(sc.SCENARIOS[name],
                         random_schedules=args.schedules, seed=args.seed,
                         preemption_schedules=args.sweep,
                         preemption_depth=args.depth)
        explored += res.schedules_explored
        status = "ok" if res.ok else f"{len(res.failures)} FAILING"
        print(f"{name}: {res.schedules_explored} schedules, {status}"
              + (f" ({res.truncated} truncated)" if res.truncated else ""))
        for f in res.failures:
            failures.append((name, f))
            print(f"  [{f.kind}] {f.error}")
            print(f"  replay: python -m tools.oeweave "
                  f"--replay '{name}:{f.token}'")
            rc = 1
    metrics.observe("weave.schedules_explored", explored)
    metrics.observe("weave.failures", len(failures))
    print(f"\nweave.schedules_explored={explored} "
          f"weave.failures={len(failures)} "
          f"({time.monotonic() - t0:.1f}s)")
    return rc


if __name__ == "__main__":
    sys.exit(main())
