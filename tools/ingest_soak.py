"""Line-rate ingest soak: find the compute ceiling, feed the pipelined
train loop through the depth-D device feed ring at that rate, and prove the
input-wait attribution lane points the right way.

One process, three measured phases over the same compiled window program
(the CI-sized version of the v5e-64 line-rate question):

  ceiling    — pre-staged windows, min ms/step: what the device mesh can
               absorb with input off the books (examples/s/chip);
  line rate  — `MeshTrainer.train_stream` fed by `data.ingest.feed`
               (per-host sharded synthetic "days" -> parse pool -> depth-D
               ring): the measured `ingest.input_wait_share` must stay under
               the tools/ingest_slo.json gate (< 5% — compute-bound);
  throttled  — the SAME loop behind a producer deliberately paced at 2x the
               measured ceiling: the share must now read input-bound (the
               control that proves the lane attributes, not flatters).

Asserted at exit: the line-rate SLO verdict (adopted as the process exit
code unless --no-slo-gate), the throttled control's inverted attribution,
and a pooled-vs-inline reader bit-identity spot check (the determinism the
reorder stage promises). The short configuration rides tier-1 via
tests/test_ingest.py; `python tools/ingest_soak.py` runs the longer
standalone battery (also the bench.py `ingest` case + upwindow
`bench_ingest` entry for chip sessions). `--weave` explores the feed ring's
and parse pool's interleavings under tools/oeweave instead.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SLO_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "ingest_slo.json")


def run(*, windows=6, window=8, batch=512, vocab=1 << 13, depth=3,
        workers=2, devices=8, throttle_factor=2.0, quiet=False):
    """-> report dict (see asserts at the bottom). Raises AssertionError when
    the soak's invariants break. The report carries the line-rate SLO
    verdicts (tools/ingest_slo.json judged over the line-rate phase only —
    the throttled phase deliberately breaches, that's its job) and
    `slo_exit_code`, which `main()` adopts as the process exit status."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import numpy as np

    import openembedding_tpu as embed
    from openembedding_tpu.data import ingest
    from openembedding_tpu.models import make_deepfm
    from openembedding_tpu.parallel import MeshTrainer, make_mesh
    from openembedding_tpu.utils import metrics, slo

    def log(msg):
        if not quiet:
            print(f"[ingest_soak] {msg}", flush=True)

    devs = jax.devices()
    S = min(devices, len(devs))
    mesh = make_mesh(devs[:S])
    files = [f"synthetic://steps={windows * window // 2}&seed={7 + s}"
             f"&id_space={vocab}" for s in range(2)]

    def ring(label, throttle_s=0.0, d=depth):
        return ingest.feed(files, batch, mesh=mesh, source="synthetic",
                           depth=d, window=window, workers=workers,
                           label=label, throttle_s=throttle_s)

    # determinism spot check: the parse pool's reorder stage must be
    # bit-identical to the inline reader over the same sharded file list
    inline = list(ingest.sharded_reader(files, batch, source="synthetic",
                                        host_id=0, num_hosts=1))
    pooled = list(ingest.sharded_reader(files, batch, source="synthetic",
                                        host_id=0, num_hosts=1,
                                        workers=workers))
    reader_identical = len(inline) == len(pooled) and all(
        np.array_equal(a["sparse"]["categorical"],
                       b["sparse"]["categorical"])
        and np.array_equal(a["dense"], b["dense"])
        for a, b in zip(inline, pooled))

    model = make_deepfm(vocabulary=vocab, dim=9)
    tr = MeshTrainer(model, embed.Adagrad(learning_rate=0.05), mesh=mesh,
                     capacity_factor=0.0, wire="fp32", pipeline_steps=True)

    # phase 1: the compute ceiling (input off the books)
    metrics._REGISTRY.clear()
    staged = list(ring("stage"))
    first = jax.tree_util.tree_map(lambda x: np.asarray(x[0]), staged[0])
    state = tr.init(first)
    many = tr.jit_train_many(staged[0], state)
    times = []
    for i, w in enumerate(staged):
        t0 = time.perf_counter()
        state, m = many(state, w)
        jax.block_until_ready((state, m))
        if i:
            times.append((time.perf_counter() - t0) / window)
    ceiling_s = min(times)
    log(f"compute ceiling: {ceiling_s * 1e3:.2f} ms/step "
        f"({batch / ceiling_s / S:.0f} examples/s/chip on {S} devices)")

    # phase 2: ring-fed at line rate — the SLO-gated run
    metrics._REGISTRY.clear()
    t0 = time.perf_counter()
    state, rep = tr.train_stream(state, ring("line"))
    elapsed = time.perf_counter() - t0
    share = ingest.input_wait_share()
    evaluator = slo.SLOEvaluator(specs=slo.load_specs(SLO_PATH))
    verdicts = evaluator.evaluate_now()
    slo_exit = evaluator.exit_code()
    log("line-rate SLOs:\n" + evaluator.render_text())

    # phase 3: the throttled control — same loop, producer paced at
    # throttle_factor x the measured ceiling, must read input-bound
    metrics._REGISTRY.clear()
    state, trep = tr.train_stream(
        state, ring("slow", throttle_s=throttle_factor * ceiling_s, d=1))
    tshare = ingest.input_wait_share()

    report = {
        "num_shards": S,
        "batch": batch,
        "window": window,
        "windows": rep["windows"],
        "compute_ms_per_step": round(ceiling_s * 1e3, 3),
        "compute_ceiling_eps_per_chip": round(batch / ceiling_s / S, 1),
        "line_rate": {
            "examples_per_sec_per_chip": round(
                rep["windows"] * window * batch / elapsed / S, 1),
            "input_wait_share": share,
            "loss": rep["loss"],
        },
        "throttled": {
            "windows": trep["windows"],
            "input_wait_share": tshare,
        },
        "reader_pool_bit_identical": reader_identical,
        "slo": {v["name"]: v["verdict"] for v in verdicts},
        "slo_exit_code": slo_exit,
    }
    log(json.dumps(report, indent=2))
    assert reader_identical, report
    assert share is not None and tshare is not None, report
    assert tshare > 0.25, (
        f"throttled producer not attributed input-bound: {tshare}", report)
    assert tshare > share, report
    return report


#: the feed path's actors, as oeweave scenarios: ring producer/consumer/
#: close interleavings and the parse pool's reorder stage
WEAVE_SCENARIOS = ("feed_ring", "parse_pool")


def run_weave(*, schedules=8, sweep=12, seed=0, quiet=False):
    """Deterministic-interleaving variant: explore seeded-random +
    preemption-bounded schedules of the ring and pool under tools/oeweave
    and fail on ANY schedule that breaks an invariant (out-of-order
    delivery, lost fault, leaked thread). Returns a report dict; raises
    AssertionError listing replay tokens on failure."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from openembedding_tpu.utils import metrics
    from tools.oeweave import explore as weave_explore
    from tools.oeweave import scenarios as weave_scenarios

    def log(msg):
        if not quiet:
            print(f"[ingest_soak --weave] {msg}", flush=True)

    weave_scenarios.warm()
    report = {"scenarios": {}, "schedules_explored": 0, "failures": 0}
    for name in WEAVE_SCENARIOS:
        res = weave_explore.explore(
            weave_scenarios.SCENARIOS[name],
            random_schedules=schedules, seed=seed,
            preemption_schedules=sweep)
        report["scenarios"][name] = {
            "explored": res.schedules_explored,
            "truncated": res.truncated,
            "failures": [{"kind": f.kind, "error": f.error,
                          "token": f.token} for f in res.failures],
        }
        report["schedules_explored"] += res.schedules_explored
        report["failures"] += len(res.failures)
        log(f"{name}: {res.schedules_explored} schedules, "
            f"{len(res.failures)} failures")
    metrics.observe("weave.schedules_explored",
                    float(report["schedules_explored"]))
    metrics.observe("weave.failures", float(report["failures"]))
    assert report["failures"] == 0, (
        "weave found failing interleavings — replay with "
        "`python -m tools.oeweave <scenario> --replay <scenario>:<token>`: "
        + json.dumps(report["scenarios"]))
    return report


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--windows", type=int, default=6)
    ap.add_argument("--window", type=int, default=8,
                    help="steps per compiled window (the scan K)")
    ap.add_argument("--batch", type=int, default=512)
    ap.add_argument("--vocab", type=int, default=1 << 13)
    ap.add_argument("--depth", type=int, default=3,
                    help="feed-ring depth (resident windows staged ahead)")
    ap.add_argument("--workers", type=int, default=2,
                    help="parse-pool workers")
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--throttle-factor", type=float, default=2.0,
                    help="throttled-control producer pace as a multiple of "
                         "the measured per-step compute ceiling")
    ap.add_argument("--no-slo-gate", action="store_true",
                    help="report SLO verdicts but exit 0 regardless "
                         "(default: exit with the line-rate SLO verdict — "
                         "0 OK, 1 breached, 2 unknown)")
    ap.add_argument("--weave", action="store_true",
                    help="explore the ring/pool interleavings under "
                         "tools/oeweave instead of the wall-clock soak")
    ap.add_argument("--weave-schedules", type=int, default=8)
    ap.add_argument("--weave-sweep", type=int, default=12)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    if args.weave:
        try:
            report = run_weave(schedules=args.weave_schedules,
                               sweep=args.weave_sweep, seed=args.seed)
        except AssertionError as e:
            print(e)
            return 1
        print(json.dumps(report))
        return 0
    report = run(windows=args.windows, window=args.window, batch=args.batch,
                 vocab=args.vocab, depth=args.depth, workers=args.workers,
                 devices=args.devices, throttle_factor=args.throttle_factor)
    print(json.dumps(report))
    return 0 if args.no_slo_gate else report["slo_exit_code"]


if __name__ == "__main__":
    sys.exit(main())
