"""Online-sync soak: train-and-persist in a thread while a subscriber-backed
serving node answers predicts; assert freshness and zero failed predicts.

One process, three actors (the CI-sized version of the production topology):

  trainer thread   — Trainer + IncrementalPersister: full base at the first
                     persist, then one committed delta every `persist_every`
                     steps into the persist root;
  publisher node   — serving HTTP server whose SyncPublisher feeds that root
                     (`GET /models/<sign>:versions`, `/delta/<step>/...`);
  serving node     — a second HTTP server that loaded the base export, with a
                     SyncSubscriber polling the feed and RCU-swapping the
                     servable, while `predict_threads` hammer /predict.

Asserted at exit: zero failed predicts across every swap, the subscriber
ended IDLE at the trainer's final committed step (version lag 0), and at
least K swaps actually happened (the soak is vacuous without them). The
short configuration rides tier-1 via tests/test_sync.py::test_sync_soak_short;
`python tools/sync_soak.py` runs the longer standalone battery (also a
bench.py `sync` case + upwindow battery entry for chip sessions).
"""

import argparse
import json
import os
import shutil
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _check_timeline(urls, *, version, log):
    """Scrape the soak nodes' /timelinez through tools/fleet_timeline and
    assert delta `version`'s commit->publish->fetch->apply->swap->
    first-predict chain merges contiguous and correctly ordered. Retries the
    scrape briefly: first-predict lands on the hammer's first post-swap hit,
    a few ms after the drain loop saw the version flip."""
    from tools import fleet_timeline as ftl
    want_full = ("birth", "commit", "publish", "fetch", "apply", "swap",
                 "first_predict")
    labels, ts, items = [], [], []
    deadline = time.monotonic() + 10
    while True:
        nodes_data = []
        for u in urls:
            doc, offset = ftl.probe(u, probes=3)
            nodes_data.append((doc.get("node") or u, doc, offset))
        items = ftl.merge(nodes_data)
        # both soak nodes live in ONE process and share the lineage book
        # (same node id, two scrape offsets), so the merged view carries the
        # chain twice: judge ONE node's copy of it
        chain = [it for it in ftl.merge(nodes_data[-1:])
                 if it["kind"] == "DELTA" and it.get("step") == version]
        labels = [it["what"].split()[1] for it in chain]
        ts = [it["ts"] for it in chain]
        if "first_predict" in labels or time.monotonic() >= deadline:
            break
        time.sleep(0.1)
    want = [l for l in want_full if l in labels]
    ok = (labels == want
          and {"commit", "publish", "fetch", "apply", "swap",
               "first_predict"} <= set(labels)
          and all(a <= b for a, b in zip(ts, ts[1:])))
    log(f"timeline chain for delta {version}: {labels} ok={ok}")
    assert ok, {"timeline_chain": labels, "version": version}
    return {"merged_items": len(items), "chain": labels, "chain_ok": ok}


def run(*, steps=24, persist_every=2, interval_s=0.05, workdir="/tmp/oetpu_sync_soak",
        predict_threads=4, wire="fp32", vocab=1 << 10, batch=16, dim=4,
        lag_bound_steps=None, step_delay_s=0.0, quiet=False,
        metrics_log=None, sentinel=True, measure_every=8,
        stall_s=0.0, stall_after_frac=0.4, freshness_threshold_ms=None,
        timeline=False):
    """-> report dict (see asserts at the bottom). Raises AssertionError when
    the soak's invariants break. The report carries the SLO verdicts
    (`utils/slo.DEFAULT_SLOS` judged once at exit over everything the soak
    observed — predict latency, sync freshness, sentinel numerics) and
    `slo_exit_code`, which `main()` adopts as the process exit status.

    `stall_s > 0` runs the CAUSALITY acceptance scenario: once the trainer
    passes `stall_after_frac` of its steps, the publisher's delta PAYLOADS
    are withheld for `stall_s` seconds (the feed keeps advancing, so the
    subscriber sees an ever-older head birth and `sync.freshness_ms` grows)
    — the `serving_freshness` SLO (threshold `freshness_threshold_ms`,
    default stall_s/2) must flip to BREACHED mid-run with the stalled hop
    dominating `sync.hop_ms{hop="fetch"}`, then recover to OK once the
    stall lifts and a post-stall delta lands. `timeline=True` additionally
    scrapes both nodes' /timelinez pre-shutdown and asserts the last
    delta's commit->publish->fetch->apply->swap->first-predict chain merges
    contiguous and correctly ordered (`tools/fleet_timeline.py`)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    import openembedding_tpu as embed
    from openembedding_tpu.data import synthetic_criteo
    from openembedding_tpu.export import export_standalone
    from openembedding_tpu.model import Trainer
    from openembedding_tpu.models import make_deepfm
    from openembedding_tpu.persist import IncrementalPersister, PersistPolicy
    from openembedding_tpu.serving import make_server
    from openembedding_tpu.sync import SyncSubscriber

    def log(msg):
        if not quiet:
            print(f"[sync_soak] {msg}", flush=True)

    shutil.rmtree(workdir, ignore_errors=True)
    os.makedirs(workdir)
    root = os.path.join(workdir, "persist")
    sign = "soak-0"

    model = make_deepfm(vocabulary=vocab, dim=dim, hidden=(8,))
    # sentinel + sampled measurement on by default: the soak IS the
    # production-day rehearsal, so it trains with the health rails it gates on
    trainer = Trainer(model, embed.Adagrad(learning_rate=0.05), seed=0,
                      sentinel=sentinel, measure_every=measure_every)
    batches = list(synthetic_criteo(batch, id_space=vocab, steps=steps,
                                    seed=1))
    reporter = None
    if metrics_log:
        from openembedding_tpu.utils.metrics import PeriodicReporter
        # reset=False: the soak judges the DEFAULT_SLOS over the whole run at
        # exit, and a resetting reporter would zero counter windows (e.g.
        # health.nonfinite_total) back to never-observed -> verdict UNKNOWN
        reporter = PeriodicReporter(max(interval_s, 0.5),
                                    sink=lambda _s: None, reset=False,
                                    jsonl_path=metrics_log).start()
    state = trainer.init(batches[0])
    # the soak's paced trainer must never re-jit across the run: identical
    # batch shapes -> one compiled program, asserted at every step
    # (utils/guards — the executable half of the never-re-jit rule), and
    # the traced collective SEQUENCE is pinned at start and re-asserted at
    # the end (the SPMD-contract half: no refresh/sync path may change
    # which collectives run, in what order)
    from openembedding_tpu.utils.guards import (assert_collective_fingerprint,
                                                assert_no_recompile,
                                                collective_fingerprint)
    raw_step = trainer.jit_train_step()
    step_fn = assert_no_recompile(raw_step, label="soak_train_step")
    collective_pin = collective_fingerprint(raw_step, state, batches[0])

    persister = IncrementalPersister(
        trainer, model, root, window=2,
        policy=PersistPolicy(every_steps=persist_every), full_every=10_000)
    # base: FORCE the first persist (the full anchor) at step 1 — serving
    # starts from an export of this exact chain step, whatever the policy says
    state, _ = step_fn(state, batches[0])
    persister.observe(batches[0])
    persister.persist(state)
    persister.wait()
    export_dir = os.path.join(workdir, "export")
    export_standalone(state, model, export_dir, model_sign=sign)

    pub_srv = make_server(os.path.join(workdir, "reg_pub"),
                          publish={sign: root}, publish_wire=wire)
    threading.Thread(target=pub_srv.serve_forever, daemon=True).start()
    pub_url = f"http://127.0.0.1:{pub_srv.server_address[1]}"
    srv = make_server(os.path.join(workdir, "reg_srv"))
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    srv_url = f"http://127.0.0.1:{srv.server_address[1]}"
    srv.manager.load_model(sign, export_dir)
    log(f"publisher {pub_url} feeds {root}; serving node {srv_url}")

    # tight backoff cap when a stall is planned: the DEGRADED retry loop
    # must re-probe fast enough to recover within the post-stall drain
    sub = SyncSubscriber(srv.manager, sign, pub_url, wire=wire,
                         interval_s=interval_s,
                         max_backoff_s=max(4 * interval_s, 0.25)
                         if stall_s > 0 else 30.0)

    from openembedding_tpu.utils import slo
    prior_specs = slo.EVALUATOR.specs
    if stall_s > 0:
        # re-anchor serving_freshness to the soak's scale: the stock 30s
        # threshold would never trip on a CI-sized stall
        thr = float(freshness_threshold_ms
                    if freshness_threshold_ms is not None
                    else stall_s * 500.0)
        specs = [s for s in prior_specs if s.name != "serving_freshness"]
        specs.append(slo.SLOSpec(
            name="serving_freshness", metric="sync.freshness_ms",
            selector="value", op="<=", threshold=thr, fast_window_s=0.0,
            slow_window_s=300.0, burn_threshold=1e-9,
            description=f"soak-scaled freshness bound ({thr:.0f}ms)"))
        slo.configure(specs)

    # predict hammer: live traffic across every swap
    stop = threading.Event()
    counts = {"ok": 0, "fail": 0}
    lock = threading.Lock()
    body = json.dumps({
        "sparse": {"categorical":
                   np.asarray(batches[0]["sparse"]["categorical"]).tolist()},
        "dense": np.asarray(batches[0]["dense"]).tolist()}).encode()

    def hammer():
        url = f"{srv_url}/models/{sign}/predict"
        while not stop.is_set():
            req = urllib.request.Request(
                url, data=body, method="POST",
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=30) as r:
                    ok = r.status == 200
            except Exception:  # noqa: BLE001 — any failure counts
                ok = False
            with lock:
                counts["ok" if ok else "fail"] += 1

    # warm the predict program before the clock starts (compile != failure)
    srv.manager.find_model(sign).predict(batches[0])
    hammers = [threading.Thread(target=hammer, daemon=True)
               for _ in range(predict_threads)]
    for t in hammers:
        t.start()

    trained = {"step": 1}
    train_done = threading.Event()

    def train():
        s = state
        for b in batches[1:]:
            s, mets = step_fn(s, b)
            trainer.record_step_stats(mets)
            persister.maybe_persist(s, batch=b)
            trained["step"] = int(s.step)
            if step_delay_s > 0:  # emulate a real per-step training cadence
                time.sleep(step_delay_s)
        persister.wait()
        train_done.set()

    max_lag = 0
    stall = {"on": False, "done": stall_s <= 0, "orig": None,
             "denied": 0, "first_deny": None}
    stall_after_step = max(2, int(steps * stall_after_frac))
    slo_track = {"breached": False, "recovered": False}

    def _slo_tick():
        v = {x["name"]: x["verdict"]
             for x in slo.EVALUATOR.evaluate_now()}.get("serving_freshness")
        if v == "BREACHED":
            slo_track["breached"] = True
        elif v == "OK" and slo_track["breached"]:
            slo_track["recovered"] = True

    def _stall_tick():
        # withhold delta PAYLOADS, not the feed: the head keeps advancing,
        # so the subscriber sees an ever-older unapplied birth (freshness
        # grows) while its payload fetches 404 into DEGRADED retries —
        # which is exactly the time the `fetch` hop is defined to absorb
        pub = pub_srv.publishers[sign]
        if (not stall["done"] and not stall["on"]
                and trained["step"] >= stall_after_step):
            stall["orig"] = pub.delta_meta

            def _withheld(step):
                # the stall window is anchored to the FIRST fetch actually
                # denied — a wall-clock window could race the training pace
                # and cover no delta at all
                if stall["first_deny"] is None:
                    stall["first_deny"] = time.monotonic()
                stall["denied"] += 1
                raise KeyError(f"soak stall: delta {step} payload withheld")

            pub.delta_meta = _withheld
            stall["on"] = True
            log(f"stall ON at step {trained['step']}: withholding payloads "
                f"for {stall_s}s past the first denied fetch")
        elif stall["on"] and (train_done.is_set()
                              or (stall["first_deny"] is not None
                                  and time.monotonic()
                                  >= stall["first_deny"] + stall_s)):
            pub.delta_meta = stall["orig"]
            stall["on"], stall["done"] = False, True
            log(f"stall OFF after {stall['denied']} denied fetches")

    t0 = time.monotonic()
    trainer_thread = threading.Thread(target=train, daemon=True)
    trainer_thread.start()
    sub.start()
    timeline_report = None
    try:
        while not train_done.is_set():
            time.sleep(interval_s)
            max_lag = max(max_lag, trained["step"] - (sub.version or 1))
            if stall_s > 0:
                _stall_tick()
                _slo_tick()
        if stall["on"]:
            _stall_tick()  # training ended first: force the stall off
        # drain: let the subscriber reach the final committed step
        deadline = time.monotonic() + 60
        final = trained["step"] - (trained["step"] - 1) % persist_every
        while (sub.version or 0) < final and time.monotonic() < deadline:
            time.sleep(interval_s)
            if stall_s > 0:
                _slo_tick()
        if stall_s > 0:
            # settle: a post-stall delta's fresh sample must re-judge OK
            settle = time.monotonic() + 10
            while not slo_track["recovered"] and time.monotonic() < settle:
                _slo_tick()
                time.sleep(interval_s)
        if timeline:
            timeline_report = _check_timeline([pub_url, srv_url],
                                              version=sub.version, log=log)
    finally:
        sub.stop()
        stop.set()
        for t in hammers:
            t.join(timeout=10)
        trainer_thread.join(timeout=60)
        persister.close()
        pub_srv.shutdown()
        srv.shutdown()
        if reporter is not None:
            reporter.stop()  # flushes the final JSONL record

    # the collective program must be exactly what we pinned before the run
    # (same shapes, same axes, same order) — raises CollectiveMismatchError
    assert_collective_fingerprint(raw_step, collective_pin, state,
                                  batches[0], label="soak_train_step")

    report = {
        "collective_fingerprint": collective_pin,
        "steps": trained["step"],
        "persist_every": persist_every,
        "wire": wire,
        "swaps": sub.applied,
        "final_version": sub.version,
        "final_committed": final,
        "final_lag_steps": final - (sub.version or 0),
        "max_observed_lag_steps": max_lag,
        "predicts": counts["ok"] + counts["fail"],
        "failed_predicts": counts["fail"],
        "subscriber_state": sub.state,
        "last_error": sub.last_error,
        "wall_s": round(time.monotonic() - t0, 2),
    }
    # the SLO gate: judge everything the soak observed (predict latency
    # hists, sync freshness gauges, sentinel numerics) against the stock
    # objectives — the process-exit verdict main() adopts
    verdicts = slo.EVALUATOR.evaluate_now()
    report["slo"] = {v["name"]: v["verdict"] for v in verdicts}
    report["slo_exit_code"] = slo.EVALUATOR.exit_code()
    log("SLOs:\n" + slo.EVALUATOR.render_text())
    if stall_s > 0:
        slo.configure(prior_specs)  # un-shadow the stock serving_freshness
        # stalled-hop attribution: the max over each sync.hop_ms{hop=} hist —
        # the withheld-payload window is DEGRADED retry time, which the
        # `fetch` hop is defined to absorb, so fetch must dominate
        from openembedding_tpu.utils import metrics as metrics_mod
        with metrics_mod._LOCK:
            hop_max = {a.labels.get("hop", "?"): a.hist_snapshot()[4]
                       for a in metrics_mod._REGISTRY.values()
                       if a.name == "sync.hop_ms" and a.count}
        stalled_hop = max(hop_max, key=hop_max.get) if hop_max else None
        report["freshness_breached"] = slo_track["breached"]
        report["freshness_recovered"] = slo_track["recovered"]
        report["hop_max_ms"] = {k: round(v, 3) for k, v in hop_max.items()}
        report["stalled_hop"] = stalled_hop
    if timeline_report is not None:
        report["timeline"] = timeline_report
    log(json.dumps(report, indent=2))
    assert report["failed_predicts"] == 0, report
    assert report["final_lag_steps"] == 0, report
    assert report["swaps"] >= 1, report
    if lag_bound_steps is not None:
        assert max_lag <= lag_bound_steps, report
    if stall_s > 0:
        assert report["freshness_breached"], report
        assert report["freshness_recovered"], report
        assert report["stalled_hop"] == "fetch", report
    return report


#: the soak topology's actors, as oeweave scenarios: subscriber state
#: machine + its lineage bookkeeping, serving batcher, persister, reporter
WEAVE_SCENARIOS = ("sync_subscriber", "sync_lineage", "micro_batcher",
                   "async_persister", "periodic_reporter")


def run_weave(*, schedules=8, sweep=12, seed=0, quiet=False):
    """Deterministic-interleaving variant of the soak: instead of racing the
    real actors against the OS scheduler for wall-clock seconds, explore
    seeded-random + preemption-bounded schedules of the same components
    under tools/oeweave and fail on ANY schedule that breaks an invariant
    (torn status, lost wakeup, double apply, leaked thread). Returns a
    report dict; raises AssertionError listing replay tokens on failure."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from openembedding_tpu.utils import metrics
    from tools.oeweave import explore as weave_explore
    from tools.oeweave import scenarios as weave_scenarios

    def log(msg):
        if not quiet:
            print(f"[sync_soak --weave] {msg}", flush=True)

    weave_scenarios.warm()
    report = {"scenarios": {}, "schedules_explored": 0, "failures": 0}
    for name in WEAVE_SCENARIOS:
        res = weave_explore.explore(
            weave_scenarios.SCENARIOS[name],
            random_schedules=schedules, seed=seed,
            preemption_schedules=sweep)
        report["scenarios"][name] = {
            "explored": res.schedules_explored,
            "truncated": res.truncated,
            "failures": [{"kind": f.kind, "error": f.error,
                          "token": f.token} for f in res.failures],
        }
        report["schedules_explored"] += res.schedules_explored
        report["failures"] += len(res.failures)
        log(f"{name}: {res.schedules_explored} schedules, "
            f"{len(res.failures)} failures")
    metrics.observe("weave.schedules_explored",
                    float(report["schedules_explored"]))
    metrics.observe("weave.failures", float(report["failures"]))
    assert report["failures"] == 0, (
        "weave found failing interleavings — replay with "
        "`python -m tools.oeweave <scenario> --replay <scenario>:<token>`: "
        + json.dumps(report["scenarios"]))
    return report


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--steps", type=int, default=24)
    ap.add_argument("--persist-every", type=int, default=2)
    ap.add_argument("--interval-s", type=float, default=0.05)
    ap.add_argument("--predict-threads", type=int, default=4)
    ap.add_argument("--wire", default="fp32")
    ap.add_argument("--workdir", default="/tmp/oetpu_sync_soak")
    ap.add_argument("--lag-bound-steps", type=int, default=None,
                    help="fail if observed version lag ever exceeds this "
                         "(only meaningful with --step-delay-s pacing the "
                         "trainer slower than the subscriber poll)")
    ap.add_argument("--step-delay-s", type=float, default=0.0,
                    help="sleep per train step, emulating a real step time "
                         "so version lag is measurable")
    ap.add_argument("--metrics-log", default=None, metavar="PATH",
                    help="append periodic accumulator reports (and a final "
                         "snapshot) as timestamped JSONL records to PATH")
    ap.add_argument("--stall-s", type=float, default=0.0,
                    help="withhold publisher delta payloads for this many "
                         "seconds mid-run (the causality acceptance "
                         "scenario: serving_freshness must flip BREACHED "
                         "with the fetch hop dominating, then recover)")
    ap.add_argument("--stall-after-frac", type=float, default=0.4,
                    help="engage the stall once the trainer passes this "
                         "fraction of its steps")
    ap.add_argument("--freshness-threshold-ms", type=float, default=None,
                    help="soak-scaled serving_freshness threshold while "
                         "stalling (default stall_s/2 in ms)")
    ap.add_argument("--timeline", action="store_true",
                    help="scrape both nodes' /timelinez pre-shutdown and "
                         "assert the last delta's lineage chain merges "
                         "contiguous and ordered (tools/fleet_timeline)")
    ap.add_argument("--no-slo-gate", action="store_true",
                    help="report SLO verdicts but exit 0 regardless "
                         "(default: exit with the SLO verdict — 0 all OK, "
                         "1 breached, 2 unknown)")
    ap.add_argument("--weave", action="store_true",
                    help="run the deterministic-interleaving variant "
                         "(tools/oeweave over the soak's actors) instead "
                         "of the wall-clock soak")
    ap.add_argument("--weave-schedules", type=int, default=8,
                    help="random schedules per scenario with --weave")
    ap.add_argument("--weave-sweep", type=int, default=12,
                    help="preemption-sweep schedules per scenario")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args(argv)
    if args.weave:
        try:
            report = run_weave(schedules=args.weave_schedules,
                               sweep=args.weave_sweep, seed=args.seed)
        except AssertionError as e:
            print(e)
            return 1
        print(json.dumps(report))
        return 0
    report = run(steps=args.steps, persist_every=args.persist_every,
                 interval_s=args.interval_s,
                 predict_threads=args.predict_threads, wire=args.wire,
                 workdir=args.workdir, lag_bound_steps=args.lag_bound_steps,
                 step_delay_s=args.step_delay_s,
                 metrics_log=args.metrics_log, stall_s=args.stall_s,
                 stall_after_frac=args.stall_after_frac,
                 freshness_threshold_ms=args.freshness_threshold_ms,
                 timeline=args.timeline)
    print(json.dumps(report))
    return 0 if args.no_slo_gate else report["slo_exit_code"]


if __name__ == "__main__":
    sys.exit(main())
