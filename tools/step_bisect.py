"""Bisect the DeepFM train step on-device with RELIABLE fences.

Each timed fn is wrapped in lax.scan over K iterations inside ONE jit dispatch and
returns a scalar that depends on everything; timing = (fetch latency of that
scalar) — dispatch overhead and unreliable block_until_ready semantics through the
remote runtime cannot distort per-iteration numbers this way.
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

K = 20


def timeit_scan(make_body, init_carry, label):
    import jax
    import jax.numpy as jnp

    def run(carry):
        def body(c, _):
            return make_body(c), None
        c, _ = jax.lax.scan(body, carry, None, length=K)
        return jax.tree_util.tree_reduce(
            lambda a, x: a + jnp.sum(x).astype(jnp.float32), c,
            jnp.float32(0))

    fn = jax.jit(run)
    float(fn(init_carry))  # compile + warm
    t0 = time.perf_counter()
    float(fn(init_carry))
    dt = (time.perf_counter() - t0) / K * 1e3
    print(f"{label:34s} {dt:8.3f} ms/iter", flush=True)
    return dt


def main():
    import jax
    import jax.numpy as jnp
    import openembedding_tpu as embed
    from openembedding_tpu.model import Trainer, dense_apply
    from openembedding_tpu.models import make_deepfm
    from openembedding_tpu.data import synthetic_criteo
    from openembedding_tpu.ops.dedup import unique_with_counts, bucket_by_owner
    from openembedding_tpu.ops.sparse import (lookup_rows,
                                              sparse_apply_dense_table)

    print(f"backend={jax.default_backend()}", flush=True)
    VOCAB, DIM, BATCH = 1 << 24, 9, 4096
    model = make_deepfm(vocabulary=VOCAB, dim=DIM)
    trainer = Trainer(model, embed.Adagrad(learning_rate=0.05))
    batch = jax.device_put(next(synthetic_criteo(
        BATCH, id_space=VOCAB, steps=1, seed=7, ids_dtype=np.int32)))
    state = trainer.init(batch)
    ids = batch["sparse"]["categorical"].reshape(-1)
    table = state.tables["categorical"]
    opt = trainer.optimizer

    # 0. whole train step (scan-fused), for reference
    def full(carry):
        st, b = carry
        st, _ = trainer.train_step(st, b)
        return (st, b)
    timeit_scan(full, (state, batch), "full train_step")

    # 1. dedup only (carry the ids so scan can't hoist)
    def dedup(carry):
        u = unique_with_counts(carry)
        return carry + u.inverse.astype(carry.dtype)
    timeit_scan(dedup, ids, "dedup (unique_with_counts)")

    # 1b. fused dedup + owner routing (the round-4 exchange plan: one
    # multi-key sort; compare against 1 + a second bucket_by_owner sort)
    from openembedding_tpu.ops.dedup import unique_and_route

    def fused_route(carry):
        u, b = unique_and_route(carry, carry >= 0, 8, carry.shape[0] // 8)
        return carry + u.inverse.astype(carry.dtype) + b.owner.astype(
            carry.dtype)
    timeit_scan(fused_route, ids, "dedup+route fused (unique_and_route S=8)")

    def split_route(carry):
        u = unique_with_counts(carry)
        b = bucket_by_owner(u.unique_ids, u.counts > 0, 8,
                            carry.shape[0] // 8)
        return carry + u.inverse.astype(carry.dtype) + b.owner.astype(
            carry.dtype)
    timeit_scan(split_route, ids, "dedup+route split (2 sorts, r3 protocol)")

    # 2. gather only
    def gather(carry):
        rows = lookup_rows(table.weights, carry)
        return carry + rows[:, 0].astype(carry.dtype)
    timeit_scan(gather, ids, "gather rows")

    # 3. sparse apply only (weights+slots carried)
    grads = jnp.ones((ids.shape[0], DIM + 1), jnp.float32)

    def apply_fn(carry):
        w, s = carry
        w, s = sparse_apply_dense_table(opt, w, s, ids, grads)
        return (w, s)
    timeit_scan(apply_fn, (table.weights, table.slots), "sparse apply")

    # 3b. PACKED sparse apply (the train_many scan layout): one gather/scatter
    # pair over the concatenated weights+slots array (ops/sparse.packed_layout)
    from openembedding_tpu.ops.sparse import (pack_table, packed_layout,
                                              sparse_apply_packed_table)
    lay = packed_layout(DIM + 1, table.slots, table.weights.dtype)
    if lay is not None:
        packed = pack_table(table.weights, table.slots, lay)

        def papply(carry):
            return sparse_apply_packed_table(opt, carry, lay, DIM + 1, ids,
                                             grads)
        timeit_scan(papply, packed, "sparse apply PACKED")

        # 0b. whole train step on the packed state (what train_many scans)
        layouts = trainer._packed_layouts(state)
        ptables = dict(state.tables)
        for name, l in layouts.items():
            ts = ptables[name]
            ptables[name] = ts.replace(
                weights=pack_table(ts.weights, ts.slots, l), slots={})
        pstate = state.replace(tables=ptables)

        def full_packed(carry):
            st, b = carry
            st, _ = trainer.train_step(st, b, packed=layouts)
            return (st, b)
        timeit_scan(full_packed, (pstate, batch), "full train_step PACKED")

    # 4. dense fwd+bwd only
    rows = jnp.ones((BATCH, 26, DIM + 1), jnp.float32)

    def fwdbwd(carry):
        p = carry

        def loss_fn(p, r):
            logits = model.module.apply({"params": p}, {"categorical": r},
                                        batch["dense"])
            return model.loss_fn(logits, batch["label"])
        _, (gp, gr) = jax.value_and_grad(loss_fn, argnums=(0, 1))(p, rows)
        return jax.tree_util.tree_map(lambda a, b: a + 0e0 * b, p, gp)
    timeit_scan(fwdbwd, state.dense_params, "dense fwd+bwd")

    # 5. dense apply only
    dgrads = jax.tree_util.tree_map(jnp.ones_like, state.dense_params)

    def dapply(carry):
        p, s = carry
        return dense_apply(opt, p, s, dgrads)
    timeit_scan(dapply, (state.dense_params, state.dense_slots), "dense apply")


if __name__ == "__main__":
    main()
