"""Operator CLI for the SLO engine: fetch a node's verdicts and exit with them.

    python tools/slo_report.py http://127.0.0.1:8501
    python tools/slo_report.py http://127.0.0.1:8501 --json
    python tools/slo_report.py http://127.0.0.1:8501 --watch 5

GETs `/sloz` on a serving node (`utils/slo.py`; the node evaluates its spec
set against its live accumulator registry per request), prints the verdict
table, and exits with the SLO verdict — 0 every objective OK, 1 any
BREACHED, 2 anything UNKNOWN (absence of evidence is not a pass) — so the
CLI slots straight into CI gates and cron checks. `--watch` re-polls and
reprints until interrupted (exit code then reflects the LAST poll).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.request


def fetch(url: str, timeout: float = 10.0) -> dict:
    base = url.rstrip("/")
    if not base.startswith("http"):
        base = f"http://{base}"
    if not base.endswith("/sloz"):
        base = f"{base}/sloz"
    with urllib.request.urlopen(base, timeout=timeout) as r:
        return json.loads(r.read().decode())


def format_verdicts(doc: dict) -> str:
    rows = doc.get("verdicts", [])
    if not rows:
        return "(no SLO verdicts)"
    lines = []
    for v in rows:
        val = ("never-observed" if v.get("value") is None
               else f"{v['value']:.6g}")
        lines.append(f"[{v['verdict']:>8}] {v['name']}: "
                     f"{v['metric']}.{v['selector']} {v['op']} "
                     f"{v['threshold']:g} (value={val}, n={v['samples']})"
                     + (f" — {v['description']}" if v.get("description")
                        else ""))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="SLO verdicts from a live node's GET /sloz; exits with "
                    "the verdict (0 OK / 1 breached / 2 unknown)")
    ap.add_argument("url", help="node base URL (or full .../sloz)")
    ap.add_argument("--json", action="store_true",
                    help="print the raw /sloz JSON instead of the table")
    ap.add_argument("--watch", type=float, default=0.0, metavar="S",
                    help="re-poll every S seconds until interrupted")
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args(argv)
    code = 2
    try:
        while True:
            doc = fetch(args.url, timeout=args.timeout)
            code = int(doc.get("exit_code", 2))
            if args.json:
                print(json.dumps(doc, indent=2))
            else:
                print(format_verdicts(doc))
            if args.watch <= 0:
                break
            time.sleep(args.watch)
            print()
    except KeyboardInterrupt:
        pass
    except OSError as e:
        print(f"slo_report: {args.url}: {e}", file=sys.stderr)
        return 2
    return code


if __name__ == "__main__":
    raise SystemExit(main())
