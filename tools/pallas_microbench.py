"""Micro-benchmark: Pallas kernels vs the XLA fallback on the current backend.

The Pallas kernels only engage for 128-lane-aligned row widths (Mosaic DMA slice
constraint, see `ops/pallas_sparse.py::_lane_aligned`), so this measures:
- dim 64 (reference benchmark shape): XLA path only (what production uses there);
- dim 128 (aligned): XLA vs Pallas gather and fused-apply head to head;
- a full single-chip DeepFM train step at the reference dims, Pallas auto vs off.

Run on the real TPU:  python tools/pallas_microbench.py
On CPU (interpreter): JAX_PLATFORMS=cpu python tools/pallas_microbench.py --interpret
"""

import argparse
import os
import sys
import time
import traceback

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def timeit(fn, *args, warmup=2, iters=20):
    import jax
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def bench_dim(dim, vocab, n, opt, interp, try_pallas):
    import jax
    import jax.numpy as jnp
    from openembedding_tpu.ops import pallas_sparse
    from openembedding_tpu.ops.sparse import lookup_rows, sparse_apply_dense_table

    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((vocab, dim)), jnp.float32)
    rows = jnp.asarray(rng.integers(0, vocab, size=n), jnp.int32)
    slots = opt.init_slots(vocab, dim)
    grads = jnp.asarray(rng.standard_normal((n, dim)), jnp.float32)

    pallas_sparse.set_mode("off")
    xla_gather = jax.jit(lookup_rows)
    t = timeit(xla_gather, w, rows)
    print(f"[dim {dim:4d}] gather XLA:    {t*1e3:8.3f} ms ({n/t/1e6:7.1f} M rows/s)")

    xla_apply = jax.jit(lambda w, s, r, g: sparse_apply_dense_table(opt, w, s, r, g))
    t = timeit(xla_apply, w, slots, rows, grads)
    print(f"[dim {dim:4d}] apply  XLA:    {t*1e3:8.3f} ms ({n/t/1e6:7.1f} M grads/s)")

    if not try_pallas:
        return
    try:
        pgather = jax.jit(
            lambda w, r: pallas_sparse.gather_rows(w, r, interpret=interp))
        np.testing.assert_array_equal(np.asarray(xla_gather(w, rows)),
                                      np.asarray(pgather(w, rows)))
        t = timeit(pgather, w, rows)
        print(f"[dim {dim:4d}] gather Pallas: {t*1e3:8.3f} ms "
              f"({n/t/1e6:7.1f} M rows/s)")
    except Exception:
        print(f"[dim {dim:4d}] gather Pallas: FAILED")
        traceback.print_exc(limit=2)
    # window-batched gather (PERF lever #1): sorted rows, two densities —
    # uniform (worst case, sigma~1) and frequency-clustered (the reference's
    # relabel-by-frequency data shape, where windows amortize)
    for label, rows_w in (
        ("uniform", jnp.sort(rows)),
        ("hot10%", jnp.sort(jnp.asarray(
            rng.integers(0, max(vocab // 10, 1), size=n), jnp.int32))),
    ):
        for window in (16, 64):
            try:
                pwin = jax.jit(lambda w, r, win=window:
                               pallas_sparse.gather_rows_windows(
                                   w, r, window=win, interpret=interp))
                np.testing.assert_array_equal(
                    np.asarray(xla_gather(w, rows_w)),
                    np.asarray(pwin(w, rows_w)))
                t = timeit(pwin, w, rows_w)
                print(f"[dim {dim:4d}] gather win{window:3d} {label}: "
                      f"{t*1e3:8.3f} ms ({n/t/1e6:7.1f} M rows/s)")
            except Exception:
                print(f"[dim {dim:4d}] gather win{window} {label}: FAILED")
                traceback.print_exc(limit=2)
    try:
        pallas_sparse.set_mode("interpret" if interp else "on")
        papply = jax.jit(
            lambda w, s, r, g: sparse_apply_dense_table(opt, w, s, r, g))
        rw, _ = xla_apply(w, slots, rows, grads)
        gw, _ = papply(w, slots, rows, grads)
        np.testing.assert_allclose(np.asarray(rw), np.asarray(gw),
                                   rtol=2e-6, atol=1e-6)
        t = timeit(papply, w, slots, rows, grads)
        print(f"[dim {dim:4d}] apply  Pallas: {t*1e3:8.3f} ms "
              f"({n/t/1e6:7.1f} M grads/s)")
    except Exception:
        print(f"[dim {dim:4d}] apply  Pallas: FAILED")
        traceback.print_exc(limit=2)
    finally:
        pallas_sparse.set_mode("off")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interpret", action="store_true")
    ap.add_argument("--n", type=int, default=26 * 4096)
    args = ap.parse_args()

    import jax
    from openembedding_tpu.ops import pallas_sparse
    from openembedding_tpu import optimizers

    print(f"backend={jax.default_backend()} devices={jax.devices()}")
    opt = optimizers.Adagrad(learning_rate=0.05)
    small = args.interpret  # interpreter is slow; shrink shapes
    n = 2048 if small else args.n
    bench_dim(64, 1 << (14 if small else 22), n, opt, args.interpret, small)
    bench_dim(128, 1 << (14 if small else 21), n, opt, args.interpret, True)

    # full single-chip train step at the reference benchmark shape
    import openembedding_tpu as embed
    from openembedding_tpu.model import Trainer
    from openembedding_tpu.models import make_deepfm
    from openembedding_tpu.data import synthetic_criteo

    # "auto" resolves to the XLA path (kernels stay off until they win)
    for mode in ("off", "interpret" if args.interpret else "auto"):
        pallas_sparse.set_mode(mode)
        model = make_deepfm(vocabulary=1 << (14 if small else 22), dim=9)
        trainer = Trainer(model, embed.Adagrad(learning_rate=0.05))
        bs = 256 if small else 4096
        batch = jax.device_put(next(synthetic_criteo(
            bs, id_space=1 << 14, steps=1, seed=7, ids_dtype=np.int32)))
        state = trainer.init(batch)
        step = trainer.jit_train_step()
        state, m = step(state, batch)
        float(m["loss"])
        t0 = time.perf_counter()
        iters = 5 if small else 30
        for _ in range(iters):
            state, m = step(state, batch)
        float(m["loss"])
        dt = (time.perf_counter() - t0) / iters
        print(f"train step [{mode:9s}]: {dt*1e3:8.3f} ms ({bs/dt:,.0f} examples/s)")
    pallas_sparse.set_mode("off")


if __name__ == "__main__":
    main()
