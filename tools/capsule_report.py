"""Render a postmortem capsule (`utils/capsule.py`) into an operator report.

    python tools/capsule_report.py /var/capsules/capsule-20260807T120001-nonfinite.json.gz
    python tools/capsule_report.py cap.json.gz --json            # raw payload
    python tools/capsule_report.py cap.json.gz --tail 40         # more flight lines
    python tools/capsule_report.py cap.json.gz --request ab12cd  # one request only

Fully offline — the capsule is self-contained (flight-recorder tail, metric
history rings, device-memory ledger, collective fingerprint, resolved
config, HLO-budget digest), so this renders a dump mailed from a production
node with no live process and no repo checkout on the reading side. Sections:

- header: reason, trigger attrs, wall time, fingerprint, HLO-budget digest;
- flight timeline: the last events/spans before the trigger, relative
  seconds, request ids kept so a NaN step correlates to its ingest batch;
- history: one sparkline per metric series ring (most recent window);
- memory: the analytic per-component/per-table ledger vs the device view;
- context: registered provider snapshots (resolved trainer/serving config).
"""

from __future__ import annotations

import argparse
import gzip
import json
import sys
from typing import Any, Dict, List, Optional

SPARK_CHARS = " ▁▂▃▄▅▆▇█"


def load(path: str) -> dict:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        return json.load(f)


def sparkline(values: List[float], width: int = 32) -> str:
    vals = [float(v) for v in values if v is not None][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return SPARK_CHARS[4] * len(vals)
    return "".join(
        SPARK_CHARS[1 + int((v - lo) / span * (len(SPARK_CHARS) - 2))]
        for v in vals)


def _fmt_bytes(n: float) -> str:
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if abs(n) < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}TiB"


def _fmt_attrs(attrs: Dict[str, Any]) -> str:
    return " ".join(f"{k}={v}" for k, v in sorted(attrs.items()))


def render_header(cap: dict) -> List[str]:
    import time
    when = time.strftime("%Y-%m-%d %H:%M:%S UTC",
                         time.gmtime(cap.get("ts", 0)))
    lines = [f"capsule v{cap.get('version')}  reason={cap.get('reason')}  "
             f"at {when}"]
    if cap.get("attrs"):
        lines.append(f"  attrs: {_fmt_attrs(cap['attrs'])}")
    if cap.get("fingerprint"):
        lines.append(f"  collective fingerprint: {cap['fingerprint']}")
    if cap.get("hlo_budget_digest"):
        lines.append(f"  hlo budget digest: {cap['hlo_budget_digest']}")
    return lines


def render_flight(cap: dict, tail: int = 25,
                  request: Optional[str] = None) -> List[str]:
    items = list(cap.get("flight", [])) + list(cap.get("open_spans", []))
    if request:
        items = [it for it in items
                 if str(it.get("request_id", "")).startswith(request)]
    if not items:
        return ["(flight recorder empty)"]
    t0 = cap.get("ts", 0.0)
    lines = []
    for it in items[-tail:]:
        ts = it.get("ts", it.get("start", 0.0))
        rel = ts - t0
        rid = it.get("request_id") or "-"
        tag = f"{it.get('group', '?')}/{it.get('name', '?')}"
        if it.get("kind") == "span":
            dur = it.get("duration_ms")
            dur_s = f"{dur:8.2f}ms" if dur is not None else "    OPEN  "
            lines.append(f"  {rel:+9.3f}s  span  {dur_s}  {tag:<34} "
                         f"rid={rid} {_fmt_attrs(it.get('attrs', {}))}")
        else:
            lines.append(f"  {rel:+9.3f}s  event            {tag:<34} "
                         f"rid={rid} {_fmt_attrs(it.get('attrs', {}))}")
    return lines


def render_history(cap: dict, width: int = 32,
                   limit: int = 24) -> List[str]:
    hist = cap.get("history", {})
    if not hist:
        return ["(no history rings)"]
    lines = []
    for key in sorted(hist)[:limit]:
        series = hist[key]
        pts = series.get("points", [])
        # hist-kind series retain {"mean","count","p50","p95","p99"} dicts
        vals = [p[1].get("p99") if isinstance(p[1], dict) else p[1]
                for p in pts]
        last = vals[-1] if vals else None
        lines.append(f"  {key:<44} {sparkline(vals, width):<{width}} "
                     f"last={last!r} n={len(pts)}")
    extra = len(hist) - limit
    if extra > 0:
        lines.append(f"  ... and {extra} more series (--json for all)")
    return lines


def render_memory(cap: dict) -> List[str]:
    mem = cap.get("memory", {})
    comps = mem.get("components", [])
    if not comps and not mem.get("device_stats"):
        return ["(no memory ledger)"]

    def _key(e):
        labels = ",".join(f"{k}={v}"
                          for k, v in sorted(e.get("labels", {}).items()))
        return e.get("component", "?") + (f"{{{labels}}}" if labels else "")

    lines = []
    for ent in sorted(comps, key=_key):
        host = " (host)" if ent.get("host") else ""
        lines.append(f"  {_key(ent):<44} "
                     f"{_fmt_bytes(ent.get('bytes', 0)):>12}{host}")
    lines.append(f"  {'-- device total (model)':<44} "
                 f"{_fmt_bytes(mem.get('device_total_bytes', 0)):>12}")
    dev = mem.get("device_stats")
    if dev:
        used, limit = dev.get("used", 0), dev.get("limit", 0)
        extra = ""
        if limit:
            drift = (used - mem.get("device_total_bytes", 0)) / limit
            extra = (f" headroom={1.0 - used / limit:.3f}"
                     f" model_drift={drift:+.4f}")
        lines.append(f"  device worst-case: used={_fmt_bytes(used)} "
                     f"limit={_fmt_bytes(limit)}{extra}")
    budget = mem.get("budget_bytes")
    if budget:
        lines.append(f"  configured budget: {_fmt_bytes(budget)}")
    return lines


def render_context(cap: dict) -> List[str]:
    ctx = cap.get("context", {})
    if not ctx:
        return []
    lines = ["", "== context"]
    for name in sorted(ctx):
        body = json.dumps(ctx[name], indent=2, sort_keys=True, default=repr)
        lines.append(f"  [{name}]")
        lines.extend("    " + ln for ln in body.splitlines())
    return lines


def render(cap: dict, tail: int = 25,
           request: Optional[str] = None) -> str:
    lines = render_header(cap)
    lines += ["", "== flight recorder (relative to trigger)"]
    lines += render_flight(cap, tail=tail, request=request)
    lines += ["", "== metric history"]
    lines += render_history(cap)
    lines += ["", "== device memory"]
    lines += render_memory(cap)
    lines += render_context(cap)
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="capsule-*.json.gz (or plain .json)")
    ap.add_argument("--json", action="store_true",
                    help="print the raw capsule payload")
    ap.add_argument("--tail", type=int, default=25,
                    help="flight-recorder lines to show (default 25)")
    ap.add_argument("--request", default=None,
                    help="only show flight items whose request id starts "
                         "with this prefix")
    args = ap.parse_args(argv)
    cap = load(args.path)
    if args.json:
        json.dump(cap, sys.stdout, indent=2, sort_keys=True)
        print()
        return 0
    print(render(cap, tail=args.tail, request=args.request))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
