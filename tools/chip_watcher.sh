#!/bin/bash
# Detached relay watcher (round 5). Probes the axon relay every 5 minutes
# with a throwaway subprocess (a hung claim = relay down; the probe eats the
# hang, not this shell) and, on the first up-window, runs the prioritized
# evidence battery tools/upwindow.py — committing results case by case.
# Re-entrant: already-green cases are skipped via /tmp/upwindow_r5_done.json.
#
# Launch:  nohup bash tools/chip_watcher.sh >/dev/null 2>&1 &
# Retire:  touch /tmp/upwindow_r5_stop      (do this before round end so the
#          driver's own bench.py capture has the chip to itself)
LOG=/tmp/chip_watcher_r5.log
MAX_ATTEMPTS=6   # a deterministically-red battery must not commit forever
# Hard wall-clock deadline (epoch seconds; default +7h): the driver's own
# round-end bench.py must find the chip FREE — a battery firing into its
# capture window would eat most of its budget. WATCHER_DEADLINE overrides.
DEADLINE=${WATCHER_DEADLINE:-$(( $(date +%s) + 7 * 3600 ))}
attempts=0
cd "$(dirname "$0")/.." || exit 1
echo "$(date -u '+%F %T') watcher started (pid $$, deadline $(date -u -d @$DEADLINE '+%F %T'))" >> "$LOG"
while true; do
  if [ -f /tmp/upwindow_r5_stop ]; then
    echo "$(date -u '+%F %T') stop marker found, exiting" >> "$LOG"
    exit 0
  fi
  if [ "$(date +%s)" -ge "$DEADLINE" ]; then
    echo "$(date -u '+%F %T') deadline reached, retiring (chip left free for the driver)" >> "$LOG"
    exit 0
  fi
  if timeout 75 python -c \
      "import jax; d=jax.devices(); assert d[0].platform != 'cpu'" \
      >> "$LOG" 2>&1; then
    echo "$(date -u '+%F %T') RELAY UP — running battery" >> "$LOG"
    python tools/upwindow.py --no-probe >> /tmp/upwindow_r5.log 2>&1
    rc=$?
    attempts=$((attempts + 1))
    echo "$(date -u '+%F %T') battery rc=$rc (attempt $attempts)" >> "$LOG"
    if [ "$rc" -eq 0 ]; then
      echo "$(date -u '+%F %T') all cases green, retiring" >> "$LOG"
      exit 0
    fi
    if [ "$attempts" -ge "$MAX_ATTEMPTS" ]; then
      echo "$(date -u '+%F %T') $attempts failed batteries, retiring" >> "$LOG"
      exit 1
    fi
  else
    echo "$(date -u '+%F %T') relay down (probe timeout/fail)" >> "$LOG"
  fi
  sleep 300
done
