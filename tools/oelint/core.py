"""oelint core: findings, source files, suppressions, annotations.

The framework is a multi-pass static analyzer over the repo's Python tree
(`python -m tools.oelint`, `make lint`). Each pass lives in
`tools/oelint/passes/` and exports:

    NAME: str            # CLI / suppression name, e.g. "trace-hazard"
    DIRS: tuple          # repo-relative dirs whose .py files it scans
    run(files, root)     # -> list[Finding]

Shared conventions every pass honors (this module implements them):

- **Suppressions** are inline, per-line, and REASONED:

      risky_line()  # oelint: disable=trace-hazard -- reason why it is safe

  The comment may sit on the offending line or the line directly above it.
  `disable=all` silences every pass for that line. A suppression WITHOUT a
  reason is itself a finding (`suppression` pseudo-pass) — the repo policy
  is zero bare suppressions; the reason is the review artifact.

- **Annotations** opt code into pass-specific contracts:

      # oelint: jit-entry             (trace-hazard: treat fn as a jit root)
      # oelint: hot-path              (host-sync: audit fn; 1 device_get ok)
      # oelint: hot-path device_get=0 (host-sync: override the sync budget)
      self._x = 0  # guarded-by: self._lock   (lockset: writes need the lock)

  An annotation binds to the `def`/assignment it trails, or to the line
  above it (decorator lines included for defs).
"""

from __future__ import annotations

import ast
import os
import re
import subprocess
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set, Tuple

SUPPRESS_RE = re.compile(
    r"#\s*oelint:\s*disable=([a-zA-Z0-9_,-]+)"
    r"(?:\s*(?:--|—|–)\s*(\S.*))?")
JIT_ENTRY_RE = re.compile(r"#\s*oelint:\s*jit-entry\b")
HOT_PATH_RE = re.compile(
    r"#\s*oelint:\s*hot-path\b(?:\s+device_get=(\d+))?")
GUARDED_BY_RE = re.compile(r"#.*?\bguarded-by:\s*([A-Za-z0-9_.]+)")


@dataclass(frozen=True)
class Finding:
    path: str       # repo-relative
    line: int
    pass_name: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.pass_name}] {self.message}"


class SourceFile:
    """One parsed source file: text, AST, and the per-line suppression map."""

    def __init__(self, root: str, rel: str):
        self.rel = rel.replace(os.sep, "/")
        self.path = os.path.join(root, rel)
        with open(self.path, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree: Optional[ast.AST] = ast.parse(self.text)
        except SyntaxError as e:  # surfaced as a finding by the runner
            self.tree = None
            self.parse_error = e
        # lineno -> (set of pass names or {"all"}, reason or None)
        self.suppressions: Dict[int, Tuple[Set[str], Optional[str]]] = {}
        for i, line in enumerate(self.lines, start=1):
            m = SUPPRESS_RE.search(line)
            if m:
                passes = {p.strip() for p in m.group(1).split(",") if p.strip()}
                self.suppressions[i] = (passes, m.group(2))

    def line_text(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed(self, lineno: int, pass_name: str) -> bool:
        """A finding at `lineno` is suppressed by a disable comment on the
        same line or in the comment block directly above it (reasonless
        suppressions still suppress — they are flagged separately so CI
        stays red until a reason lands)."""
        for ln in [lineno] + list(self._comment_block_above(lineno)):
            entry = self.suppressions.get(ln)
            if entry and (pass_name in entry[0] or "all" in entry[0]):
                return True
        return False

    def bare_suppressions(self) -> List[Finding]:
        out = []
        for ln, (passes, reason) in sorted(self.suppressions.items()):
            if not reason:
                out.append(Finding(
                    self.rel, ln, "suppression",
                    f"bare suppression of {','.join(sorted(passes))}: every "
                    "`# oelint: disable=` needs ` -- <reason>` (repo policy: "
                    "zero bare suppressions)"))
        return out

    # -- annotation helpers ---------------------------------------------------

    def _is_comment_line(self, lineno: int) -> bool:
        return self.line_text(lineno).lstrip().startswith("#")

    def _comment_block_above(self, lineno: int) -> Iterable[int]:
        """Contiguous comment-ONLY lines directly above `lineno`, nearest
        first. A trailing comment on a CODE line never leaks onto the next
        statement — it binds to its own line only."""
        ln = lineno - 1
        while ln >= 1 and self._is_comment_line(ln):
            yield ln
            ln -= 1

    def _def_marker_lines(self, node: ast.AST) -> Iterable[int]:
        """Candidate annotation lines for a def: its own line, its decorator
        lines, and the contiguous comment block above the first of those."""
        linenos = [node.lineno]
        for dec in getattr(node, "decorator_list", []):
            linenos.append(dec.lineno)
        first = min(linenos)
        return sorted(set(linenos) | set(self._comment_block_above(first)))

    def def_annotation(self, node: ast.AST, regex: re.Pattern):
        for ln in self._def_marker_lines(node):
            m = regex.search(self.line_text(ln))
            if m:
                return m
        return None

    def stmt_annotation(self, node: ast.AST, regex: re.Pattern):
        """Annotation trailing a (possibly multi-line) statement, or in the
        comment block directly above it."""
        end = getattr(node, "end_lineno", node.lineno)
        lines = [node.lineno, end] + list(
            self._comment_block_above(node.lineno))
        for ln in lines:
            m = regex.search(self.line_text(ln))
            if m:
                return m
        return None


# -- shared concurrency-annotation support -----------------------------------
#
# The three threaded-control-plane passes (lockset, atomicity, cond-wait) all
# key off the same two class-level facts; they live here so the annotation
# semantics cannot drift between passes.


def self_attr(node: ast.AST) -> Optional[str]:
    """`self.X` -> "X", else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def condition_aliases(cls: ast.ClassDef) -> Dict[str, str]:
    """self.Y -> self.X for `self.Y = threading.Condition(self.X)` (holding
    the Condition holds its underlying lock)."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            func = node.value.func
            if isinstance(func, ast.Attribute) and func.attr == "Condition" \
                    and node.value.args:
                try:
                    lock_src = ast.unparse(node.value.args[0])
                except Exception:  # noqa: BLE001
                    continue
                for tgt in node.targets:
                    attr = self_attr(tgt)
                    if attr is not None:
                        aliases[f"self.{attr}"] = lock_src
    return aliases


def guarded_attrs(sf: "SourceFile", cls: ast.ClassDef) -> Dict[str, str]:
    """attr name -> lock expression, from `# guarded-by:` annotations on
    assignments (typically in __init__) or class-level AnnAssign lines."""
    guarded: Dict[str, str] = {}
    for node in ast.walk(cls):
        if not isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            continue
        m = sf.stmt_annotation(node, GUARDED_BY_RE)
        if not m:
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for tgt in targets:
            attr = self_attr(tgt)
            if attr is None and isinstance(tgt, ast.Name):
                attr = tgt.id  # class-level declaration
            if attr is not None:
                guarded[attr] = m.group(1)
    return guarded


def iter_py_files(root: str, dirs: Iterable[str],
                  skip: Iterable[str] = ()) -> List[str]:
    """Repo-relative .py paths under `dirs`, sorted; `skip` entries are
    repo-relative prefixes (files or directories)."""
    skip = tuple(s.replace(os.sep, "/") for s in skip)
    out = []
    for d in dirs:
        base = os.path.join(root, d)
        if not os.path.isdir(base):
            continue
        for dirpath, dirnames, files in os.walk(base):
            dirnames[:] = [x for x in dirnames if x != "__pycache__"]
            for fn in sorted(files):
                if not fn.endswith(".py"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                rel = rel.replace(os.sep, "/")
                if any(rel == s or rel.startswith(s.rstrip("/") + "/")
                       for s in skip):
                    continue
                out.append(rel)
    return sorted(set(out))


def load_files(root: str, rels: Iterable[str]) -> List[SourceFile]:
    return [SourceFile(root, rel) for rel in rels]


def changed_files(root: str) -> Optional[Set[str]]:
    """Repo-relative paths changed vs HEAD (worktree + staged + untracked);
    None when git is unavailable (callers fall back to a full run)."""
    try:
        out = subprocess.run(
            ["git", "-C", root, "status", "--porcelain"],
            capture_output=True, text=True, timeout=30, check=True).stdout
    except Exception:  # noqa: BLE001 — no git, no incremental mode
        return None
    rels: Set[str] = set()
    for line in out.splitlines():
        if len(line) < 4:
            continue
        path = line[3:].strip()
        if " -> " in path:  # rename: take the new side
            path = path.split(" -> ", 1)[1]
        rels.add(path.strip('"'))
    return rels


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
