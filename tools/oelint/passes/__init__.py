"""oelint pass registry, in documentation order."""

from . import (trace_hazard, host_sync, sharding, spmd_divergence,
               hlo_budget, implicit_reshard, lockset, atomicity, condwait,
               lifecycle, metrics)

ALL_PASSES = (trace_hazard, host_sync, sharding, spmd_divergence,
              hlo_budget, implicit_reshard, lockset, atomicity, condwait,
              lifecycle, metrics)
BY_NAME = {p.NAME: p for p in ALL_PASSES}
