"""oelint pass registry, in documentation order."""

from . import trace_hazard, host_sync, hlo_budget, lockset, metrics

ALL_PASSES = (trace_hazard, host_sync, hlo_budget, lockset, metrics)
BY_NAME = {p.NAME: p for p in ALL_PASSES}
