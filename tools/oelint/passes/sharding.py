"""sharding pass: one placement registry, one spelling, per logical leaf.

Round 11's runtime guard caught a real production bug: two sites spelled the
same row-sharded placement differently — `P(axis, None)` in one and `P(axis)`
in the other. As PLACEMENTS they are identical; as JIT CACHE KEYS they are
not (`PartitionSpec('data', None) != PartitionSpec('data')`), so the step
silently recompiled the whole program on step 2. The fix was a one-off; this
pass generalizes it into a checked invariant over every statically-resolvable
`PartitionSpec` declaration site in the tree.

Two rules:

- R1 placement-conflict: every keyword binding of a `P(...)` literal to a
  field of the table-state constructors (`EmbeddingTableState`, `HotRows`,
  `MigRows`) registers `constructor.field -> canonical spec` in a
  cross-file placement registry. Two sites binding the same logical leaf to
  UNEQUAL canonical specs is a finding at every site that disagrees with the
  registry's reference spelling (first site in path/line order among the
  most common canonical form). Canonicalization trims trailing `None`s and
  resolves axis-name spellings (`axis`, `self.axis`, `self.data_axis`,
  `DATA_AXIS`, the literal `'data'`) to one token, so the rule compares
  PLACEMENTS, not surface syntax.
- R2 spelling-drift: any statically-resolvable `P(...)` literal with a
  TRAILING `None` is flagged on its own, wherever it appears. Trimming is
  the canonical spelling everywhere in this repo (jit outputs carry the
  trimmed form), so an untrimmed literal is at best a latent cache-key
  bug waiting for a comparison — see `MeshTrainer._table_pspec`.

Sites the pass cannot resolve statically (starred dims, computed axis
tuples, specs built in loops over `range(ndim)`) are skipped, not guessed:
`SeqMeshTrainer`'s `P(d, *pad, s)` specs stay a human's job. Suppress a
deliberate disagreement with `# oelint: disable=sharding -- <reason>`.
"""

from __future__ import annotations

import ast
from typing import Dict, List, NamedTuple, Optional, Tuple

from ..core import Finding, SourceFile
from .trace_hazard import _call_chain

NAME = "sharding"
DIRS = ("openembedding_tpu",)
# R1 needs the whole tree even under --changed-only: a conflict pairs a
# changed site with an unchanged one.
NEEDS_ALL_FILES = True

# constructors whose PartitionSpec keywords define the placement registry
STATE_CTORS = ("EmbeddingTableState", "HotRows", "MigRows")
# spellings that all resolve to the mesh's data axis (mesh.DATA_AXIS)
_AXIS_TOKEN = "<axis>"
_AXIS_NAMES = {"axis", "DATA_AXIS"}
_AXIS_ATTRS = {"self.axis", "self.data_axis"}
_AXIS_STRINGS = {"data"}


class Site(NamedTuple):
    """One registry entry: a P(...) literal bound to a constructor field."""
    key: str          # "EmbeddingTableState.weights", "...slots[]", ...
    canon: Tuple[str, ...]
    spelled: str      # source spelling, for the message
    rel: str
    line: int


def _canon_arg(node: ast.AST) -> Optional[str]:
    """One P(...) positional arg -> canonical token, or None if unresolvable."""
    if isinstance(node, ast.Constant):
        if node.value is None:
            return "None"
        if isinstance(node.value, str):
            return _AXIS_TOKEN if node.value in _AXIS_STRINGS \
                else repr(node.value)
        return None
    try:
        txt = ast.unparse(node)
    except Exception:  # noqa: BLE001 — unparse failure == unresolvable
        return None
    if txt in _AXIS_NAMES or txt in _AXIS_ATTRS:
        return _AXIS_TOKEN
    return None


def canonicalize(call: ast.Call) -> Optional[Tuple[Tuple[str, ...], int]]:
    """(canonical dim tuple, trailing-None count) for a P(...) literal;
    None when any dim is statically unresolvable (starred/computed)."""
    if call.keywords:
        return None
    parts: List[str] = []
    for a in call.args:
        if isinstance(a, ast.Starred):
            return None
        c = _canon_arg(a)
        if c is None:
            return None
        parts.append(c)
    n = len(parts)
    while parts and parts[-1] == "None":
        parts.pop()
    return tuple(parts), n - len(parts)


def _is_pspec_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    chain = _call_chain(node)
    return chain is not None and chain[-1] in ("P", "PartitionSpec")


def _spec_values(kw_value: ast.AST):
    """P(...) literals inside a constructor keyword value, with a key suffix:
    direct call / either ternary arm -> ""; dict or dict-comp values -> "[]"
    (slot specs are per-slot-name but share one placement by protocol)."""
    if _is_pspec_call(kw_value):
        yield "", kw_value
    elif isinstance(kw_value, ast.IfExp):
        for arm in (kw_value.body, kw_value.orelse):
            if _is_pspec_call(arm):
                yield "", arm
    elif isinstance(kw_value, ast.Dict):
        for v in kw_value.values:
            if _is_pspec_call(v):
                yield "[]", v
    elif isinstance(kw_value, ast.DictComp):
        if _is_pspec_call(kw_value.value):
            yield "[]", kw_value.value


def build_registry(files: List[SourceFile]) -> List[Site]:
    """The cross-file placement registry: every statically-resolvable
    P(...) keyword binding on the table-state constructors."""
    sites: List[Site] = []
    for sf in files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _call_chain(node)
            if chain is None or chain[-1] not in STATE_CTORS:
                continue
            ctor = chain[-1]
            for kw in node.keywords:
                if kw.arg is None:
                    continue
                for suffix, call in _spec_values(kw.value):
                    canon = canonicalize(call)
                    if canon is None:
                        continue
                    try:
                        spelled = ast.unparse(call)
                    except Exception:  # noqa: BLE001
                        spelled = "P(...)"
                    sites.append(Site(f"{ctor}.{kw.arg}{suffix}", canon[0],
                                      spelled, sf.rel, call.lineno))
    return sorted(sites, key=lambda s: (s.key, s.rel, s.line))


def _conflicts(sites: List[Site]) -> List[Tuple[Site, Site]]:
    """(disagreeing site, reference site) pairs across the registry."""
    by_key: Dict[str, List[Site]] = {}
    for s in sites:
        by_key.setdefault(s.key, []).append(s)
    out: List[Tuple[Site, Site]] = []
    for key in sorted(by_key):
        group = by_key[key]
        canons = {s.canon for s in group}
        if len(canons) <= 1:
            continue
        # reference = the most common canonical form; ties break to the
        # first site in (path, line) order so the report is deterministic
        counts: Dict[Tuple[str, ...], int] = {}
        for s in group:
            counts[s.canon] = counts.get(s.canon, 0) + 1
        ordered = sorted(group, key=lambda s: (s.rel, s.line))
        ref = max(ordered, key=lambda s: (counts[s.canon],
                                          -ordered.index(s)))
        for s in ordered:
            if s.canon != ref.canon:
                out.append((s, ref))
    return out


def run(files: List[SourceFile], root: str) -> List[Finding]:
    findings: List[Finding] = []
    by_rel = {sf.rel: sf for sf in files}

    # R1: placement registry conflicts
    for site, ref in _conflicts(build_registry(files)):
        sf = by_rel.get(site.rel)
        if sf is not None and sf.suppressed(site.line, NAME):
            continue
        findings.append(Finding(
            site.rel, site.line, NAME,
            f"`{site.key}` bound to `{site.spelled}` here but to "
            f"`{ref.spelled}` at {ref.rel}:{ref.line} — every placement "
            "site for a logical leaf must agree (unequal PartitionSpecs "
            "are unequal jit cache keys: the step silently recompiles)"))

    # R2: trailing-None spelling drift
    for sf in files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not _is_pspec_call(node):
                continue
            canon = canonicalize(node)
            if canon is None or canon[1] == 0:
                continue
            if sf.suppressed(node.lineno, NAME):
                continue
            try:
                spelled = ast.unparse(node)
            except Exception:  # noqa: BLE001
                spelled = "P(..., None)"
            findings.append(Finding(
                sf.rel, node.lineno, NAME,
                f"untrimmed PartitionSpec spelling `{spelled}`: trailing "
                "`None`s are placement-identical but cache-key-UNEQUAL to "
                "the trimmed form jit outputs carry — spell it trimmed "
                "(see MeshTrainer._table_pspec)"))

    return sorted(set(findings), key=lambda f: (f.path, f.line, f.message))
