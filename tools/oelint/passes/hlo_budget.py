"""hlo-budget pass: pin the collective set of every key entry-point config.

The scattered per-test HLO pins (tests/test_dedup.py, test_wire.py,
test_hot.py each count all-to-alls for one path) generalize here: this pass
COMPILES the train step for every key configuration on the 8-virtual-device
CPU mesh, counts collectives by kind in the optimized HLO, records the
static wire-bytes model, and compares against the checked-in budget
(`tools/oelint/hlo_budget.json`). A PR that adds a collective (or grows the
wire) to a pinned path fails `make lint` with a human-readable diff instead
of silently costing every future step.

Configurations (the acceptance matrix): the per-table protocol, the fused
dim-group exchange, hot-row cache on/off, and all three wire formats —
collective counts AND `exchange.wire_bytes_per_step` are pinned per config.

Regenerate after an intentional change:

    make lint-budget            # python -m tools.oelint --update-budget

and commit the diff — the json IS the review surface for collective changes.
Runs CPU-only (`JAX_PLATFORMS=cpu`, 8 virtual devices); no chip needed.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
from typing import Dict, List, Optional, Tuple

from ..core import Finding, iter_py_files

NAME = "hlo-budget"
DIRS = ()  # compiles programs; scans no source files
BUDGET_REL = "tools/oelint/hlo_budget.json"
# measured-counts cache keyed on a source digest: warm `make lint` skips all
# ten XLA compiles (see measure_cached). Local state, gitignored.
CACHE_REL = "tools/oelint/.hlo_measure_cache.json"

# --changed-only reruns this pass only when these paths changed (anything
# else cannot alter the compiled collective set)
TRIGGERS = (
    "openembedding_tpu/parallel/", "openembedding_tpu/ops/",
    "openembedding_tpu/model.py", "openembedding_tpu/embedding.py",
    "openembedding_tpu/optimizers.py", "openembedding_tpu/tables/",
    "tools/oelint/",
)

COLLECTIVES = {
    "all_to_all": r" all-to-all(?:-start)?\(",
    "all_reduce": r" all-reduce(?:-start)?\(",
    "all_gather": r" all-gather(?:-start)?\(",
    "reduce_scatter": r" reduce-scatter(?:-start)?\(",
    "collective_permute": r" collective-permute(?:-start)?\(",
}

# result-buffer tensor types on a collective's definition line, e.g.
# `%all-to-all.1 = s8[8,56,16]{2,1,0} all-to-all(...)`
_TYPE_RE = re.compile(
    r"(pred|bf16|f16|f32|f64|s8|u8|s16|u16|s32|u32|s64|u64)\[([0-9,]*)\]")
_ITEMSIZE = {"pred": 1, "s8": 1, "u8": 1, "bf16": 2, "f16": 2, "s16": 2,
             "u16": 2, "f32": 4, "s32": 4, "u32": 4, "f64": 8, "s64": 8,
             "u64": 8}

# the acceptance matrix: per-table vs fused, wire formats, hot on/off, and
# full placement (hot cache + cold-tail migration directory) — the
# `fused_fp32_placement` steady-state step must pin the IDENTICAL
# exchange-collective set as `fused_fp32_hot` (3 a2a, 0 all-gather, same
# wire bytes): the owner-assignment indirection is pure local math (two
# extra hash probes riding the fused sort), never a wire collective. The
# only delta is +4 scalar all-reduces — the `mig_unique`/`mig_hits` stats
# riding the existing per-key stats psum (2 stats x 2 tables).
CONFIGS = (
    {"name": "per_table_fp32", "group_exchange": False, "wire": "fp32",
     "hot_rows": 0},
    {"name": "fused_fp32", "group_exchange": True, "wire": "fp32",
     "hot_rows": 0},
    {"name": "fused_bf16", "group_exchange": True, "wire": "bf16",
     "hot_rows": 0},
    {"name": "fused_int8", "group_exchange": True, "wire": "int8",
     "hot_rows": 0},
    {"name": "fused_fp32_hot", "group_exchange": True, "wire": "fp32",
     "hot_rows": 32},
    {"name": "fused_fp32_placement", "group_exchange": True, "wire": "fp32",
     "hot_rows": 32, "mig_rows": 32},
    # round-13 in-collective configs: the compiled a2a operands must carry
    # the narrow dtype — `forbid_a2a_dtypes` turns a silent fall-back to
    # fp32-through-the-a2a into a lint failure even when the budget matches
    # (a fresh --update-budget would otherwise just pin the regression).
    # fused_int8_inband also runs error feedback (the default for int8) and
    # the two-stage s8 hot reduce; fused_fp32_hot_int8 isolates the hot
    # reduce's format from the exchange's.
    {"name": "fused_bf16_inband", "group_exchange": True, "wire": "bf16",
     "hot_rows": 32, "forbid_a2a_dtypes": ("f32",)},
    {"name": "fused_int8_inband", "group_exchange": True, "wire": "int8",
     "hot_rows": 32, "forbid_a2a_dtypes": ("f32", "bf16", "u16")},
    {"name": "fused_fp32_hot_int8", "group_exchange": True, "wire": "fp32",
     "hot_rows": 32, "hot_wire": "int8"},
    # round-14 ZeRO dense sharding: the sharded dense update must cost
    # EXACTLY one reduce-scatter + one all-gather over the flat dense state
    # (bytes pinned below) and must not perturb the exchange collectives —
    # same a2a set and wire bytes as fused_fp32.
    {"name": "fused_fp32_zero", "group_exchange": True, "wire": "fp32",
     "hot_rows": 0, "dense_shard": True},
    # round-16 numerics sentinel: the health stats ride the step's stats
    # psum — the pinned contract is that sentinel=True costs ONLY a handful
    # of extra SCALAR all-reduces (one per health stat key) and changes the
    # exchange a2a set and wire bytes by exactly zero vs fused_fp32 (and
    # every sentinel-off config above stays byte-identical, delta 0).
    {"name": "fused_fp32_sentinel", "group_exchange": True, "wire": "fp32",
     "hot_rows": 0, "sentinel": True},
    # round-17 per-table wire: the one dim-8 group splits on (dim, fmt) into
    # TWO fused a2a groups (6 a2as, not 3) and the compiled payloads must
    # carry BOTH formats — `require_a2a_dtypes` fails the lint when either
    # side silently falls back (f32 gone = table "a" got quantized, s8 gone
    # = table "b" fell back to fp32), budget-independently.
    {"name": "fused_mixed_wire", "group_exchange": True,
     "wire": {"a": "fp32", "b": "int8"}, "hot_rows": 0,
     "require_a2a_dtypes": ("f32", "s8")},
    # round-17 quantized dense ZeRO collectives: dense_wire="int8" replaces
    # the fp32 reduce-scatter with an s8 in-band a2a + per-replica fp32 sum
    # and ships the params all_gather on the u16 bf16 carrier. `pins` holds
    # hlo_reduce_scatter_bytes at EXACTLY 0 budget-independently (a silent
    # fall-back to the fp32 reduce_scatter fails `make lint` even straight
    # after --update-budget), and the s8 requirement pins the encoded grad
    # a2a itself.
    {"name": "fused_fp32_zero_int8", "group_exchange": True, "wire": "fp32",
     "hot_rows": 0, "dense_shard": True, "dense_wire": "int8",
     "require_a2a_dtypes": ("s8",),
     "pins": {"hlo_reduce_scatter_bytes": 0}},
    # round-23 density-adaptive sparse dense collectives: dense_wire=
    # "sparse_topk" ships each destination's top-k gradient entries as s8
    # values + in-band scales + bitcast-s8 index lanes through the same
    # encoded a2a slot the int8 path uses (reduce-scatter stays at exactly
    # 0, pinned), with dense_stats=True riding the per-key stats psum (one
    # extra scalar lane — the measured density that drives the crossover).
    # The unattributed pin proves the sparse scatter-sum decode stays local:
    # GSPMD must not insert resharding around the index-lane plumbing.
    {"name": "fused_fp32_zero_sparse", "group_exchange": True,
     "wire": "fp32", "hot_rows": 0, "dense_shard": True,
     "dense_wire": "sparse_topk", "dense_stats": True,
     "require_a2a_dtypes": ("s8",),
     "pins": {"hlo_reduce_scatter_bytes": 0,
              "unattributed_collectives": 0}},
    # round-18 software-pipelined train_many: the K-step window compiles a
    # scan whose body prefetches batch t+1's exchange BEFORE batch t's dense
    # compute/apply. fused_fp32_many is the serial K-step window on the same
    # model so the pipelined delta is a reviewable json diff: pipelining may
    # add ONLY the conflict-patch collectives (wire_conflict_patch_bytes —
    # the exact-replay re-gather of rows batch t updated) on top of the
    # serial set — zero hidden wire beyond the patch. The unattributed pin
    # is update-proof: GSPMD must not insert resharding into the rotated
    # carry plumbing.
    {"name": "fused_fp32_many", "group_exchange": True, "wire": "fp32",
     "hot_rows": 0, "train_many": 4},
    {"name": "fused_fp32_pipelined", "group_exchange": True, "wire": "fp32",
     "hot_rows": 0, "train_many": 4, "pipeline_steps": True,
     "pins": {"unattributed_collectives": 0}},
)


def _ensure_cpu() -> None:
    """8 virtual CPU devices, never the axon TPU handshake — same contract
    as the root conftest.py; must run before jax initializes a backend."""
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax
    jax.config.update("jax_platforms", "cpu")
    # pin the id-key layout the budget compiles under: x64 ON is the repo's
    # test-suite convention (63-bit hashed id spaces need int64 keys —
    # tests/conftest.py), and the budget must measure ONE fixed world
    jax.config.update("jax_enable_x64", True)


def count_collectives(hlo_text: str) -> Dict[str, int]:
    return {kind: len(re.findall(pat, hlo_text))
            for kind, pat in COLLECTIVES.items()}


# -- implicit-reshard attribution (consumed by the implicit-reshard pass) ----
#
# Every collective the PROTOCOL asks for is traced from an explicit lax call,
# and XLA stamps those ops with `metadata={op_name="jit(...)/.../psum"}` —
# the op_name tail is the traced primitive. GSPMD-INSERTED collectives
# (resharding between mismatched in/out shardings) carry no such traced-op
# tail: that absence is the detection signal for the silent-all-gather class.
_OPNAME_RE = re.compile(r'op_name="([^"]+)"')
_EXPLICIT_TAILS = {
    "psum", "psum2", "pmean", "pmax", "pmin", "ppermute", "pbroadcast",
    "all_to_all", "all_gather", "all_gather_invariant", "reduce_scatter",
    "psum_scatter",
}


def unattributed_collectives(hlo_text: str) -> List[Tuple[str, str]]:
    """[(kind, attribution)] for compiled collectives that do NOT trace back
    to an explicit collective primitive — i.e. GSPMD inserted them."""
    out: List[Tuple[str, str]] = []
    for line in hlo_text.splitlines():
        for kind, pat in COLLECTIVES.items():
            if not re.search(pat, line):
                continue
            m = _OPNAME_RE.search(line)
            tail = m.group(1).rsplit("/", 1)[-1] if m else ""
            base = tail.split("[", 1)[0]
            if base not in _EXPLICIT_TAILS:
                out.append((kind, m.group(1) if m else "<no metadata>"))
            break
    return out


def collective_payloads(hlo_text: str,
                        kinds=("all_to_all", "all_gather")):
    """[(kind, dtype, result_bytes)] per matching collective in the compiled
    HLO — one entry per tensor in the op's RESULT type (tuple results
    contribute one entry each). This is the measured counterpart of
    `ops/wire.exchange_cost`, which prices exactly these result buffers."""
    out = []
    for line in hlo_text.splitlines():
        for kind in kinds:
            m = re.search(COLLECTIVES[kind], line)
            if not m:
                continue
            head = line[:m.start()]
            eq = head.find("= ")
            if eq < 0:
                continue
            for dt, dims in _TYPE_RE.findall(head[eq + 2:]):
                n = 1
                for d in dims.split(","):
                    if d.strip():
                        n *= int(d)
                out.append((kind, dt, n * _ITEMSIZE[dt]))
            break
    return out


def _budget_model():
    """The smallest model that exercises every pinned path: two dim-8 tables
    (array + hash) in ONE dim-group, duplicate-heavy planted batch — the
    same shape family the HLO pin tests use."""
    import numpy as np

    import flax.linen as nn
    import jax.numpy as jnp

    import openembedding_tpu as embed
    from openembedding_tpu.model import EmbeddingModel

    class Tower(nn.Module):
        @nn.compact
        def __call__(self, embedded, dense):
            bias = self.param("bias", nn.initializers.zeros, (1,),
                              jnp.float32)
            out = (jnp.sum(embedded["a"].astype(jnp.float32), axis=(1, 2))
                   + jnp.sum(embedded["b"].astype(jnp.float32), axis=(1, 2)))
            return out + bias[0]

    model = EmbeddingModel(Tower(), [
        embed.Embedding(256, 8, name="a"),
        embed.Embedding(-1, 8, name="b", capacity=4096),
    ])
    rng = np.random.default_rng(0)
    B = 64
    a = rng.integers(0, 256, (B, 4)).astype(np.int32)
    # hash ids < 2^31: the x64-off truncation warning is model.py's to give,
    # not lint noise (collective counts are id-range-invariant)
    b = rng.integers(0, 1 << 20, (B, 3)).astype(np.int64)
    a[:, 0] = np.array([7, 13])[rng.integers(0, 2, B)]
    batch = {"sparse": {"a": a, "b": b},
             "label": rng.integers(0, 2, (B,)).astype(np.float32)}
    return model, batch


def make_trainer(config: Dict):
    """Budget trainer for one config (also the corpus tests' hook — they
    measure deliberately violated variants through the same plumbing)."""
    _ensure_cpu()
    import openembedding_tpu as embed
    from openembedding_tpu.parallel import MeshTrainer, make_mesh

    model, batch = _budget_model()
    wire = config["wire"]
    if isinstance(wire, dict):
        wire = dict(wire)  # MeshTrainer keeps the per-table dict as-is
    trainer = MeshTrainer(
        model, embed.Adagrad(learning_rate=0.1), mesh=make_mesh(),
        wire=wire, group_exchange=config["group_exchange"],
        hot_rows=config["hot_rows"], mig_rows=config.get("mig_rows", 0),
        hot_wire=config.get("hot_wire"),
        dense_shard=config.get("dense_shard", False),
        dense_wire=config.get("dense_wire"),
        dense_topk=config.get("dense_topk"),
        dense_stats=config.get("dense_stats", False),
        sentinel=config.get("sentinel", False),
        pipeline_steps=config.get("pipeline_steps", False))
    return trainer, batch


def measure_trainer(trainer, batch, *, train_many: int = 0) -> Dict[str, int]:
    """Compile the train step, count collectives, record the static wire
    model (`exchange.wire_bytes_per_step` from `trainer.last_wire_cost`)
    AND the measured truth: per-collective payload bytes/dtypes read off
    the compiled HLO, plus `wire_model_delta` = measured minus modeled a2a
    bytes (0 == the cost model prices the compiled program exactly).

    `train_many=K` compiles the K-step `jit_train_many` window instead of
    the single step (the round-18 pipelined-scan configs): counts are then
    per compiled MODULE — the scan body's collectives appear once however
    many iterations run — with the prologue/epilogue instances on top, so
    a serial-window baseline config is what makes the numbers comparable."""
    state = trainer.init(batch)
    if train_many:
        import jax as _jax
        import numpy as _np
        stacked = _jax.tree_util.tree_map(
            lambda x: _np.stack([_np.asarray(x)] * int(train_many)), batch)
        fn = trainer.jit_train_many(stacked, state)
        text = fn.lower(state, stacked).compile().as_text()
    else:
        step = trainer.jit_train_step(batch, state)
        text = step.lower(state, batch).compile().as_text()
    counts = count_collectives(text)
    cost = trainer.last_wire_cost or {}
    counts["wire_bytes_per_step"] = int(cost.get("bytes_per_step", 0))
    if "conflict_patch_bytes" in cost:
        # pipelined configs only: the ONLY wire the pipeline may add, plus
        # the modeled bytes it moves off the critical path
        counts["wire_conflict_patch_bytes"] = int(
            cost["conflict_patch_bytes"])
        counts["wire_overlapped_bytes"] = int(
            cost.get("overlapped_bytes", 0))
    pay = collective_payloads(
        text, kinds=("all_to_all", "all_gather", "reduce_scatter"))
    a2a = [(d, b) for k, d, b in pay if k == "all_to_all"]
    ag = [(d, b) for k, d, b in pay if k == "all_gather"]
    rs = [(d, b) for k, d, b in pay if k == "reduce_scatter"]
    counts["hlo_a2a_bytes"] = sum(b for _, b in a2a)
    counts["hlo_all_gather_bytes"] = sum(b for _, b in ag)
    # ZeRO dense sharding's reduce-scatter (result = the 1/S local chunk)
    counts["hlo_reduce_scatter_bytes"] = sum(b for _, b in rs)
    counts["hlo_a2a_dtypes"] = ",".join(sorted({d for d, _ in a2a}))
    model_a2a = (int(cost.get("bytes_per_step", 0))
                 + int(cost.get("hot_a2a_bytes", 0))
                 + int(cost.get("dense_a2a_bytes", 0)))
    counts["wire_model_delta"] = counts["hlo_a2a_bytes"] - model_a2a
    # GSPMD-inserted collectives (no traced-op attribution). The count is a
    # pinned budget key (0 everywhere); the "_"-prefixed detail is carried
    # for the implicit-reshard pass's message and skipped by compare().
    unattr = unattributed_collectives(text)
    counts["unattributed_collectives"] = len(unattr)
    counts["_unattributed_detail"] = "; ".join(
        f"{kind} <- {attr}" for kind, attr in unattr)
    return counts


def measure(configs=CONFIGS) -> Dict[str, Dict[str, int]]:
    out: Dict[str, Dict[str, int]] = {}
    for cfg in configs:
        trainer, batch = make_trainer(cfg)
        out[cfg["name"]] = measure_trainer(
            trainer, batch, train_many=cfg.get("train_many", 0))
    return out


# -- source-digest compile cache ---------------------------------------------
#
# The ten config compiles dominate `make lint` wall time (~minutes cold).
# Nothing outside the package source (plus this pass and the jax build) can
# change what they compile to, so measured counts are cached keyed on a
# digest of exactly those inputs; a warm `make lint` replays the cached
# counts and still runs compare()/forbidden_dtype_findings()/the
# implicit-reshard check against the CURRENT budget json.

_MEASURE_LOCK = threading.Lock()
_MEASURE_MEMO: Dict[str, Dict[str, Dict]] = {}


def source_digest(root: str) -> str:
    h = hashlib.sha256()
    try:
        import jax
        h.update(jax.__version__.encode())
    except Exception:  # noqa: BLE001 — no jax == cache never hits anyway
        pass
    rels = list(iter_py_files(root, ("openembedding_tpu",)))
    rels.append("tools/oelint/passes/hlo_budget.py")
    for rel in sorted(rels):
        h.update(rel.encode())
        try:
            with open(os.path.join(root, rel), "rb") as f:
                h.update(hashlib.sha256(f.read()).digest())
        except OSError:
            h.update(b"<unreadable>")
    h.update(repr(CONFIGS).encode())
    return h.hexdigest()


def measure_cached(root: str, *, force: bool = False) -> Dict[str, Dict]:
    """measure() with the digest cache in front. Thread-safe: the hlo-budget
    and implicit-reshard passes run concurrently and share one compile."""
    with _MEASURE_LOCK:
        digest = source_digest(root)
        if not force:
            if digest in _MEASURE_MEMO:
                return _MEASURE_MEMO[digest]
            path = os.path.join(root, CACHE_REL)
            try:
                with open(path, encoding="utf-8") as f:
                    doc = json.load(f)
                if doc.get("digest") == digest:
                    _MEASURE_MEMO[digest] = doc["measured"]
                    return doc["measured"]
            except (OSError, ValueError, KeyError):
                pass
        measured = measure()
        _MEASURE_MEMO[digest] = measured
        tmp = os.path.join(root, CACHE_REL) + f".tmp.{os.getpid()}"
        try:
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"digest": digest, "measured": measured}, f,
                          indent=2, sort_keys=True)
                f.write("\n")
            os.replace(tmp, os.path.join(root, CACHE_REL))
        except OSError:
            pass
        return measured


def load_budget(root: str) -> Optional[Dict]:
    path = os.path.join(root, BUDGET_REL)
    if not os.path.exists(path):
        return None
    with open(path, encoding="utf-8") as f:
        return json.load(f)


def compare(measured: Dict[str, Dict[str, int]],
            budget: Optional[Dict]) -> List[Finding]:
    """Human-readable diff of measured collective counts vs the checked-in
    budget; empty list == pinned paths unchanged."""
    out: List[Finding] = []
    if budget is None or "configs" not in budget:
        return [Finding(BUDGET_REL, 1, NAME,
                        "no checked-in HLO budget; generate one with "
                        "`python -m tools.oelint --update-budget` and "
                        "commit it")]
    pinned = budget["configs"]
    for name, counts in sorted(measured.items()):
        if name not in pinned:
            out.append(Finding(
                BUDGET_REL, 1, NAME,
                f"config {name!r} is not in the checked-in budget; "
                "run --update-budget and review the diff"))
            continue
        for kind in sorted(set(counts) | set(pinned[name])):
            if kind.startswith("_"):
                continue  # detail payloads ride along unpinned
            got_raw = counts.get(kind, 0)
            want_raw = pinned[name].get(kind, 0)
            if isinstance(got_raw, str) or isinstance(want_raw, str):
                # string-valued pins (hlo_a2a_dtypes): equality, not deltas
                if str(got_raw) == str(want_raw):
                    continue
                out.append(Finding(
                    BUDGET_REL, 1, NAME,
                    f"config {name!r}: {kind} changed "
                    f"{want_raw!r} -> {got_raw!r}. If intentional, "
                    "regenerate the budget (`python -m tools.oelint "
                    "--update-budget`) and commit the json diff; otherwise "
                    "a collective payload silently changed dtype"))
                continue
            got = int(got_raw)
            want = int(want_raw)
            if got == want:
                continue
            delta = got - want
            if kind == "wire_bytes_per_step":
                what = (f"per-device exchange bytes/step "
                        f"{'grew' if delta > 0 else 'shrank'} "
                        f"{want} -> {got} ({delta:+d})")
            elif kind in ("hlo_a2a_bytes", "hlo_all_gather_bytes",
                          "wire_model_delta"):
                what = (f"compiled-HLO {kind} "
                        f"{'grew' if delta > 0 else 'shrank'} "
                        f"{want} -> {got} ({delta:+d})")
            else:
                what = (f"{abs(delta)} {kind.replace('_', '-')} "
                        f"collective(s) {'ADDED to' if delta > 0 else 'removed from'} "
                        f"the compiled step ({want} -> {got})")
            out.append(Finding(
                BUDGET_REL, 1, NAME,
                f"config {name!r}: {what}. If intentional, regenerate the "
                "budget (`python -m tools.oelint --update-budget`) and "
                "commit the json diff; otherwise a collective/recompile "
                "crept onto a pinned path"))
    return out


def forbidden_dtype_findings(measured: Dict[str, Dict],
                             configs=CONFIGS) -> List[Finding]:
    """Budget-independent dtype policy: configs declaring
    `forbid_a2a_dtypes` fail when the compiled all-to-alls carry a forbidden
    payload dtype — a silent fp32 fall-back in a quantized wire mode is a
    lint failure even straight after --update-budget."""
    out: List[Finding] = []
    by_name = {c["name"]: c for c in configs}
    for name, counts in sorted(measured.items()):
        forbid = by_name.get(name, {}).get("forbid_a2a_dtypes", ())
        if not forbid:
            continue
        got = {d for d in
               str(counts.get("hlo_a2a_dtypes", "")).split(",") if d}
        bad = sorted(got & set(forbid))
        if bad:
            out.append(Finding(
                BUDGET_REL, 1, NAME,
                f"config {name!r}: compiled all-to-all payload dtype(s) "
                f"{', '.join(bad)} are forbidden for this wire mode — the "
                "quantized exchange fell back to a wide payload (measured "
                f"a2a dtypes: {counts.get('hlo_a2a_dtypes')!r})"))
    return out


def required_dtype_findings(measured: Dict[str, Dict],
                            configs=CONFIGS) -> List[Finding]:
    """Budget-independent inverse of `forbidden_dtype_findings`: configs
    declaring `require_a2a_dtypes` fail when any required payload dtype is
    MISSING from the compiled all-to-alls — a quantized path that silently
    widened (or a mixed-wire split that collapsed to one format) is a lint
    failure even straight after --update-budget."""
    out: List[Finding] = []
    by_name = {c["name"]: c for c in configs}
    for name, counts in sorted(measured.items()):
        require = by_name.get(name, {}).get("require_a2a_dtypes", ())
        if not require:
            continue
        got = {d for d in
               str(counts.get("hlo_a2a_dtypes", "")).split(",") if d}
        missing = sorted(set(require) - got)
        if missing:
            out.append(Finding(
                BUDGET_REL, 1, NAME,
                f"config {name!r}: compiled all-to-all payload dtype(s) "
                f"{', '.join(missing)} are REQUIRED for this wire mode but "
                "absent — a quantized path silently widened or a mixed-wire "
                "group collapsed to one format (measured a2a dtypes: "
                f"{counts.get('hlo_a2a_dtypes')!r})"))
    return out


def pinned_value_findings(measured: Dict[str, Dict],
                          configs=CONFIGS) -> List[Finding]:
    """Budget-independent exact-value pins: configs declaring `pins`
    ({counter: value}) fail when the measured counter differs — unlike the
    json budget, --update-budget cannot absorb a regression on these (e.g.
    dense_wire configs pin hlo_reduce_scatter_bytes at 0: any fp32
    reduce_scatter reappearing on the quantized dense path fails loud)."""
    out: List[Finding] = []
    by_name = {c["name"]: c for c in configs}
    for name, counts in sorted(measured.items()):
        pins = by_name.get(name, {}).get("pins", {})
        for key, want in sorted(pins.items()):
            got = counts.get(key, 0)
            if got != want:
                out.append(Finding(
                    BUDGET_REL, 1, NAME,
                    f"config {name!r}: {key} = {got} but this config PINS "
                    f"it at {want} (declared in hlo_budget.CONFIGS, not the "
                    "json budget — --update-budget cannot absorb this; the "
                    "compiled path regressed)"))
    return out


def update_budget(root: str) -> str:
    _ensure_cpu()
    import jax
    path = os.path.join(root, BUDGET_REL)
    measured = measure_cached(root, force=True)
    configs = {name: {k: v for k, v in counts.items()
                      if not k.startswith("_")}
               for name, counts in measured.items()}
    doc = {
        "_comment": "Pinned collective counts + static wire bytes per "
                    "compiled train-step config (tools/oelint/passes/"
                    "hlo_budget.py). Regenerate with `python -m "
                    "tools.oelint --update-budget`; the diff is the review "
                    "surface for collective changes.",
        "jax": jax.__version__,
        "mesh_devices": 8,
        "configs": configs,
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def run(files, root: str) -> List[Finding]:
    measured = measure_cached(root)
    return (compare(measured, load_budget(root))
            + forbidden_dtype_findings(measured)
            + required_dtype_findings(measured)
            + pinned_value_findings(measured))
