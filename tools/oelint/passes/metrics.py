"""metrics pass: metric-name hygiene at observe()/vtimer()/span() call sites.

The fifth oelint pass — the former standalone `tools/lint_metrics.py`,
folded into the framework (that script is now a thin alias so
`make lint-metrics` keeps working). Rules are unchanged:

- metric names are dot-joined lowercase `group.name[.qualifier]` segments of
  `[a-z0-9_]+` (utils/metrics.py naming scheme); timer/span call sites pass
  group and name as separate lowercase segments;
- the GROUP (first name segment / the group argument of vtimer/span) is a
  closed registry (KNOWN_GROUPS) — a new group is a conscious act, not a
  typo minting `skwe.hot_id` silently;
- per-instance dimensions (table/shard/model) belong in labels, never
  embedded in a NAME segment (`pull.user_table.ms` reads like a conforming
  name; the INSTANCE_DIM rule rejects it mechanically).

Scans literal string arguments only (f-strings and variables pass through —
they are composed FROM checked literals). Inline suppression:
`# oelint: disable=metrics -- <reason>`.
"""

from __future__ import annotations

import re
from typing import List

from ..core import Finding, SourceFile

NAME = "metrics"
DIRS = ("openembedding_tpu", "examples", "tools")
SKIP = ("tools/oelint", "tools/lint_metrics.py")

NAME_RE = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")
SEGMENT = re.compile(r"^[a-z0-9_]+$")

# the metric-group registry: every observe() name's first segment and every
# vtimer()/span() group must be one of these (utils/metrics.py doc scheme)
KNOWN_GROUPS = {
    "dense",      # ZeRO dense-state sharding (MeshTrainer(dense_shard=True))
    "exchange",   # sharded-exchange wire costs + per-shard load/skew gauges
    "fleet",      # /fleetz cross-node scrape health
    "guard",      # runtime invariant guards (utils/guards.py fingerprints)
    "health",     # numerics sentinel (grad norms, non-finite counts, ef/quant error)
    "hot",        # replicated hot-row cache (MeshTrainer(hot_rows=...))
    "ingest",     # line-rate input path (data/ingest.py feed ring + parse pool)
    "lint",       # oelint's own run health (pass wall times, finding counts)
    "capsule",    # postmortem capsule emission health (utils/capsule.py)
    "history",    # metric history rings (utils/history.py /historz surface)
    "memory",     # device-memory ledger + preflight gate (utils/memwatch.py)
    "metrics",    # the metrics subsystem's own health (report_errors)
    "offload",    # host-cached table cache admission/flush/staging pipeline
    "persist",    # async/incremental persistence
    "placement",  # self-driving placement controller + cold-tail migration
    "serving",    # REST predict/pull/batching
    "skew",       # heavy-hitter sketches (utils/sketch.py)
    "slo",        # SLO engine verdicts/evaluation health (utils/slo.py)
    "sync",       # online model sync
    "train",      # example-loop wall timers
    "trainer",    # train-step phases + per-table pull stats
    "weave",      # oeweave deterministic-interleaving runs (tools/oeweave)
}

# per-instance dimensions embedded in a NAME segment instead of a label:
# a specific instance (`shard3`, `table_12`) or a smuggled instance name
# (`user_table`). Generic uses (`shard_rows`, `bucket_fill`) stay legal.
INSTANCE_DIM = re.compile(
    r"^(?:(?:table|shard|model|instance)_?\d+"
    r"|[a-z0-9_]+_(?:table|shard|model|instance))$")

# the label-KEY registry: every literal key in a labels={...} dict at an
# observe()/vtimer()/span() site must be one of these. Label keys are
# series DIMENSIONS — each new key multiplies registry cardinality (and
# history-ring count) across every value it ever takes, so an unbounded
# dimension (request_id, step, a raw feature value) is a memory leak with a
# metrics API. A new key is a conscious act, like a new group.
KNOWN_LABELS = {
    "component",  # memory ledger component (utils/memwatch.py)
    "hop",        # sync lineage hop (bounded enum: commit/publish/fetch/
                  # apply/swap/serve — sync/lineage.py HOP_ORDER)
    "instance",   # fleet-merge node id (metrics.merge_prometheus)
    "kind",       # operation kind within a group (bounded enum)
    "model",      # serving model sign
    "pass",       # oelint pass name (bounded by the pass registry)
    "pool",       # parse-pool instance label (data/ingest.py)
    "rank",       # hot-row popularity rank bucket (utils/sketch.py)
    "ring",       # feed-ring instance label (data/ingest.py)
    "shard",      # table shard ordinal (bounded by mesh size)
    "slo",        # SLO spec name (bounded by the spec file)
    "slot",       # optimizer slot name (bounded enum)
    "table",      # embedding table / variable name
}

# labels={...} dict literals near a metrics call site; keys checked against
# KNOWN_LABELS. Only LITERAL keys are checkable — a computed key passes
# through here, but composes from a dict some other literal site built.
LABELS_DICT = re.compile(r"""labels\s*=\s*\{(?P<body>[^{}]*)\}""")
LABEL_KEY = re.compile(r"""(["'])(?P<key>[^"']+)\1\s*:""")

# observe("metric.name", ...) — metrics.observe or bare observe
OBSERVE = re.compile(r"""(?<![\w.])(?:metrics\.|M\.)?observe\(\s*
                         (["'])(?P<name>[^"']+)\1""", re.VERBOSE)
# vtimer("group", "name") / trace.span("group", "name") / span("group", ...)
TIMER = re.compile(r"""(?<![\w.])(?:metrics\.|M\.|trace\.|_trace\.)?
                       (?:vtimer|span)\(\s*
                       (["'])(?P<group>[^"']+)\1\s*,\s*
                       (["'])(?P<name>[^"']+)\3""", re.VERBOSE)


def lint_text(sf: SourceFile) -> List[Finding]:
    text = sf.text
    bad: List[Finding] = []

    def flag(pos: int, message: str) -> None:
        line = text.count("\n", 0, pos) + 1
        if not sf.suppressed(line, NAME):
            bad.append(Finding(sf.rel, line, NAME, message))

    for m in OBSERVE.finditer(text):
        name = m.group("name")
        if not NAME_RE.fullmatch(name):
            flag(m.start(), f"observe({name!r}) — metric names are "
                 "dot-joined lowercase group.name segments")
            continue
        segments = name.split(".")
        if segments[0] not in KNOWN_GROUPS:
            flag(m.start(), f"observe({name!r}) — unknown metric group "
                 f"{segments[0]!r}; register it in "
                 "tools/oelint/passes/metrics.py KNOWN_GROUPS")
        for seg in segments:
            if INSTANCE_DIM.fullmatch(seg):
                flag(m.start(), f"observe({name!r}) — segment {seg!r} "
                     "embeds a per-instance dimension (table/shard/model) "
                     "in the NAME; put it in labels={...} instead")
    for m in TIMER.finditer(text):
        for part in (m.group("group"), m.group("name")):
            if not SEGMENT.fullmatch(part):
                flag(m.start(), f"timer/span segment {part!r} — group and "
                     "name are single lowercase [a-z0-9_]+ segments")
            elif INSTANCE_DIM.fullmatch(part):
                flag(m.start(), f"timer/span segment {part!r} — embeds a "
                     "per-instance dimension (table/shard/model); use "
                     "labels={...}")
        group = m.group("group")
        if SEGMENT.fullmatch(group) and group not in KNOWN_GROUPS:
            flag(m.start(), f"span/vtimer group {group!r} — unknown metric "
                 "group; register it in tools/oelint/passes/metrics.py "
                 "KNOWN_GROUPS")
    for m in LABELS_DICT.finditer(text):
        for km in LABEL_KEY.finditer(m.group("body")):
            key = km.group("key")
            if key not in KNOWN_LABELS:
                flag(m.start(), f"label key {key!r} — unknown label "
                     "dimension; every label key multiplies series "
                     "cardinality, so the set is a closed registry "
                     "(tools/oelint/passes/metrics.py KNOWN_LABELS)")
    return bad


def _lint_slo_specs(root: str) -> List[Finding]:
    """Checked-in SLO spec files (tools/**/*slo*.json) must reference metric
    names in the `group.name` scheme with a registered group — a spec with a
    typo'd metric would otherwise sit at UNKNOWN forever, and an unregistered
    group means the metric can never be emitted by linted code."""
    import glob
    import json
    import os
    findings: List[Finding] = []
    pattern = os.path.join(root, "tools", "**", "*slo*.json")
    for path in sorted(glob.glob(pattern, recursive=True)):
        rel = os.path.relpath(path, root)
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            findings.append(Finding(rel, 1, NAME,
                                    f"unparseable SLO spec file: {e}"))
            continue
        if not isinstance(doc, list):
            findings.append(Finding(rel, 1, NAME,
                                    "SLO spec file must be a JSON list of "
                                    "spec objects"))
            continue
        for i, d in enumerate(doc):
            where = f"spec #{i} ({d.get('name', '?')})" \
                if isinstance(d, dict) else f"spec #{i}"
            if not isinstance(d, dict) or "metric" not in d:
                findings.append(Finding(rel, 1, NAME,
                                        f"{where}: not a spec object with a "
                                        "'metric' field"))
                continue
            metric = str(d["metric"])
            if not NAME_RE.fullmatch(metric):
                findings.append(Finding(
                    rel, 1, NAME, f"{where}: metric {metric!r} — metric "
                    "names are dot-joined lowercase group.name segments"))
                continue
            segments = metric.split(".")
            if segments[0] not in KNOWN_GROUPS:
                findings.append(Finding(
                    rel, 1, NAME, f"{where}: metric {metric!r} — unknown "
                    f"group {segments[0]!r}; register it in "
                    "tools/oelint/passes/metrics.py KNOWN_GROUPS"))
            for seg in segments:
                if INSTANCE_DIM.fullmatch(seg):
                    findings.append(Finding(
                        rel, 1, NAME, f"{where}: metric {metric!r} — "
                        f"segment {seg!r} embeds a per-instance dimension; "
                        "SLO specs pin instances with 'labels'"))
    return findings


def run(files: List[SourceFile], root: str) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        findings.extend(lint_text(sf))
    findings.extend(_lint_slo_specs(root))
    return sorted(findings, key=lambda f: (f.path, f.line))
