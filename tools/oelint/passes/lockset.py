"""lockset pass: annotation-driven lock discipline for the threaded classes.

The serving/sync/telemetry tier is plain-`threading` code (SyncSubscriber's
poll loop, MicroBatcher's leader/follower window, SkewMonitor's worker,
PeriodicReporter, ModelManager's RCU cache, the trace FlightRecorder). The
invariant that keeps it correct — "this attribute is only written under that
lock" — lives in heads and docstrings; this pass makes it checkable:

    def __init__(self):
        self._lock = threading.Lock()
        self._cache = {}     # guarded-by: self._lock

Every assignment (`self._cache = ...`, `self._cache |= ...`) to a guarded
attribute anywhere else in the class must then sit lexically inside a
`with self._lock:` block (or a `with` on a Condition CONSTRUCTED from that
lock — `threading.Condition(self._lock)` aliases are resolved). `__init__`/
`__new__` are exempt (the object is not shared yet).

Limitations, by design: the check is lexical and write-only. Mutating calls
(`self._cache.pop(...)`) and reads are not tracked — flag-worthy races there
need a human; the pass catches the regression class that actually bites
(someone adds a fast-path `self.state = X` outside the lock). Cross-function
discipline ("caller holds the lock") is expressed with a reasoned
suppression, which is exactly the documentation such code needs anyway.

Second rule, annotation-free: MUTABLE CLASS-LEVEL state (`x = []` / `= {}` /
`= set()` in a class body) is flagged everywhere — one shared instance
behind every object of the class is the classic silent-aliasing bug, and in
this codebase class attributes double as cross-thread state (ServingHandler
handler classes). Intentional shared state takes a reasoned suppression.

Third rule, lock ORDERING: every `with <lock>` acquired while another
declared lock is held adds an acquire-while-held edge `held -> acquired` —
directly, or through a call whose callee (transitively, bare-name call graph
as in trace-hazard) acquires locks. A cycle in that graph means two threads
can take the same pair of locks in opposite orders and deadlock; each edge
of the cycle is a finding at its witness site. Acquiring a NON-reentrant
`threading.Lock` while already holding it (directly or through a callee) is
flagged immediately — that deadlocks a single thread. Lock identity is the
declaration site (`ClassName.attr` for `self.X = threading.Lock()`,
`module.NAME` for module-level locks); `threading.Condition(self.X)`
aliases resolve to the underlying lock. Establish a fixed acquisition order
to fix a real inversion, or suppress with the invariant that prevents the
two orders from racing.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from ..core import (Finding, SourceFile, condition_aliases, guarded_attrs,
                    self_attr)
from .trace_hazard import _GENERIC_TAILS, _call_chain

NAME = "lockset"
DIRS = ("openembedding_tpu",)
# the ordering rule follows calls across files: a changed callee can create
# an edge from an unchanged caller
NEEDS_ALL_FILES = True

_EXEMPT_METHODS = {"__init__", "__new__"}


# hoisted into core.py (round 19) so lockset/atomicity/cond-wait share one
# definition of the annotations; kept under the old names for local callers
_self_attr = self_attr
_condition_aliases = condition_aliases
_guarded_attrs = guarded_attrs


def _with_lock_exprs(stack: List[ast.AST]) -> List[str]:
    """Unparsed context expressions of every enclosing `with`."""
    out = []
    for node in stack:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                try:
                    out.append(ast.unparse(item.context_expr))
                except Exception:  # noqa: BLE001 — unparse is best-effort
                    pass
    return out


def _check_class(sf: SourceFile, cls: ast.ClassDef) -> List[Finding]:
    out: List[Finding] = []
    guarded = _guarded_attrs(sf, cls)
    aliases = _condition_aliases(cls)

    if guarded:
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                continue
            if method.name in _EXEMPT_METHODS:
                continue
            out.extend(_check_method(sf, cls, method, guarded, aliases))

    # mutable class-level state (annotation-free rule)
    for node in cls.body:
        value = None
        name = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            name, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            name, value = node.target.id, node.value
        if value is None or name is None:
            continue
        kind = _mutable_literal(value)
        if kind and not sf.suppressed(node.lineno, NAME):
            out.append(Finding(
                sf.rel, node.lineno, NAME,
                f"class-level mutable default `{cls.name}.{name} = "
                f"{kind}`: one shared {kind.rstrip('()')} behind every "
                "instance (and every thread); initialize per-instance in "
                "__init__ or use an immutable default"))
    return out


def _mutable_literal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.List):
        return "[...]" if node.elts else "[]"
    if isinstance(node, ast.Dict):
        return "{...}" if node.keys else "{}"
    if isinstance(node, ast.Set):
        return "{...}"
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) and \
            node.func.id in ("list", "dict", "set") and not node.args and \
            not node.keywords:
        return f"{node.func.id}()"
    return None


def _check_method(sf: SourceFile, cls: ast.ClassDef, method: ast.AST,
                  guarded: Dict[str, str],
                  aliases: Dict[str, str]) -> List[Finding]:
    out: List[Finding] = []

    def written_attrs(tgt: ast.AST):
        """Guardable writes in one assignment target: `self.x = ...`,
        `self.x[...] = ...` (container rebinds AND keyed stores), and
        tuple/list unpacking (`a, self.x = ...`)."""
        attr = _self_attr(tgt)
        if attr is not None:
            yield attr, tgt
        elif isinstance(tgt, ast.Subscript):
            attr = _self_attr(tgt.value)
            if attr is not None:
                yield attr, tgt
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                yield from written_attrs(elt)

    def walk(node: ast.AST, stack: List[ast.AST]) -> None:
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for top in targets:
              for attr, tgt in written_attrs(top):
                if attr not in guarded:
                    continue
                lock = guarded[attr]
                held = _with_lock_exprs(stack)
                held_resolved = held + [aliases.get(h) for h in held
                                        if aliases.get(h)]
                if lock not in held_resolved and \
                        not sf.suppressed(tgt.lineno, NAME):
                    out.append(Finding(
                        sf.rel, tgt.lineno, NAME,
                        f"write to `self.{attr}` outside `with {lock}:` "
                        f"(declared guarded-by in {cls.name}; writer: "
                        f"`{method.name}`) — take the lock or suppress "
                        "with the cross-function holder as the reason"))
        for child in ast.iter_child_nodes(node):
            walk(child, stack + [node])

    walk(method, [])
    return out


# -- lock-ordering cycle detection (third rule) ------------------------------


_LOCK_CTORS = {"Lock": "Lock", "RLock": "RLock", "Condition": "Condition"}


class _LockWorld:
    """Lock declarations, aliases and per-function acquire summaries across
    the scanned files. Node identity = declaration site: `ClassName.attr`
    for `self.X = threading.Lock()`, `<module>.NAME` for module globals."""

    def __init__(self, files: List[SourceFile]):
        self.kinds: Dict[str, str] = {}        # node -> Lock/RLock/Condition
        self.aliases: Dict[str, str] = {}      # Condition node -> lock node
        # (file id, class name or "") -> {local expr text -> node}
        self.scopes: Dict[Tuple[int, str], Dict[str, str]] = {}
        self.fns: Dict[str, List[Tuple[SourceFile, ast.AST, str]]] = {}
        for sf in files:
            if sf.tree is None:
                continue
            self._collect_module(sf)
        self.may_acquire = self._summarize()

    @staticmethod
    def _lock_ctor(value: ast.AST) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        chain = _call_chain(value)
        if chain is None:
            return None
        return _LOCK_CTORS.get(chain[-1])

    def _collect_module(self, sf: SourceFile) -> None:
        mod = os.path.splitext(os.path.basename(sf.rel))[0]
        mod_scope = self.scopes.setdefault((id(sf), ""), {})
        for node in sf.tree.body:
            if isinstance(node, ast.Assign):
                kind = self._lock_ctor(node.value)
                if kind:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            ref = f"{mod}.{tgt.id}"
                            self.kinds[ref] = kind
                            mod_scope[tgt.id] = ref
        for cls in ast.walk(sf.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            scope = self.scopes.setdefault((id(sf), cls.name), dict(mod_scope))
            conditions: List[Tuple[str, ast.Call]] = []
            for node in ast.walk(cls):
                if not isinstance(node, ast.Assign):
                    continue
                kind = self._lock_ctor(node.value)
                if not kind:
                    continue
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr is None:
                        continue
                    ref = f"{cls.name}.{attr}"
                    self.kinds[ref] = kind
                    scope[f"self.{attr}"] = ref
                    if kind == "Condition" and node.value.args:
                        conditions.append((ref, node.value))
            for ref, call in conditions:
                try:
                    under = ast.unparse(call.args[0])
                except Exception:  # noqa: BLE001
                    continue
                if under in scope:
                    self.aliases[ref] = scope[under]
        # index functions with their class scope attached
        stack: List[Tuple[ast.AST, str]] = [(sf.tree, "")]
        while stack:
            node, cls_name = stack.pop()
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self.fns.setdefault(child.name, []).append(
                        (sf, child, cls_name))
                    stack.append((child, cls_name))
                elif isinstance(child, ast.ClassDef):
                    stack.append((child, child.name))

    def resolve(self, sf: SourceFile, cls_name: str,
                expr: ast.AST) -> Optional[str]:
        """With-context expression -> lock node (aliases folded), or None
        for expressions that are not declared locks (`other._lock`, files,
        monkeypatch contexts, ...)."""
        try:
            txt = ast.unparse(expr)
        except Exception:  # noqa: BLE001
            return None
        scope = self.scopes.get((id(sf), cls_name)) or \
            self.scopes.get((id(sf), ""), {})
        ref = scope.get(txt)
        if ref is None:
            return None
        return self.aliases.get(ref, ref)

    def _direct_acquires(self, sf: SourceFile, fn: ast.AST,
                         cls_name: str) -> Set[str]:
        out: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    ref = self.resolve(sf, cls_name, item.context_expr)
                    if ref is not None:
                        out.add(ref)
        return out

    def _summarize(self) -> Dict[str, Set[str]]:
        """Bare fn name -> lock nodes it may (transitively) acquire."""
        direct: Dict[str, Set[str]] = {}
        calls: Dict[str, Set[str]] = {}
        for name, defs in self.fns.items():
            d: Set[str] = set()
            c: Set[str] = set()
            for sf, fn, cls_name in defs:
                d |= self._direct_acquires(sf, fn, cls_name)
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call):
                        chain = _call_chain(node)
                        if chain and chain[-1] not in _GENERIC_TAILS:
                            c.add(chain[-1])
            direct[name], calls[name] = d, c
        summary = {n: set(d) for n, d in direct.items()}
        changed = True
        while changed:
            changed = False
            for name, callees in calls.items():
                for callee in callees:
                    extra = summary.get(callee)
                    if extra and not extra <= summary[name]:
                        summary[name] |= extra
                        changed = True
        return summary


def _order_findings(files: List[SourceFile]) -> List[Finding]:
    world = _LockWorld(files)
    # acquire-while-held edges: (held, acquired) -> first witness
    edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
    findings: List[Finding] = []
    flagged: Set[Tuple[str, int, str]] = set()

    def flag(sf: SourceFile, line: int, msg: str) -> None:
        key = (sf.rel, line, msg)
        if key in flagged or sf.suppressed(line, NAME):
            return
        flagged.add(key)
        findings.append(Finding(sf.rel, line, NAME, msg))

    def walk(sf: SourceFile, cls_name: str, node: ast.AST,
             held: List[str]) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired: List[str] = []
            for item in node.items:
                ref = world.resolve(sf, cls_name, item.context_expr)
                if ref is None:
                    continue
                for h in held:
                    if h == ref:
                        if world.kinds.get(ref) != "RLock":
                            flag(sf, node.lineno,
                                 f"re-acquire of non-reentrant `{ref}` "
                                 "while already held: this deadlocks the "
                                 "acquiring thread (use RLock or drop the "
                                 "inner acquire)")
                    else:
                        edges.setdefault((h, ref),
                                         (sf.rel, node.lineno,
                                          f"`with {ref.split('.', 1)[1]}` "
                                          f"while holding `{h}`"))
                acquired.append(ref)
            for child in ast.iter_child_nodes(node):
                walk(sf, cls_name, child, held + acquired)
            return
        if isinstance(node, ast.Call):
            chain = _call_chain(node)
            if chain and chain[-1] not in _GENERIC_TAILS and held:
                for ref in sorted(world.may_acquire.get(chain[-1], ())):
                    for h in held:
                        if h == ref:
                            if world.kinds.get(ref) != "RLock":
                                flag(sf, node.lineno,
                                     f"call `{'.'.join(chain)}` acquires "
                                     f"non-reentrant `{ref}` already held "
                                     "here: single-thread deadlock")
                        else:
                            edges.setdefault(
                                (h, ref),
                                (sf.rel, node.lineno,
                                 f"call `{'.'.join(chain)}` acquires "
                                 f"`{ref}` while holding `{h}`"))
        for child in ast.iter_child_nodes(node):
            walk(sf, cls_name, child, held)

    for name in sorted(world.fns):
        for sf, fn, cls_name in world.fns[name]:
            walk(sf, cls_name, fn, [])

    # cycles: DFS over the held->acquired graph; every edge on a cycle is a
    # finding at its witness site
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)

    def on_cycle(a: str, b: str) -> bool:
        """Is there a path b ->* a (making edge a->b part of a cycle)?"""
        seen: Set[str] = set()
        stack = [b]
        while stack:
            n = stack.pop()
            if n == a:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(graph.get(n, ()))
        return False

    for (a, b), (rel, line, how) in sorted(edges.items()):
        if on_cycle(a, b):
            sf = next(s for s in files if s.rel == rel)
            flag(sf, line,
                 f"lock-order cycle: {how}, but the reverse order is also "
                 f"taken elsewhere (`{b}` -> `{a}` path exists) — two "
                 "threads can deadlock; fix a global acquisition order or "
                 "suppress with the invariant that serializes them")
    return findings


def run(files: List[SourceFile], root: str) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(_check_class(sf, node))
    findings.extend(_order_findings(files))
    return sorted(set(findings), key=lambda f: (f.path, f.line, f.message))
