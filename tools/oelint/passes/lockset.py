"""lockset pass: annotation-driven lock discipline for the threaded classes.

The serving/sync/telemetry tier is plain-`threading` code (SyncSubscriber's
poll loop, MicroBatcher's leader/follower window, SkewMonitor's worker,
PeriodicReporter, ModelManager's RCU cache, the trace FlightRecorder). The
invariant that keeps it correct — "this attribute is only written under that
lock" — lives in heads and docstrings; this pass makes it checkable:

    def __init__(self):
        self._lock = threading.Lock()
        self._cache = {}     # guarded-by: self._lock

Every assignment (`self._cache = ...`, `self._cache |= ...`) to a guarded
attribute anywhere else in the class must then sit lexically inside a
`with self._lock:` block (or a `with` on a Condition CONSTRUCTED from that
lock — `threading.Condition(self._lock)` aliases are resolved). `__init__`/
`__new__` are exempt (the object is not shared yet).

Limitations, by design: the check is lexical and write-only. Mutating calls
(`self._cache.pop(...)`) and reads are not tracked — flag-worthy races there
need a human; the pass catches the regression class that actually bites
(someone adds a fast-path `self.state = X` outside the lock). Cross-function
discipline ("caller holds the lock") is expressed with a reasoned
suppression, which is exactly the documentation such code needs anyway.

Second rule, annotation-free: MUTABLE CLASS-LEVEL state (`x = []` / `= {}` /
`= set()` in a class body) is flagged everywhere — one shared instance
behind every object of the class is the classic silent-aliasing bug, and in
this codebase class attributes double as cross-thread state (ServingHandler
handler classes). Intentional shared state takes a reasoned suppression.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional

from ..core import Finding, GUARDED_BY_RE, SourceFile

NAME = "lockset"
DIRS = ("openembedding_tpu",)

_EXEMPT_METHODS = {"__init__", "__new__"}


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _with_lock_exprs(stack: List[ast.AST]) -> List[str]:
    """Unparsed context expressions of every enclosing `with`."""
    out = []
    for node in stack:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                try:
                    out.append(ast.unparse(item.context_expr))
                except Exception:  # noqa: BLE001 — unparse is best-effort
                    pass
    return out


def _condition_aliases(cls: ast.ClassDef) -> Dict[str, str]:
    """self.Y -> self.X for `self.Y = threading.Condition(self.X)` (holding
    the Condition holds its underlying lock)."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            func = node.value.func
            if isinstance(func, ast.Attribute) and func.attr == "Condition" \
                    and node.value.args:
                try:
                    lock_src = ast.unparse(node.value.args[0])
                except Exception:  # noqa: BLE001
                    continue
                for tgt in node.targets:
                    attr = _self_attr(tgt)
                    if attr is not None:
                        aliases[f"self.{attr}"] = lock_src
    return aliases


def _guarded_attrs(sf: SourceFile, cls: ast.ClassDef) -> Dict[str, str]:
    """attr name -> lock expression, from `# guarded-by:` annotations on
    assignments (typically in __init__) or class-level AnnAssign lines."""
    guarded: Dict[str, str] = {}
    for node in ast.walk(cls):
        if not isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            continue
        m = sf.stmt_annotation(node, GUARDED_BY_RE)
        if not m:
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for tgt in targets:
            attr = _self_attr(tgt)
            if attr is None and isinstance(tgt, ast.Name):
                attr = tgt.id  # class-level declaration
            if attr is not None:
                guarded[attr] = m.group(1)
    return guarded


def _check_class(sf: SourceFile, cls: ast.ClassDef) -> List[Finding]:
    out: List[Finding] = []
    guarded = _guarded_attrs(sf, cls)
    aliases = _condition_aliases(cls)

    if guarded:
        for method in cls.body:
            if not isinstance(method, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                continue
            if method.name in _EXEMPT_METHODS:
                continue
            out.extend(_check_method(sf, cls, method, guarded, aliases))

    # mutable class-level state (annotation-free rule)
    for node in cls.body:
        value = None
        name = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name):
            name, value = node.targets[0].id, node.value
        elif isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name):
            name, value = node.target.id, node.value
        if value is None or name is None:
            continue
        kind = _mutable_literal(value)
        if kind and not sf.suppressed(node.lineno, NAME):
            out.append(Finding(
                sf.rel, node.lineno, NAME,
                f"class-level mutable default `{cls.name}.{name} = "
                f"{kind}`: one shared {kind.rstrip('()')} behind every "
                "instance (and every thread); initialize per-instance in "
                "__init__ or use an immutable default"))
    return out


def _mutable_literal(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.List):
        return "[...]" if node.elts else "[]"
    if isinstance(node, ast.Dict):
        return "{...}" if node.keys else "{}"
    if isinstance(node, ast.Set):
        return "{...}"
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) and \
            node.func.id in ("list", "dict", "set") and not node.args and \
            not node.keywords:
        return f"{node.func.id}()"
    return None


def _check_method(sf: SourceFile, cls: ast.ClassDef, method: ast.AST,
                  guarded: Dict[str, str],
                  aliases: Dict[str, str]) -> List[Finding]:
    out: List[Finding] = []

    def written_attrs(tgt: ast.AST):
        """Guardable writes in one assignment target: `self.x = ...`,
        `self.x[...] = ...` (container rebinds AND keyed stores), and
        tuple/list unpacking (`a, self.x = ...`)."""
        attr = _self_attr(tgt)
        if attr is not None:
            yield attr, tgt
        elif isinstance(tgt, ast.Subscript):
            attr = _self_attr(tgt.value)
            if attr is not None:
                yield attr, tgt
        elif isinstance(tgt, (ast.Tuple, ast.List)):
            for elt in tgt.elts:
                yield from written_attrs(elt)

    def walk(node: ast.AST, stack: List[ast.AST]) -> None:
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for top in targets:
              for attr, tgt in written_attrs(top):
                if attr not in guarded:
                    continue
                lock = guarded[attr]
                held = _with_lock_exprs(stack)
                held_resolved = held + [aliases.get(h) for h in held
                                        if aliases.get(h)]
                if lock not in held_resolved and \
                        not sf.suppressed(tgt.lineno, NAME):
                    out.append(Finding(
                        sf.rel, tgt.lineno, NAME,
                        f"write to `self.{attr}` outside `with {lock}:` "
                        f"(declared guarded-by in {cls.name}; writer: "
                        f"`{method.name}`) — take the lock or suppress "
                        "with the cross-function holder as the reason"))
        for child in ast.iter_child_nodes(node):
            walk(child, stack + [node])

    walk(method, [])
    return out


def run(files: List[SourceFile], root: str) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(_check_class(sf, node))
    return sorted(findings, key=lambda f: (f.path, f.line))
