"""host-sync pass: device→host synchronization discipline on hot paths.

The repo's one-device_get-per-step rule (`utils/metrics.record_step_stats`
doc: "ONE jax.device_get of the whole dict — per-key float() on device
arrays would force one host sync per stat on the hot path") is enforced here
mechanically. Functions opt in with an annotation on/above their `def`:

    # oelint: hot-path                 (sync budget: 1 jax.device_get)
    # oelint: hot-path device_get=0    (pure jit code: ZERO host syncs)

Inside an annotated function (nested defs included) the pass flags:

- `jax.device_get(...)` calls beyond the budget (default 1 — the documented
  one-get-per-step allowance);
- `.block_until_ready()` / `jax.block_until_ready(...)` — always (a hot path
  never spins on device completion; the caller's timing wrapper does);
- `np.asarray(...)` / `np.array(...)` whose argument is a jnp/jax op result
  — an implicit device→host copy that silently serializes the pipeline;
- `float()` / `int()` / `bool()` on a jnp/jax op result — the implicit-sync
  scalar conversion (each one is a hidden blocking transfer).

Host-side numpy math and conversions of already-fetched (post-device_get)
values are NOT flagged: the argument must syntactically contain a jnp/jax
call for the implicit-sync rules to fire, which keeps the pass quiet on
host-only code while catching the real regression class (someone adding
`float(jnp.mean(...))` to a step loop).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from ..core import Finding, HOT_PATH_RE, SourceFile
from .trace_hazard import _call_chain, _is_jaxish

NAME = "host-sync"
DIRS = ("openembedding_tpu",)

DEFAULT_DEVICE_GET_BUDGET = 1


def _contains_jax_call(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) and _is_jaxish(sub):
            return True
    return False


def _hot_path_functions(sf: SourceFile):
    if sf.tree is None:
        return
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        m = sf.def_annotation(node, HOT_PATH_RE)
        if m:
            budget = (int(m.group(1)) if m.group(1) is not None
                      else DEFAULT_DEVICE_GET_BUDGET)
            yield node, budget


def _check_function(sf: SourceFile, fn: ast.AST, budget: int
                    ) -> List[Finding]:
    out: List[Finding] = []
    device_gets: List[ast.Call] = []

    def flag(node: ast.AST, message: str) -> None:
        if not sf.suppressed(node.lineno, NAME):
            out.append(Finding(sf.rel, node.lineno, NAME,
                               f"{message} (in hot-path fn `{fn.name}`)"))

    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        chain = _call_chain(node)
        tail = chain[-1] if chain else None
        if tail is None and isinstance(node.func, ast.Attribute):
            tail = node.func.attr  # method on a non-Name (e.g. a call result)
        if tail == "device_get":
            device_gets.append(node)
        elif tail == "block_until_ready":
            flag(node, "block_until_ready on a hot path: spins the caller "
                       "on device completion; time/synchronize outside the "
                       "hot path")
        elif chain and chain[0] == "np" and tail in ("asarray", "array") \
                and node.args and _contains_jax_call(node.args[0]):
            flag(node, f"np.{tail}() of a device value: implicit blocking "
                       "device→host copy; batch it into the step's single "
                       "jax.device_get")
        elif chain and len(chain) == 1 and tail in ("float", "int", "bool") \
                and node.args and _contains_jax_call(node.args[0]):
            flag(node, f"{tail}() of a device value: implicit-sync scalar "
                       "conversion (one hidden blocking transfer per call); "
                       "batch it into the step's single jax.device_get")
    if len(device_gets) > budget:
        for call in device_gets:
            flag(call, f"{len(device_gets)} jax.device_get calls on a hot "
                       f"path with a budget of {budget}: the "
                       "one-device_get-per-step rule (fetch everything in "
                       "ONE call, like metrics.record_step_stats)")
    return out


def run(files: List[SourceFile], root: str) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        for fn, budget in _hot_path_functions(sf):
            findings.extend(_check_function(sf, fn, budget))
    return sorted(findings, key=lambda f: (f.path, f.line))
