"""cond-wait pass: Condition wait/notify discipline.

`threading.Condition` has two usage rules that Python will not enforce for
you, and whose violations are the two canonical lost-wakeup bugs:

1. **wait() must sit in a predicate loop.** A bare `cond.wait()` (or one
   guarded by `if`) misses both spurious wakeups and the window where the
   state changed and changed back; the fix is always

       with self._cv:
           while not predicate:
               self._cv.wait()

   The pass requires every `.wait(...)` on a declared Condition attribute
   to have a `while` ancestor inside the `with` that holds the condition
   (or its underlying lock — `threading.Condition(self._lock)` aliases
   resolve). `wait_for(pred)` loops internally and is exempt from the loop
   rule (it still needs the lock). `Event.wait` is a different protocol
   (level-triggered, no predicate) and is not a Condition — only attributes
   assigned `threading.Condition(...)` in the class are checked.

2. **notify()/notify_all() must be called with the lock held.** CPython
   raises RuntimeError at runtime for this one, but only on the interleaving
   that actually executes the call — i.e. in the branch your tests never
   hit. The pass makes it a static finding: every notify on a declared
   Condition must be lexically inside a `with` on that condition or its
   underlying lock. (Beyond the crash, an unlocked notify is the classic
   lost wakeup: the waiter checks its predicate, the notifier fires before
   the waiter blocks, the waiter sleeps forever.)

Timed waits used as interruptible ticks (`cond.wait(timeout)` where the
loop exit is the timeout, not the predicate) are still predicate loops in
correct code — `while not self._stop: self._cv.wait(t)` passes; if a bare
timed wait is genuinely deliberate, that is what a reasoned suppression is
for.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from ..core import Finding, SourceFile, condition_aliases, self_attr

NAME = "cond-wait"
DIRS = ("openembedding_tpu",)


def _declared_conditions(cls: ast.ClassDef) -> Set[str]:
    """Attrs assigned `threading.Condition(...)` / `Condition(...)`."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign) or \
                not isinstance(node.value, ast.Call):
            continue
        func = node.value.func
        name = func.attr if isinstance(func, ast.Attribute) else \
            (func.id if isinstance(func, ast.Name) else None)
        if name != "Condition":
            continue
        for tgt in node.targets:
            attr = self_attr(tgt)
            if attr is not None:
                out.add(attr)
    return out


def _with_exprs(node: ast.AST) -> List[str]:
    out = []
    for item in node.items:
        try:
            out.append(ast.unparse(item.context_expr))
        except Exception:  # noqa: BLE001
            pass
    return out


def _check_class(sf: SourceFile, cls: ast.ClassDef) -> List[Finding]:
    conds = _declared_conditions(cls)
    if not conds:
        return []
    aliases = condition_aliases(cls)
    out: List[Finding] = []

    def holds(cond_attr: str, withs: List[ast.AST]) -> bool:
        cond_expr = f"self.{cond_attr}"
        accept = {cond_expr}
        under = aliases.get(cond_expr)
        if under:
            accept.add(under)
        return any(e in accept for w in withs for e in _with_exprs(w))

    def walk(node: ast.AST, stack: List[ast.AST]) -> None:
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute):
            op = node.func.attr
            attr = self_attr(node.func.value)
            if attr in conds and op in ("wait", "wait_for",
                                        "notify", "notify_all"):
                withs = [n for n in stack
                         if isinstance(n, (ast.With, ast.AsyncWith))]
                if not holds(attr, withs):
                    if not sf.suppressed(node.lineno, NAME):
                        out.append(Finding(
                            sf.rel, node.lineno, NAME,
                            f"`self.{attr}.{op}()` outside `with "
                            f"self.{attr}:` — "
                            + ("an unlocked notify is a lost wakeup (the "
                               "signal can fire between a waiter's check "
                               "and its block)"
                               if op.startswith("notify") else
                               "wait without the lock raises at runtime "
                               "and tears the predicate")
                            + f" ({cls.name})"))
                elif op == "wait":
                    # predicate-loop rule: a while between the with and the
                    # wait (the innermost holding with, conservatively: any)
                    inner_with = max(
                        (i for i, n in enumerate(stack)
                         if isinstance(n, (ast.With, ast.AsyncWith))
                         and holds(attr, [n])), default=-1)
                    looped = any(isinstance(n, ast.While)
                                 for n in stack[inner_with + 1:])
                    if not looped and not sf.suppressed(node.lineno, NAME):
                        out.append(Finding(
                            sf.rel, node.lineno, NAME,
                            f"`self.{attr}.wait()` is not inside a `while "
                            f"<predicate>` loop under the lock — spurious "
                            f"wakeups and check/act windows break straight-"
                            f"line waits; use `while not pred: "
                            f"self.{attr}.wait()` or `wait_for` "
                            f"({cls.name})"))
        for child in ast.iter_child_nodes(node):
            walk(child, stack + [node])

    for method in cls.body:
        if isinstance(method, (ast.FunctionDef, ast.AsyncFunctionDef)):
            walk(method, [])
    return out


def run(files: List[SourceFile], root: str) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        if sf.tree is None:
            continue
        for cls in ast.walk(sf.tree):
            if isinstance(cls, ast.ClassDef):
                findings.extend(_check_class(sf, cls))
    return sorted(set(findings), key=lambda f: (f.path, f.line, f.message))
