"""spmd-divergence pass: per-process host control flow upstream of collectives.

The SPMD contract (GSPMD, arXiv:2105.04663): every process executes the SAME
collective sequence in the SAME order, or the mesh hangs — there is no
timeout, no error, just 256 chips waiting on a rendezvous one process never
reaches. The compiler enforces nothing on the HOST side of that contract:
Python is free to branch on `jax.process_index()`, wall clock, per-shard
device values, or to iterate an unordered set while issuing collectives, and
all four compile fine and hang in production.

What the pass flags, per function (whole tree, not just jit roots — the
hazard lives in HOST orchestration code like persisters and sync loops):

- a collective call lexically inside an `if`/`while` whose test is
  PER-PROCESS DIVERGENT: derived from `process_index`/`host_id`, wall clock
  (`time.time/monotonic/perf_counter`), entropy (`os.urandom`, `uuid*`,
  `random.*`), or per-shard device views (`.addressable_shards`,
  `addressable_data`). `process_count` is uniform across processes and is
  NOT divergent.
- a collective call AFTER a divergent branch that can return/raise/break —
  the early-exit form of the same hang (process 0 reaches the collective,
  process 1 already returned).
- a collective call inside `for ... in <set>`: unordered iteration feeding a
  collective sequence means two processes can issue the same collectives in
  different orders (deadlock, or silently exchanged payloads).

Divergence propagates through local assignments in source order and through
function RETURN VALUES: a function whose return is divergent-tainted (or
sits under a divergent branch) marks its callers' tests divergent — that is
how `policy.should_persist(step)` (wall-clock inside) taints the persist
branch that guards `allgather_host_ids`. Collective reachability likewise
propagates through simple-name calls (same call-graph discipline as
trace-hazard).

Deliberate, defended cases carry reasoned suppressions
(`# oelint: disable=spmd-divergence -- <why this cannot diverge>`); the
canonical example is a wall-clock policy whose constructor already rejects
multi-process use.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..core import Finding, SourceFile
from .trace_hazard import (_GENERIC_TAILS, _call_chain, _index_functions,
                           _is_set_expr)

NAME = "spmd-divergence"
DIRS = ("openembedding_tpu",)
# call-graph + return-taint summaries span files: a changed caller can pick
# up divergence from an unchanged callee and vice versa
NEEDS_ALL_FILES = True

# call tails that ARE collectives (jax.lax + multihost wrappers): issuing one
# is a cross-process rendezvous
COLLECTIVE_TAILS = {
    "psum", "pmean", "pmax", "pmin", "ppermute", "pbroadcast",
    "all_to_all", "all_gather", "all_gather_invariant", "reduce_scatter",
    "psum_scatter",
    "process_allgather", "broadcast_one_to_all", "sync_global_devices",
    "allgather_host_ids", "global_batch",
    "make_array_from_process_local_data",
}

# call tails whose VALUE differs per process
_DIVERGENT_TAILS = {"process_index", "getpid", "gethostname", "urandom",
                    "uuid1", "uuid4"}
# time.<tail>() reads the wall clock (value-returning; time.sleep has no
# value and is uniform-enough to ignore here)
_WALL_CLOCK_TAILS = {"time", "monotonic", "perf_counter", "time_ns",
                     "monotonic_ns", "perf_counter_ns"}
# attribute reads that expose a per-process device view
_DIVERGENT_ATTRS = {"addressable_shards", "addressable_data",
                    "addressable_devices", "local_devices"}


def _is_divergent_call(call: ast.Call, div_fns: Set[str]) -> bool:
    chain = _call_chain(call)
    if chain is None:
        return False
    tail = chain[-1]
    if tail in _DIVERGENT_TAILS:
        return True
    if chain[0] == "time" and tail in _WALL_CLOCK_TAILS:
        return True
    if chain[0] == "random" and len(chain) == 2:
        return True
    if tail in _DIVERGENT_ATTRS:
        return True
    return tail not in _GENERIC_TAILS and tail in div_fns


def _is_collective_call(call: ast.Call, coll_fns: Set[str]) -> bool:
    chain = _call_chain(call)
    if chain is None:
        return False
    tail = chain[-1]
    if tail in COLLECTIVE_TAILS:
        return True
    return tail not in _GENERIC_TAILS and tail in coll_fns


def _summarize(index: Dict[str, List], name_filter=None):
    """Fixpoint over bare function names -> (collective-reaching set,
    divergent-returning set).

    collective-reaching: calls a collective tail directly or calls a
    collective-reaching name. divergent-returning: some return expression is
    divergence-tainted, or a return sits under a divergent test — computed
    with the same local walk the checker uses, iterated to fixpoint so
    wrappers (`host_id() -> jax.process_index()`) propagate.
    """
    coll_fns: Set[str] = set()
    div_fns: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for fname, infos in index.items():
            for fi in infos:
                if fname not in coll_fns and \
                        _reaches_collective(fi.node, coll_fns):
                    coll_fns.add(fname)
                    changed = True
                if fname not in div_fns and \
                        _returns_divergent(fi.node, div_fns):
                    div_fns.add(fname)
                    changed = True
    return coll_fns, div_fns


def _reaches_collective(fn: ast.AST, coll_fns: Set[str]) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and _is_collective_call(node, coll_fns):
            return True
    return False


class _Walk:
    """One function's source-order divergence-taint walk. Shared by the
    summary computation (does any return diverge?) and the finding checker
    (is a collective guarded by / sequenced after a divergent decision?)."""

    def __init__(self, fn: ast.AST, div_fns: Set[str]):
        self.fn = fn
        self.div_fns = div_fns
        self.tainted: Set[str] = set()

    def expr_divergent(self, node: Optional[ast.AST]) -> bool:
        if node is None:
            return False
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and sub.id in self.tainted:
                return True
            if isinstance(sub, ast.Call) and \
                    _is_divergent_call(sub, self.div_fns):
                return True
            if isinstance(sub, ast.Attribute) and \
                    sub.attr in _DIVERGENT_ATTRS:
                return True
        return False

    def assign(self, target: ast.AST, divergent: bool) -> None:
        if isinstance(target, ast.Name):
            (self.tainted.add if divergent
             else self.tainted.discard)(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self.assign(elt, divergent)

    def process_assign(self, stmt: ast.stmt) -> None:
        value = getattr(stmt, "value", None)
        if value is None:
            return
        targets = (stmt.targets if isinstance(stmt, ast.Assign)
                   else [stmt.target])
        # elementwise for tuple-to-tuple: `pidx, pcount = process_index(),
        # process_count()` must taint only pidx
        for tgt in targets:
            if isinstance(tgt, (ast.Tuple, ast.List)) and \
                    isinstance(value, ast.Tuple) and \
                    len(tgt.elts) == len(value.elts):
                for t, v in zip(tgt.elts, value.elts):
                    self.assign(t, self.expr_divergent(v))
            else:
                self.assign(tgt, self.expr_divergent(value))


def _returns_divergent(fn: ast.AST, div_fns: Set[str]) -> bool:
    walk = _Walk(fn, div_fns)
    divergent_depth = 0

    def scan(body) -> bool:
        nonlocal divergent_depth
        for stmt in body:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                walk.process_assign(stmt)
            elif isinstance(stmt, ast.Return):
                if divergent_depth or walk.expr_divergent(stmt.value):
                    return True
            elif isinstance(stmt, (ast.If, ast.While)):
                div = walk.expr_divergent(stmt.test)
                divergent_depth += bool(div)
                hit = scan(stmt.body) or scan(stmt.orelse)
                divergent_depth -= bool(div)
                if hit:
                    return True
            elif isinstance(stmt, ast.For):
                if scan(stmt.body) or scan(stmt.orelse):
                    return True
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                if scan(stmt.body):
                    return True
            elif isinstance(stmt, ast.Try):
                if scan(stmt.body) or scan(stmt.orelse) or \
                        scan(stmt.finalbody) or \
                        any(scan(h.body) for h in stmt.handlers):
                    return True
        return False

    return scan(fn.body)


def _can_exit(body: List[ast.stmt]) -> bool:
    """Does this branch body contain an early exit (return/raise/break)?"""
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, (ast.Return, ast.Raise, ast.Break)):
                return True
    return False


class _Checker:
    def __init__(self, sf: SourceFile, fn: ast.AST, qualname: str,
                 coll_fns: Set[str], div_fns: Set[str]):
        self.sf = sf
        self.qualname = qualname
        self.fn = fn
        self.coll = coll_fns
        self.walk = _Walk(fn, div_fns)
        self.findings: List[Finding] = []
        self._flagged: Set[int] = set()

    def _flag(self, node: ast.AST, message: str) -> None:
        if node.lineno in self._flagged or \
                self.sf.suppressed(node.lineno, NAME):
            return
        self._flagged.add(node.lineno)
        self.findings.append(Finding(
            self.sf.rel, node.lineno, NAME,
            f"{message} (in `{self.qualname}`) — if any process skips or "
            "reorders a collective the mesh hangs; make the decision "
            "uniform (broadcast_one_to_all / step-driven) or hoist the "
            "collective out"))

    def _collectives_in(self, body: List[ast.stmt]) -> List[ast.Call]:
        out = []
        for stmt in body:
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call) and \
                        _is_collective_call(node, self.coll):
                    out.append(node)
        return out

    def run(self) -> List[Finding]:
        self._scan(self.fn.body, exited_divergent=False)
        return self.findings

    def _scan(self, body: List[ast.stmt], exited_divergent: bool) -> bool:
        """Walks one body; returns True if a divergent early-exit was seen
        (callers use it to flag LATER collectives at their level too)."""
        for stmt in body:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                self.walk.process_assign(stmt)
                if exited_divergent:
                    for c in self._collectives_in([stmt]):
                        self._flag(c, self._after_msg(c))
            elif isinstance(stmt, (ast.If, ast.While)):
                div = self.walk.expr_divergent(stmt.test)
                if div:
                    for c in self._collectives_in(stmt.body) + \
                            self._collectives_in(stmt.orelse):
                        self._flag(c, self._under_msg(c, stmt))
                    if isinstance(stmt, ast.If) and (
                            _can_exit(stmt.body) or _can_exit(stmt.orelse)):
                        exited_divergent = True
                else:
                    if self._scan(stmt.body, exited_divergent):
                        exited_divergent = True
                    if self._scan(stmt.orelse, exited_divergent):
                        exited_divergent = True
            elif isinstance(stmt, ast.For):
                if _is_set_expr(stmt.iter):
                    for c in self._collectives_in(stmt.body):
                        self._flag(
                            c, "collective issued while iterating an "
                            "unordered set: two processes can emit the "
                            "same collectives in different orders")
                if self._scan(stmt.body, exited_divergent) or \
                        self._scan(stmt.orelse, exited_divergent):
                    exited_divergent = True
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                if self._scan(stmt.body, exited_divergent):
                    exited_divergent = True
            elif isinstance(stmt, ast.Try):
                for sub in ([stmt.body, stmt.orelse, stmt.finalbody] +
                            [h.body for h in stmt.handlers]):
                    if self._scan(sub, exited_divergent):
                        exited_divergent = True
            else:
                if exited_divergent:
                    for c in self._collectives_in([stmt]):
                        self._flag(c, self._after_msg(c))
        return exited_divergent

    @staticmethod
    def _name(call: ast.Call) -> str:
        chain = _call_chain(call)
        return ".".join(chain) if chain else "<collective>"

    def _under_msg(self, call: ast.Call, branch: ast.stmt) -> str:
        return (f"collective `{self._name(call)}` under a per-process-"
                f"divergent `{type(branch).__name__.lower()}` (test at "
                f"line {branch.lineno} derives from process_index/wall "
                "clock/per-shard state)")

    def _after_msg(self, call: ast.Call) -> str:
        return (f"collective `{self._name(call)}` sequenced after a "
                "divergent branch that can return/raise early: processes "
                "taking the exit never reach this rendezvous")


def run(files: List[SourceFile], root: str) -> List[Finding]:
    index = _index_functions(files)
    coll_fns, div_fns = _summarize(index)
    findings: List[Finding] = []
    for fname in sorted(index):
        for fi in index[fname]:
            findings.extend(
                _Checker(fi.sf, fi.node, fi.qualname, coll_fns,
                         div_fns).run())
    return sorted(set(findings), key=lambda f: (f.path, f.line, f.message))
