"""implicit-reshard pass: no compiled collective without a traced-op alibi.

GSPMD (arXiv:2105.04663) is free to INSERT collectives the program never
asked for: when a value flows between two ops whose shardings disagree, the
partitioner materializes a reshard — typically an all-gather — and the step
silently pays full-table wire cost forever. The hlo-budget pass would catch
the count change, but only against a budget someone could just regenerate;
this pass is budget-INDEPENDENT (same design as `forbid_a2a_dtypes`): every
collective in the compiled HLO of every pinned config must attribute back to
an explicit collective primitive via its `op_name` metadata tail (`psum`,
`all_to_all`, `reduce_scatter`, ...). A collective with no such traced-op
attribution is GSPMD-inserted by construction, and is a lint failure with
the op kind + whatever attribution the line does carry — fix the
in/out_shardings disagreement, don't regenerate the budget.

Shares the hlo-budget measurement (one compile, one source-digest cache —
see `hlo_budget.measure_cached`); `--changed-only` reruns it under the same
trigger paths.
"""

from __future__ import annotations

from typing import List

from ..core import Finding
from . import hlo_budget

NAME = "implicit-reshard"
DIRS = ()  # consumes the hlo-budget measurement; scans no source files
TRIGGERS = hlo_budget.TRIGGERS


def findings_for(measured) -> List[Finding]:
    out: List[Finding] = []
    for name, counts in sorted(measured.items()):
        n = int(counts.get("unattributed_collectives", 0))
        if not n:
            continue
        detail = counts.get("_unattributed_detail", "") or "<no detail>"
        out.append(Finding(
            hlo_budget.BUDGET_REL, 1, NAME,
            f"config {name!r}: {n} compiled collective(s) have no traced-op "
            f"attribution ({detail}) — GSPMD inserted a reshard (mismatched "
            "in/out shardings on the pinned path); fix the sharding "
            "disagreement instead of regenerating the budget"))
    return out


def run(files, root: str) -> List[Finding]:
    return findings_for(hlo_budget.measure_cached(root))
