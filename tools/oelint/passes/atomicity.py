"""atomicity pass: check-then-act on `# guarded-by:` state split across a
lock release.

lockset proves every WRITE to a guarded attribute happens under its lock.
That is necessary but not sufficient: the classic control-plane race is a
*decision* made from guarded state while the lock is NOT held, followed by a
locked write that assumes the decision still holds. Both halves pass lockset
individually; the interleaving between them is the bug. Two lexical shapes
cover every instance this repo has actually shipped:

**Shape A — tainted-local check-then-act.** Guarded state is read under the
lock into a local, the lock is released, a branch is taken on that local,
and the branch re-acquires the same lock to write guarded state:

    with self._lock:
        n = len(self._groups[key])      # read under lock -> taints `n`
    if n == 1:                          # decision on stale snapshot
        ...
        with self._lock:
            self._groups.pop(key)       # act — state may have changed

Taint propagates through locals (`leader = n == 1` taints `leader`); acting
writes include mutator calls (`.pop/.append/.clear/...`) and keyed stores,
not just rebinds. The window between the two `with` blocks is where another
thread invalidates the decision.

**Shape B — unlocked guard of a locked write.** The test itself reads a
guarded attribute with no lock held, and the guarded branch takes the lock
to write guarded state:

    if self.version is None:            # unlocked read of guarded attr
        ...
        with self._mu:
            self.version = head         # two threads both saw None

Double-checked locking is the textbook instance; the fix is to move the
check inside the lock (or re-check under it).

Both shapes are lexical and method-local by design (same honesty contract
as lockset): cross-method protocols that make a split safe ("only one
thread ever calls this") are documented with a reasoned suppression.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from ..core import (Finding, SourceFile, condition_aliases, guarded_attrs,
                    self_attr)

NAME = "atomicity"
DIRS = ("openembedding_tpu",)

_EXEMPT_METHODS = {"__init__", "__new__"}

# attribute calls that mutate the receiver in place — `self.x.pop()` is a
# write to guarded `x` just as much as `self.x = ...`
_MUTATORS = {
    "append", "add", "clear", "discard", "extend", "insert", "pop",
    "popitem", "popleft", "remove", "setdefault", "update",
    "appendleft", "sort", "reverse",
}


def _lock_names(guarded: Dict[str, str],
                aliases: Dict[str, str]) -> Dict[str, Set[str]]:
    """lock expr -> every expression whose `with` holds it (itself plus any
    Condition constructed from it)."""
    out: Dict[str, Set[str]] = {}
    for lock in set(guarded.values()):
        holds = {lock}
        for cond, under in aliases.items():
            if under == lock:
                holds.add(cond)
        out[lock] = holds
    return out


def _held_locks(stack: List[ast.AST],
                holders: Dict[str, Set[str]]) -> Set[str]:
    """Declared locks held at this point in the lexical stack."""
    held: Set[str] = set()
    for node in stack:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                try:
                    txt = ast.unparse(item.context_expr)
                except Exception:  # noqa: BLE001
                    continue
                for lock, holds in holders.items():
                    if txt in holds:
                        held.add(lock)
    return held


def _reads_of(node: ast.AST, guarded: Dict[str, str]) -> Set[str]:
    """Guarded attrs read anywhere inside `node` (as `self.attr`)."""
    out: Set[str] = set()
    for sub in ast.walk(node):
        attr = self_attr(sub)
        if attr is not None and attr in guarded:
            out.add(attr)
    return out


def _guarded_writes(node: ast.AST, guarded: Dict[str, str]):
    """(attr, lineno) for every write/mutation of a guarded attr in `node`:
    rebinds, aug-assigns, keyed stores/deletes, and mutator calls."""
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = (sub.targets if isinstance(sub, ast.Assign)
                       else [sub.target])
            for tgt in targets:
                yield from _target_writes(tgt, guarded)
        elif isinstance(sub, ast.Delete):
            for tgt in sub.targets:
                yield from _target_writes(tgt, guarded)
        elif isinstance(sub, ast.Call) and isinstance(sub.func,
                                                      ast.Attribute):
            if sub.func.attr in _MUTATORS:
                attr = self_attr(sub.func.value)
                if attr is not None and attr in guarded:
                    yield attr, sub.lineno


def _target_writes(tgt: ast.AST, guarded: Dict[str, str]):
    attr = self_attr(tgt)
    if attr is None and isinstance(tgt, ast.Subscript):
        attr = self_attr(tgt.value)
    if attr is not None and attr in guarded:
        yield attr, tgt.lineno
    elif isinstance(tgt, (ast.Tuple, ast.List)):
        for elt in tgt.elts:
            yield from _target_writes(elt, guarded)


def _local_names(expr: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(expr) if isinstance(n, ast.Name)}


def _check_method(sf: SourceFile, cls: ast.ClassDef, method: ast.AST,
                  guarded: Dict[str, str],
                  aliases: Dict[str, str]) -> List[Finding]:
    out: List[Finding] = []
    holders = _lock_names(guarded, aliases)

    # -- shape B: unlocked guarded read in a test, locked write inside ------
    def walk_b(node: ast.AST, stack: List[ast.AST]) -> None:
        if isinstance(node, (ast.If, ast.While)):
            held = _held_locks(stack, holders)
            checked = {a for a in _reads_of(node.test, guarded)
                       if guarded[a] not in held}
            if checked:
                for sub in ast.walk(node):
                    if not isinstance(sub, (ast.With, ast.AsyncWith)):
                        continue
                    inner = _held_locks(stack + [node, sub], holders)
                    for attr, line in _guarded_writes(sub, guarded):
                        lock = guarded[attr]
                        if lock not in inner:
                            continue  # lockset's department
                        stale = sorted(a for a in checked
                                       if guarded[a] == lock)
                        if not stale:
                            continue
                        if sf.suppressed(node.lineno, NAME):
                            continue
                        out.append(Finding(
                            sf.rel, node.lineno, NAME,
                            f"check-then-act: test reads guarded "
                            f"`self.{stale[0]}` without `{lock}`, then the "
                            f"branch takes the lock to write `self.{attr}` "
                            f"(line {line}) — two threads can both pass the "
                            f"check; move the check inside `with {lock}:` "
                            f"({cls.name}.{method.name})"))
                        break
        for child in ast.iter_child_nodes(node):
            walk_b(child, stack + [node])

    walk_b(method, [])

    # -- shape A: locked read -> tainted local -> branch -> locked write ----
    def scan_suite(stmts: List[ast.stmt], outer_stack: List[ast.AST]) -> None:
        # taint per suite: local name -> (lock, read attr, read line)
        taint: Dict[str, tuple] = {}
        for stmt in stmts:
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                locks = _held_locks(outer_stack + [stmt], holders)
                for sub in ast.walk(stmt):
                    if not isinstance(sub, ast.Assign):
                        continue
                    reads = _reads_of(sub.value, guarded)
                    via = _local_names(sub.value) & set(taint)
                    src = None
                    for a in sorted(reads):
                        if guarded[a] in locks:
                            src = (guarded[a], a, sub.lineno)
                            break
                    if src is None and via:
                        src = taint[sorted(via)[0]]
                    if src is None:
                        continue
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Name):
                            taint[tgt.id] = src
            elif isinstance(stmt, (ast.If, ast.While)):
                used = _local_names(stmt.test) & set(taint)
                if used and not _held_locks(outer_stack, holders):
                    name = sorted(used)[0]
                    lock, attr, read_line = taint[name]
                    for sub in ast.walk(stmt):
                        if not isinstance(sub, (ast.With, ast.AsyncWith)):
                            continue
                        inner = _held_locks(
                            outer_stack + [stmt, sub], holders)
                        if lock not in inner:
                            continue
                        hits = [(a, ln) for a, ln in
                                _guarded_writes(sub, guarded)
                                if guarded[a] == lock]
                        if not hits:
                            continue
                        if sf.suppressed(stmt.lineno, NAME):
                            break
                        wa, wl = hits[0]
                        out.append(Finding(
                            sf.rel, stmt.lineno, NAME,
                            f"check-then-act split across `{lock}`: "
                            f"`{name}` snapshots guarded `self.{attr}` "
                            f"under the lock (line {read_line}), the lock "
                            f"is released, and the branch re-acquires it "
                            f"to write `self.{wa}` (line {wl}) — the "
                            f"snapshot can be stale; hold the lock across "
                            f"check and act ({cls.name}.{method.name})"))
                        break
            # descend into nested suites (loop/branch bodies, try blocks)
            for body in (getattr(stmt, "body", None),
                         getattr(stmt, "orelse", None),
                         getattr(stmt, "finalbody", None)):
                if isinstance(body, list) and body and \
                        isinstance(body[0], ast.stmt):
                    scan_suite(body, outer_stack + [stmt])
            for handler in getattr(stmt, "handlers", []) or []:
                scan_suite(handler.body, outer_stack + [stmt])

    scan_suite(list(method.body), [])
    return out


def run(files: List[SourceFile], root: str) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        if sf.tree is None:
            continue
        for cls in ast.walk(sf.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            guarded = guarded_attrs(sf, cls)
            if not guarded:
                continue
            aliases = condition_aliases(cls)
            for method in cls.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                if method.name in _EXEMPT_METHODS:
                    continue
                findings.extend(
                    _check_method(sf, cls, method, guarded, aliases))
    return sorted(set(findings), key=lambda f: (f.path, f.line, f.message))
