"""trace-hazard pass: recompile / concretization hazards in jit-reachable code.

The repo's hot paths live or die by the never-re-jit discipline: the sharded
exchange, the hot-row cache lifecycle and the trainer step all compile ONCE
and must keep running across refreshes, capacity changes and traffic drift
(`parallel/sharded.py` module doc, tests/test_hot.py). The hazards that break
it are all *Python-level* patterns invisible to the type checker:

- Python `if`/`while`/`assert` on a TRACED value — under jit this either
  raises ConcretizationTypeError or (via `int()`-style escapes) silently
  retraces per value;
- `int()` / `float()` / `bool()` on a tracer — the concretization escape
  hatch itself;
- data-dependent shapes: `jnp.nonzero`/`jnp.unique`/... without `size=`, or
  using their result's `.shape` as a Python value;
- unhashable (list/dict/set) or float literals fed to `static_argnums` /
  `static_argnames` positions — per-value recompiles or immediate TypeErrors;
- iterating a `set` while tracing — nondeterministic iteration order, so two
  runs of the same code can emit different programs (cache-buster).

Scope: functions REACHABLE from the jitted entry points. Roots are the
protocol functions below plus anything annotated `# oelint: jit-entry`;
reachability follows simple-name calls across the scanned files (method and
free-function calls alike). Library calls (jnp/jax/np) and GENERIC method
tails (`.get`, `.load`, `.items`, ...) are not followed — the latter collide
with half the stdlib and would drag host-only code into jit scope.

Taint: a value is considered traced when it (transitively) comes from a
jnp/jax array op, propagated in SOURCE ORDER through local assignments.
Attribute reads of `.shape`/`.ndim`/`.dtype`/`.size` are STATIC under jit
and never tainted — that is what keeps `if x.shape[0]:` legal and this pass
quiet on the real tree; `x is None` identity tests and known static
predicates (`is_pair`) are static too. Function parameters are NOT assumed
traced (the pass cannot know call sites), so a hazard on a raw parameter
needs a human; hazards on op RESULTS — the overwhelming majority — are
caught mechanically.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from ..core import Finding, JIT_ENTRY_RE, SourceFile

NAME = "trace-hazard"
DIRS = ("openembedding_tpu",)

# the jitted protocol entry points (parallel/sharded.py, model.py Trainer)
DEFAULT_ROOTS = {
    "sharded_lookup_train", "grouped_lookup_train", "sharded_lookup",
    "sharded_apply_gradients", "grouped_apply_gradients",
    "hot_writeback", "hot_gather", "mig_writeback", "mig_gather",
    "train_step", "train_many", "eval_step",
}

# library roots whose calls SEED taint (array-producing ops)
_JAX_ROOTS = {"jnp", "jax", "lax"}
# ...except these tails, which return static Python values under jit
_STATIC_TAILS = {
    "axis_size", "ndim", "shape", "size", "dtype", "itemsize",
    "issubdtype", "result_type", "can_cast", "promote_types",
}
# repo predicates that only inspect dtype/shape — static under jit
_STATIC_PREDICATES = {"is_pair"}
# calls whose OUTPUT SHAPE is data-dependent: illegal under jit without
# `size=`, and their `.shape` is a trace hazard even outside jit
_DATA_DEP_TAILS = {"nonzero", "flatnonzero", "argwhere", "unique"}
# method tails too generic to follow in the call graph (dict.get, json.load,
# file.read, ... would alias half the repo into "jit-reachable")
_GENERIC_TAILS = {
    "get", "set", "load", "loads", "dump", "dumps", "save", "open", "close",
    "read", "write", "replace", "copy", "items", "keys", "values", "update",
    "pop", "append", "extend", "add", "remove", "discard", "join", "split",
    "strip", "format", "encode", "decode", "setdefault", "sort", "index",
    "count", "clear", "put", "wait", "start", "stop", "run", "next", "send",
}


def _attr_chain(node: ast.AST) -> Optional[List[str]]:
    """Attribute/Name chain as ["jax", "lax", "psum"]; None if not a chain."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return list(reversed(parts))
    return None


def _call_chain(call: ast.Call) -> Optional[List[str]]:
    return _attr_chain(call.func)


def _is_jaxish(call: ast.Call) -> bool:
    chain = _call_chain(call)
    if not chain or chain[0] not in _JAX_ROOTS:
        return False
    return chain[-1] not in _STATIC_TAILS


def _is_data_dep(call: ast.Call) -> bool:
    chain = _call_chain(call)
    if not chain or chain[0] not in _JAX_ROOTS:
        return False
    if chain[-1] not in _DATA_DEP_TAILS:
        return False
    return not any(kw.arg == "size" for kw in call.keywords)


def _is_set_expr(node: ast.AST) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        chain = _call_chain(node)
        return chain is not None and len(chain) == 1 and \
            chain[0] in ("set", "frozenset")
    return False


class _FnInfo:
    def __init__(self, sf: SourceFile, node: ast.AST, qualname: str):
        self.sf = sf
        self.node = node
        self.qualname = qualname


def _index_functions(files: List[SourceFile]) -> Dict[str, List[_FnInfo]]:
    """name -> defs across all files (methods indexed by bare method name)."""
    index: Dict[str, List[_FnInfo]] = {}
    for sf in files:
        if sf.tree is None:
            continue
        stack: List[Tuple[ast.AST, str]] = [(sf.tree, "")]
        while stack:
            node, prefix = stack.pop()
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qual = f"{prefix}{child.name}"
                    index.setdefault(child.name, []).append(
                        _FnInfo(sf, child, qual))
                    stack.append((child, qual + "."))
                elif isinstance(child, ast.ClassDef):
                    stack.append((child, f"{prefix}{child.name}."))
    return index


def _called_names(fn: ast.AST) -> Set[str]:
    """Simple names this function calls, minus library and generic tails."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        chain = _call_chain(node)
        if chain is None:
            continue
        if chain[0] in _JAX_ROOTS or chain[0] == "np":
            continue
        if chain[-1] in _GENERIC_TAILS:
            continue
        out.add(chain[-1])
    return out


def _reachable(index: Dict[str, List[_FnInfo]],
               roots: Set[str]) -> List[_FnInfo]:
    seen: Set[int] = set()
    order: List[_FnInfo] = []
    work = [fi for name in sorted(roots) for fi in index.get(name, [])]
    while work:
        fi = work.pop()
        if id(fi.node) in seen:
            continue
        seen.add(id(fi.node))
        order.append(fi)
        for name in sorted(_called_names(fi.node)):
            for nxt in index.get(name, []):
                if id(nxt.node) not in seen:
                    work.append(nxt)
    return order


class _TaintChecker:
    """Source-order taint propagation + hazard checks for one function.
    Nested defs share the enclosing scope (a closure traced by the same
    jit). Single forward sweep: taint follows the order statements execute,
    so a later `jax.lax.scan` result never poisons an earlier static
    branch (loop-carried taint into a `while` test is re-checked once)."""

    def __init__(self, sf: SourceFile, fn: ast.AST, qualname: str):
        self.sf = sf
        self.fn = fn
        self.qualname = qualname
        self.tainted: Set[str] = set()
        self.data_dep: Set[str] = set()
        self.findings: List[Finding] = []
        self._flagged: Set[Tuple[int, str]] = set()

    # -- expression taint -----------------------------------------------------

    def is_tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Call):
            chain = _call_chain(node)
            if chain and chain[-1] in _STATIC_PREDICATES:
                return False
            if _is_jaxish(node):
                return True
            # unknown call with a tainted argument: assume it flows through
            return any(self.is_tainted(a) for a in node.args) or \
                any(self.is_tainted(kw.value) for kw in node.keywords)
        if isinstance(node, ast.Attribute):
            if node.attr in _STATIC_TAILS:
                return False  # .shape/.ndim/.dtype/... are static under jit
            return self.is_tainted(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_tainted(node.value)
        if isinstance(node, ast.BinOp):
            return self.is_tainted(node.left) or self.is_tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self.is_tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self.is_tainted(v) for v in node.values)
        if isinstance(node, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in node.ops):
                return False  # identity tests are static Python decisions
            return self.is_tainted(node.left) or \
                any(self.is_tainted(c) for c in node.comparators)
        if isinstance(node, ast.IfExp):
            return self.is_tainted(node.body) or self.is_tainted(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self.is_tainted(e) for e in node.elts)
        if isinstance(node, ast.Starred):
            return self.is_tainted(node.value)
        return False

    def is_data_dep(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Call):
            return _is_data_dep(node)
        if isinstance(node, ast.Name):
            return node.id in self.data_dep
        if isinstance(node, ast.Subscript):
            return self.is_data_dep(node.value)
        return False

    # -- findings -------------------------------------------------------------

    def _flag(self, node: ast.AST, message: str) -> None:
        key = (node.lineno, message)
        if key in self._flagged or self.sf.suppressed(node.lineno, NAME):
            return
        self._flagged.add(key)
        self.findings.append(
            Finding(self.sf.rel, node.lineno, NAME,
                    f"{message} (in `{self.qualname}`, jit-reachable)"))

    def _assign_targets(self, target: ast.AST, value_tainted: bool,
                        value_data_dep: bool) -> None:
        if isinstance(target, ast.Name):
            if value_tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
            if value_data_dep:
                self.data_dep.add(target.id)
            else:
                self.data_dep.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._assign_targets(elt, value_tainted, value_data_dep)

    # -- expression checks (R2/R3/ternary/set-comprehension) ------------------

    def _check_expr(self, expr: Optional[ast.AST]) -> None:
        if expr is None:
            return
        # comprehension targets first: their taint feeds the element exprs
        for sub in ast.walk(expr):
            if isinstance(sub, ast.comprehension):
                if self.is_tainted(sub.iter):
                    self._assign_targets(sub.target, True, False)
                if _is_set_expr(sub.iter):
                    self._flag(sub.iter,
                               "iterating a set while tracing: "
                               "nondeterministic iteration order feeds "
                               "nondeterministic trace order; sort it "
                               "(`sorted(...)`)")
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                chain = _call_chain(sub)
                if chain and len(chain) == 1 and \
                        chain[0] in ("int", "float", "bool") and sub.args \
                        and self.is_tainted(sub.args[0]):
                    self._flag(sub, f"`{chain[0]}()` on a traced value: "
                                    "forces a concretization/host sync and "
                                    "retraces per distinct value")
                elif _is_data_dep(sub):
                    self._flag(sub, f"`{'.'.join(chain)}` without `size=`: "
                                    "data-dependent output shape cannot "
                                    "trace under jit (and re-traces per "
                                    "shape when it can)")
            elif isinstance(sub, ast.IfExp) and self.is_tainted(sub.test):
                self._flag(sub, "ternary on a traced value: concretizes "
                                "the tracer; use jnp.where")
            elif isinstance(sub, ast.Attribute) and sub.attr == "shape" \
                    and self.is_data_dep(sub.value):
                self._flag(sub, ".shape of a data-dependent array "
                                "(nonzero/unique/...): the value is not "
                                "static under jit — carry an explicit "
                                "`size=` instead")

    # -- statement driver (source order) --------------------------------------

    def run(self) -> List[Finding]:
        for arg_default in getattr(self.fn.args, "defaults", []):
            self._check_expr(arg_default)
        self._process_body(self.fn.body)
        return self.findings

    def _process_body(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self._process_stmt(stmt)

    def _process_stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            if stmt.value is not None:
                self._check_expr(stmt.value)
                t = self.is_tainted(stmt.value)
                d = self.is_data_dep(stmt.value)
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                for tgt in targets:
                    self._check_expr(tgt)
                    self._assign_targets(tgt, t, d)
        elif isinstance(stmt, ast.If):
            self._check_expr(stmt.test)
            if self.is_tainted(stmt.test):
                self._flag(stmt, "Python `if` on a traced value: "
                                 "concretizes the tracer (error or "
                                 "per-value recompile); use jnp.where/"
                                 "lax.cond or hoist the decision to a "
                                 "static shape/config")
            self._process_body(stmt.body)
            self._process_body(stmt.orelse)
        elif isinstance(stmt, ast.While):
            self._check_while(stmt)
        elif isinstance(stmt, ast.For):
            self._check_expr(stmt.iter)
            if _is_set_expr(stmt.iter):
                self._flag(stmt.iter,
                           "iterating a set while tracing: nondeterministic "
                           "iteration order feeds nondeterministic trace "
                           "order; sort it (`sorted(...)`)")
            if self.is_tainted(stmt.iter):
                self._assign_targets(stmt.target, True, False)
            self._process_body(stmt.body)
            self._process_body(stmt.orelse)
        elif isinstance(stmt, ast.Assert):
            self._check_expr(stmt.test)
            if self.is_tainted(stmt.test):
                self._flag(stmt, "`assert` on a traced value: concretizes "
                                 "the tracer under jit; use checkify or a "
                                 "host-side check")
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._check_expr(item.context_expr)
            self._process_body(stmt.body)
        elif isinstance(stmt, ast.Try):
            self._process_body(stmt.body)
            for handler in stmt.handlers:
                self._process_body(handler.body)
            self._process_body(stmt.orelse)
            self._process_body(stmt.finalbody)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: traced by the same jit; shares the taint scope
            self._process_body(stmt.body)
        elif isinstance(stmt, (ast.Return, ast.Expr)):
            self._check_expr(stmt.value)
        elif isinstance(stmt, (ast.Raise,)):
            self._check_expr(stmt.exc)
        # remaining statement kinds carry no checkable expressions

    def _check_while(self, stmt: ast.While) -> None:
        self._check_expr(stmt.test)
        tainted_before = self.is_tainted(stmt.test)
        if tainted_before:
            self._flag(stmt, "Python `while` on a traced value: "
                             "concretizes the tracer; use lax.while_loop")
        self._process_body(stmt.body)
        if not tainted_before and self.is_tainted(stmt.test):
            # loop-carried taint: the test reads a name the body taints
            self._flag(stmt, "Python `while` on a traced value (tainted by "
                             "the loop body): concretizes the tracer; use "
                             "lax.while_loop")
        self._process_body(stmt.orelse)


# -- static-arg hashability (checked at every jit call site, not only the
# reachable set: a bad static arg breaks the caller wherever it lives) -------


def _static_positions(call: ast.Call) -> Tuple[Set[int], Set[str]]:
    """(static argnums, static argnames) declared on a jax.jit(...) call."""
    nums: Set[int] = set()
    names: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, int):
                    nums.add(n.value)
        elif kw.arg == "static_argnames":
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    names.add(n.value)
    return nums, names


def _bad_static_literal(node: ast.AST) -> Optional[str]:
    if isinstance(node, (ast.List, ast.ListComp)):
        return "list"
    if isinstance(node, (ast.Dict, ast.DictComp)):
        return "dict"
    if isinstance(node, (ast.Set, ast.SetComp)):
        return "set"
    if isinstance(node, ast.Constant) and isinstance(node.value, float):
        return "float"
    return None


def _check_static_args(sf: SourceFile) -> List[Finding]:
    """Flag unhashable/float literals fed to declared static positions.
    Covers `g = jax.jit(f, static_argnums=...)` assignments followed by
    `g(...)` calls, and direct `jax.jit(f, ...)(...)` invocations."""
    out: List[Finding] = []
    if sf.tree is None:
        return out
    jitted: Dict[str, Tuple[Set[int], Set[str]]] = {}
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            chain = _call_chain(node.value)
            if chain and chain[-1] == "jit" and chain[0] == "jax":
                nums, names = _static_positions(node.value)
                if nums or names:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            jitted[tgt.id] = (nums, names)

    def check_call(call: ast.Call, nums: Set[int], names: Set[str]) -> None:
        def why(kind: str) -> str:
            return ("floats recompile per distinct value" if kind == "float"
                    else "unhashable static args raise at call time")
        for i, arg in enumerate(call.args):
            kind = _bad_static_literal(arg)
            if i in nums and kind and not sf.suppressed(arg.lineno, NAME):
                out.append(Finding(
                    sf.rel, arg.lineno, NAME,
                    f"{kind} literal at static_argnums position {i}: "
                    f"{why(kind)} — pass a hashable config or trace it"))
        for kw in call.keywords:
            kind = _bad_static_literal(kw.value)
            if kw.arg in names and kind and \
                    not sf.suppressed(kw.value.lineno, NAME):
                out.append(Finding(
                    sf.rel, kw.value.lineno, NAME,
                    f"{kind} literal for static_argnames={kw.arg!r}: "
                    f"{why(kind)} — pass a hashable config or trace it"))

    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        if isinstance(node.func, ast.Name) and node.func.id in jitted:
            check_call(node, *jitted[node.func.id])
        elif isinstance(node.func, ast.Call):  # jax.jit(f, ...)(args)
            chain = _call_chain(node.func)
            if chain and chain[-1] == "jit" and chain[0] == "jax":
                check_call(node, *_static_positions(node.func))
    return out


def run(files: List[SourceFile], root: str) -> List[Finding]:
    roots = set(DEFAULT_ROOTS)
    index = _index_functions(files)
    for sf in files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and sf.def_annotation(node, JIT_ENTRY_RE):
                roots.add(node.name)
    findings: List[Finding] = []
    for fi in _reachable(index, roots):
        findings.extend(_TaintChecker(fi.sf, fi.node, fi.qualname).run())
    for sf in files:
        findings.extend(_check_static_args(sf))
    return sorted(set(findings), key=lambda f: (f.path, f.line))
