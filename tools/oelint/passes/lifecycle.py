"""thread-lifecycle pass: every thread needs a reachable join.

A `threading.Thread` with no stop path outlives its owner: shutdown hangs
(non-daemon), work is silently dropped mid-task (daemon), pytest leaks
threads across tests, and the oeweave harness reports it as a WeaveLeak.
The repo convention is that whoever *stores* a thread owns its lifecycle —
this pass makes the convention checkable, in two rules:

**Owned threads** (`self.X = threading.Thread(...)`): some stop-entry
method of the class — a method whose name starts with stop/close/shutdown/
terminate/abort/quit/finalize/teardown/drain, or `__exit__`/`__del__` —
must reach `self.X.join(...)`, directly or through same-class `self.m()`
calls. The tuple-swap idiom counts (and is preferred, it is also the
race-free one):

    t, self._thread = self._thread, None
    if t is not None:
        t.join()

A class that stores a thread but has no stop-entry method at all is the
purest form of the bug (pre-round-19 SkewMonitor): flagged at the
assignment.

**Fire-and-forget locals**: `threading.Thread(target=...).start()` — or a
local `t = Thread(...)` that is started but never joined, returned, stored
on self, appended to a container, or passed to another call — has *no*
owner. Nothing can ever wait for it, observe its failure, or stop it.
Either hand it to an owner or suppress with the reason the leak is
deliberate (e.g. a self-terminating shutdown helper).

The check is lexical and per-class; threads whose join lives in a different
class (handed-off ownership) take a reasoned suppression naming the owner,
which is exactly the documentation the hand-off needs.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set

from ..core import Finding, SourceFile, self_attr

NAME = "thread-lifecycle"
DIRS = ("openembedding_tpu",)

STOP_RE = re.compile(
    r"^(stop|close|shutdown|terminate|abort|quit|finalize|teardown|drain"
    r"|__exit__|__del__)")


def _is_thread_ctor(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    func = value.func
    name = func.attr if isinstance(func, ast.Attribute) else \
        (func.id if isinstance(func, ast.Name) else None)
    return name == "Thread"


def _thread_attr_assigns(cls: ast.ClassDef) -> Dict[str, int]:
    """attr -> first assignment line for `self.X = threading.Thread(...)`."""
    out: Dict[str, int] = {}
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign) or \
                not _is_thread_ctor(node.value):
            continue
        for tgt in node.targets:
            attr = self_attr(tgt)
            if attr is not None:
                out.setdefault(attr, node.lineno)
    return out


def _attrs_joined_in(method: ast.AST) -> Set[str]:
    """Thread attrs this method joins: `self.X.join()` directly, or via a
    local alias (`t = self.X` / `t, self.X = self.X, None` / any tuple or
    plain assignment whose RHS mentions self.X) that is later `.join()`ed."""
    aliases: Dict[str, Set[str]] = {}  # local name -> attrs it may hold
    joined: Set[str] = set()
    for node in ast.walk(method):
        if isinstance(node, ast.Assign):
            rhs_attrs = {a for a in
                         (self_attr(s) for s in ast.walk(node.value))
                         if a is not None}
            if not rhs_attrs:
                continue
            for tgt in node.targets:
                names = ([tgt] if isinstance(tgt, ast.Name)
                         else list(tgt.elts)
                         if isinstance(tgt, (ast.Tuple, ast.List)) else [])
                for n in names:
                    if isinstance(n, ast.Name):
                        aliases.setdefault(n.id, set()).update(rhs_attrs)
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "join":
            recv = node.func.value
            attr = self_attr(recv)
            if attr is not None:
                joined.add(attr)
            elif isinstance(recv, ast.Name):
                joined |= aliases.get(recv.id, set())
    return joined


def _self_calls(method: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(method):
        if isinstance(node, ast.Call):
            name = self_attr(node.func)
            if name is not None:
                out.add(name)
    return out


def _check_owned(sf: SourceFile, cls: ast.ClassDef) -> List[Finding]:
    threads = _thread_attr_assigns(cls)
    if not threads:
        return []
    methods = {m.name: m for m in cls.body
               if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))}
    joins = {name: _attrs_joined_in(m) for name, m in methods.items()}
    calls = {name: _self_calls(m) & set(methods)
             for name, m in methods.items()}
    stop_entries = [n for n in methods if STOP_RE.match(n)]

    # transitive: attrs joined by anything reachable from each stop entry
    reachable_joins: Set[str] = set()
    for entry in stop_entries:
        seen: Set[str] = set()
        stack = [entry]
        while stack:
            m = stack.pop()
            if m in seen:
                continue
            seen.add(m)
            reachable_joins |= joins.get(m, set())
            stack.extend(calls.get(m, ()))

    out: List[Finding] = []
    for attr, line in sorted(threads.items()):
        if attr in reachable_joins or sf.suppressed(line, NAME):
            continue
        if not stop_entries:
            msg = (f"`{cls.name}.{attr}` stores a Thread but the class has "
                   f"no stop()/close() method that joins it — the worker "
                   f"outlives every owner (leaked thread); add a stop path "
                   f"with a sentinel + join")
        else:
            msg = (f"`{cls.name}.{attr}` stores a Thread but no stop path "
                   f"({', '.join(sorted(stop_entries))}) reaches "
                   f"`self.{attr}.join()` — shutdown leaks the worker; "
                   f"join it (tuple-swap `t, self.{attr} = self.{attr}, "
                   f"None; t.join()` is the race-free idiom)")
        out.append(Finding(sf.rel, line, NAME, msg))
    return out


def _check_fire_and_forget(sf: SourceFile, fn: ast.AST) -> List[Finding]:
    out: List[Finding] = []

    # anonymous: Thread(...).start()
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "start" and \
                _is_thread_ctor(node.func.value):
            if not sf.suppressed(node.lineno, NAME):
                out.append(Finding(
                    sf.rel, node.lineno, NAME,
                    "fire-and-forget `Thread(...).start()`: nobody can "
                    "join, observe, or stop this thread; bind it to an "
                    "owner with a stop path, or suppress with why the "
                    "leak is deliberate"))

    # named locals: t = Thread(...); t.start() with no escape
    local_lines: Dict[str, int] = {}
    escaped: Set[str] = set()
    started: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and _is_thread_ctor(node.value):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    local_lines[tgt.id] = node.lineno
                else:  # stored on self/container: owned elsewhere
                    pass
    if not local_lines:
        return out
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            if isinstance(node.func, ast.Attribute) and \
                    isinstance(node.func.value, ast.Name) and \
                    node.func.value.id in local_lines:
                if node.func.attr == "start":
                    started.add(node.func.value.id)
                elif node.func.attr == "join":
                    escaped.add(node.func.value.id)
            # passed as an argument -> someone else may own it
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name) and sub.id in local_lines:
                        escaped.add(sub.id)
        elif isinstance(node, ast.Return) and node.value is not None:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name) and sub.id in local_lines:
                    escaped.add(sub.id)
        elif isinstance(node, ast.Assign):
            # rebound onto self.X / a container / another name -> escapes
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name) and sub.id in local_lines and \
                        not _is_thread_ctor(node.value):
                    escaped.add(sub.id)
        elif isinstance(node, (ast.Yield, ast.YieldFrom)) and \
                getattr(node, "value", None) is not None:
            for sub in ast.walk(node.value):
                if isinstance(sub, ast.Name) and sub.id in local_lines:
                    escaped.add(sub.id)
    for name in sorted(started - escaped):
        line = local_lines[name]
        if not sf.suppressed(line, NAME):
            out.append(Finding(
                sf.rel, line, NAME,
                f"local thread `{name}` is started but never joined, "
                f"returned, stored, or handed off — a fire-and-forget "
                f"leak; join it on the exit path or give it an owner"))
    return out


def run(files: List[SourceFile], root: str) -> List[Finding]:
    findings: List[Finding] = []
    for sf in files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(_check_owned(sf, node))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(_check_fire_and_forget(sf, node))
    return sorted(set(findings), key=lambda f: (f.path, f.line, f.message))
