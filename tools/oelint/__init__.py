"""oelint: static-analysis + invariant-guard suite for this repo.

Eleven passes over `openembedding_tpu/` (see each module's doc):

- trace-hazard     — recompile/concretization hazards in jit-reachable code
- host-sync        — device→host sync discipline in `# oelint: hot-path` fns
- sharding         — one PartitionSpec spelling per logical placement leaf
- spmd-divergence  — per-process host control flow upstream of collectives
- hlo-budget       — per-config collective counts vs tools/oelint/hlo_budget.json
- implicit-reshard — no compiled collective without a traced-op attribution
- lockset          — `# guarded-by:` discipline + lock-ordering cycles
- atomicity        — check-then-act on guarded state split across the lock
- cond-wait        — Condition.wait predicate loops, notify under the lock
- thread-lifecycle — every stored/started thread has a reachable join
- metrics          — metric-name hygiene (the former tools/lint_metrics.py)

Run them all with `make lint` / `python -m tools.oelint`; the runtime
counterpart (executable never-re-jit + collective-fingerprint assertions) is
`openembedding_tpu/utils/guards.py`.

Passes run CONCURRENTLY (the hlo-budget/implicit-reshard XLA compiles
release the GIL under the AST walks); the two compiling passes share one
measurement behind `hlo_budget.measure_cached`'s source-digest cache, so a
warm full run costs seconds, not minutes.
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterable, List, Optional, Tuple

from .core import (Finding, SourceFile, changed_files, iter_py_files,
                   load_files, repo_root)
from .passes import ALL_PASSES, BY_NAME


def run_passes(pass_names: Optional[Iterable[str]] = None, *,
               root: Optional[str] = None,
               changed_only: bool = False,
               parallel: bool = True,
               ) -> Tuple[List[Finding], Dict[str, float]]:
    """Run the named passes (default: all) over the repo.

    Returns (findings, {pass name: seconds}). Suppressed findings are
    already filtered by each pass; bare (reasonless) suppressions in any
    scanned file surface as `suppression` findings.

    `changed_only` narrows file-scanning passes to files changed vs HEAD —
    except passes declaring `NEEDS_ALL_FILES` (cross-file registries /
    call graphs), which run on their full file set whenever ANY of their
    files changed — and runs the compiling passes (hlo-budget,
    implicit-reshard) only when one of their `TRIGGERS` paths changed.
    """
    root = root or repo_root()
    selected = [BY_NAME[n] for n in (pass_names or BY_NAME)]
    changed = changed_files(root) if changed_only else None

    findings: List[Finding] = []
    timings: Dict[str, float] = {}
    file_cache: Dict[str, SourceFile] = {}
    suppression_checked: set = set()
    tasks: List[Tuple[str, object, List[SourceFile]]] = []

    for p in selected:
        if not p.DIRS:  # compiling pass: no files, gated on TRIGGERS
            if changed is not None and not any(
                    rel.startswith(p.TRIGGERS) for rel in changed):
                timings[p.NAME] = 0.0
                continue
            tasks.append((p.NAME, p, []))
            continue
        rels = iter_py_files(root, p.DIRS, skip=getattr(p, "SKIP", ()))
        if changed is not None:
            if getattr(p, "NEEDS_ALL_FILES", False):
                # cross-file pass: all files, but only if one of them changed
                if not any(r in changed for r in rels):
                    timings[p.NAME] = 0.0
                    continue
            else:
                rels = [r for r in rels if r in changed]
        files = []
        for rel in rels:
            sf = file_cache.get(rel)
            if sf is None:
                sf = file_cache[rel] = SourceFile(root, rel)
                if sf.parse_error is not None:
                    findings.append(Finding(
                        rel, sf.parse_error.lineno or 1, "parse",
                        f"syntax error: {sf.parse_error.msg}"))
            if sf.tree is not None or p.NAME == "metrics":
                files.append(sf)
            if rel not in suppression_checked:
                suppression_checked.add(rel)
                findings.extend(sf.bare_suppressions())
        tasks.append((p.NAME, p, files))

    def _one(task):
        name, p, files = task
        t0 = time.monotonic()
        return name, p.run(files, root), time.monotonic() - t0

    if parallel and len(tasks) > 1:
        with ThreadPoolExecutor(max_workers=min(8, len(tasks))) as ex:
            results = list(ex.map(_one, tasks))
    else:
        results = [_one(t) for t in tasks]
    for name, fs, dt in results:
        findings.extend(fs)
        timings[name] = dt
    findings = sorted(set(findings),
                      key=lambda f: (f.path, f.line, f.pass_name, f.message))
    return findings, timings
