"""oelint: static-analysis + invariant-guard suite for this repo.

Five passes over `openembedding_tpu/` (see each module's doc):

- trace-hazard — recompile/concretization hazards in jit-reachable code
- host-sync   — device→host sync discipline in `# oelint: hot-path` fns
- hlo-budget  — per-config collective counts vs tools/oelint/hlo_budget.json
- lockset     — `# guarded-by:` lock discipline + mutable class-level state
- metrics     — metric-name hygiene (the former tools/lint_metrics.py)

Run them all with `make lint` / `python -m tools.oelint`; the runtime
counterpart (executable never-re-jit assertions) is
`openembedding_tpu/utils/guards.py`.
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, List, Optional, Tuple

from .core import (Finding, SourceFile, changed_files, iter_py_files,
                   load_files, repo_root)
from .passes import ALL_PASSES, BY_NAME


def run_passes(pass_names: Optional[Iterable[str]] = None, *,
               root: Optional[str] = None,
               changed_only: bool = False,
               ) -> Tuple[List[Finding], Dict[str, float]]:
    """Run the named passes (default: all) over the repo.

    Returns (findings, {pass name: seconds}). Suppressed findings are
    already filtered by each pass; bare (reasonless) suppressions in any
    scanned file surface as `suppression` findings. `changed_only` narrows
    file-scanning passes to files changed vs HEAD and skips the hlo-budget
    compile unless one of its trigger paths changed.
    """
    root = root or repo_root()
    selected = [BY_NAME[n] for n in (pass_names or BY_NAME)]
    changed = changed_files(root) if changed_only else None

    findings: List[Finding] = []
    timings: Dict[str, float] = {}
    file_cache: Dict[str, SourceFile] = {}
    suppression_checked: set = set()

    for p in selected:
        t0 = time.monotonic()
        if p.NAME == "hlo-budget":
            if changed is not None and not any(
                    rel.startswith(p.TRIGGERS) for rel in changed):
                timings[p.NAME] = 0.0
                continue
            findings.extend(p.run([], root))
            timings[p.NAME] = time.monotonic() - t0
            continue
        rels = iter_py_files(root, p.DIRS, skip=getattr(p, "SKIP", ()))
        if changed is not None:
            rels = [r for r in rels if r in changed]
        files = []
        for rel in rels:
            sf = file_cache.get(rel)
            if sf is None:
                sf = file_cache[rel] = SourceFile(root, rel)
                if sf.parse_error is not None:
                    findings.append(Finding(
                        rel, sf.parse_error.lineno or 1, "parse",
                        f"syntax error: {sf.parse_error.msg}"))
            if sf.tree is not None or p.NAME == "metrics":
                files.append(sf)
            if rel not in suppression_checked:
                suppression_checked.add(rel)
                findings.extend(sf.bare_suppressions())
        findings.extend(p.run(files, root))
        timings[p.NAME] = time.monotonic() - t0
    findings = sorted(set(findings),
                      key=lambda f: (f.path, f.line, f.pass_name, f.message))
    return findings, timings
