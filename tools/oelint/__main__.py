"""CLI: `python -m tools.oelint [pass ...]` (make lint).

Exit code 1 on any finding. `--changed-only` restricts file-scanning passes
to files changed vs HEAD (and skips the hlo-budget compile unless a trigger
path changed) for fast local iteration; `--update-budget` regenerates
tools/oelint/hlo_budget.json after an INTENTIONAL collective change.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def _cpu_env() -> None:
    """CPU-only before anything imports jax: the hlo-budget pass compiles on
    8 virtual host devices and must never perform the axon TPU handshake
    (same contract as the Makefile's CPU_ENV / root conftest.py)."""
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()


def main(argv=None) -> int:
    _cpu_env()
    from . import BY_NAME, run_passes
    from .core import repo_root
    from .passes import hlo_budget

    ap = argparse.ArgumentParser(
        prog="python -m tools.oelint",
        description="static-analysis + invariant-guard suite "
                    "(trace-hazard, host-sync, sharding, spmd-divergence, "
                    "hlo-budget, implicit-reshard, lockset, atomicity, "
                    "cond-wait, thread-lifecycle, metrics)")
    ap.add_argument("passes", nargs="*", metavar="PASS",
                    help=f"passes to run (default all): "
                         f"{', '.join(BY_NAME)}")
    ap.add_argument("--changed-only", action="store_true",
                    help="lint only files changed vs HEAD; skip the "
                         "hlo-budget compile unless a trigger path changed")
    ap.add_argument("--update-budget", action="store_true",
                    help="recompile every pinned config and rewrite "
                         "tools/oelint/hlo_budget.json (commit the diff)")
    ap.add_argument("--list", action="store_true", help="list passes")
    args = ap.parse_args(argv)

    if args.list:
        for name, mod in BY_NAME.items():
            first = (mod.__doc__ or "").strip().splitlines()[0]
            print(f"{name:<14s} {first}")
        return 0
    if args.update_budget:
        t0 = time.monotonic()
        path = hlo_budget.update_budget(repo_root())
        print(f"oelint: budget regenerated at {path} "
              f"({time.monotonic() - t0:.1f}s) — review + commit the diff")
        return 0
    for name in args.passes:
        if name not in BY_NAME:
            ap.error(f"unknown pass {name!r}; expected one of "
                     f"{', '.join(BY_NAME)}")

    t0 = time.monotonic()
    findings, timings = run_passes(args.passes or None,
                                   changed_only=args.changed_only)
    try:  # expose run health as gauges (scraped when run in-process)
        from openembedding_tpu.utils import metrics as _metrics
        for n, dt in timings.items():
            _metrics.observe("lint.pass_seconds", dt, "gauge",
                             labels={"pass": n})
        _metrics.observe("lint.findings", float(len(findings)), "gauge")
    except Exception:  # noqa: BLE001 — lint must not die on telemetry
        pass
    for f in findings:
        print(f)
    ran = ", ".join(f"{n} {dt:.1f}s" for n, dt in timings.items())
    total = time.monotonic() - t0
    if findings:
        print(f"\noelint: {len(findings)} finding(s) [{ran}; total "
              f"{total:.1f}s]")
        print("suppress a false positive with "
              "`# oelint: disable=<pass> -- <reason>` (reason mandatory); "
              "regenerate the HLO budget with --update-budget only for "
              "INTENTIONAL collective changes")
        return 1
    print(f"oelint: clean [{ran}; total {total:.1f}s]")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
