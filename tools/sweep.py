"""Benchmark sweep harness over {model} x {dim} x {mode} — the counterpart of
the reference's `laboratory/benchmark/benchmark.py` matrix
({data} x {WDL,DeepFM,xDeepFM} x {9,64} x {none,server,cache,prefetch} x np).

Each cell shells out to `examples/criteo_deepctr.py` (the same workload the
reference sweeps via its own benchmark CLI), parses the throughput/AUC lines,
and appends a CSV row — partial results survive an aborted sweep.

    python tools/sweep.py --out sweep.csv                         # full matrix
    python tools/sweep.py --models lr deepfm --dims 9 --steps 40  # subset
    JAX_PLATFORMS=cpu python tools/sweep.py --smoke               # CI-sized

Modes: plain (single device), mesh (all local devices, sharded tables),
cache (sparse_as_dense dense mirror), prefetch (device-staged input),
scan (K steps fused per dispatch), offload (host_cached two-tier table),
offload_scan (both composed — union-of-K admission per window).
"""

import argparse
import csv
import itertools
import os
import re
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLE = os.path.join(REPO, "examples", "criteo_deepctr.py")

MODE_FLAGS = {
    "plain": [],
    "mesh": ["--mesh"],
    "cache": None,        # filled per-run: --cache <vocabulary>
    "prefetch": ["--prefetch"],
    "scan": ["--scan", "8"],
    "offload": None,      # filled per-run: --offload <vocabulary // 4>
    "offload_scan": None,
}

THROUGHPUT_RE = re.compile(r"([\d,]+) examples/s \(([\d,]+)/chip\)")
AUC_RE = re.compile(r"train AUC ([\d.]+)")
LOSS_RE = re.compile(r"trained \d+ steps[^,]*, loss ([\d.]+)")


def run_cell(model, dim, mode, args):
    cmd = [sys.executable, EXAMPLE, "--model", model,
           "--batch-size", str(args.batch_size), "--steps", str(args.steps),
           "--vocabulary", str(args.vocabulary), "--synthetic"]
    if model != "lr":
        cmd += ["--dim", str(dim)]
    if mode == "cache":
        cmd += ["--cache", str(args.vocabulary)]
    elif mode in ("offload", "offload_scan"):
        # cache a quarter of the id space: flushes/evictions really happen
        cmd += ["--offload", str(max(1024, args.vocabulary // 4))]
        if mode == "offload_scan":
            cmd += ["--scan", "8"]
    else:
        cmd += MODE_FLAGS[mode]
    existing = os.environ.get("PYTHONPATH")
    env = dict(os.environ, PYTHONPATH=(
        REPO + os.pathsep + existing if existing else REPO))
    t0 = time.time()

    def _text(chunk):
        return (chunk or b"").decode(errors="replace") \
            if isinstance(chunk, bytes) else (chunk or "")

    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, env=env,
                              timeout=args.cell_timeout)
        rc, out = proc.returncode, proc.stdout + proc.stderr
    except subprocess.TimeoutExpired as e:
        # a hung cell becomes a failed ROW; the rest of the matrix still runs
        rc = "timeout"
        out = _text(e.stdout) + _text(e.stderr)
    wall = time.time() - t0
    row = {"model": model, "dim": dim if model != "lr" else "-", "mode": mode,
           "rc": rc, "wall_s": round(wall, 1), "examples_per_s": "",
           "per_chip": "", "loss": "", "auc": "", "error": ""}
    m = THROUGHPUT_RE.search(out)
    if m:
        row["examples_per_s"] = m.group(1).replace(",", "")
        row["per_chip"] = m.group(2).replace(",", "")
    m = LOSS_RE.search(out)
    if m:
        row["loss"] = m.group(1)
    m = AUC_RE.search(out)
    if m:
        row["auc"] = m.group(1)
    if rc != 0:
        row["error"] = (out.strip().splitlines() or ["?"])[-1][:120]
    return row


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--models", nargs="*",
                    default=["lr", "wdl", "deepfm", "xdeepfm", "dcn"])
    ap.add_argument("--dims", nargs="*", type=int, default=[9, 64])
    ap.add_argument("--modes", nargs="*",
                    default=["plain", "mesh", "cache", "prefetch"])
    ap.add_argument("--batch-size", type=int, default=4096)
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--vocabulary", type=int, default=1 << 22)
    ap.add_argument("--cell-timeout", type=int, default=900)
    ap.add_argument("--out", default="sweep.csv")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny CI-sized matrix (seconds per cell)")
    args = ap.parse_args()
    if args.smoke:
        args.models = ["lr", "deepfm"]
        args.dims = [4]
        args.modes = ["plain", "mesh"]
        args.batch_size = 64
        args.steps = 6
        args.vocabulary = 1 << 14

    fields = ["model", "dim", "mode", "rc", "wall_s", "examples_per_s",
              "per_chip", "loss", "auc", "error"]
    fresh = not os.path.exists(args.out)
    with open(args.out, "a", newline="") as f:
        writer = csv.DictWriter(f, fieldnames=fields)
        if fresh:
            writer.writeheader()
        for model, dim, mode in itertools.product(args.models, args.dims,
                                                  args.modes):
            if model == "lr" and dim != args.dims[0]:
                continue  # LR has no dim axis; run it once
            row = run_cell(model, dim, mode, args)
            writer.writerow(row)
            f.flush()
            print(f"{model:8s} dim={row['dim']:>3} {mode:9s} rc={row['rc']} "
                  f"{row['examples_per_s'] or '-':>9} ex/s  "
                  f"auc={row['auc'] or '-'}"
                  + (f"  error={row['error']}" if row["error"] else ""))
    print(f"sweep -> {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
