"""Thin alias for the metric-name lint (back-compat for `make lint-metrics`).

The check itself moved into the oelint framework as its fifth pass
(`tools/oelint/passes/metrics.py` — same rules: dot-joined lowercase
`group.name` segments, the closed KNOWN_GROUPS registry, no per-instance
dimensions smuggled into metric NAMES). Run the full suite with `make lint`;
this entry point runs ONLY the metrics pass so existing workflows keep
working unchanged.
"""

from __future__ import annotations

import os
import sys


def main(argv=None) -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    from tools.oelint import run_passes
    findings, _ = run_passes(["metrics"], root=root)
    if findings:
        for f in findings:
            print(f)
        print(f"\nlint-metrics: {len(findings)} metric name(s) outside the "
              "documented group.name scheme (utils/metrics.py)")
        return 1
    print("lint-metrics: all observe()/vtimer()/span() call sites conform")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
