"""Lint metric names at observe()/vtimer()/trace.span() call sites.

The documented naming scheme (utils/metrics.py module doc): metric names are
dot-joined lowercase `group.name[.qualifier]` segments matching `[a-z0-9_]+`
(e.g. `serving.predict.ms`, `sync.rollbacks`); timer/span call sites pass
group and name as separate lowercase segments. Per-instance dimensions
(table, model) belong in labels, never in the name — so a name that smuggles
one in (`pull.user_table.ms`, `exchange.shard3.rows`) reads the same as a
conforming name and only a human (or this lint) catches it at review time;
the INSTANCE_DIM rule rejects those shapes mechanically.

Metric GROUPS (the first name segment, and the group argument of
vtimer/span) are a closed registry: adding a new group is a conscious act
(extend KNOWN_GROUPS here and document it), not a typo — `skwe.hot_id`
would otherwise mint a new group silently.

Scans literal string arguments only (f-strings and variables pass through —
they are composed FROM checked literals). `make lint-metrics` runs this and
fails CI on any violation.
"""

from __future__ import annotations

import os
import re
import sys

NAME = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")
SEGMENT = re.compile(r"^[a-z0-9_]+$")

# the metric-group registry: every observe() name's first segment and every
# vtimer()/span() group must be one of these (utils/metrics.py doc scheme)
KNOWN_GROUPS = {
    "exchange",   # sharded-exchange wire costs + per-shard load/skew gauges
    "fleet",      # /fleetz cross-node scrape health
    "hot",        # replicated hot-row cache (MeshTrainer(hot_rows=...))
    "metrics",    # the metrics subsystem's own health (report_errors)
    "offload",    # host-cached table cache admission/flush
    "persist",    # async/incremental persistence
    "serving",    # REST predict/pull/batching
    "skew",       # heavy-hitter sketches (utils/sketch.py)
    "sync",       # online model sync
    "train",      # example-loop wall timers
    "trainer",    # train-step phases + per-table pull stats
}

# per-instance dimensions embedded in a NAME segment instead of a label:
# a specific instance (`shard3`, `table_12`) or a smuggled instance name
# (`user_table`). Generic uses (`shard_rows`, `bucket_fill`) stay legal.
INSTANCE_DIM = re.compile(
    r"^(?:(?:table|shard|model|instance)_?\d+"
    r"|[a-z0-9_]+_(?:table|shard|model|instance))$")

# observe("metric.name", ...) — metrics.observe or bare observe
OBSERVE = re.compile(r"""(?<![\w.])(?:metrics\.|M\.)?observe\(\s*
                         (["'])(?P<name>[^"']+)\1""", re.VERBOSE)
# vtimer("group", "name") / trace.span("group", "name") / span("group", ...)
TIMER = re.compile(r"""(?<![\w.])(?:metrics\.|M\.|trace\.|_trace\.)?
                       (?:vtimer|span)\(\s*
                       (["'])(?P<group>[^"']+)\1\s*,\s*
                       (["'])(?P<name>[^"']+)\3""", re.VERBOSE)

SCAN_DIRS = ("openembedding_tpu", "examples", "tools")
SKIP = {os.path.join("tools", "lint_metrics.py")}


def lint_file(path: str, rel: str) -> list:
    with open(path, encoding="utf-8") as f:
        text = f.read()
    bad = []
    for m in OBSERVE.finditer(text):
        name = m.group("name")
        line = text.count("\n", 0, m.start()) + 1
        if not NAME.fullmatch(name):
            bad.append(f"{rel}:{line}: observe({name!r}) — metric names are "
                       "dot-joined lowercase group.name segments")
            continue
        segments = name.split(".")
        if segments[0] not in KNOWN_GROUPS:
            bad.append(f"{rel}:{line}: observe({name!r}) — unknown metric "
                       f"group {segments[0]!r}; register it in "
                       "tools/lint_metrics.py KNOWN_GROUPS")
        for seg in segments:
            if INSTANCE_DIM.fullmatch(seg):
                bad.append(f"{rel}:{line}: observe({name!r}) — segment "
                           f"{seg!r} embeds a per-instance dimension "
                           "(table/shard/model) in the NAME; put it in "
                           "labels={...} instead")
    for m in TIMER.finditer(text):
        line = text.count("\n", 0, m.start()) + 1
        for part in (m.group("group"), m.group("name")):
            if not SEGMENT.fullmatch(part):
                bad.append(f"{rel}:{line}: timer/span segment {part!r} — "
                           "group and name are single lowercase "
                           "[a-z0-9_]+ segments")
            elif INSTANCE_DIM.fullmatch(part):
                bad.append(f"{rel}:{line}: timer/span segment {part!r} — "
                           "embeds a per-instance dimension "
                           "(table/shard/model); use labels={...}")
        group = m.group("group")
        if SEGMENT.fullmatch(group) and group not in KNOWN_GROUPS:
            bad.append(f"{rel}:{line}: span/vtimer group {group!r} — "
                       "unknown metric group; register it in "
                       "tools/lint_metrics.py KNOWN_GROUPS")
    return bad


def main(argv=None) -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bad = []
    for d in SCAN_DIRS:
        base = os.path.join(root, d)
        if not os.path.isdir(base):
            continue
        for dirpath, _dirs, files in os.walk(base):
            for fn in sorted(files):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, root)
                if rel in SKIP:
                    continue
                bad.extend(lint_file(path, rel))
    if bad:
        print("\n".join(bad))
        print(f"\nlint-metrics: {len(bad)} metric name(s) outside the "
              "documented group.name scheme (utils/metrics.py)")
        return 1
    print("lint-metrics: all observe()/vtimer()/span() call sites conform")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
