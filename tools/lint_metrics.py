"""Lint metric names at observe()/vtimer()/trace.span() call sites.

The documented naming scheme (utils/metrics.py module doc): metric names are
dot-joined lowercase `group.name[.qualifier]` segments matching `[a-z0-9_]+`
(e.g. `serving.predict.ms`, `sync.rollbacks`); timer/span call sites pass
group and name as separate lowercase segments. Per-instance dimensions
(table, model) belong in labels, never in the name — so a name that smuggles
one in (`pull.user_table.ms`) reads the same as a conforming name and only a
human (or this lint) catches it at review time.

Scans literal string arguments only (f-strings and variables pass through —
they are composed FROM checked literals). `make lint-metrics` runs this and
fails CI on any violation.
"""

from __future__ import annotations

import os
import re
import sys

NAME = re.compile(r"^[a-z0-9_]+(\.[a-z0-9_]+)+$")
SEGMENT = re.compile(r"^[a-z0-9_]+$")

# observe("metric.name", ...) — metrics.observe or bare observe
OBSERVE = re.compile(r"""(?<![\w.])(?:metrics\.|M\.)?observe\(\s*
                         (["'])(?P<name>[^"']+)\1""", re.VERBOSE)
# vtimer("group", "name") / trace.span("group", "name") / span("group", ...)
TIMER = re.compile(r"""(?<![\w.])(?:metrics\.|M\.|trace\.|_trace\.)?
                       (?:vtimer|span)\(\s*
                       (["'])(?P<group>[^"']+)\1\s*,\s*
                       (["'])(?P<name>[^"']+)\3""", re.VERBOSE)

SCAN_DIRS = ("openembedding_tpu", "examples", "tools")
SKIP = {os.path.join("tools", "lint_metrics.py")}


def lint_file(path: str, rel: str) -> list:
    with open(path, encoding="utf-8") as f:
        text = f.read()
    bad = []
    for m in OBSERVE.finditer(text):
        name = m.group("name")
        if not NAME.fullmatch(name):
            line = text.count("\n", 0, m.start()) + 1
            bad.append(f"{rel}:{line}: observe({name!r}) — metric names are "
                       "dot-joined lowercase group.name segments")
    for m in TIMER.finditer(text):
        for part in (m.group("group"), m.group("name")):
            if not SEGMENT.fullmatch(part):
                line = text.count("\n", 0, m.start()) + 1
                bad.append(f"{rel}:{line}: timer/span segment {part!r} — "
                           "group and name are single lowercase "
                           "[a-z0-9_]+ segments")
    return bad


def main(argv=None) -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bad = []
    for d in SCAN_DIRS:
        base = os.path.join(root, d)
        if not os.path.isdir(base):
            continue
        for dirpath, _dirs, files in os.walk(base):
            for fn in sorted(files):
                if not fn.endswith(".py"):
                    continue
                path = os.path.join(dirpath, fn)
                rel = os.path.relpath(path, root)
                if rel in SKIP:
                    continue
                bad.extend(lint_file(path, rel))
    if bad:
        print("\n".join(bad))
        print(f"\nlint-metrics: {len(bad)} metric name(s) outside the "
              "documented group.name scheme (utils/metrics.py)")
        return 1
    print("lint-metrics: all observe()/vtimer()/span() call sites conform")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
