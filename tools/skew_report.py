"""Workload-skew report: hot ids + coverage curve + shard balance from a
node's /metrics.

    python tools/skew_report.py http://node:8501            # live scrape
    python tools/skew_report.py /tmp/metrics.txt            # saved scrape
    python tools/skew_report.py http://node:8501 --fleet    # /fleetz merge
    python tools/skew_report.py http://node:8501 --recommend  # policy dry run

Renders the `skew.*` rank-labeled gauges the heavy-hitter sketches publish
(`utils/sketch.py` — `skew.hot_id{table=,rank=}` / `hot_id_count` /
`hot_id_error` / `stream_ids`) as a per-table hot-id table with the
documented `est - err <= true <= est` bound, the COVERAGE CURVE (cumulative
traffic share vs top-K — the sizing input for `MeshTrainer(hot_rows=...)`:
read off the K where the curve knees and check `hot.hit_ratio` reproduces it
live), and the per-shard exchange load gauges (`exchange.shard_rows` /
`shard_positions` / `bucket_fill`, plus the `exchange.shard_imbalance`
histogram's mean) as a shard-balance table — the measurements Parallax-style
skew-aware placement decisions need, offline, from one scrape. The same
coverage curve renders on the node's own `GET /statusz` next to the hot-id
table.
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from typing import Dict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from openembedding_tpu.utils.metrics import parse_prometheus  # noqa: E402


def fetch(source: str, *, fleet: bool = False, timeout: float = 10.0) -> str:
    if os.path.exists(source):
        with open(source) as f:
            return f.read()
    import urllib.request
    url = source.rstrip("/")
    if not url.startswith("http"):
        url = f"http://{url}"
    if not url.endswith(("/metrics", "/fleetz")):
        url += "/fleetz" if fleet else "/metrics"
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def _by_table_rank(samples, name: str) -> Dict[str, Dict[int, float]]:
    out: Dict[str, Dict[int, float]] = {}
    for n, labels, value in samples:
        if n == name and "table" in labels and "rank" in labels:
            out.setdefault(labels["table"], {})[int(labels["rank"])] = value
    return out


def hot_id_report(samples, top: int) -> str:
    ids = _by_table_rank(samples, "oetpu_skew_hot_id")
    counts = _by_table_rank(samples, "oetpu_skew_hot_id_count")
    errs = _by_table_rank(samples, "oetpu_skew_hot_id_error")
    totals = {labels.get("table"): value for n, labels, value in samples
              if n == "oetpu_skew_stream_ids"}
    if not ids:
        return "(no skew.* series — node has no id streams observed yet)"
    lines = []
    for table in sorted(ids):
        total = max(totals.get(table, 0.0), 1.0)
        lines.append(f"table {table}: {totals.get(table, 0):.0f} ids seen "
                     "(est - err <= true <= est)")
        lines.append(f"  {'rank':<5}{'id':<22}{'est':<12}{'err<=':<10}share")
        for rank in sorted(ids[table])[:top]:
            est = counts.get(table, {}).get(rank, 0.0)
            err = errs.get(table, {}).get(rank, 0.0)
            lines.append(f"  #{rank:<4d}{ids[table][rank]:<22.0f}"
                         f"{est:<12.0f}{err:<10.0f}{est / total:6.2%}")
    return "\n".join(lines)


def coverage_report(samples) -> str:
    """Cumulative traffic share vs top-K per table, from the rank-labeled
    `skew.hot_id_count` gauges + `skew.stream_ids` — bounded by the sketch's
    tracked set (k), which is exactly the range `hot_rows` can be sized in."""
    counts = _by_table_rank(samples, "oetpu_skew_hot_id_count")
    totals = {labels.get("table"): value for n, labels, value in samples
              if n == "oetpu_skew_stream_ids"}
    if not counts:
        return "(no skew.* series — node has no id streams observed yet)"
    lines = []
    for table in sorted(counts):
        total = max(totals.get(table, 0.0), 1.0)
        est = sorted(counts[table].values(), reverse=True)
        cum, acc = [], 0.0
        for v in est:
            acc += v
            cum.append(acc / total)
        ks, k = [], 1
        while k < len(cum):
            ks.append(k)
            k *= 2
        ks.append(len(cum))
        lines.append(f"table {table}: top-K traffic share "
                     f"(size hot_rows at the knee; {len(cum)} tracked)")
        lines.append("  " + "  ".join(f"top{k}={cum[k - 1]:.1%}"
                                      for k in ks))
    return "\n".join(lines)


def shard_balance_report(samples) -> str:
    stats = ("oetpu_exchange_shard_rows", "oetpu_exchange_shard_positions",
             "oetpu_exchange_bucket_fill")
    per: Dict[str, Dict[str, Dict[int, float]]] = {}
    hist: Dict[str, Dict[str, float]] = {}
    for n, labels, value in samples:
        if n in stats and "table" in labels and "shard" in labels:
            per.setdefault(labels["table"], {}).setdefault(
                n, {})[int(labels["shard"])] = value
        if n.startswith("oetpu_exchange_shard_imbalance_") and "table" in labels:
            hist.setdefault(labels["table"], {})[n.rsplit("_", 1)[-1]] = value
    if not per:
        return "(no per-shard exchange stats — sharded trainer nodes only)"
    lines = []
    for table in sorted(per):
        parts = [f"table {table}:"]
        h = hist.get(table, {})
        if h.get("count"):
            parts.append(f"imbalance(max/mean) mean={h['sum'] / h['count']:.3f}"
                         f" over {h['count']:.0f} steps")
        lines.append(" ".join(parts))
        for name in stats:
            if name not in per[table]:
                continue
            vals = per[table][name]
            row = [vals.get(i, 0.0) for i in range(max(vals) + 1)]
            fmt = "{:.3f}" if name.endswith("bucket_fill") else "{:.0f}"
            lines.append(f"  {name.split('oetpu_exchange_')[-1]:<16s} "
                         + " ".join(fmt.format(v) for v in row))
    return "\n".join(lines)


def telemetry_from_samples(samples, *, default_dim: int = 16):
    """Rebuild per-table `placement.TableTelemetry` from scrape samples —
    the same inputs the live `PlacementController` reads from its sketches,
    reconstructed from the rank-labeled gauges so the policy dry-runs
    offline against exactly what the node measured."""
    import numpy as np

    from openembedding_tpu.placement.policy import TableTelemetry
    ids = _by_table_rank(samples, "oetpu_skew_hot_id")
    counts = _by_table_rank(samples, "oetpu_skew_hot_id_count")
    totals = {labels.get("table"): value for n, labels, value in samples
              if n == "oetpu_skew_stream_ids"}
    dims = {labels.get("table"): value for n, labels, value in samples
            if n == "oetpu_exchange_row_dim"}
    pos: Dict[str, Dict[int, float]] = {}
    for n, labels, value in samples:
        if n == "oetpu_exchange_shard_positions" and "table" in labels \
                and "shard" in labels:
            pos.setdefault(labels["table"], {})[int(labels["shard"])] = value
    out = []
    for table in sorted(ids):
        total = max(totals.get(table, 0.0), 1.0)
        top = [(int(ids[table][r]), counts.get(table, {}).get(r, 0.0))
               for r in sorted(ids[table])]
        top.sort(key=lambda x: -x[1])
        cum, acc, cov = [], 0.0, []
        for k, (_i, e) in enumerate(top):
            acc += e
            cov.append((k + 1, min(acc / total, 1.0)))
        sp = None
        if table in pos:
            sp = np.asarray([pos[table].get(i, 0.0)
                             for i in range(max(pos[table]) + 1)])
        out.append(TableTelemetry(
            name=table, dim=int(dims.get(table, default_dim)),
            coverage=cov, total=total, top_ids=top, shard_positions=sp))
    return out


def recommend_report(samples, *, budget_bytes: int, mig_rows: int,
                     imbalance_target: float,
                     default_dim: int = 16) -> str:
    """The --recommend dry run: what the self-driving controller WOULD do
    with this scrape — per-table hot-cache size against the byte budget,
    the predicted hit ratio at that size, and the migration plan — so an
    operator can audit the policy before enabling
    `placement.PlacementController` on the trainer."""
    from openembedding_tpu.placement.migration import (candidate_weights,
                                                       plan_migration)
    from openembedding_tpu.placement.policy import PlacementPolicy, row_bytes
    tel = telemetry_from_samples(samples, default_dim=default_dim)
    policy = PlacementPolicy(budget_bytes, mig_rows=mig_rows,
                             imbalance_target=imbalance_target)
    if not tel:
        return "\n".join(
            ["(no skew.* series — node has no id streams observed yet)"]
            + _dense_wire_lines(samples, policy))
    sizes = policy.size_hot(tel)
    wires = policy.recommend_wire(tel)
    # per-table annex capacity off the measured cold-tail imbalance — the
    # same sizing `PlacementController.prime` installs
    migs = policy.size_mig(tel)
    lines = [f"policy: hot_budget={budget_bytes}B mig_rows={mig_rows} "
             f"(flat default; per-table M below) "
             f"imbalance_target={imbalance_target}"]
    for t in tel:
        H = sizes.get(t.name, 0)
        M = migs.get(t.name, mig_rows)
        hot_ids = [i for i, _e in t.top_ids[:H]]
        line = (f"table {t.name}: hot_rows={H} "
                f"({H * row_bytes(t.dim, t.slot_cols)}B replicated) "
                f"predicted_hit={t.share_at(H):.3f} "
                f"wire={wires.get(t.name, 'bf16')} "
                f"mig_rows={M}")
        if t.shard_positions is not None and t.shard_positions.sum() > 0:
            load = t.shard_positions
            imb = float(load.max() / load.mean())
            mids, mown, proj = plan_migration(
                load, candidate_weights(t.top_ids, hot_ids),
                num_shards=load.size, max_moves=M,
                target=imbalance_target, total=t.total, exclude=hot_ids)
            line += (f" imbalance={imb:.3f} migration_plan={mids.size} rows"
                     f" -> projected {proj:.3f}")
            lines.append(line)
            for i, o in list(zip(mids.tolist(), mown.tolist()))[:10]:
                lines.append(f"    move id={i} shard {i % load.size} -> {o}")
            if mids.size > 10:
                lines.append(f"    ... {mids.size - 10} more")
        else:
            line += " (no shard load vector — trainer nodes only)"
            lines.append(line)
    lines.extend(_dense_wire_lines(samples, policy))
    return "\n".join(lines)


def _dense_wire_lines(samples, policy) -> list:
    """The dense-gradient wire row of --recommend: the measured gradient
    density (`dense.grad_density` — a `MeshTrainer(dense_stats=True)` run
    publishes it) against the sparse/dense crossover
    (`policy.recommend_dense_wire` — what a manage_wire controller would
    install, hysteresis aside)."""
    density = next((v for n, _labels, v in samples
                    if n == "oetpu_dense_grad_density"), None)
    if density is None:
        return ["dense wire: (no oetpu_dense_grad_density gauge — a "
                "MeshTrainer(dense_stats=True) trainer publishes it)"]
    mode, k, reason = policy.recommend_dense_wire(float(density))
    return [f"dense wire: measured grad density {float(density):.3f}"
            f" -> {mode}" + (f" (k={k}/chunk)" if k else "")
            + f" — {reason}"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="hot-id / shard-balance report from a /metrics scrape")
    ap.add_argument("source", help="node base URL, /metrics URL, or a saved "
                                   "scrape file")
    ap.add_argument("--top", type=int, default=10, help="hot ids per table")
    ap.add_argument("--fleet", action="store_true",
                    help="scrape GET /fleetz (merged fleet view) instead of "
                         "the node's own /metrics")
    ap.add_argument("--timeout", type=float, default=10.0)
    ap.add_argument("--recommend", action="store_true",
                    help="dry-run the self-driving placement policy on this "
                         "scrape: per-table hot_rows vs the byte budget, "
                         "predicted hit ratio, migration plan, recommended "
                         "wire format")
    ap.add_argument("--hot-budget-kb", type=float, default=64.0,
                    help="--recommend: replicated hot-cache byte budget")
    ap.add_argument("--mig-rows", type=int, default=64,
                    help="--recommend: migration annex scale (the policy "
                         "sizes each table's M within [x/4, 4x] off the "
                         "measured shard imbalance)")
    ap.add_argument("--imbalance-target", type=float, default=1.05)
    ap.add_argument("--dim", type=int, default=16,
                    help="--recommend: row dim fallback when the scrape "
                         "carries no oetpu_exchange_row_dim gauge")
    args = ap.parse_args(argv)
    parsed = parse_prometheus(
        fetch(args.source, fleet=args.fleet, timeout=args.timeout))
    samples = parsed["samples"]
    print("== hot ids (heavy-hitter sketches) ==")
    print(hot_id_report(samples, args.top))
    print()
    print("== coverage curve (hot_rows sizing) ==")
    print(coverage_report(samples))
    print()
    print("== shard balance (exchange load accounting) ==")
    print(shard_balance_report(samples))
    if args.recommend:
        print()
        print("== placement recommendation (policy dry run) ==")
        print(recommend_report(
            samples, budget_bytes=int(args.hot_budget_kb * 1024),
            mig_rows=args.mig_rows,
            imbalance_target=args.imbalance_target,
            default_dim=args.dim))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
