"""Workload-skew report: hot ids + coverage curve + shard balance from a
node's /metrics.

    python tools/skew_report.py http://node:8501            # live scrape
    python tools/skew_report.py /tmp/metrics.txt            # saved scrape
    python tools/skew_report.py http://node:8501 --fleet    # /fleetz merge

Renders the `skew.*` rank-labeled gauges the heavy-hitter sketches publish
(`utils/sketch.py` — `skew.hot_id{table=,rank=}` / `hot_id_count` /
`hot_id_error` / `stream_ids`) as a per-table hot-id table with the
documented `est - err <= true <= est` bound, the COVERAGE CURVE (cumulative
traffic share vs top-K — the sizing input for `MeshTrainer(hot_rows=...)`:
read off the K where the curve knees and check `hot.hit_ratio` reproduces it
live), and the per-shard exchange load gauges (`exchange.shard_rows` /
`shard_positions` / `bucket_fill`, plus the `exchange.shard_imbalance`
histogram's mean) as a shard-balance table — the measurements Parallax-style
skew-aware placement decisions need, offline, from one scrape. The same
coverage curve renders on the node's own `GET /statusz` next to the hot-id
table.
"""

from __future__ import annotations

import argparse
import os
import re
import sys
from typing import Dict

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from openembedding_tpu.utils.metrics import parse_prometheus  # noqa: E402


def fetch(source: str, *, fleet: bool = False, timeout: float = 10.0) -> str:
    if os.path.exists(source):
        with open(source) as f:
            return f.read()
    import urllib.request
    url = source.rstrip("/")
    if not url.startswith("http"):
        url = f"http://{url}"
    if not url.endswith(("/metrics", "/fleetz")):
        url += "/fleetz" if fleet else "/metrics"
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def _by_table_rank(samples, name: str) -> Dict[str, Dict[int, float]]:
    out: Dict[str, Dict[int, float]] = {}
    for n, labels, value in samples:
        if n == name and "table" in labels and "rank" in labels:
            out.setdefault(labels["table"], {})[int(labels["rank"])] = value
    return out


def hot_id_report(samples, top: int) -> str:
    ids = _by_table_rank(samples, "oetpu_skew_hot_id")
    counts = _by_table_rank(samples, "oetpu_skew_hot_id_count")
    errs = _by_table_rank(samples, "oetpu_skew_hot_id_error")
    totals = {labels.get("table"): value for n, labels, value in samples
              if n == "oetpu_skew_stream_ids"}
    if not ids:
        return "(no skew.* series — node has no id streams observed yet)"
    lines = []
    for table in sorted(ids):
        total = max(totals.get(table, 0.0), 1.0)
        lines.append(f"table {table}: {totals.get(table, 0):.0f} ids seen "
                     "(est - err <= true <= est)")
        lines.append(f"  {'rank':<5}{'id':<22}{'est':<12}{'err<=':<10}share")
        for rank in sorted(ids[table])[:top]:
            est = counts.get(table, {}).get(rank, 0.0)
            err = errs.get(table, {}).get(rank, 0.0)
            lines.append(f"  #{rank:<4d}{ids[table][rank]:<22.0f}"
                         f"{est:<12.0f}{err:<10.0f}{est / total:6.2%}")
    return "\n".join(lines)


def coverage_report(samples) -> str:
    """Cumulative traffic share vs top-K per table, from the rank-labeled
    `skew.hot_id_count` gauges + `skew.stream_ids` — bounded by the sketch's
    tracked set (k), which is exactly the range `hot_rows` can be sized in."""
    counts = _by_table_rank(samples, "oetpu_skew_hot_id_count")
    totals = {labels.get("table"): value for n, labels, value in samples
              if n == "oetpu_skew_stream_ids"}
    if not counts:
        return "(no skew.* series — node has no id streams observed yet)"
    lines = []
    for table in sorted(counts):
        total = max(totals.get(table, 0.0), 1.0)
        est = sorted(counts[table].values(), reverse=True)
        cum, acc = [], 0.0
        for v in est:
            acc += v
            cum.append(acc / total)
        ks, k = [], 1
        while k < len(cum):
            ks.append(k)
            k *= 2
        ks.append(len(cum))
        lines.append(f"table {table}: top-K traffic share "
                     f"(size hot_rows at the knee; {len(cum)} tracked)")
        lines.append("  " + "  ".join(f"top{k}={cum[k - 1]:.1%}"
                                      for k in ks))
    return "\n".join(lines)


def shard_balance_report(samples) -> str:
    stats = ("oetpu_exchange_shard_rows", "oetpu_exchange_shard_positions",
             "oetpu_exchange_bucket_fill")
    per: Dict[str, Dict[str, Dict[int, float]]] = {}
    hist: Dict[str, Dict[str, float]] = {}
    for n, labels, value in samples:
        if n in stats and "table" in labels and "shard" in labels:
            per.setdefault(labels["table"], {}).setdefault(
                n, {})[int(labels["shard"])] = value
        if n.startswith("oetpu_exchange_shard_imbalance_") and "table" in labels:
            hist.setdefault(labels["table"], {})[n.rsplit("_", 1)[-1]] = value
    if not per:
        return "(no per-shard exchange stats — sharded trainer nodes only)"
    lines = []
    for table in sorted(per):
        parts = [f"table {table}:"]
        h = hist.get(table, {})
        if h.get("count"):
            parts.append(f"imbalance(max/mean) mean={h['sum'] / h['count']:.3f}"
                         f" over {h['count']:.0f} steps")
        lines.append(" ".join(parts))
        for name in stats:
            if name not in per[table]:
                continue
            vals = per[table][name]
            row = [vals.get(i, 0.0) for i in range(max(vals) + 1)]
            fmt = "{:.3f}" if name.endswith("bucket_fill") else "{:.0f}"
            lines.append(f"  {name.split('oetpu_exchange_')[-1]:<16s} "
                         + " ".join(fmt.format(v) for v in row))
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="hot-id / shard-balance report from a /metrics scrape")
    ap.add_argument("source", help="node base URL, /metrics URL, or a saved "
                                   "scrape file")
    ap.add_argument("--top", type=int, default=10, help="hot ids per table")
    ap.add_argument("--fleet", action="store_true",
                    help="scrape GET /fleetz (merged fleet view) instead of "
                         "the node's own /metrics")
    ap.add_argument("--timeout", type=float, default=10.0)
    args = ap.parse_args(argv)
    parsed = parse_prometheus(
        fetch(args.source, fleet=args.fleet, timeout=args.timeout))
    samples = parsed["samples"]
    print("== hot ids (heavy-hitter sketches) ==")
    print(hot_id_report(samples, args.top))
    print()
    print("== coverage curve (hot_rows sizing) ==")
    print(coverage_report(samples))
    print()
    print("== shard balance (exchange load accounting) ==")
    print(shard_balance_report(samples))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
