"""Measure incremental persist and host-offload at a scale that hurts.

VERDICT r4 weak #3/#4: the 27x/4.5x delta-persist numbers came from a 320 MB
state and offload had no performance datum at all. This probe produces the
missing curve points:

  persist:  full-vs-delta wall time + bytes at --vocab-log2 {22..27}
            (dim-9 DeepFM state = 80 B/row: 2^22 = 336 MB ... 2^27 = 10.7 GB)
  offload:  offload_train_many examples/s at a hashed table whose id space
            is ~2x the device cache, vs the SAME workload on a plain in-HBM
            (in-RAM on CPU) table — the price of the two-tier path when the
            table does not fit

Honest-labeling note: on CPU the "device cache" and "host store" live in the
same RAM, so the offload number isolates the admission/eviction/bookkeeping
COMPUTE cost — there is no PCIe/tunnel transfer in it. On a host-attached
TPU VM the same path pays real DMA; the round-3 chip number (458 ex/s) was
dominated by the axon relay tunnel and is not representative of either.

Usage:
  python tools/scale_probe.py persist --vocab-log2 24 [--steps 8]
  python tools/scale_probe.py offload [--cache-log2 20] [--steps 32]
Writes one JSON line per case to stdout; run under JAX_PLATFORMS=cpu for the
scale cases (the v5e cannot hold 2^27 x 20 f32 anyway).
"""

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _emit(case, payload):
    print(json.dumps({"case": case, **payload}), flush=True)


def probe_persist(vocab_log2: int, steps: int, batch: int):
    import jax

    import openembedding_tpu as embed
    from openembedding_tpu.data import synthetic_criteo
    from openembedding_tpu.model import Trainer
    from openembedding_tpu.models import make_deepfm
    from openembedding_tpu.persist import (AsyncPersister, IncrementalPersister,
                                           PersistPolicy, list_deltas,
                                           list_persists)

    V = 1 << vocab_log2
    model = make_deepfm(vocabulary=V, dim=9)
    trainer = Trainer(model, embed.Adagrad(learning_rate=0.05), seed=0)
    batches = list(synthetic_criteo(batch, id_space=V, steps=steps, seed=1,
                                    ids_dtype=np.int32))
    t0 = time.perf_counter()
    state = trainer.init(batches[0])
    step = trainer.jit_train_step()
    for b in batches:
        state, m = step(state, b)
    float(m["loss"])
    train_s = time.perf_counter() - t0
    state_bytes = sum(
        int(np.prod(a.shape)) * a.dtype.itemsize
        for ts in state.tables.values()
        for a in ([ts.weights] + list(ts.slots.values())))

    def du(path):
        total = 0
        for root, _, files in os.walk(path):
            for f in files:
                total += os.path.getsize(os.path.join(root, f))
        return total

    tmp = tempfile.mkdtemp(prefix="persist_probe_")
    out = {"vocab_log2": vocab_log2, "state_gib": round(state_bytes / 2**30, 3),
           "train_warm_s": round(train_s, 1), "batch": batch, "steps": steps}
    try:
        # FULL persist: snapshot + write, measured to COMMIT (wait drains)
        with AsyncPersister(trainer, model, os.path.join(tmp, "full"),
                            policy=PersistPolicy(every_steps=1)) as p:
            t0 = time.perf_counter()
            p.persist(state)
            p.wait()
            out["full_persist_s"] = round(time.perf_counter() - t0, 2)
        out["full_bytes"] = du(os.path.join(tmp, "full"))

        # DELTA: base once, then observe one batch window and persist deltas
        with IncrementalPersister(trainer, model, os.path.join(tmp, "incr"),
                                  policy=PersistPolicy(every_steps=1),
                                  full_every=1000) as p:
            p.observe(batches[0])
            p.persist(state)  # base (full)
            p.wait()
            base_bytes = du(os.path.join(tmp, "incr"))
            ts = []
            st = state
            for b in batches[:3]:
                p.observe(b)
                st = st.replace(step=st.step + 1)
                t0 = time.perf_counter()
                p.persist(st)
                p.wait()
                ts.append(time.perf_counter() - t0)
            out["delta_persist_s"] = round(float(np.median(ts)), 3)
            out["delta_bytes"] = (du(os.path.join(tmp, "incr")) - base_bytes
                                  ) // max(1, len(ts))
            out["touched_rows_per_window"] = int(np.unique(
                batches[0]["sparse"]["categorical"]).size)
        out["speedup_time"] = round(
            out["full_persist_s"] / max(1e-9, out["delta_persist_s"]), 1)
        out["ratio_bytes"] = round(
            out["full_bytes"] / max(1, out["delta_bytes"]), 1)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    _emit("persist", out)


def probe_offload(cache_log2: int, steps: int, batch: int, scan: int):
    import dataclasses

    import jax

    import openembedding_tpu as embed
    from openembedding_tpu.data import synthetic_criteo
    from openembedding_tpu.model import Trainer
    from openembedding_tpu.models import make_deepfm

    cache = 1 << cache_log2
    id_space = 1 << (cache_log2 + 1)  # ~2x the cache (Zipf uniques less, see out)

    def run(offload: bool):
        model = make_deepfm(vocabulary=-1 if offload else id_space, dim=9,
                            hashed=offload, capacity=(cache if offload
                                                      else 0))
        if offload:
            model.specs["categorical"] = dataclasses.replace(
                model.specs["categorical"], storage="host_cached")
        trainer = Trainer(model, embed.Adagrad(learning_rate=0.05), seed=0)
        batches = list(synthetic_criteo(batch, id_space=id_space, steps=steps,
                                        seed=1, ids_dtype=np.int32))
        state = trainer.init(batches[0])
        windows = [batches[i:i + scan] for i in range(0, steps, scan)]
        stacked = [jax.tree_util.tree_map(lambda *xs: np.stack(xs), *w)
                   for w in windows]
        # warm (compile + first admissions)
        state, m = trainer.offload_train_many(state, stacked[0])
        float(np.asarray(m["loss"])[-1])
        t0 = time.perf_counter()
        done = 0
        for w in stacked[1:]:
            state, m = trainer.offload_train_many(state, w)
            done += scan
        float(np.asarray(m["loss"])[-1])
        dt = time.perf_counter() - t0
        uniq = int(np.unique(np.concatenate(
            [b["sparse"]["categorical"].reshape(-1) for b in batches])).size)
        return done * batch / dt, uniq

    eps_off, uniq = run(True)
    eps_plain, _ = run(False)
    _emit("offload", {
        "cache_rows": cache, "id_space": id_space, "unique_ids_seen": uniq,
        "batch": batch, "scan": scan, "steps": steps,
        "offload_examples_per_s": round(eps_off, 1),
        "plain_examples_per_s": round(eps_plain, 1),
        "offload_cost_factor": round(eps_plain / max(1e-9, eps_off), 2),
    })


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("mode", choices=["persist", "offload"])
    ap.add_argument("--vocab-log2", type=int, default=24)
    ap.add_argument("--cache-log2", type=int, default=20)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--batch", type=int, default=4096)
    ap.add_argument("--scan", type=int, default=8)
    args = ap.parse_args()
    if args.mode == "persist":
        probe_persist(args.vocab_log2, args.steps, args.batch)
    else:
        probe_offload(args.cache_log2, args.steps, args.batch, args.scan)


if __name__ == "__main__":
    main()
