"""Micro-benchmark: fused-vs-unfused multi-table exchange + wire formats.

Measures the round-6 exchange work on the 8-virtual-device CPU mesh (real
collectives over XLA host devices — the same substrate the tier-1 suite
pins parity on; the single physical chip cannot exercise an S>1 exchange):

- step time of a 3-table / 2-dim-group model through the per-table protocol
  (9 all_to_alls, fp32) vs the fused exchange (6 all_to_alls) at fp32, bf16
  and int8 wire;
- the STATIC wire-cost model (`ops/wire.exchange_cost`): exchange bytes/step
  per format — the acceptance bound is fp32/bf16 >= 1.7x (re-anchored in
  round 13: the model now prices hash-table id slots at their true 8 B pair
  layout and the int8 in-band scale lanes, so the same exchange reads a
  slightly lower — honest — ratio than the round-6 4-B-id model's 1.8x);
- since round 13, the REAL compiled collective bytes per wire mode, counted
  from the lowered HLO with the same `collective_payloads` parser the oelint
  hlo-budget pass pins — printed next to the analytic model with the
  model-vs-HLO delta (asserted 0: the model prices what actually ships);
- pull/push parity: the bf16- and int8-wire runs must land within format
  tolerance of the fp32 run (trained table rows compared), with table
  storage still fp32.

Emits ONE BENCH-format JSON line on stdout:
  {"metric": "wire_bf16_bytes_ratio", "value": ..., "unit": "x",
   "vs_baseline": ..., "extra": {...}, "errors": {...}}

Run: python tools/wire_microbench.py [--steps 8] [--batch 256]
(Also a battery entry in tools/upwindow.py so the chip driver commits the
stanza to PERF_CHIP_R5.md on the next relay up-window.)
"""

import argparse
import json
import os
import sys
import time
import traceback

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# CPU mesh by design (see module docstring) — set BEFORE jax import
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags +
                               " --xla_force_host_platform_device_count=8")
os.environ["JAX_PLATFORMS"] = "cpu"

S = 8
VOCAB = 1 << 14
DIM = 16


def build_model():
    """3 PS tables in 2 dim-groups (the tests/test_wire.py shape at bench
    scale): dim-16 {latent (array), hashed (hash)} + dim-1 {first_order}."""
    import flax.linen as nn
    import jax.numpy as jnp
    import openembedding_tpu as embed
    from openembedding_tpu.model import EmbeddingModel

    class Tower(nn.Module):
        @nn.compact
        def __call__(self, embedded, dense):
            bias = self.param("bias", nn.initializers.zeros, (1,),
                              jnp.float32)
            out = (jnp.sum(embedded["latent"].astype(jnp.float32),
                           axis=(1, 2))
                   + jnp.sum(embedded["hashed"].astype(jnp.float32),
                             axis=(1, 2))
                   + jnp.sum(embedded["first_order"][..., 0]
                             .astype(jnp.float32), axis=1))
            return out + bias[0]

    embs = [
        embed.Embedding(VOCAB, DIM, name="latent"),
        embed.Embedding(-1, DIM, name="hashed", capacity=1 << 16),
        embed.Embedding(VOCAB, 1, name="first_order", feature="latent"),
    ]
    return EmbeddingModel(Tower(), embs)


def batches(batch, steps, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(steps):
        # Zipf-ish skew so dedup and the duplicate-count lanes do real work
        lat = (rng.zipf(1.3, (batch, 8)) % VOCAB).astype(np.int32)
        hsh = (rng.zipf(1.3, (batch, 4)).astype(np.int64) * 2654435761
               % (1 << 40))
        out.append({"sparse": {"latent": lat, "hashed": hsh},
                    "label": rng.integers(0, 2, (batch,))
                    .astype(np.float32)})
    return out


def train(wire, group_exchange, bs, steps=3):
    import jax
    import openembedding_tpu as embed
    from openembedding_tpu.parallel import MeshTrainer, make_mesh

    tr = MeshTrainer(build_model(), embed.Adagrad(learning_rate=0.1),
                     mesh=make_mesh(), wire=wire,
                     group_exchange=group_exchange)
    bs = [jax.device_put(b) for b in bs]
    state = tr.init(bs[0])
    step = tr.jit_train_step(bs[0], state)
    # compiled-HLO truth BEFORE the donating warmup call: the byte counts
    # reported next to the analytic model come from the same counter the
    # oelint hlo-budget pass pins (`collective_payloads`)
    hlo_text = step.lower(state, bs[0]).compile().as_text()
    state, m = step(state, bs[0])  # compile + warmup
    jax.block_until_ready(m["loss"])
    t0 = time.perf_counter()
    n = 0
    for _ in range(steps):
        for b in bs:
            state, m = step(state, b)
            n += 1
    jax.block_until_ready(m["loss"])
    ms = (time.perf_counter() - t0) / n * 1e3
    return tr, state, ms, hlo_text


def probe(tr, state):
    """Trained latent-table rows (the parity comparison payload)."""
    import jax
    from functools import partial
    from jax.sharding import PartitionSpec as P
    from openembedding_tpu.parallel.sharded import sharded_lookup

    spec = tr.model.specs["latent"]
    pull = jax.jit(jax.shard_map(
        partial(sharded_lookup, spec, axis=tr.axis), mesh=tr.mesh,
        in_specs=(tr._table_pspec(spec), P()), out_specs=P(),
        check_vma=False))
    return np.asarray(pull(state.tables["latent"],
                           np.arange(VOCAB, dtype=np.int32)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--steps", type=int, default=4)
    args = ap.parse_args()

    result = {"metric": "wire_bf16_bytes_ratio", "value": None, "unit": "x",
              "vs_baseline": None}
    extra, errors = {}, {}
    try:
        from openembedding_tpu.ops import wire as wire_mod  # noqa: F401
        from tools.oelint.passes.hlo_budget import collective_payloads

        bs = batches(args.batch, args.steps)
        runs = {}
        for label, (fmt, fused) in {
            "unfused_fp32": ("fp32", False),
            "fused_fp32": ("fp32", True),
            "fused_bf16": ("bf16", True),
            "fused_int8": ("int8", True),
            # round-17 per-table mixed wire: the dim-16 group splits on
            # (dim, fmt) into an int8 and an fp32 a2a group (9 collectives,
            # not 6) — the analytic model must price every mixed-format
            # group exactly (delta 0), same as the uniform modes
            "fused_mixed": ({"latent": "int8", "*": "fp32"}, True),
        }.items():
            tr, state, ms, hlo_text = train(fmt, fused, bs)
            runs[label] = (tr, state)
            cost = tr.last_wire_cost
            # real compiled bytes from the same counter the oelint
            # hlo-budget pass pins — the analytic model must agree
            payloads = collective_payloads(hlo_text)
            hlo_a2a = sum(b for k, _, b in payloads if k == "all_to_all")
            model = (cost["bytes_per_step"]
                     + cost.get("hot_a2a_bytes", 0))
            extra[label] = {
                "step_ms": round(ms, 2),
                "collectives_per_step": cost["collectives_per_step"],
                "wire_bytes_per_step": cost["bytes_per_step"],
                "hlo_a2a_bytes": hlo_a2a,
                "hlo_a2a_dtypes": ",".join(sorted(
                    {d for k, d, _ in payloads if k == "all_to_all"})),
                "model_vs_hlo_delta": hlo_a2a - model,
            }
            print(f"[wire] {label:13s}: {ms:8.2f} ms/step, "
                  f"{cost['collectives_per_step']} a2a, "
                  f"model {cost['bytes_per_step']} B/step/device, "
                  f"HLO {hlo_a2a} B "
                  f"({extra[label]['hlo_a2a_dtypes']}), "
                  f"delta {extra[label]['model_vs_hlo_delta']}",
                  file=sys.stderr, flush=True)
            assert extra[label]["model_vs_hlo_delta"] == 0, (
                label, extra[label])

        # parity: lossy wire within format tolerance of fp32; storage fp32
        base = probe(*runs["fused_fp32"])
        exactf = probe(*runs["unfused_fp32"])
        np.testing.assert_array_equal(base, exactf)  # fusion is transparent
        for label, tol in (("fused_bf16", 0.02), ("fused_int8", 0.06),
                           ("fused_mixed", 0.06)):  # latent rides int8
            got = probe(*runs[label])
            err = np.abs(got - base).max()
            scale = max(np.abs(base).max(), 1e-6)
            extra[label]["max_abs_err_vs_fp32"] = float(err)
            assert err <= tol * scale + tol, (label, err)
            ts = runs[label][1].tables["latent"]
            assert str(ts.weights.dtype) == "float32"
        extra["parity"] = "fused==unfused bit-exact; bf16/int8 within tol"

        ratio = (extra["fused_fp32"]["wire_bytes_per_step"]
                 / extra["fused_bf16"]["wire_bytes_per_step"])
        result["value"] = round(ratio, 3)
        # vs_baseline: the acceptance floor (>= 1.7x fewer exchange bytes;
        # see module docstring for the round-13 re-anchor)
        result["vs_baseline"] = round(ratio / 1.7, 3)
        extra["int8_bytes_ratio"] = round(
            extra["fused_fp32"]["wire_bytes_per_step"]
            / extra["fused_int8"]["wire_bytes_per_step"], 3)
        extra["fused_speedup_fp32"] = round(
            extra["unfused_fp32"]["step_ms"]
            / extra["fused_fp32"]["step_ms"], 3)
    except Exception as e:  # noqa: BLE001 — recorded in the stanza
        errors["wire"] = f"{type(e).__name__}: {e}"[:500]
        traceback.print_exc(file=sys.stderr)

    if extra:
        result["extra"] = extra
    if errors:
        result["errors"] = errors
    print(json.dumps(result), flush=True)
    return 0 if result["value"] is not None and not errors else 1


if __name__ == "__main__":
    sys.exit(main())
