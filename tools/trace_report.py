"""Turn a `trace.dump_chrome()` dump into a per-group latency table.

    python tools/trace_report.py /tmp/serving_trace.json
    python tools/trace_report.py /tmp/serving_trace.json --by name --sort p99
    python tools/trace_report.py http://127.0.0.1:8501/tracez

Reads the Chrome-trace JSON the flight recorder exports (`utils/trace.py
dump_chrome`, serving `--trace-dump`, examples `--trace-dump`) — or, given
an `http(s)://` URL, fetches a RUNNING node's `GET /tracez` ring live, so an
operator can profile without a restart — aggregates the complete ("X")
events per span name (or per group/category with `--by group`) and prints
count / mean / p50 / p95 / p99 / max / total milliseconds — the offline twin
of the live `/metrics` histograms, with the advantage that it works on a
dump mailed from a production node.
"""

from __future__ import annotations

import argparse
import json
from typing import Dict, List


def _tracez_events(doc: dict) -> List[dict]:
    """A live `GET /tracez` body ({"spans": [...], "events": [...]},
    `Span.as_dict` shape) -> Chrome-trace "X" event dicts the aggregator
    already understands (ms -> us for `dur`)."""
    out = []
    for s in doc.get("spans", []):
        out.append({"ph": "X", "name": str(s.get("name", "?")),
                    "cat": str(s.get("group", "?")),
                    "dur": float(s.get("duration_ms") or 0.0) * 1e3})
    return out


def load_events(path: str) -> List[dict]:
    """Chrome-trace dump path, or an `http(s)://` URL to a node (its
    `/tracez` is fetched — appended automatically when missing)."""
    if path.startswith(("http://", "https://")):
        import urllib.request
        url = path.rstrip("/")
        if not url.endswith("/tracez"):
            url = f"{url}/tracez"
        with urllib.request.urlopen(url, timeout=10.0) as r:
            return _tracez_events(json.loads(r.read().decode()))
    with open(path) as f:
        doc = json.load(f)
    events = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    if not isinstance(events, list):
        raise ValueError(f"{path}: not a Chrome-trace dump "
                         "(no traceEvents array)")
    return events


def report(events: List[dict], by: str = "name") -> List[dict]:
    """-> rows [{key, count, mean_ms, p50_ms, p95_ms, p99_ms, max_ms,
    total_ms}], slowest p99 first. `by`: "name" (span name) or "group"
    (Chrome-trace category)."""
    import numpy as np

    if by not in ("name", "group"):
        raise ValueError(f"by={by!r}: expected 'name' or 'group'")
    field = "name" if by == "name" else "cat"
    groups: Dict[str, List[float]] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        key = str(ev.get(field, "?"))
        groups.setdefault(key, []).append(float(ev.get("dur", 0.0)) / 1e3)
    rows = []
    for key, durs in groups.items():
        d = np.asarray(durs)
        rows.append({"key": key, "count": int(d.size),
                     "mean_ms": float(d.mean()),
                     "p50_ms": float(np.percentile(d, 50)),
                     "p95_ms": float(np.percentile(d, 95)),
                     "p99_ms": float(np.percentile(d, 99)),
                     "max_ms": float(d.max()),
                     "total_ms": float(d.sum())})
    rows.sort(key=lambda r: r["p99_ms"], reverse=True)
    return rows


def format_table(rows: List[dict]) -> str:
    if not rows:
        return "(no complete spans in dump)"
    cols = ("count", "mean_ms", "p50_ms", "p95_ms", "p99_ms", "max_ms",
            "total_ms")
    width = max(len("span"), max(len(r["key"]) for r in rows))
    head = "span".ljust(width) + "".join(c.rjust(12) for c in cols)
    lines = [head, "-" * len(head)]
    for r in rows:
        cells = "".join(
            (f"{r[c]:d}" if c == "count" else f"{r[c]:.3f}").rjust(12)
            for c in cols)
        lines.append(r["key"].ljust(width) + cells)
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-group latency table from a trace.dump_chrome() dump")
    ap.add_argument("dump", help="Chrome-trace JSON path, or a live node's "
                                 "http(s)://host:port[/tracez] URL")
    ap.add_argument("--by", choices=("name", "group"), default="name",
                    help="aggregate per span name (default) or per group")
    ap.add_argument("--sort", choices=("p50", "p95", "p99", "mean", "max",
                                       "total", "count"), default="p99",
                    help="sort column (descending)")
    args = ap.parse_args(argv)
    rows = report(load_events(args.dump), by=args.by)
    key = args.sort if args.sort == "count" else f"{args.sort}_ms"
    rows.sort(key=lambda r: r[key], reverse=True)
    print(format_table(rows))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
