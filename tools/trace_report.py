"""Turn `trace.dump_chrome()` dumps into a latency table or a stitched tree.

    python tools/trace_report.py /tmp/serving_trace.json
    python tools/trace_report.py /tmp/serving_trace.json --by name --sort p99
    python tools/trace_report.py http://127.0.0.1:8501/tracez
    python tools/trace_report.py sub_dump.json pub_dump.json --trace <rid>

Reads the Chrome-trace JSON the flight recorder exports (`utils/trace.py
dump_chrome`, serving `--trace-dump`, examples `--trace-dump`) — or, given
an `http(s)://` URL, fetches a RUNNING node's `GET /tracez` ring live, so an
operator can profile without a restart — aggregates the complete ("X")
events per span name (or per group/category with `--by group`) and prints
count / mean / p50 / p95 / p99 / max / total milliseconds — the offline twin
of the live `/metrics` histograms, with the advantage that it works on a
dump mailed from a production node.

`--trace <request_id>` switches to the STITCHED-TREE view: spans of that
trace are collected across every given dump (one per process — e.g. the
subscriber node's and the publisher node's), linked by their
process-qualified `span_uid`/`parent_uid` args and, ACROSS the HTTP
boundary, by `remote_parent` (the caller's span uid the callee's root span
recorded off the `X-OETPU-Trace` header), and printed as one indented
cross-process tree.
"""

from __future__ import annotations

import argparse
import json
from typing import Dict, List


def _tracez_events(doc: dict) -> List[dict]:
    """A live `GET /tracez` body ({"spans": [...], "events": [...]},
    `Span.as_dict` shape) -> Chrome-trace "X" event dicts the aggregator
    already understands (ms -> us for `dur`)."""
    out = []
    for s in doc.get("spans", []):
        proc = s.get("process")
        args = {k: v for k, v in (("request_id", s.get("request_id")),
                                  ("span_id", s.get("span_id")),
                                  ("remote_parent", s.get("remote_parent")))
                if v is not None}
        if proc is not None and s.get("span_id") is not None:
            args["span_uid"] = f"{proc}:{s['span_id']}"
            if s.get("parent_id") is not None:
                args["parent_uid"] = f"{proc}:{s['parent_id']}"
        out.append({"ph": "X", "name": str(s.get("name", "?")),
                    "cat": str(s.get("group", "?")),
                    "ts": float(s.get("start") or 0.0) * 1e6,
                    "dur": float(s.get("duration_ms") or 0.0) * 1e3,
                    "args": args})
    return out


def load_events(path: str) -> List[dict]:
    """Chrome-trace dump path, or an `http(s)://` URL to a node (its
    `/tracez` is fetched — appended automatically when missing)."""
    if path.startswith(("http://", "https://")):
        import urllib.request
        url = path.rstrip("/")
        if not url.endswith("/tracez"):
            url = f"{url}/tracez"
        with urllib.request.urlopen(url, timeout=10.0) as r:
            return _tracez_events(json.loads(r.read().decode()))
    with open(path) as f:
        doc = json.load(f)
    events = doc.get("traceEvents", doc if isinstance(doc, list) else [])
    if not isinstance(events, list):
        raise ValueError(f"{path}: not a Chrome-trace dump "
                         "(no traceEvents array)")
    return events


def report(events: List[dict], by: str = "name") -> List[dict]:
    """-> rows [{key, count, mean_ms, p50_ms, p95_ms, p99_ms, max_ms,
    total_ms}], slowest p99 first. `by`: "name" (span name) or "group"
    (Chrome-trace category)."""
    import numpy as np

    if by not in ("name", "group"):
        raise ValueError(f"by={by!r}: expected 'name' or 'group'")
    field = "name" if by == "name" else "cat"
    groups: Dict[str, List[float]] = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        key = str(ev.get(field, "?"))
        groups.setdefault(key, []).append(float(ev.get("dur", 0.0)) / 1e3)
    rows = []
    for key, durs in groups.items():
        d = np.asarray(durs)
        rows.append({"key": key, "count": int(d.size),
                     "mean_ms": float(d.mean()),
                     "p50_ms": float(np.percentile(d, 50)),
                     "p95_ms": float(np.percentile(d, 95)),
                     "p99_ms": float(np.percentile(d, 99)),
                     "max_ms": float(d.max()),
                     "total_ms": float(d.sum())})
    rows.sort(key=lambda r: r["p99_ms"], reverse=True)
    return rows


def format_table(rows: List[dict]) -> str:
    if not rows:
        return "(no complete spans in dump)"
    cols = ("count", "mean_ms", "p50_ms", "p95_ms", "p99_ms", "max_ms",
            "total_ms")
    width = max(len("span"), max(len(r["key"]) for r in rows))
    head = "span".ljust(width) + "".join(c.rjust(12) for c in cols)
    lines = [head, "-" * len(head)]
    for r in rows:
        cells = "".join(
            (f"{r[c]:d}" if c == "count" else f"{r[c]:.3f}").rjust(12)
            for c in cols)
        lines.append(r["key"].ljust(width) + cells)
    return "\n".join(lines)


def trace_tree(events: List[dict], request_id: str) -> List[str]:
    """One trace's spans across N processes' dumps as an indented tree.

    Spans link locally by `span_uid` -> `parent_uid` and across the HTTP
    boundary by `remote_parent` (both args `chrome_events` emits); a span
    whose parent is in no dump renders as a root. Siblings sort by start
    time. Lines carry the owning process id so the hop between processes is
    visible in the stitched rendering."""
    spans = [ev for ev in events
             if ev.get("ph") == "X"
             and (ev.get("args") or {}).get("request_id") == request_id
             and (ev.get("args") or {}).get("span_uid")]
    by_uid = {ev["args"]["span_uid"]: ev for ev in spans}
    children: Dict[str, List[dict]] = {}
    roots = []
    for ev in spans:
        a = ev["args"]
        parent = a.get("parent_uid") or a.get("remote_parent")
        if parent is not None and parent in by_uid:
            children.setdefault(parent, []).append(ev)
        else:
            roots.append(ev)
    lines: List[str] = []

    def emit(ev: dict, depth: int) -> None:
        a = ev["args"]
        proc = str(a.get("span_uid", ":")).split(":")[0]
        hop = " <-remote" if (a.get("remote_parent")
                              and not a.get("parent_uid")) else ""
        lines.append(f"{'  ' * depth}{ev.get('cat', '?')}.{ev['name']} "
                     f"[{proc}] {float(ev.get('dur', 0.0)) / 1e3:.3f}ms"
                     f"{hop}")
        for c in sorted(children.get(a["span_uid"], []),
                        key=lambda e: float(e.get("ts", 0.0))):
            emit(c, depth + 1)

    for r in sorted(roots, key=lambda e: float(e.get("ts", 0.0))):
        emit(r, 0)
    return lines


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-group latency table (or, with --trace, a stitched "
                    "cross-process span tree) from trace.dump_chrome() dumps")
    ap.add_argument("dump", nargs="+",
                    help="Chrome-trace JSON path(s), or live node "
                         "http(s)://host:port[/tracez] URL(s)")
    ap.add_argument("--by", choices=("name", "group"), default="name",
                    help="aggregate per span name (default) or per group")
    ap.add_argument("--sort", choices=("p50", "p95", "p99", "mean", "max",
                                       "total", "count"), default="p99",
                    help="sort column (descending)")
    ap.add_argument("--trace", default=None, metavar="REQUEST_ID",
                    help="render ONE trace as a stitched cross-process span "
                         "tree instead of the latency table")
    args = ap.parse_args(argv)
    events: List[dict] = []
    for path in args.dump:
        events.extend(load_events(path))
    if args.trace is not None:
        lines = trace_tree(events, args.trace)
        print("\n".join(lines) if lines
              else f"(no spans for trace {args.trace!r})")
        return 0
    rows = report(events, by=args.by)
    key = args.sort if args.sort == "count" else f"{args.sort}_ms"
    rows.sort(key=lambda r: r[key], reverse=True)
    print(format_table(rows))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
