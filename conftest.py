"""Repo-level pytest bootstrap.

Every Python interpreter in this image claims the single axon TPU at startup
(`/root/.axon_site/sitecustomize.py`); concurrent claims block each other. Tests run on
a virtual 8-device CPU mesh (SURVEY.md §4) and must neither hold nor contend for that
claim, so pytest re-execs itself once in a cleaned environment before any JAX backend
initializes. Benchmarks (`bench.py`) are the only thing that should touch the real TPU.
"""

import os
import sys

if os.environ.get("PALLAS_AXON_POOL_IPS") and os.environ.get("_OE_TPU_TEST_REEXEC") != "1":
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["_OE_TPU_TEST_REEXEC"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
    os.execve(sys.executable, [sys.executable, "-m", "pytest"] + sys.argv[1:], env)
