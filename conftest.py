"""Repo-level pytest bootstrap.

Every Python interpreter in this image registers the axon TPU backend at startup
(`/root/.axon_site/sitecustomize.py`) and claims the single real TPU chip the first
time a JAX backend initializes; concurrent claims block each other. Tests run on a
virtual 8-device CPU mesh (SURVEY.md §4) and must neither hold nor contend for that
claim, so before any backend initializes we (a) point XLA at 8 virtual host devices
and (b) flip jax's platform selection to cpu — the registered axon plugin is then
never instantiated and the chip is never claimed. Benchmarks (`bench.py`) are the
only thing that should touch the real TPU.

(An earlier version re-exec'd the interpreter with a cleaned env; that silently
swallowed all pytest output because pytest's capture already owned fd 1 when the
execve ran.)
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
