"""63-bit ids in the DEFAULT config (jax_enable_x64=False): the split-pair
uint32 layout (`ops/id64.py`) must carry the full id through dedup, routing,
probing, training, and checkpoints — the reference's `input_dim=-1` -> 2^63
claim (`variable/Meta.h:44-46`) without int64 arrays.

THE regression: ids congruent mod 2^32 (e.g. 5 and 5 + 2^32) must never
collide. The suite's conftest enables x64 globally, so every test here runs
inside `jax.enable_x64(False)` — builds AND jit calls stay inside the
context (the config is part of the trace)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import openembedding_tpu as embed
from openembedding_tpu.embedding import (EmbeddingSpec, apply_gradients,
                                         init_table_state, lookup,
                                         lookup_train)
from openembedding_tpu.initializers import Constant
from openembedding_tpu.ops.id64 import (np_join_ids, np_pair_mod,
                                        np_split_ids, pair_mod)

DIM = 4
# ids that are identical mod 2^32 — int32 truncation would alias all of them
A, B, C = 5, 5 + (1 << 32), 5 + (7 << 32)
CONGRUENT = np.asarray([A, B, C], np.int64)


def test_split_join_roundtrip():
    ids = np.asarray([0, 1, (1 << 62) + 12345, -1, (1 << 32) + 5], np.int64)
    pair = np_split_ids(ids)
    assert pair.dtype == np.uint32 and pair.shape == (5, 2)
    back = np_join_ids(pair)
    np.testing.assert_array_equal(back, ids)
    # congruent ids differ in the hi lane
    p = np_split_ids(CONGRUENT)
    assert len({tuple(r) for r in p}) == 3


def test_pair_mod_matches_int64():
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 1 << 62, size=1000).astype(np.int64)
    for m in (1, 2, 3, 7, 8, 13, 4096):
        np.testing.assert_array_equal(np_pair_mod(np_split_ids(ids), m),
                                      (ids % m).astype(np.uint32))
    with jax.enable_x64(False):
        got = np.asarray(jax.jit(lambda p: pair_mod(p, 13))(
            jnp.asarray(np_split_ids(ids))))
    np.testing.assert_array_equal(got, (ids % 13).astype(np.uint32))


def test_pair_unique_with_counts():
    from openembedding_tpu.ops.dedup import unique_with_counts
    rng = np.random.default_rng(1)
    ids = rng.integers(0, 1 << 62, size=64).astype(np.int64)
    ids = np.concatenate([ids, ids[:16], CONGRUENT])  # duplicates + congruent
    with jax.enable_x64(False):
        uniq = jax.jit(unique_with_counts)(jnp.asarray(np_split_ids(ids)))
        n_unique = int(uniq.num_unique)
        uids = np_join_ids(np.asarray(uniq.unique_ids))[:n_unique]
        counts = np.asarray(uniq.counts)[:n_unique]
        inverse = np.asarray(uniq.inverse)
    want_ids, want_counts = np.unique(ids, return_counts=True)
    np.testing.assert_array_equal(np.sort(uids), want_ids)
    # inverse maps every position back to its own id
    np.testing.assert_array_equal(
        np_join_ids(np.asarray(uniq.unique_ids))[inverse], ids)
    total = dict(zip(uids.tolist(), counts.tolist()))
    for i, c in zip(want_ids.tolist(), want_counts.tolist()):
        assert total[i] == c


def _spec(capacity=256):
    return EmbeddingSpec(name="t", input_dim=-1, output_dim=DIM,
                         capacity=capacity, variable_id=0,
                         initializer=Constant(0.0))


def test_congruent_ids_do_not_collide_x64_off():
    """Train id A; ids A+k*2^32 must still read ZERO rows, and training each
    separately keeps them distinct — int32 keys would alias all three."""
    with jax.enable_x64(False):
        spec = _spec()
        opt = embed.Adagrad(learning_rate=0.5)
        state = init_table_state(spec, opt)
        assert state.keys.ndim == 2  # split-pair layout engaged by default

        pair = jnp.asarray(np_split_ids(CONGRUENT))
        state, _ = lookup_train(spec, state, pair)
        grads = jnp.stack([jnp.full((DIM,), g, jnp.float32)
                           for g in (1.0, 2.0, 3.0)])
        state = apply_gradients(spec, state, opt, pair, grads)
        rows = np.asarray(lookup(spec, state, pair))
        # three DISTINCT rows (collision would have summed the gradients)
        assert len({tuple(np.round(r, 5)) for r in rows}) == 3
        # an untouched congruent id still reads zeros
        fresh = np.asarray(lookup(
            spec, state, jnp.asarray(np_split_ids(
                np.asarray([5 + (3 << 32)], np.int64)))))
        assert (fresh == 0).all()


def test_trainer_end_to_end_pair_x64_off():
    """Full Trainer loop in the default config with pair ids from the data
    pipeline (`synthetic_criteo(ids_dtype='pair')`)."""
    from openembedding_tpu.data import synthetic_criteo
    from openembedding_tpu.model import EmbeddingModel, Trainer
    from openembedding_tpu.models import make_deepfm

    with jax.enable_x64(False):
        base = make_deepfm(vocabulary=-1, dim=DIM, hidden=(16,), hashed=True,
                           capacity=4096)
        trainer = Trainer(base, embed.Adagrad(learning_rate=0.1))
        batches = list(synthetic_criteo(16, id_space=1 << 62, steps=3,
                                        seed=3, ids_dtype="pair"))
        assert batches[0]["sparse"]["categorical"].shape[-1] == 2
        state = trainer.init(batches[0])
        step = trainer.jit_train_step()
        for b in batches:
            state, m = step(state, b)
            assert np.isfinite(float(m["loss"]))
        assert int(state.tables["categorical"].overflow) == 0


def test_mesh_trainer_pair_x64_off():
    """The sharded exchange (dedup -> pair_mod routing -> all_to_all -> pair
    probe) on an 8-device mesh in the default config; parity vs single-device
    training of the same stream."""
    from openembedding_tpu.data import synthetic_criteo
    from openembedding_tpu.model import Trainer
    from openembedding_tpu.models import make_deepfm
    from openembedding_tpu.parallel import MeshTrainer, make_mesh

    with jax.enable_x64(False):
        S = 8

        def build(cls, loss_scale=1.0, **kw):
            import dataclasses
            m = make_deepfm(vocabulary=-1, dim=DIM, hidden=(16,), hashed=True,
                            capacity=4096)
            # Constant init so slot placement differences cannot show
            m.specs["categorical"] = dataclasses.replace(
                m.specs["categorical"], initializer=Constant(0.0))
            lf = m.loss_fn
            m.loss_fn = lambda lo, la, *a: loss_scale * lf(lo, la, *a)
            return cls(m, embed.Adagrad(learning_rate=0.1), **kw)

        batches = list(synthetic_criteo(16, id_space=1 << 62, steps=2,
                                        seed=4, ids_dtype="pair"))
        b = batches[0]
        # mesh semantics: grads SUM across shards of the batch (Horovod-SUM
        # parity) == single device with the loss scaled by S for ONE step
        single = build(Trainer, loss_scale=float(S))
        s_state = single.init(b)
        s_state, sm = single.jit_train_step()(s_state, b)
        mesh = build(MeshTrainer, mesh=make_mesh())
        m_state = mesh.init(b)
        m_state, mm = mesh.jit_train_step(b, m_state)(m_state, b)
        np.testing.assert_allclose(float(mm["loss"]), float(sm["loss"]) / S,
                                   rtol=1e-5)
        # the trained rows must be identical, read back BY ID through each
        # trainer's own pull path (slot layouts differ)
        ids = np.unique(np_join_ids(b["sparse"]["categorical"]).reshape(-1))
        pair = jnp.asarray(np_split_ids(ids))
        spec = single.model.specs["categorical"]
        want = np.asarray(lookup(spec, s_state.tables["categorical"], pair))
        from functools import partial
        from jax.sharding import PartitionSpec as P
        from openembedding_tpu.parallel.sharded import sharded_lookup
        pull = jax.jit(jax.shard_map(
            partial(sharded_lookup, mesh.model.specs["categorical"],
                    axis=mesh.axis),
            mesh=mesh.mesh,
            in_specs=(mesh._table_pspec(mesh.model.specs["categorical"]), P()),
            out_specs=P(), check_vma=False))
        got = np.asarray(pull(m_state.tables["categorical"], pair))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
        assert int(np.asarray(m_state.tables["categorical"].overflow)) == 0


def test_pair_checkpoint_roundtrip_and_cross_config(tmp_path):
    """Pair-keyed tables checkpoint as plain int64 on disk; reload into a pair
    table AND into an x64 int64 table — both serve the same rows."""
    from openembedding_tpu.data import synthetic_criteo
    from openembedding_tpu.model import Trainer
    from openembedding_tpu.models import make_deepfm

    def build():
        import dataclasses
        m = make_deepfm(vocabulary=-1, dim=DIM, hidden=(16,), hashed=True,
                        capacity=4096)
        m.specs["categorical"] = dataclasses.replace(
            m.specs["categorical"], initializer=Constant(0.0))
        return Trainer(m, embed.Adagrad(learning_rate=0.1))

    path = str(tmp_path / "ck")
    with jax.enable_x64(False):
        trainer = build()
        batches = list(synthetic_criteo(16, id_space=1 << 62, steps=3,
                                        seed=5, ids_dtype="pair"))
        state = trainer.init(batches[0])
        step = trainer.jit_train_step()
        for b in batches:
            state, _ = step(state, b)
        trainer.save(state, path)
        ids64 = np_join_ids(np.asarray(state.tables["categorical"].keys))
        ids64 = np.sort(ids64[ids64 >= 0])[:64]
        want = np.asarray(lookup(trainer.model.specs["categorical"],
                                 state.tables["categorical"],
                                 jnp.asarray(np_split_ids(ids64))))

        # reload into a FRESH pair-keyed trainer (x64 still off)
        t2 = build()
        s2 = t2.init(batches[0])
        s2 = t2.load(s2, path)
        got = np.asarray(lookup(t2.model.specs["categorical"],
                                s2.tables["categorical"],
                                jnp.asarray(np_split_ids(ids64))))
        np.testing.assert_allclose(got, want, rtol=0, atol=0)

    # cross-config: the same checkpoint loads into an int64-keyed table
    t3 = build()
    from openembedding_tpu.data import synthetic_criteo as sc
    b0 = next(sc(16, id_space=1 << 62, steps=1, seed=5))
    s3 = t3.init(b0)
    assert s3.tables["categorical"].keys.ndim == 1  # x64-on single lane
    s3 = t3.load(s3, path)
    got = np.asarray(lookup(t3.model.specs["categorical"],
                            s3.tables["categorical"], jnp.asarray(ids64)))
    np.testing.assert_allclose(got, want, rtol=0, atol=0)


def test_mesh_single_lane_ids_on_pair_table_x64_off():
    """REGRESSION: under x64-off every hash table keys in the pair layout, but
    a user feeding plain int32 ids (id space < 2^31) went through the sharded
    protocol with single-lane routing and crashed in the server-side pair
    probe. `parallel/sharded.adapt_batch_ids` now widens at the protocol
    entry; training must match the same stream fed as explicit pairs."""
    from openembedding_tpu.data import synthetic_criteo
    from openembedding_tpu.models import make_deepfm
    from openembedding_tpu.parallel import MeshTrainer, make_mesh

    def build():
        import dataclasses
        m = make_deepfm(vocabulary=-1, dim=DIM, hidden=(16,), hashed=True,
                        capacity=4096)
        m.specs["categorical"] = dataclasses.replace(
            m.specs["categorical"], initializer=Constant(0.0))
        return MeshTrainer(m, embed.Adagrad(learning_rate=0.1),
                           mesh=make_mesh())

    with jax.enable_x64(False):
        i32 = list(synthetic_criteo(16, id_space=1 << 20, steps=3, seed=6,
                                    ids_dtype=np.int32))
        pair = [dict(b, sparse={"categorical": np_split_ids(
            b["sparse"]["categorical"].astype(np.int64))}) for b in i32]

        ta = build()
        sa = ta.init(i32[0])
        assert sa.tables["categorical"].keys.ndim == 2  # pair-keyed cache
        step_a = ta.jit_train_step(i32[0], sa)
        la = []
        for b in i32:
            sa, m = step_a(sa, b)
            la.append(float(m["loss"]))

        tb = build()
        sb = tb.init(pair[0])
        step_b = tb.jit_train_step(pair[0], sb)
        lb = []
        for b in pair:
            sb, m = step_b(sb, b)
            lb.append(float(m["loss"]))

        np.testing.assert_allclose(la, lb, rtol=1e-6, atol=1e-7)
        np.testing.assert_array_equal(
            np.asarray(sa.tables["categorical"].weights),
            np.asarray(sb.tables["categorical"].weights))
