"""Pallas kernel parity vs the XLA path (interpreter mode on CPU).

The reference validates its server hot path with self-checking expected-value tests
(`entry/c_api_test.h:32-154`); here the XLA implementation in `ops/sparse.py` is the
checked-elsewhere oracle and the Pallas kernels must match it bit-for-bit (same f32
math, same masking contract)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from openembedding_tpu.ops import pallas_sparse
from openembedding_tpu.ops.sparse import lookup_rows, sparse_apply_dense_table
from openembedding_tpu import optimizers


@pytest.fixture(autouse=True)
def _pallas_off_by_default():
    """Each test drives the mode explicitly; never leak state across tests."""
    pallas_sparse.set_mode("off")
    yield
    pallas_sparse.set_mode("off")


def _rand_table(rng, n_rows, dim, dtype=jnp.float32):
    return jnp.asarray(rng.standard_normal((n_rows, dim)), dtype)


def test_gather_rows_matches_xla():
    rng = np.random.default_rng(0)
    w = _rand_table(rng, 64, 12)
    rows = jnp.asarray(rng.integers(-5, 80, size=50), jnp.int32)  # incl. OOB both ends
    ref = lookup_rows(w, rows)
    got = pallas_sparse.gather_rows(w, rows, interpret=True)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_gather_rows_valid_mask():
    rng = np.random.default_rng(1)
    w = _rand_table(rng, 32, 8)
    rows = jnp.asarray(rng.integers(0, 32, size=20), jnp.int32)
    valid = jnp.asarray(rng.integers(0, 2, size=20).astype(bool))
    ref = lookup_rows(w, rows, valid)
    got = pallas_sparse.gather_rows(w, rows, valid, interpret=True)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_gather_rows_non_divisible_block():
    rng = np.random.default_rng(2)
    w = _rand_table(rng, 300, 9)  # dim 9: the reference benchmark dim, unaligned
    rows = jnp.asarray(rng.integers(0, 300, size=37), jnp.int32)
    ref = lookup_rows(w, rows)
    got = pallas_sparse.gather_rows(w, rows, block=16, interpret=True)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


ALL_OPTS = [
    optimizers.Default(learning_rate=0.1),
    optimizers.SGD(learning_rate=0.05, momentum=0.9, nesterov=True),
    optimizers.Adagrad(learning_rate=0.1),
    optimizers.Adadelta(learning_rate=0.5),
    optimizers.Adam(learning_rate=0.01),
    optimizers.Adamax(learning_rate=0.01),
    optimizers.Ftrl(learning_rate=0.05, l1_regularization_strength=0.01,
                    l2_regularization_strength=0.01),
    optimizers.RMSprop(learning_rate=0.05, momentum=0.5),
    optimizers.TestOptimizer(),
]


@pytest.mark.parametrize("opt", ALL_OPTS, ids=lambda o: o.category)
def test_fused_apply_matches_xla(opt):
    rng = np.random.default_rng(3)
    n_rows, dim, n = 64, 12, 40
    w = _rand_table(rng, n_rows, dim)
    slots = opt.init_slots(n_rows, dim)
    # warm the slots so non-trivial state paths are exercised
    ids0 = jnp.asarray(rng.integers(0, n_rows, size=n))
    g0 = jnp.asarray(rng.standard_normal((n, dim)), jnp.float32)
    w, slots = sparse_apply_dense_table(opt, w, slots, ids0, g0)

    ids = jnp.asarray(rng.integers(0, n_rows, size=n))  # duplicates likely
    g = jnp.asarray(rng.standard_normal((n, dim)), jnp.float32)

    ref_w, ref_s = sparse_apply_dense_table(opt, w, slots, ids, g)
    pallas_sparse.set_mode("interpret")
    got_w, got_s = sparse_apply_dense_table(opt, w, slots, ids, g)

    # rtol covers ftrl's slightly different operation order in the kernel (~1e-7 rel)
    np.testing.assert_allclose(np.asarray(ref_w), np.asarray(got_w),
                               rtol=2e-6, atol=1e-6)
    for k in ref_s:
        np.testing.assert_allclose(np.asarray(ref_s[k]), np.asarray(got_s[k]),
                                   rtol=2e-6, atol=1e-6, err_msg=k)


def test_fused_apply_padding_rows_untouched():
    """counts == 0 and out-of-range rows must leave the table bit-identical."""
    rng = np.random.default_rng(4)
    opt = optimizers.Adagrad(learning_rate=0.1)
    n_rows, dim = 32, 8
    w = _rand_table(rng, n_rows, dim)
    slots = opt.init_slots(n_rows, dim)
    rows = jnp.asarray([3, 7, n_rows, -1, 3 + n_rows * 10], jnp.int32)
    counts = jnp.asarray([1, 2, 1, 1, 1], jnp.int32)
    grads = jnp.asarray(rng.standard_normal((5, dim)), jnp.float32)
    new_w, new_s = pallas_sparse.fused_sparse_apply(
        opt, w, slots, rows, grads, counts, interpret=True)
    touched = {3, 7}
    for r in range(n_rows):
        if r in touched:
            assert not np.allclose(np.asarray(new_w[r]), np.asarray(w[r]))
        else:
            np.testing.assert_array_equal(np.asarray(new_w[r]), np.asarray(w[r]))
            np.testing.assert_array_equal(np.asarray(new_s["accum"][r]),
                                          np.asarray(slots["accum"][r]))


def test_fused_apply_bf16_table():
    """bf16 weights: f32 update math, bf16 store (slots stay f32)."""
    rng = np.random.default_rng(5)
    opt = optimizers.Adam(learning_rate=0.05)
    n_rows, dim, n = 48, 16, 24
    w = _rand_table(rng, n_rows, dim, jnp.bfloat16)
    slots = opt.init_slots(n_rows, dim)
    ids = jnp.asarray(rng.integers(0, n_rows, size=n))
    g = jnp.asarray(rng.standard_normal((n, dim)), jnp.float32)
    ref_w, ref_s = sparse_apply_dense_table(opt, w, slots, ids, g)
    pallas_sparse.set_mode("interpret")
    got_w, got_s = sparse_apply_dense_table(opt, w, slots, ids, g)
    np.testing.assert_array_equal(np.asarray(ref_w, np.float32),
                                  np.asarray(got_w, np.float32))
    for k in ref_s:
        np.testing.assert_allclose(np.asarray(ref_s[k]), np.asarray(got_s[k]),
                                   atol=1e-6)


def test_hash_table_apply_via_pallas():
    """The hash push path routes slots through the same fused apply."""
    from openembedding_tpu.embedding import (EmbeddingSpec, apply_gradients,
                                             init_table_state, lookup_train)
    rng = np.random.default_rng(6)
    spec = EmbeddingSpec(name="h", input_dim=-1, output_dim=8, capacity=128,
                         variable_id=0)
    opt = optimizers.Adagrad(learning_rate=0.1)
    state = init_table_state(spec, opt)
    ids = jnp.asarray(rng.integers(0, 1 << 40, size=30).astype(np.int64))
    state, _ = lookup_train(spec, state, ids)
    grads = jnp.asarray(rng.standard_normal((30, 8)), jnp.float32)

    ref = apply_gradients(spec, state, opt, ids, grads)
    pallas_sparse.set_mode("interpret")
    got = apply_gradients(spec, state, opt, ids, grads)
    np.testing.assert_allclose(np.asarray(ref.weights), np.asarray(got.weights),
                               atol=1e-6)


def test_single_device_train_step_with_pallas():
    """Whole Trainer step under interpret mode stays numerically on the XLA path."""
    import openembedding_tpu as embed
    from openembedding_tpu.model import Trainer
    from openembedding_tpu.models import make_deepfm
    from openembedding_tpu.data import synthetic_criteo

    model = make_deepfm(vocabulary=1 << 12, dim=8)
    batch = next(synthetic_criteo(64, id_space=1 << 12, steps=1, seed=0))

    def run():
        trainer = Trainer(model, embed.Adagrad(learning_rate=0.05), seed=1)
        state = trainer.init(batch)
        state, metrics = trainer.jit_train_step()(state, batch)
        return float(metrics["loss"]), state

    loss_ref, state_ref = run()
    pallas_sparse.set_mode("interpret")
    loss_got, state_got = run()
    assert np.isfinite(loss_got)
    np.testing.assert_allclose(loss_got, loss_ref, atol=1e-6)
    for name in state_ref.tables:
        np.testing.assert_allclose(
            np.asarray(state_ref.tables[name].weights),
            np.asarray(state_got.tables[name].weights), atol=1e-6)


def test_env_mode_validated(monkeypatch):
    """Round-1 advisor: OETPU_PALLAS=garbage must not silently enable Pallas."""
    monkeypatch.setenv("OETPU_PALLAS", "TRUE")
    with pytest.warns(RuntimeWarning, match="OETPU_PALLAS"):
        assert pallas_sparse._env_mode() == "off"
    monkeypatch.setenv("OETPU_PALLAS", "interpret")
    assert pallas_sparse._env_mode() == "interpret"


def test_gather_rows_windows_matches_xla():
    """Window-batched gather (PERF lever #1): sorted, clustered, uniform, and
    OOB ids all match the XLA oracle."""
    rng = np.random.default_rng(3)
    w = _rand_table(rng, 1000, 12)
    # clustered (frequency-relabeled shape): many ids in the hot low range
    hot = np.sort(rng.integers(0, 64, size=40))
    cold = np.sort(rng.integers(64, 1000, size=24))
    for rows_np in (
        np.concatenate([hot, cold]),                      # sorted, clustered
        rng.integers(0, 1000, size=77),                   # unsorted uniform
        np.asarray([0, 1, 2, 998, 999]),                  # table-edge windows
        np.asarray([-3, 5, 1005]),                        # OOB both ends
    ):
        rows = jnp.asarray(rows_np, jnp.int32)
        ref = lookup_rows(w, rows)
        got = pallas_sparse.gather_rows_windows(w, rows, window=16,
                                                interpret=True)
        np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_gather_rows_windows_small_table_falls_back():
    rng = np.random.default_rng(4)
    w = _rand_table(rng, 8, 4)  # table smaller than the window
    rows = jnp.asarray([0, 3, 7, 9, -1], jnp.int32)
    ref = lookup_rows(w, rows)
    got = pallas_sparse.gather_rows_windows(w, rows, window=16,
                                            interpret=True)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_gather_rows_windows_multiblock():
    rng = np.random.default_rng(5)
    w = _rand_table(rng, 4096, 8)
    rows = jnp.asarray(np.sort(rng.integers(0, 4096, size=700)), jnp.int32)
    ref = lookup_rows(w, rows)
    got = pallas_sparse.gather_rows_windows(w, rows, window=32, block=256,
                                            interpret=True)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
