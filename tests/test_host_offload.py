"""Host-offload (two-tier) table: small device cache must train EXACTLY like an
infinite device table (the reference's DRAM-cache-over-PMem design,
`variable/PmemEmbeddingTable.h`), with weights AND optimizer state surviving
evict/re-admit round trips."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import openembedding_tpu as embed
from openembedding_tpu.embedding import (EmbeddingSpec, apply_gradients,
                                         init_table_state, lookup,
                                         lookup_train)
from openembedding_tpu.initializers import Constant
from openembedding_tpu.tables.host_offload import HostOffloadTable, HostStore

DIM = 4


def _spec(capacity, initializer=None, name="t"):
    return EmbeddingSpec(name=name, input_dim=-1, output_dim=DIM,
                         capacity=capacity, variable_id=0,
                         initializer=initializer or Constant(0.0))


def _train_rounds(table_state_or_offload, spec, opt, rounds, offload=None):
    """Run pull+push rounds over a cycling id stream; returns per-round ids."""
    rng = np.random.default_rng(7)
    seen = []
    for r in range(rounds):
        ids = jnp.asarray(rng.integers(0, 1 << 30, size=12).astype(np.int64))
        seen.append(np.asarray(ids))
        grads = jnp.asarray(rng.standard_normal((12, DIM)), jnp.float32)
        if offload is not None:
            offload.prepare(ids)
            state, _ = lookup_train(spec, offload.state, ids)
            offload.state = apply_gradients(spec, state, opt, ids, grads)
        else:
            state, _ = lookup_train(spec, table_state_or_offload, ids)
            table_state_or_offload = apply_gradients(spec, state, opt, ids,
                                                     grads)
    return table_state_or_offload, seen


def test_store_lookup_merge():
    store = HostStore(DIM, {"accum": DIM})
    hit, w, s = store.lookup(np.asarray([5, 9], np.int64))
    assert not hit.any() and (w == 0).all()
    store.merge(np.asarray([9, 5], np.int64), np.ones((2, DIM), np.float32),
                {"accum": np.full((2, DIM), 2.0, np.float32)})
    hit, w, s = store.lookup(np.asarray([5, 7, 9], np.int64))
    np.testing.assert_array_equal(hit, [True, False, True])
    assert (w[0] == 1).all() and (w[1] == 0).all()
    # upsert overwrites
    store.merge(np.asarray([5], np.int64), np.full((1, DIM), 3.0, np.float32),
                {"accum": np.zeros((1, DIM), np.float32)})
    _, w, _ = store.lookup(np.asarray([5], np.int64))
    assert (w[0] == 3).all()
    assert len(store) == 2 and store.nbytes() > 0


def test_offload_equals_infinite_table():
    """10 rounds over ~100 unique ids with a 32-slot cache (forced flushes) must
    produce the same per-id weights as one big uncached table."""
    opt = embed.Adagrad(learning_rate=0.3)
    big_spec = _spec(4096)
    big = init_table_state(big_spec, opt)
    big, seen = _train_rounds(big, big_spec, opt, rounds=10)

    small_spec = _spec(32)
    off = HostOffloadTable(small_spec, opt, high_water=0.8)
    _, seen2 = _train_rounds(None, small_spec, opt, rounds=10, offload=off)
    assert [s.tolist() for s in seen] == [s.tolist() for s in seen2]
    assert off.store.ids.size > 0  # flushes really happened

    all_ids = np.unique(np.concatenate(seen))
    want = np.asarray(lookup(big_spec, big, jnp.asarray(all_ids)))
    got = off.lookup_anywhere(all_ids)
    np.testing.assert_allclose(want, got, rtol=1e-6, atol=1e-6)


def test_offload_optimizer_state_round_trips():
    """Adagrad accumulators must survive evict + re-admit bit-exactly: train id
    A, force eviction via other ids, train A again — accum == two uncached
    updates."""
    opt = embed.Adagrad(learning_rate=0.5)
    spec = _spec(16)
    off = HostOffloadTable(spec, opt, high_water=0.5)
    A = jnp.asarray([12345], jnp.int64)
    g = jnp.ones((1, DIM), jnp.float32)

    off.prepare(A)
    st, _ = lookup_train(spec, off.state, A)
    off.state = apply_gradients(spec, st, opt, A, g)
    # evict A by filling the cache past high water
    filler = jnp.asarray(np.arange(100, 100 + 12, dtype=np.int64))
    off.prepare(filler)
    assert not off.is_resident(12345)  # flushed to host
    off.prepare(A)                      # re-admitted with state
    st, _ = lookup_train(spec, off.state, A)
    off.state = apply_gradients(spec, st, opt, A, g)

    ref_spec = _spec(64)
    ref = init_table_state(ref_spec, opt)
    for _ in range(2):
        ref, _ = lookup_train(ref_spec, ref, A)
        ref = apply_gradients(ref_spec, ref, opt, A, g)
    want = np.asarray(lookup(ref_spec, ref, A))
    got = off.lookup_anywhere(np.asarray(A))
    np.testing.assert_array_equal(want, got)


def test_offload_with_trainer_step():
    """End to end with the Trainer: small cache, loss finite, rows round-trip."""
    from openembedding_tpu.model import Trainer
    from openembedding_tpu.models import make_sasrec  # any model works; use LR
    from openembedding_tpu.models import make_lr
    from openembedding_tpu.data import synthetic_criteo

    model = make_lr(vocabulary=-1, hashed=True, capacity=256)
    spec = model.specs["categorical"]
    opt = embed.Adagrad(learning_rate=0.1)
    trainer = Trainer(model, opt)
    off = HostOffloadTable(spec, opt, high_water=0.5)
    batches = list(synthetic_criteo(8, id_space=1 << 40, steps=6, seed=3))
    state = trainer.init(batches[0])
    step = trainer.jit_train_step()
    for b in batches:
        off.prepare(b["sparse"]["categorical"])
        state = state.replace(tables={"categorical": off.state})
        state, m = step(state, b)
        off.state = state.tables["categorical"]
        assert np.isfinite(float(m["loss"]))
    assert off.resident_count > 0


def test_flush_triggering_batch_readmits_resident_ids():
    """Round-1 advisor (high): when prepare() trips the high-water flush, the
    batch's PREVIOUSLY-RESIDENT ids are evicted by that flush and must be
    admitted back with their state — otherwise the train step reinserts them
    at initializer values and their weights/optimizer state are lost."""
    opt = embed.Adagrad(learning_rate=0.5)
    spec = _spec(32)
    off = HostOffloadTable(spec, opt, high_water=0.5)
    A = jnp.asarray([777], jnp.int64)
    g1 = jnp.ones((1, DIM), jnp.float32)

    off.prepare(A)
    st, _ = lookup_train(spec, off.state, A)
    off.state = apply_gradients(spec, st, opt, A, g1)
    # raise residency close to the high-water mark (0.5 * 32 = 16)
    filler = jnp.asarray(np.arange(100, 100 + 12, dtype=np.int64))
    off.prepare(filler)
    assert off.is_resident(777)

    # this batch CONTAINS resident id 777 and trips the flush (13 + 4 > 16)
    batch = jnp.asarray([777, 900, 901, 902, 903], jnp.int64)
    off.prepare(batch)
    assert off.is_resident(777)  # re-admitted after the flush, not dropped
    st, _ = lookup_train(spec, off.state, batch)
    g2 = jnp.full((5, DIM), 2.0, jnp.float32)
    off.state = apply_gradients(spec, st, opt, batch, g2)

    # oracle: infinite table, same two updates on id 777
    ref_spec = _spec(4096)
    ref = init_table_state(ref_spec, opt)
    ref, _ = lookup_train(ref_spec, ref, A)
    ref = apply_gradients(ref_spec, ref, opt, A, g1)
    ref, _ = lookup_train(ref_spec, ref, batch)
    ref = apply_gradients(ref_spec, ref, opt, batch, g2)
    want = np.asarray(lookup(ref_spec, ref, A))
    got = off.lookup_anywhere(np.asarray(A))
    np.testing.assert_array_equal(want, got)


def test_oversized_batch_warns_and_residency_is_truthful():
    """A single batch with more unique ids than high_water*capacity cannot fit;
    prepare() must warn, and ids whose admission overflowed must NOT be marked
    resident (they'd otherwise read zeros from the device path forever)."""
    opt = embed.Adagrad(learning_rate=0.5)
    spec = _spec(16)
    off = HostOffloadTable(spec, opt, high_water=0.5)
    big = jnp.asarray(np.arange(100, 100 + 40, dtype=np.int64))
    with pytest.warns(RuntimeWarning, match="unique ids"):
        off.prepare(big)
    assert off.resident_count <= off.capacity
    # every id marked resident really does live in the device table
    from openembedding_tpu.tables.hash_table import hash_find
    slot = hash_find(off.state.keys, jnp.asarray(off.resident_ids()))
    assert bool((np.asarray(slot) < off.capacity).all())


def test_offload_rejects_array_table():
    with pytest.raises(ValueError, match="hash-table"):
        HostOffloadTable(EmbeddingSpec(name="a", input_dim=100, output_dim=DIM,
                                       variable_id=0), embed.Adagrad())


# -- clock / second-chance eviction ------------------------------------------


def test_clock_eviction_keeps_hot_resident():
    """A stable hot set must survive evictions ON DEVICE: after pressure
    forces evictions, every hot id is still resident, cold one-shot ids went
    to the store, and no whole-cache flush happened."""
    from openembedding_tpu.utils import metrics as M

    opt = embed.Adagrad(learning_rate=0.1)
    # capacity 32, high_water 0.6 -> ~19 slots; hot set of 8 + 6 fresh ids
    # per round overflows after ~2 rounds, forcing eviction rounds
    ot = HostOffloadTable(_spec(32), opt, high_water=0.6)
    rng = np.random.default_rng(3)
    hot = rng.integers(0, 1 << 19, size=8).astype(np.int64)
    hot = np.unique(hot)
    flushes_before = M.report().get("offload.flushes", 0)
    evictions = 0
    for r in range(12):
        cold = (np.arange(6, dtype=np.int64) + (1 << 20) + 100 * r)
        ids = jnp.asarray(np.concatenate([hot, cold]))
        ot.prepare(ids)
        state, _ = lookup_train(_spec(32), ot.state, ids)
        ot.state = apply_gradients(_spec(32), state, opt, ids,
                                   jnp.ones((ids.shape[0], DIM), jnp.float32))
    # hot ids never left the device
    for h in hot:
        assert ot.is_resident(int(h)), f"hot id {h} was evicted"
    # cold ids from earlier rounds reached the store
    assert ot.store.ids.size > 0
    assert (ot.store.ids >= (1 << 20)).any()
    # and the hot set never round-tripped through the store
    store_ids = set(ot.store.ids.tolist())
    assert sum(1 for h in hot if int(h) in store_ids) == 0
    flushes_after = M.report().get("offload.flushes", 0)
    assert flushes_after == flushes_before  # clock eviction, never full flush


def test_clock_eviction_matches_infinite_table():
    """Training through eviction rounds stays lossless (Constant init):
    equal to one big in-HBM table on the same stream."""
    opt_a = embed.Adagrad(learning_rate=0.1)
    opt_b = embed.Adagrad(learning_rate=0.1)
    spec_small, spec_big = _spec(32), _spec(1 << 13)
    ot = HostOffloadTable(spec_small, opt_a, high_water=0.6)
    big = init_table_state(spec_big, opt_b, seed=0)
    rng = np.random.default_rng(5)
    hot = np.unique(rng.integers(0, 1 << 19, size=8).astype(np.int64))
    all_ids = []
    for r in range(10):
        cold = (np.arange(5, dtype=np.int64) + (1 << 20) + 64 * r)
        ids_np = np.concatenate([hot, cold])
        all_ids.append(ids_np)
        ids = jnp.asarray(ids_np)
        grads = jnp.asarray(rng.standard_normal((ids_np.size, DIM)),
                            jnp.float32)
        ot.prepare(ids)
        state, _ = lookup_train(spec_small, ot.state, ids)
        ot.state = apply_gradients(spec_small, state, opt_a, ids, grads)
        bstate, _ = lookup_train(spec_big, big, ids)
        big = apply_gradients(spec_big, bstate, opt_b, ids, grads)
    ids = np.unique(np.concatenate(all_ids))
    got = ot.lookup_anywhere(jnp.asarray(ids))
    want = np.asarray(lookup(spec_big, big, jnp.asarray(ids)))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)


def test_flush_policy_still_available():
    """eviction='flush' preserves the coarse whole-cache behavior."""
    from openembedding_tpu.utils import metrics as M
    opt = embed.Adagrad(learning_rate=0.1)
    ot = HostOffloadTable(_spec(32), opt, high_water=0.6, eviction="flush")
    before = M.report().get("offload.flushes", 0)
    rng = np.random.default_rng(9)
    for r in range(6):
        ids = jnp.asarray(rng.integers(0, 1 << 30, size=12).astype(np.int64))
        ot.prepare(ids)
    assert M.report().get("offload.flushes", 0) > before


# ---------------------------------------------------------------------------
# Round 14: the staging pipeline + densified flush
# ---------------------------------------------------------------------------


def _pipelined_rounds(off, spec, opt, batches, grads):
    """The canonical pipelined loop: stage batch 0, then per step
    prepare(current) + stage(next) so the host lookup overlaps the step."""
    off.stage(batches[0])
    for r, ids in enumerate(batches):
        off.prepare(ids)
        if r + 1 < len(batches):
            off.stage(batches[r + 1])
        st, _ = lookup_train(spec, off.state, jnp.asarray(ids))
        off.state = apply_gradients(spec, st, opt, jnp.asarray(ids),
                                    jnp.asarray(grads[r]))


def _id_stream(rounds, seed=7, size=12):
    rng = np.random.default_rng(seed)
    batches = [rng.integers(0, 1 << 20, size=size).astype(np.int64)
               for _ in range(rounds)]
    grads = [np.asarray(rng.standard_normal((size, DIM)), np.float32)
             for _ in range(rounds)]
    return batches, grads


@pytest.mark.parametrize("densify_k", [1, 4, 16])
def test_pipeline_matches_sync_path(densify_k):
    """Pipelined staging (and densified flushes) must train EXACTLY like the
    synchronous path — same per-id weights, with the staged payloads
    actually consumed (hits > 0) under churn that forces evictions."""
    opt = embed.Adagrad(learning_rate=0.3)
    batches, grads = _id_stream(rounds=12)

    base = HostOffloadTable(_spec(32), opt, high_water=0.8)
    for r, ids in enumerate(batches):
        base.prepare(ids)
        st, _ = lookup_train(base.spec, base.state, jnp.asarray(ids))
        base.state = apply_gradients(base.spec, st, opt, jnp.asarray(ids),
                                     jnp.asarray(grads[r]))

    off = HostOffloadTable(_spec(32), opt, high_water=0.8,
                           pipeline=True, densify_k=densify_k)
    _pipelined_rounds(off, off.spec, opt, batches, grads)
    assert off._pipe_hits > 0

    all_ids = np.unique(np.concatenate(batches))
    np.testing.assert_array_equal(base.lookup_anywhere(all_ids),
                                  off.lookup_anywhere(all_ids))


def test_pipeline_churn_single_admit_trace():
    """The pipelined admit path must never re-jit under churn: constant
    batch size + pow2 id padding keep the compiled admit program at AT MOST
    one new trace across 20 rounds of admissions, evictions, and flushes
    (0 when another table already compiled the shape — jit wrappers of one
    underlying function share the executable cache, and the guard budgets
    GROWTH since wrap time)."""
    opt = embed.Adagrad(learning_rate=0.1)
    batches, grads = _id_stream(rounds=20, seed=11)
    off = HostOffloadTable(_spec(32), opt, high_water=0.8, pipeline=True)
    _pipelined_rounds(off, off.spec, opt, batches, grads)
    assert off.store.ids.size > 0          # churn really flushed
    assert off._admit.trace_count() <= 1, off._admit.trace_count()


def test_pipeline_stale_stage_discarded():
    """A staged payload for the WRONG batch must be discarded (miss, never
    consumed); a residency-only change (reset_cache — the store untouched)
    REVALIDATES the staged payload against the new snapshot and accepts it
    (the round-18 ring steady state); a store MUTATION after staging
    genuinely invalidates it (miss — stale store values could overwrite
    trained rows). Every path still trains correctly."""
    from openembedding_tpu.utils import metrics as M
    opt = embed.Adagrad(learning_rate=0.2)
    off = HostOffloadTable(_spec(32), opt, high_water=0.8, pipeline=True)
    a = np.arange(100, 112, dtype=np.int64)
    b = np.arange(200, 212, dtype=np.int64)
    off.stage(a)
    off.prepare(b)          # staged ids mismatch -> miss
    assert off._pipe_misses == 1 and off._pipe_hits == 0
    assert all(off.is_resident(int(i)) for i in b)
    off.stage(a)
    off.reset_cache()       # residency-only: the staged lookup re-splits
    off.prepare(a)          # to the same non-resident set -> accepted
    assert off._pipe_misses == 1 and off._pipe_hits == 1
    assert all(off.is_resident(int(i)) for i in a)
    c = np.arange(300, 312, dtype=np.int64)
    off.stage(c)
    init = {k: np.asarray(v) for k, v in
            jax.device_get(opt.init_slots(1, DIM)).items()}
    off.store.merge(np.array([999], np.int64),
                    np.zeros((1, DIM), np.float32), init)  # version bump
    off.prepare(c)          # store mutated under the stage -> miss
    assert off._pipe_misses == 2 and off._pipe_hits == 1
    assert all(off.is_resident(int(i)) for i in c)
    assert M.report().get("offload.pipeline_occupancy") == pytest.approx(1 / 3)


def test_densified_flush_equals_direct_merges():
    """densify_k=K defers K store writebacks into ONE drained merge with
    last-wins semantics; the store contents after sync_to_store equal the
    K=1 run's exactly, and lookups BETWEEN drains see pending rows."""
    opt = embed.Adagrad(learning_rate=0.3)
    batches, grads = _id_stream(rounds=10, seed=13)

    def run(k):
        off = HostOffloadTable(_spec(16), opt, high_water=0.6, densify_k=k)
        for r, ids in enumerate(batches):
            off.prepare(ids)
            st, _ = lookup_train(off.spec, off.state, jnp.asarray(ids))
            off.state = apply_gradients(off.spec, st, opt, jnp.asarray(ids),
                                        jnp.asarray(grads[r]))
        off.sync_to_store()
        return off

    o1, o8 = run(1), run(8)
    assert o8.store.ids.size == o1.store.ids.size
    np.testing.assert_array_equal(o1.store.ids, o8.store.ids)
    np.testing.assert_array_equal(o1.store.weights, o8.store.weights)
    for name in o1.store.slots:
        np.testing.assert_array_equal(o1.store.slots[name],
                                      o8.store.slots[name])


def test_store_defer_drain_last_wins():
    """HostStore.defer/drain unit pin: pending chunks overlay lookups
    newest-first, and drain() collapses them into one last-wins merge."""
    store = HostStore(DIM, {"accum": DIM})
    ids = np.asarray([5, 9], np.int64)
    store.defer(ids, np.ones((2, DIM), np.float32),
                {"accum": np.full((2, DIM), 1.0, np.float32)})
    store.defer(np.asarray([9], np.int64),
                np.full((1, DIM), 7.0, np.float32),
                {"accum": np.full((1, DIM), 7.0, np.float32)})
    # pending rows are visible before any drain, newest wins
    hit, w, s = store.lookup(np.asarray([5, 9], np.int64))
    assert hit.all()
    assert (w[0] == 1).all() and (w[1] == 7).all()
    assert len(store) == 0          # nothing merged yet
    merged = store.drain()
    assert merged == 2 and len(store) == 2
    _, w, s = store.lookup(np.asarray([5, 9], np.int64))
    assert (w[0] == 1).all() and (w[1] == 7).all()
    assert (s["accum"][1] == 7).all()
    assert store.drain() == 0       # idempotent when nothing is pending
