"""tools/upwindow.py battery smoke: `--dry-run` renders the full case plan
(argv + env + timeout) without probing the relay or running anything — the
cheap tier-1 guard that a battery edit (new case, typo'd env knob) fails in
CI instead of at the next scarce chip up-window."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_upwindow_dry_run_lists_battery():
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "upwindow.py"),
         "--dry-run", "--skip", "bench_dim64"],
        capture_output=True, text=True, timeout=60)
    assert p.returncode == 0, p.stderr
    out = p.stdout
    # every battery entry renders, including the round-14 additions
    for name in ("bench_dim9", "bench_placement", "bench_zero",
                 "bench_offload_pipe"):
        assert f"[run ] {name}:" in out, out
    assert "[skip] bench_dim64:" in out
    # env overrides and timeouts are part of the rendered plan
    assert "OETPU_BENCH_CASES=zero" in out
    assert "OETPU_BENCH_CASES=offload_pipe" in out
    assert "timeout=" in out
    # dry run must not have touched the evidence file or probed anything
    assert "probing relay" not in out
