"""Metrics subsystem tests (reference §5: accumulators, VTIMER, periodic report,
Prometheus exposition)."""

import time

import pytest

from openembedding_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _fresh():
    metrics._REGISTRY.clear()
    yield
    metrics._REGISTRY.clear()


def test_accumulator_kinds():
    metrics.observe("a.sum", 2)
    metrics.observe("a.sum", 3)
    metrics.Accumulator.get("a.avg", "avg").observe(2)
    metrics.Accumulator.get("a.avg", "avg").observe(4)
    metrics.Accumulator.get("a.max", "max").observe(5)
    metrics.Accumulator.get("a.max", "max").observe(1)
    metrics.Accumulator.get("a.g", "gauge").observe(7)
    metrics.Accumulator.get("a.g", "gauge").observe(9)
    rep = metrics.report()
    assert rep["a.sum"] == 5
    assert rep["a.avg"] == 3
    assert rep["a.max"] == 5
    assert rep["a.g"] == 9


def test_accumulator_kind_conflict_raises():
    """Round-1 advisor: re-registering a name with a different kind must not
    silently aggregate with whichever kind ran first."""
    metrics.Accumulator.get("k", "sum").observe(1)
    with pytest.raises(ValueError, match="kind"):
        metrics.Accumulator.get("k", "gauge")
    metrics.Accumulator.get("k", "sum").observe(1)  # same kind still fine
    assert metrics.report()["k"] == 2


def test_vtimer_records():
    with metrics.vtimer("pull", "exchange"):
        time.sleep(0.01)
    rep = metrics.report()
    assert rep["pull.exchange.ms"] >= 10
    assert rep["pull.exchange.max_ms"] >= rep["pull.exchange.ms"]


def test_record_step_stats_from_device_dict():
    import jax.numpy as jnp
    metrics.record_step_stats({"categorical/pull_indices": jnp.asarray(128),
                               "categorical/pull_unique": jnp.asarray(50),
                               "categorical/pull_overflow": jnp.asarray(0)})
    rep = metrics.report()
    assert rep["categorical.pull_indices"] == 128
    assert rep["categorical.pull_unique"] == 50
    # per-table stats double as LABELED counters (per-table skew on /metrics)
    assert rep['trainer.pull_indices{table="categorical"}'] == 128


def test_record_step_stats_single_host_sync_and_mixed_types(monkeypatch):
    """The hot-path contract: ONE jax.device_get for the whole stats dict
    (per-key float() on device arrays = one host sync per stat), accepting
    jax arrays, numpy scalars, and plain floats interchangeably."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    calls = {"n": 0}
    real = jax.device_get

    def counting(x):
        calls["n"] += 1
        return real(x)

    monkeypatch.setattr(jax, "device_get", counting)
    metrics.record_step_stats({"t/pull_indices": jnp.asarray(7),
                               "t/pull_unique": np.float32(3.5),
                               "t/pull_overflow": 0.25,
                               "t/not_numeric": "skipped"})
    assert calls["n"] == 1
    rep = metrics.report()
    assert rep["t.pull_indices"] == 7
    assert rep["t.pull_unique"] == 3.5
    assert rep["t.pull_overflow"] == 0.25
    assert "t.not_numeric" not in rep


def test_report_reset():
    metrics.observe("x", 1)
    assert metrics.report(reset=True)["x"] == 1
    assert metrics.report()["x"] == 0


def test_reset_skips_gauges():
    """Regression: one-shot gauges (`exchange.*` wire costs,
    `sync.wire_bytes_per_delta`) must survive `report(reset=True)` — the
    PeriodicReporter wiped them from /metrics after its first report."""
    metrics.observe("exchange.wire_bytes_per_step", 4096, "gauge")
    metrics.observe("win.count", 2)
    rep = metrics.report(reset=True)
    assert rep["exchange.wire_bytes_per_step"] == 4096
    rep = metrics.report()
    assert rep["exchange.wire_bytes_per_step"] == 4096  # gauge survives
    assert rep["win.count"] == 0                        # counter windowed
    # the PeriodicReporter path (report_table(reset=True)) behaves the same
    metrics.PeriodicReporter(0).interval  # (construction only; no thread)
    metrics.report_table(reset=True)
    assert metrics.report()["exchange.wire_bytes_per_step"] == 4096


def test_hist_survives_reset_and_reports_quantiles():
    for v in (1.0, 2.0, 3.0, 4.0):
        metrics.observe("lat.ms", v, "hist")
    rep = metrics.report(reset=True)
    assert rep["lat.ms"] == 2.5  # mean under the bare key
    assert set(k for k in rep if k.startswith("lat.ms.")) == {
        "lat.ms.p50", "lat.ms.p95", "lat.ms.p99"}
    # histogram series are cumulative (Prometheus contract): not windowed
    assert metrics.Accumulator.get("lat.ms", "hist").count == 4


def test_prometheus_text():
    metrics.observe("pull.indices", 10)
    metrics.Accumulator.get("step.ms", "avg", help="step time").observe(5.0)
    text = metrics.prometheus_text()
    # counters carry the _total suffix (Prometheus conformance)
    assert "# TYPE oetpu_pull_indices_total counter" in text
    assert "oetpu_pull_indices_total 10.0" in text
    # avg/max kinds stay a single well-typed gauge series
    assert "# HELP oetpu_step_ms step time" in text
    assert "# TYPE oetpu_step_ms gauge" in text
    assert "oetpu_step_ms 5.0" in text


def test_prometheus_histogram_series():
    for v in (0.5, 1.0, 2.0, 400.0):
        metrics.observe("serving.predict.ms", v, "hist",
                        labels={"model": "m-0"})
    text = metrics.prometheus_text()
    assert "# TYPE oetpu_serving_predict_ms histogram" in text
    assert 'oetpu_serving_predict_ms_bucket{model="m-0",le="+Inf"} 4' in text
    assert 'oetpu_serving_predict_ms_count{model="m-0"} 4' in text
    assert 'oetpu_serving_predict_ms_sum{model="m-0"} 403.5' in text
    # cumulative bucket counts, monotone le boundaries
    import re
    pairs = re.findall(
        r'oetpu_serving_predict_ms_bucket\{model="m-0",le="([^"]+)"\} (\d+)',
        text)
    counts = [int(c) for _le, c in pairs]
    assert counts == sorted(counts) and counts[-1] == 4


def test_prometheus_label_escaping():
    metrics.observe("pull.rows", 1, "gauge",
                    labels={"table": 'we"ird\\na\nme'})
    text = metrics.prometheus_text()
    assert r'oetpu_pull_rows{table="we\"ird\\na\nme"} 1.0' in text


def test_label_series_are_distinct_and_kinds_consistent():
    metrics.observe("pull.rows_total", 3, labels={"table": "user"})
    metrics.observe("pull.rows_total", 5, labels={"table": "item"})
    metrics.observe("pull.rows_total", 1, labels={"table": "user"})
    rep = metrics.report()
    assert rep['pull.rows_total{table="user"}'] == 4
    assert rep['pull.rows_total{table="item"}'] == 5
    # one name aggregates ONE way across all its label sets
    with pytest.raises(ValueError, match="kind"):
        metrics.Accumulator.get("pull.rows_total", "gauge",
                                labels={"table": "other"})


def test_periodic_reporter():
    metrics.observe("tick", 1)
    seen = []
    rep = metrics.PeriodicReporter(0.05, sink=seen.append)
    with rep:
        time.sleep(0.2)
    assert seen and "tick" in seen[0]


def test_serving_metrics_endpoint(tmp_path):
    import json
    import threading
    import urllib.request
    from openembedding_tpu.serving import make_server

    metrics.observe("serving.requests", 3)
    httpd = make_server(str(tmp_path / "reg"), port=0)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}/metrics"
        with urllib.request.urlopen(url) as resp:
            body = resp.read().decode()
        assert "oetpu_serving_requests_total 3.0" in body
    finally:
        httpd.shutdown()


def test_auc():
    from openembedding_tpu.utils.metrics import auc
    import numpy as np
    # perfect separation
    assert auc([0, 0, 1, 1], [0.1, 0.2, 0.8, 0.9]) == 1.0
    # perfect inversion
    assert auc([1, 1, 0, 0], [0.1, 0.2, 0.8, 0.9]) == 0.0
    # random-ish mid value
    v = auc([0, 1, 0, 1], [0.4, 0.3, 0.6, 0.7])
    assert 0.0 < v < 1.0
    # one-class degenerate -> nan
    assert np.isnan(auc([1, 1], [0.5, 0.6]))
    # matches sklearn on random data when available
    try:
        from sklearn.metrics import roc_auc_score
    except Exception:
        return
    rng = np.random.default_rng(0)
    y = rng.integers(0, 2, 500)
    s = rng.random(500)
    np.testing.assert_allclose(auc(y, s), roc_auc_score(y, s), atol=1e-12)
