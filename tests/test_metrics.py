"""Metrics subsystem tests (reference §5: accumulators, VTIMER, periodic report,
Prometheus exposition)."""

import time

import pytest

from openembedding_tpu.utils import metrics


@pytest.fixture(autouse=True)
def _fresh():
    metrics._REGISTRY.clear()
    yield
    metrics._REGISTRY.clear()


def test_accumulator_kinds():
    metrics.observe("a.sum", 2)
    metrics.observe("a.sum", 3)
    metrics.Accumulator.get("a.avg", "avg").observe(2)
    metrics.Accumulator.get("a.avg", "avg").observe(4)
    metrics.Accumulator.get("a.max", "max").observe(5)
    metrics.Accumulator.get("a.max", "max").observe(1)
    metrics.Accumulator.get("a.g", "gauge").observe(7)
    metrics.Accumulator.get("a.g", "gauge").observe(9)
    rep = metrics.report()
    assert rep["a.sum"] == 5
    assert rep["a.avg"] == 3
    assert rep["a.max"] == 5
    assert rep["a.g"] == 9


def test_accumulator_kind_conflict_raises():
    """Round-1 advisor: re-registering a name with a different kind must not
    silently aggregate with whichever kind ran first."""
    metrics.Accumulator.get("k", "sum").observe(1)
    with pytest.raises(ValueError, match="kind"):
        metrics.Accumulator.get("k", "gauge")
    metrics.Accumulator.get("k", "sum").observe(1)  # same kind still fine
    assert metrics.report()["k"] == 2


def test_vtimer_records():
    with metrics.vtimer("pull", "exchange"):
        time.sleep(0.01)
    rep = metrics.report()
    assert rep["pull.exchange.ms"] >= 10
    assert rep["pull.exchange.max_ms"] >= rep["pull.exchange.ms"]


def test_record_step_stats_from_device_dict():
    import jax.numpy as jnp
    metrics.record_step_stats({"categorical/pull_indices": jnp.asarray(128),
                               "categorical/pull_unique": jnp.asarray(50),
                               "categorical/pull_overflow": jnp.asarray(0)})
    rep = metrics.report()
    assert rep["categorical.pull_indices"] == 128
    assert rep["categorical.pull_unique"] == 50


def test_report_reset():
    metrics.observe("x", 1)
    assert metrics.report(reset=True)["x"] == 1
    assert metrics.report()["x"] == 0


def test_prometheus_text():
    metrics.observe("pull.indices", 10)
    metrics.Accumulator.get("step.ms", "avg", help="step time").observe(5.0)
    text = metrics.prometheus_text()
    assert "# TYPE oetpu_pull_indices counter" in text
    assert "oetpu_pull_indices 10.0" in text
    assert "# HELP oetpu_step_ms step time" in text
    assert "# TYPE oetpu_step_ms gauge" in text


def test_periodic_reporter():
    metrics.observe("tick", 1)
    seen = []
    rep = metrics.PeriodicReporter(0.05, sink=seen.append)
    with rep:
        time.sleep(0.2)
    assert seen and "tick" in seen[0]


def test_serving_metrics_endpoint(tmp_path):
    import json
    import threading
    import urllib.request
    from openembedding_tpu.serving import make_server

    metrics.observe("serving.requests", 3)
    httpd = make_server(str(tmp_path / "reg"), port=0)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}/metrics"
        with urllib.request.urlopen(url) as resp:
            body = resp.read().decode()
        assert "oetpu_serving_requests 3.0" in body
    finally:
        httpd.shutdown()


def test_auc():
    from openembedding_tpu.utils.metrics import auc
    import numpy as np
    # perfect separation
    assert auc([0, 0, 1, 1], [0.1, 0.2, 0.8, 0.9]) == 1.0
    # perfect inversion
    assert auc([1, 1, 0, 0], [0.1, 0.2, 0.8, 0.9]) == 0.0
    # random-ish mid value
    v = auc([0, 1, 0, 1], [0.4, 0.3, 0.6, 0.7])
    assert 0.0 < v < 1.0
    # one-class degenerate -> nan
    assert np.isnan(auc([1, 1], [0.5, 0.6]))
    # matches sklearn on random data when available
    try:
        from sklearn.metrics import roc_auc_score
    except Exception:
        return
    rng = np.random.default_rng(0)
    y = rng.integers(0, 2, 500)
    s = rng.random(500)
    np.testing.assert_allclose(auc(y, s), roc_auc_score(y, s), atol=1e-12)
