"""oeweave acceptance (ISSUE 15).

The deterministic-interleaving harness must itself be trustworthy before
its verdicts mean anything, so this file pins:

- seed determinism: the same seed explores the identical schedule;
- planted torn write (read/yield/write without the lock): the explorer
  finds a failing schedule, the emitted replay token reproduces it
  deterministically, and the locked fix is clean under identical budgets;
- planted lost wakeup (flag checked outside the lock, bare `wait()`): found
  as a deadlock, token-reproducible, and the while-under-lock fix is clean;
- planted leak (worker blocked forever at scenario exit): reported as a
  WeaveLeak by the drain phase — the zero-leaked-threads assertion;
- every real control-plane scenario stays green under a small budget (the
  full budget runs in `make weave` / sync_soak --weave).
"""

import os
import sys
import threading
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if ROOT not in sys.path:
    sys.path.insert(0, ROOT)

from tools.oeweave import explore as ex  # noqa: E402
from tools.oeweave import scenarios as sc  # noqa: E402
from tools.oeweave.scheduler import (WeaveLeak,  # noqa: E402
                                     WeaveScheduler)


# ---------------------------------------------------------------------------
# planted bugs: the harness catches what it claims to catch
# ---------------------------------------------------------------------------


class _Box:
    pass


def torn_write_scenario():
    """Two writers read-modify-write a counter around a yield point with no
    lock: the classic lost update. Correct total is 2."""
    box = _Box()
    box.n = 0

    def bump():
        tmp = box.n
        time.sleep(0)  # yield point between read and write
        box.n = tmp + 1

    ts = [threading.Thread(target=bump) for _ in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert box.n == 2, f"torn write: {box.n} != 2"


def torn_write_fixed():
    box = _Box()
    box.n = 0
    lock = threading.Lock()

    def bump():
        with lock:
            tmp = box.n
            time.sleep(0)
            box.n = tmp + 1

    ts = [threading.Thread(target=bump) for _ in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert box.n == 2, f"torn write: {box.n} != 2"


def lost_wakeup_scenario():
    """Consumer checks the flag OUTSIDE the lock then waits without a loop:
    the notify can land between check and wait, and the consumer sleeps
    forever (surfaces as a weave deadlock)."""
    box = _Box()
    box.flag = False
    cv = threading.Condition()

    def consumer():
        if not box.flag:  # unlocked check: the planted race
            with cv:
                cv.wait()

    def producer():
        with cv:
            box.flag = True
            cv.notify()

    c = threading.Thread(target=consumer)
    p = threading.Thread(target=producer)
    c.start()
    p.start()
    c.join()
    p.join()


def lost_wakeup_fixed():
    box = _Box()
    box.flag = False
    cv = threading.Condition()

    def consumer():
        with cv:
            while not box.flag:
                cv.wait()

    def producer():
        with cv:
            box.flag = True
            cv.notify()

    c = threading.Thread(target=consumer)
    p = threading.Thread(target=producer)
    c.start()
    p.start()
    c.join()
    p.join()


def leaked_thread_scenario():
    """Worker parks on an Event nobody sets; the scenario returns without
    joining it — the drain phase must report a WeaveLeak."""
    ev = threading.Event()
    t = threading.Thread(target=ev.wait)
    t.start()
    # no stop path, no join: the planted lifecycle bug


def test_explorer_finds_planted_torn_write_and_replay_reproduces():
    res = ex.explore(torn_write_scenario, random_schedules=20, seed=7,
                     preemption_schedules=20)
    assert res.failures, "explorer missed the planted torn write"
    fail = res.failures[0]
    assert fail.kind == "exception" and "torn write" in fail.error
    # the token IS the bug report: replaying it reproduces the failure
    again = ex.replay(torn_write_scenario, fail.token)
    assert again is not None and "torn write" in again.error
    # and replay is deterministic: same token, same failure, twice (compare
    # the stable message text — pytest's rewritten assert embeds object ids)
    third = ex.replay(torn_write_scenario, fail.token)
    assert third is not None and third.kind == again.kind
    assert "torn write: 1 != 2" in third.error


def test_torn_write_fix_is_clean_under_identical_budget():
    res = ex.explore(torn_write_fixed, random_schedules=20, seed=7,
                     preemption_schedules=20)
    assert res.ok, [f.error for f in res.failures]
    assert res.schedules_explored >= 20


def test_explorer_finds_planted_lost_wakeup_as_deadlock():
    res = ex.explore(lost_wakeup_scenario, random_schedules=20, seed=3,
                     preemption_schedules=20)
    assert any(f.kind == "deadlock" for f in res.failures), (
        "explorer missed the planted lost wakeup: "
        f"{[(f.kind, f.error) for f in res.failures]}")
    fail = next(f for f in res.failures if f.kind == "deadlock")
    again = ex.replay(lost_wakeup_scenario, fail.token)
    assert again is not None and again.kind == "deadlock"


def test_lost_wakeup_fix_is_clean_under_identical_budget():
    res = ex.explore(lost_wakeup_fixed, random_schedules=20, seed=3,
                     preemption_schedules=20)
    assert res.ok, [f.error for f in res.failures]


def test_drain_reports_leaked_thread():
    """Zero-leak assertion: a worker still blocked when the scenario body
    returns is a WeaveLeak, on every schedule."""
    sched = WeaveScheduler(ex.SweepPolicy())
    with pytest.raises(WeaveLeak):
        sched.run(leaked_thread_scenario)
    res = ex.explore(leaked_thread_scenario, random_schedules=5, seed=0,
                     preemption_schedules=3)
    assert res.failures and all(f.kind == "leak" for f in res.failures)


# ---------------------------------------------------------------------------
# determinism of the exploration itself
# ---------------------------------------------------------------------------


def test_same_seed_same_schedule():
    def run_once(seed):
        _, sched = ex.run_schedule(torn_write_scenario,
                                   ex.RandomPolicy(seed))
        return list(sched.choices)

    assert run_once(11) == run_once(11)
    # different seeds do explore (at least sometimes) different schedules
    assert any(run_once(11) != run_once(s) for s in range(12, 18))


def test_token_roundtrip():
    for choices in ([], [0, 1, 2], [35, 36, 0, 400]):
        assert ex.decode_token(ex.encode_token(choices)) == choices
    with pytest.raises(ValueError):
        ex.decode_token("not-a-token")


# ---------------------------------------------------------------------------
# the real control-plane scenarios stay green (small budget; the full
# budget is `make weave`)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", sorted(sc.SCENARIOS))
def test_scenario_clean_small_budget(name):
    sc.warm()
    res = ex.explore(sc.SCENARIOS[name], random_schedules=4, seed=0,
                     preemption_schedules=6)
    assert res.ok, (name, [(f.kind, f.error, f.token)
                           for f in res.failures])
    assert res.truncated == 0
