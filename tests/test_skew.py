"""Workload-skew telemetry: heavy-hitter sketches, per-shard load accounting,
and fleet-wide /metrics aggregation (round 9).

E2E acceptance (ISSUE 4): a Zipf id stream through the sharded exchange must
raise `exchange.shard_imbalance` above a uniform stream's; the Space-Saving
top-K must contain the true top-K of an exact counter (with the documented
`est - err <= true <= est` bound); `/statusz` shows the hot-id table; and
`merge_prometheus` over two live node scrapes yields histogram bucket counts
equal to the sum of the parts (verified against each node's `_count`)."""

import json
import threading
import urllib.request

import numpy as np
import pytest

import openembedding_tpu as embed
from openembedding_tpu.utils import metrics
from openembedding_tpu.utils.sketch import (CountMin, SkewMonitor,
                                            SpaceSaving, shard_balance_text)

S = 8  # conftest forces 8 virtual CPU devices


@pytest.fixture(autouse=True)
def _fresh():
    metrics._REGISTRY.clear()
    yield
    metrics._REGISTRY.clear()


# -- sketches -----------------------------------------------------------------


def test_count_min_never_undercounts():
    rng = np.random.default_rng(0)
    ids = rng.integers(0, 5000, size=200_000)
    cm = CountMin(width=2048, depth=4)
    uniq, cnt = np.unique(ids, return_counts=True)
    for chunk in np.array_split(np.arange(uniq.size), 7):
        cm.add(uniq[chunk], cnt[chunk])
    est = cm.query(uniq)
    assert (est >= cnt).all()  # over-count only, by construction
    assert cm.total == ids.size


def test_space_saving_topk_contains_true_topk():
    """The acceptance bound: the sketch's tracked set must contain the exact
    counter's true top-K, and every tracked estimate must satisfy
    est - err <= true <= est (the documented Space-Saving invariant)."""
    rng = np.random.default_rng(1)
    # heavy Zipf head over a vocab far bigger than the sketch
    ids = rng.zipf(1.3, size=300_000)
    ids = ids[ids < 100_000]
    sk = SpaceSaving(k=64)
    for chunk in np.array_split(ids, 23):  # stream in batches
        sk.update(chunk)
    uniq, cnt = np.unique(ids, return_counts=True)
    true = dict(zip(uniq.tolist(), cnt.tolist()))
    true_top10 = set(uniq[np.argsort(-cnt)][:10].tolist())
    tracked = {hid: (est, err) for hid, est, err in sk.topk()}
    missing = true_top10 - set(tracked)
    assert not missing, f"true top-10 ids missing from sketch: {missing}"
    for hid in true_top10:
        est, err = tracked[hid]
        assert est - err <= true[hid] <= est, (hid, est, err, true[hid])
    assert sk.total == ids.size


def test_space_saving_pair_and_padding_ids():
    """Split-pair (n, 2) uint32 batches re-join to int64; -1 serving padding
    is dropped, not counted."""
    from openembedding_tpu.ops.id64 import np_split_ids
    ids64 = np.array([7, 7, 7, (1 << 40) + 3, (1 << 40) + 3, 9], np.int64)
    sk = SpaceSaving(k=8)
    sk.update(np_split_ids(ids64))
    sk.update(np.array([-1, -1, 7]))
    top = dict((h, e) for h, e, _ in sk.topk())
    assert top[7] == 4
    assert top[(1 << 40) + 3] == 2
    assert sk.total == 7  # padding ids never counted


def test_space_saving_decay_rotates_topk_under_drift():
    """`SpaceSaving(decay=...)`: after a distribution shift the NEW heavy
    hitters must displace the stale ones from the top-K within a bounded
    number of batches (~the e-folding window 1/(1-decay)), instead of being
    drowned by accumulated old mass. The no-decay control shows the failure
    this fixes: old ids keep the top ranks long after the shift."""
    rng = np.random.default_rng(11)
    old_hot = np.arange(0, 8, dtype=np.int64)          # phase 1 heavy hitters
    new_hot = np.arange(1000, 1008, dtype=np.int64)    # phase 2 heavy hitters

    def batch(hot):
        ids = rng.integers(0, 100_000, 512)
        ids[: 512 // 2] = hot[rng.integers(0, hot.size, 512 // 2)]
        return ids

    decayed = SpaceSaving(k=32, decay=0.8)   # window ~5 batches
    plain = SpaceSaving(k=32)
    warmup = 40
    for _ in range(warmup):
        b = batch(old_hot)
        decayed.update(b)
        plain.update(b)

    def top8(sk):
        return {h for h, _est, _err in sk.topk(8)}

    assert top8(decayed) == set(old_hot.tolist())
    rotated_at = None
    shift_batches = 15  # a few e-folding windows; << the 40-batch warmup
    for i in range(shift_batches):
        b = batch(new_hot)
        decayed.update(b)
        plain.update(b)
        if rotated_at is None and top8(decayed) == set(new_hot.tolist()):
            rotated_at = i + 1
    assert rotated_at is not None and rotated_at <= shift_batches, \
        f"decayed top-K never rotated: {sorted(top8(decayed))}"
    # control: without decay the stale warmup mass still holds the top ranks
    assert top8(plain) == set(old_hot.tolist())


def test_space_saving_coverage_curve():
    """`coverage()` is the hot_rows sizing input: monotone shares in (0, 1],
    and on a stream the sketch tracks exactly, the top-k share equals the
    true cumulative traffic fraction."""
    sk = SpaceSaving(k=16)
    # 4 ids with counts 40, 30, 20, 10 (total 100): exact coverage known
    ids = np.repeat(np.array([1, 2, 3, 4], np.int64), [40, 30, 20, 10])
    sk.update(ids)
    cov = dict(sk.coverage([1, 2, 4]))
    assert cov[1] == pytest.approx(0.40)
    assert cov[2] == pytest.approx(0.70)
    assert cov[4] == pytest.approx(1.00)
    ks = [k for k, _ in sk.coverage()]
    shares = [s for _, s in sk.coverage()]
    assert ks == sorted(ks) and shares == sorted(shares)  # monotone curve


def test_coverage_stays_bounded_and_monotone_after_decay():
    """Regression (round 12): `scale()`'s floor-rounding shrinks the stream
    total faster than the tracked estimates (and count-min over-counts), so
    the raw cumulative share could exceed 1.0 after decay — and a total
    decayed to zero must not divide. The curve is clamped to [0, 1] and
    stays monotone; the placement policy sizes hot caches from it."""
    rng = np.random.default_rng(0)
    sk = SpaceSaving(k=32, decay=0.5)
    for _ in range(30):
        # heavy head + noisy tail: count-min over-counts the tail admits
        ids = np.concatenate([np.repeat(np.arange(8, dtype=np.int64), 40),
                              rng.integers(0, 1 << 20, 200)])
        sk.update(ids)
    for cov in (sk.coverage(), sk.coverage([1, 2, 7, 31, 10**6])):
        shares = [s for _k, s in cov]
        assert all(0.0 <= s <= 1.0 for s in shares), cov
        assert shares == sorted(shares), cov
    # decay the stream total all the way to zero: tracked estimates may
    # still be positive, and the share must stay defined and bounded
    with sk._lock:
        sk.cm.scale(0.0)
    cov0 = sk.coverage()
    assert cov0, "curve vanished"
    assert all(0.0 <= s <= 1.0 for _k, s in cov0), cov0


def test_skew_monitor_publishes_rank_labeled_gauges():
    mon = SkewMonitor(k=8, sync=True)
    mon.observe("user", np.array([5, 5, 5, 5, 9, 9, 3]))
    mon.publish()
    rep = metrics.report()
    assert rep['skew.hot_id{rank="0",table="user"}'] == 5
    assert rep['skew.hot_id_count{rank="0",table="user"}'] == 4
    assert rep['skew.stream_ids{table="user"}'] == 7
    assert "hot" in mon.render_text() or "id=5" in mon.render_text()


def test_skew_monitor_worker_thread_drains():
    mon = SkewMonitor(k=8)
    for _ in range(10):
        assert mon.observe("t", np.arange(100) % 7)
    mon.drain()
    assert mon.sketch("t").total == 1000


# -- per-shard load accounting through the sharded exchange -------------------


def _mesh_step_stats(ids):
    """Run ONE jitted MeshTrainer step over the 8-device CPU mesh with the
    given (B, F) id batch; -> host stats dict."""
    import jax
    from openembedding_tpu.data import synthetic_criteo
    from openembedding_tpu.models import make_deepfm
    from openembedding_tpu.parallel import MeshTrainer

    model = make_deepfm(vocabulary=1 << 14, dim=4)
    trainer = MeshTrainer(model, embed.Adagrad(0.05))
    batch = next(synthetic_criteo(ids.shape[0], id_space=1 << 14,
                                  num_fields=ids.shape[1],
                                  ids_dtype=np.int64))
    batch["sparse"]["categorical"] = ids.astype(np.int64)
    state = trainer.init(batch)
    step = trainer.jit_train_step(batch, state)
    _state, m = step(state, batch)
    return jax.device_get(m["stats"])


def _imbalance(stats):
    pos = np.asarray(stats["categorical/shard_positions"], np.float64)
    return float(pos.max() / pos.mean())


def test_zipf_stream_raises_shard_imbalance_above_uniform():
    """E2E acceptance: Zipf -> hot shards -> exchange.shard_imbalance above
    the uniform stream's, end to end through the jitted exchange AND the
    record_step_stats fold into labeled gauges."""
    rng = np.random.default_rng(7)
    B, F = 64, 26
    uniform = rng.integers(0, 1 << 14, size=(B, F))
    # planted heavy hitters: half of all positions hit 4 hot ids that share
    # owner shard (id % 8 == 5) — the unambiguous skew case
    zipf = rng.integers(0, 1 << 14, size=(B, F))
    hot = rng.random((B, F)) < 0.5
    zipf[hot] = np.array([5, 13, 21, 29])[rng.integers(0, 4, hot.sum())]

    s_uni = _mesh_step_stats(uniform)
    metrics.record_step_stats(s_uni)
    s_zipf = _mesh_step_stats(zipf)
    metrics.record_step_stats(s_zipf)

    assert _imbalance(s_zipf) > _imbalance(s_uni) + 0.5, (
        _imbalance(s_zipf), _imbalance(s_uni))
    rep = metrics.report()
    # the labeled gauge series exist per shard, and the imbalance histogram
    # (mean of the two steps) sits above the uniform baseline
    assert rep['exchange.shard_rows{shard="0",table="categorical"}'] >= 0
    assert rep['exchange.shard_imbalance{table="categorical"}'] > 1.0
    # shard 5 received the planted hot mass
    per_shard = [rep[f'exchange.shard_positions{{shard="{i}",'
                     f'table="categorical"}}'] for i in range(S)]
    assert int(np.argmax(per_shard)) == 5
    # derived unique ratio present and sane
    assert 0 < rep['exchange.unique_ratio{table="categorical"}'] <= 1.0
    # bucket_fill: per-source occupancy fractions in (0, 1]
    fills = [rep[f'exchange.bucket_fill{{shard="{i}",'
                 f'table="categorical"}}'] for i in range(S)]
    assert all(0 < f <= 1.0 for f in fills)
    # renderer smoke
    text = shard_balance_text()
    assert "categorical" in text and "shard_positions" in text


def test_shard_stats_off_drops_vectors():
    import jax
    from openembedding_tpu.data import synthetic_criteo
    from openembedding_tpu.models import make_deepfm
    from openembedding_tpu.parallel import MeshTrainer

    model = make_deepfm(vocabulary=1 << 12, dim=4)
    trainer = MeshTrainer(model, embed.Adagrad(0.05), shard_stats=False)
    batch = next(synthetic_criteo(32, id_space=1 << 12, ids_dtype=np.int64))
    state = trainer.init(batch)
    step = trainer.jit_train_step(batch, state)
    _state, m = step(state, batch)
    stats = jax.device_get(m["stats"])
    assert "categorical/shard_rows" not in stats
    assert "categorical/pull_indices" in stats  # scalars stay


# -- fleet aggregation --------------------------------------------------------


def _serve(tmp_path, name, **kw):
    from openembedding_tpu.serving import make_server
    httpd = make_server(str(tmp_path / name), port=0, **kw)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.read().decode()


def test_merge_prometheus_sums_counters_and_hist_buckets():
    metrics.observe("serving.requests", 3)
    for v in (0.5, 2.0, 400.0):
        metrics.observe("serving.predict.ms", v, "hist",
                        labels={"model": "m-0"})
    metrics.observe("exchange.wire_bytes_per_step", 128, "gauge")
    text = metrics.prometheus_text()
    merged = metrics.merge_prometheus([("a", text), ("b", text)])
    p = metrics.parse_prometheus(merged)
    samples = {(n, tuple(sorted(l.items()))): v for n, l, v in p["samples"]}
    # counters sum
    assert samples[("oetpu_serving_requests_total", ())] == 6
    # histogram _count/_sum/buckets sum; +Inf bucket == summed _count
    key_count = ("oetpu_serving_predict_ms_count", (("model", "m-0"),))
    assert samples[key_count] == 6
    inf = ("oetpu_serving_predict_ms_bucket",
           (("le", "+Inf"), ("model", "m-0")))
    assert samples[inf] == 6
    # gauges keep per-instance series (last write wins per instance)
    ga = ("oetpu_exchange_wire_bytes_per_step",
          (("instance", "a"),))
    gb = ("oetpu_exchange_wire_bytes_per_step",
          (("instance", "b"),))
    assert samples[ga] == 128 and samples[gb] == 128
    # bucket series stay monotone on the union grid
    cums = [v for (n, l), v in samples.items()
            if n == "oetpu_serving_predict_ms_bucket"]
    assert cums == sorted(cums)


def test_merge_handles_differently_elided_buckets():
    """Nodes elide different empty interior buckets; the merge must
    de-cumulate per node, sum on the union le grid, and re-cumulate."""
    a = ("# TYPE m_ms histogram\n"
         'm_ms_bucket{le="1"} 2\nm_ms_bucket{le="+Inf"} 3\n'
         "m_ms_sum 10.0\nm_ms_count 3\n")
    b = ("# TYPE m_ms histogram\n"
         'm_ms_bucket{le="4"} 1\nm_ms_bucket{le="+Inf"} 5\n'
         "m_ms_sum 40.0\nm_ms_count 5\n")
    p = metrics.parse_prometheus(metrics.merge_prometheus([("a", a),
                                                           ("b", b)]))
    got = {(n, tuple(sorted(l.items()))): v for n, l, v in p["samples"]}
    assert got[("m_ms_count", ())] == 8
    assert got[("m_ms_bucket", (("le", "1"),))] == 2   # only a's mass
    assert got[("m_ms_bucket", (("le", "4"),))] == 3   # a's 2 + b's 1
    assert got[("m_ms_bucket", (("le", "+Inf"),))] == 8


def test_fleetz_merges_two_live_nodes(tmp_path):
    """E2E acceptance: two live serving nodes; /fleetz on node A (peers=B)
    returns bucket/_count sums equal to the sum of the two /metrics parts."""
    metrics.observe("serving.requests", 2)
    for v in (1.0, 3.0):
        metrics.observe("serving.predict.ms", v, "hist")
    ha, url_a = _serve(tmp_path, "a")
    hb, url_b = _serve(tmp_path, "b")
    try:
        part_a = metrics.parse_prometheus(_get(f"{url_a}/metrics"))
        part_b = metrics.parse_prometheus(_get(f"{url_b}/metrics"))
        def count_of(p):
            return sum(v for n, _l, v in p["samples"]
                       if n == "oetpu_serving_predict_ms_count")
        fleet = metrics.parse_prometheus(
            _get(f"{url_a}/fleetz?peers={url_b}"))
        assert count_of(fleet) == count_of(part_a) + count_of(part_b)
        reqs = {n: v for n, _l, v in fleet["samples"]}
        assert reqs["oetpu_serving_requests_total"] == sum(
            v for p in (part_a, part_b) for n, _l, v in p["samples"]
            if n == "oetpu_serving_requests_total")
    finally:
        ha.shutdown()
        hb.shutdown()


def test_fleetz_degrades_on_dead_peer(tmp_path):
    metrics.observe("serving.requests", 1)
    ha, url_a = _serve(tmp_path, "a")
    try:
        body = _get(f"{url_a}/fleetz?peers=http://127.0.0.1:1/")
        assert "unreachable" in body
        assert "oetpu_serving_requests_total" in body  # own scrape survives
    finally:
        ha.shutdown()


def test_metrics_fleet_tool(tmp_path, capsys):
    import tools.metrics_fleet as mf
    metrics.observe("serving.requests", 4)
    ha, url_a = _serve(tmp_path, "a")
    try:
        assert mf.main([url_a, url_a]) == 0
        out = capsys.readouterr().out
        assert "oetpu_serving_requests_total 8" in out
    finally:
        ha.shutdown()


# -- operator surfaces --------------------------------------------------------


def test_statusz_shows_hot_id_table(tmp_path):
    from openembedding_tpu.utils import sketch
    sketch.MONITOR.reset()
    sketch.MONITOR.observe("categorical", np.array([42] * 9 + [7, 7, 1]))
    sketch.MONITOR.drain()
    ha, url_a = _serve(tmp_path, "a")
    try:
        body = _get(f"{url_a}/statusz")
        assert "workload skew (hot ids)" in body
        assert "table categorical" in body
        assert "id=42" in body
        # hot_rows sizing curve renders next to the hot-id table
        assert "coverage:" in body and "top1=" in body
    finally:
        ha.shutdown()
        sketch.MONITOR.reset()


def test_metrics_endpoint_publishes_skew_series(tmp_path):
    from openembedding_tpu.utils import sketch
    sketch.MONITOR.reset()
    sketch.MONITOR.observe("categorical", np.array([42] * 5))
    sketch.MONITOR.drain()
    ha, url_a = _serve(tmp_path, "a")
    try:
        body = _get(f"{url_a}/metrics")
        assert ('oetpu_skew_hot_id_count{rank="0",table="categorical"} 5'
                in body)
        assert 'oetpu_skew_stream_ids{table="categorical"} 5' in body
    finally:
        ha.shutdown()
        sketch.MONITOR.reset()


def test_skew_report_tool_renders_scrape(tmp_path, capsys):
    import tools.skew_report as sr
    from openembedding_tpu.utils import sketch
    sketch.MONITOR.reset()
    sketch.MONITOR.observe("categorical", np.array([42] * 5 + [9]))
    sketch.MONITOR.drain()
    sketch.MONITOR.publish()
    scrape = tmp_path / "metrics.txt"
    scrape.write_text(metrics.prometheus_text())
    assert sr.main([str(scrape)]) == 0
    out = capsys.readouterr().out
    assert "table categorical" in out and "42" in out
    # coverage curve from the same scrape (top-1 is 5 of 6 observed ids)
    assert "coverage curve (hot_rows sizing)" in out
    assert "top1=83.3%" in out
    sketch.MONITOR.reset()


def test_serving_predict_feeds_sketch(tmp_path):
    """Predict ids reach the heavy-hitter sketch through the servable hook
    (export.StandaloneModel.predict)."""
    from openembedding_tpu.data import synthetic_criteo
    from openembedding_tpu.export import export_standalone
    from openembedding_tpu.model import Trainer
    from openembedding_tpu.models import make_deepfm
    from openembedding_tpu.serving import make_server
    from openembedding_tpu.utils import sketch

    sketch.MONITOR.reset()
    model = make_deepfm(vocabulary=512, dim=4)
    trainer = Trainer(model, embed.Adagrad(0.05))
    batch = next(synthetic_criteo(8, id_space=512, ids_dtype=np.int64))
    state = trainer.init(batch)
    step = trainer.jit_train_step()
    state, _ = step(state, batch)
    export_dir = tmp_path / "export"
    export_standalone(state, model, str(export_dir), model_sign="m-0")
    httpd = make_server(str(tmp_path / "reg"), port=0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{httpd.server_address[1]}"
    try:
        req = urllib.request.Request(
            f"{url}/models", method="POST",
            data=json.dumps({"model_sign": "m-0",
                             "model_uri": str(export_dir)}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30):
            pass
        sparse = {"categorical": [[3] * 26, [3] * 26]}
        req = urllib.request.Request(
            f"{url}/models/m-0/predict", method="POST",
            data=json.dumps({"sparse": sparse,
                             "dense": [[0.0] * 13] * 2}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30):
            pass
        sketch.MONITOR.drain()
        top = dict((h, e) for h, e, _ in
                   sketch.MONITOR.sketch("categorical").topk())
        assert top.get(3, 0) >= 52  # 2 rows x 26 fields
    finally:
        httpd.shutdown()
        sketch.MONITOR.reset()


def test_periodic_reporter_survives_broken_sink():
    """Satellite: a raising sink must not kill the reporter thread; failures
    count in metrics.report_errors and later reports still arrive."""
    import time as _time
    calls = {"n": 0}

    def sink(_s):
        calls["n"] += 1
        if calls["n"] == 1:
            raise BrokenPipeError("gone")

    rep = metrics.PeriodicReporter(0.03, sink=sink, reset=False)
    with rep:
        deadline = _time.time() + 5.0
        while calls["n"] < 3 and _time.time() < deadline:
            _time.sleep(0.02)
    assert calls["n"] >= 3  # thread survived the first raise
    assert metrics.report()["metrics.report_errors"] == 1


def test_report_uses_one_hist_snapshot(monkeypatch):
    """Satellite: report() must derive a histogram's mean AND quantiles from
    ONE hist_snapshot per accumulator (consistency under concurrent load)."""
    for v in (1.0, 2.0, 3.0, 4.0):
        metrics.observe("serving.lat.ms", v, "hist")
    acc = metrics.Accumulator.get("serving.lat.ms", "hist")
    calls = {"n": 0}
    real = type(acc).hist_snapshot

    def counting(self):
        calls["n"] += 1
        return real(self)

    monkeypatch.setattr(type(acc), "hist_snapshot", counting)
    rep = metrics.report()
    assert calls["n"] == 1
    assert rep["serving.lat.ms"] == 2.5
    assert rep["serving.lat.ms.p50"] > 0
