"""Ring attention + Ulysses sequence parallelism vs the single-device oracle.

Run on the 8-virtual-device CPU mesh (tests/conftest.py), both on a 1-D 'seq' mesh
and on the 'seq' axis of a 2-D (data, seq) mesh — the layout context-parallel
training uses."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from openembedding_tpu.parallel.sequence import (reference_attention,
                                                 ring_attention,
                                                 ulysses_attention)


def _qkv(rng, b, s, h, d, dtype=jnp.float32):
    return tuple(jnp.asarray(rng.standard_normal((b, s, h, d)), dtype)
                 for _ in range(3))


def _seq_mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("seq",))


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_reference_1d(causal):
    rng = np.random.default_rng(0)
    q, k, v = _qkv(rng, 2, 32, 4, 8)
    want = reference_attention(q, k, v, causal=causal)
    mesh = _seq_mesh(8)
    got = jax.jit(jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis="seq", causal=causal),
        mesh=mesh, in_specs=P(None, "seq"), out_specs=P(None, "seq"),
        check_vma=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_ulysses_matches_reference_1d(causal):
    rng = np.random.default_rng(1)
    q, k, v = _qkv(rng, 2, 32, 8, 4)  # H=8 divisible by P=8
    want = reference_attention(q, k, v, causal=causal)
    mesh = _seq_mesh(8)
    got = jax.jit(jax.shard_map(
        lambda q, k, v: ulysses_attention(q, k, v, axis="seq", causal=causal),
        mesh=mesh, in_specs=P(None, "seq"), out_specs=P(None, "seq"),
        check_vma=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                               rtol=2e-5, atol=2e-5)


def test_ring_on_2d_mesh_seq_axis():
    """Batch over 'data', sequence over 'seq' — the CP training layout."""
    rng = np.random.default_rng(2)
    q, k, v = _qkv(rng, 4, 16, 2, 8)
    want = reference_attention(q, k, v, causal=True)
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "seq"))
    got = jax.jit(jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis="seq", causal=True),
        mesh=mesh, in_specs=P("data", "seq"), out_specs=P("data", "seq"),
        check_vma=False))(q, k, v)
    np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                               rtol=2e-5, atol=2e-5)


def test_ring_bf16_inputs():
    rng = np.random.default_rng(3)
    q, k, v = _qkv(rng, 2, 16, 2, 8, jnp.bfloat16)
    want = reference_attention(q, k, v, causal=True)
    mesh = _seq_mesh(4)
    got = jax.jit(jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis="seq", causal=True),
        mesh=mesh, in_specs=P(None, "seq"), out_specs=P(None, "seq"),
        check_vma=False))(q, k, v)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(want, np.float32),
                               np.asarray(got, np.float32), rtol=0.1, atol=0.1)


def test_ulysses_rejects_indivisible_heads():
    rng = np.random.default_rng(4)
    q, k, v = _qkv(rng, 1, 8, 3, 4)  # H=3, P=4
    mesh = _seq_mesh(4)
    with pytest.raises(ValueError, match="not divisible"):
        jax.jit(jax.shard_map(
            lambda q, k, v: ulysses_attention(q, k, v, axis="seq"),
            mesh=mesh, in_specs=P(None, "seq"), out_specs=P(None, "seq"),
            check_vma=False))(q, k, v)


def test_ring_gradients_match_reference():
    """CP must be differentiable — the training path runs attention under grad."""
    rng = np.random.default_rng(5)
    q, k, v = _qkv(rng, 2, 16, 2, 4)
    mesh = _seq_mesh(4)

    def loss_ref(q, k, v):
        return jnp.sum(jnp.square(reference_attention(q, k, v, causal=True)))

    sharded = jax.shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis="seq", causal=True),
        mesh=mesh, in_specs=P(None, "seq"), out_specs=P(None, "seq"),
        check_vma=False)

    def loss_ring(q, k, v):
        return jnp.sum(jnp.square(sharded(q, k, v)))

    g_ref = jax.grad(loss_ref)(q, k, v)
    g_ring = jax.jit(jax.grad(loss_ring))(q, k, v)
    np.testing.assert_allclose(np.asarray(g_ref), np.asarray(g_ring),
                               rtol=1e-4, atol=1e-4)
