"""Single-device end-to-end slice: Embedding + Trainer train smoke, hash-vs-array
equivalence, EmbeddingVariable facade (SURVEY.md §7 build-order step 2)."""

import dataclasses

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import openembedding_tpu as embed
from openembedding_tpu.embedding import (EmbeddingSpec, apply_gradients,
                                         init_table_state, lookup, lookup_train)


class TinyDense(nn.Module):
    """Dense tower consuming pulled embedding rows: logit = w . concat(rows)."""

    @nn.compact
    def __call__(self, embedded, dense_inputs):
        parts = [embedded[k].reshape(embedded[k].shape[0], -1)
                 for k in sorted(embedded)]
        if dense_inputs is not None:
            parts.append(dense_inputs)
        x = jnp.concatenate(parts, axis=-1)
        return nn.Dense(1)(x)[:, 0]


def make_batch(rng, batch=32, fields=3, vocab=100):
    ids = rng.integers(0, vocab, size=(batch, fields))
    label = (ids.sum(axis=1) % 2).astype(np.float32)
    return {"sparse": {"emb": jnp.asarray(ids)},
            "dense": None,
            "label": jnp.asarray(label)}


def test_train_loss_decreases():
    rng = np.random.default_rng(0)
    layer = embed.Embedding(100, 8, name="emb",
                            optimizer=embed.Adagrad(learning_rate=0.1))
    model = embed.EmbeddingModel(TinyDense(), [layer])
    trainer = embed.Trainer(model, optimizer=embed.Adagrad(learning_rate=0.1))
    batch = make_batch(rng)
    state = trainer.init(batch)
    step = trainer.jit_train_step()
    losses = []
    for _ in range(60):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.7, losses[::10]
    assert int(state.step) == 60


def test_trainer_updates_only_touched_rows():
    layer = embed.Embedding(50, 4, name="emb")
    model = embed.EmbeddingModel(TinyDense(), [layer])
    trainer = embed.Trainer(model, optimizer=embed.SGD(learning_rate=0.5))
    ids = jnp.asarray([[1, 2], [3, 1]])
    batch = {"sparse": {"emb": ids}, "dense": None,
             "label": jnp.asarray([1.0, 0.0])}
    state = trainer.init(batch)
    w0 = np.asarray(state.tables["emb"].weights)
    state, _ = trainer.jit_train_step()(state, batch)
    w1 = np.asarray(state.tables["emb"].weights)
    touched = [1, 2, 3]
    untouched = [i for i in range(50) if i not in touched]
    np.testing.assert_array_equal(w1[untouched], w0[untouched])
    assert not np.allclose(w1[touched], w0[touched])


def test_hash_table_matches_array_table():
    """Same id stream through a hash-table variable and an array variable must produce
    identical per-id weights (capacity ample, same initializer constant)."""
    opt = embed.Adagrad(learning_rate=0.1)
    array_spec = EmbeddingSpec(name="a", input_dim=64, output_dim=4,
                               initializer=embed.Constant(0.5), variable_id=0)
    hash_spec = EmbeddingSpec(name="h", input_dim=-1, output_dim=4,
                              initializer=embed.Constant(0.5), capacity=256,
                              variable_id=1)
    a_state = init_table_state(array_spec, opt)
    h_state = init_table_state(hash_spec, opt)
    rng = np.random.default_rng(0)
    for step in range(5):
        ids = jnp.asarray(rng.integers(0, 64, size=24))
        grads = jnp.asarray(rng.normal(size=(24, 4)).astype(np.float32))
        a_state, a_rows = lookup_train(array_spec, a_state, ids)
        h_state, h_rows = lookup_train(hash_spec, h_state, ids)
        np.testing.assert_allclose(np.asarray(a_rows), np.asarray(h_rows),
                                   rtol=1e-6, err_msg=f"step {step} pull")
        a_state = apply_gradients(array_spec, a_state, opt, ids, grads)
        h_state = apply_gradients(hash_spec, h_state, opt, ids, grads)
    probe = jnp.arange(64)
    a_final = lookup(array_spec, a_state, probe)
    h_final = lookup(hash_spec, h_state, probe)
    seen = np.asarray(h_state.keys) >= 0
    assert seen.sum() > 0
    # ids never pulled return 0 from the hash table; compare only inserted ids
    inserted = np.zeros(64, bool)
    h_keys = np.asarray(h_state.keys)
    inserted[h_keys[h_keys >= 0]] = True
    np.testing.assert_allclose(np.asarray(h_final)[inserted],
                               np.asarray(a_final)[inserted], rtol=1e-6)
    assert np.all(np.asarray(h_final)[~inserted] == 0)


def test_hash_table_collision_heavy():
    """Tiny capacity forces long probe chains; ids must still resolve distinctly."""
    from openembedding_tpu.tables.hash_table import hash_find, hash_find_or_insert
    keys = jnp.full((16,), -1, jnp.int64)
    ids = jnp.asarray(np.arange(12) * 16, jnp.int64)  # adversarial: same low bits
    keys, slots, overflow = hash_find_or_insert(keys, ids, num_probes=16)
    assert int(overflow) == 0
    s = np.asarray(slots)
    assert len(set(s.tolist())) == 12  # all distinct slots
    found = hash_find(keys, ids, num_probes=16)
    np.testing.assert_array_equal(np.asarray(found), s)


def test_embedding_variable_facade():
    var = embed.EmbeddingVariable(
        EmbeddingSpec(name="v", input_dim=20, output_dim=4,
                      initializer=embed.Constant(1.0), variable_id=0),
        optimizer=embed.TestOptimizer(learning_rate=1.0, flip=10.0))
    rows = var.sparse_read(jnp.asarray([3, 3, 5]))
    np.testing.assert_allclose(np.asarray(rows), 1.0)
    grads = jnp.asarray([[1.0] * 4, [1.0] * 4, [2.0] * 4], jnp.float32)
    var.push_gradients(jnp.asarray([3, 3, 5]), grads)
    var.update_weights()
    after = np.asarray(var.sparse_read(jnp.asarray([3, 5, 7])))
    # id 3: w = 1 + 1.0*(1+1)/2 + 10 = 12; id 5: 1 + 2/1 + 10 = 13; id 7 untouched
    np.testing.assert_allclose(after[0], 12.0, rtol=1e-6)
    np.testing.assert_allclose(after[1], 13.0, rtol=1e-6)
    np.testing.assert_allclose(after[2], 1.0, rtol=1e-6)


def test_sparse_as_dense_mode():
    """'Cache' mode: small tables live in dense params and train via the dense path
    (reference `exb.py:241-248,593-642`)."""
    rng = np.random.default_rng(0)
    layer = embed.Embedding(100, 8, name="emb", sparse_as_dense=True)
    model = embed.EmbeddingModel(TinyDense(), [layer])
    trainer = embed.Trainer(model, optimizer=embed.Adagrad(learning_rate=0.1))
    batch = make_batch(rng)
    state = trainer.init(batch)
    assert "emb" in state.dense_params["__embeddings__"]
    assert state.tables == {}
    step = trainer.jit_train_step()
    losses = []
    for _ in range(60):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.7, losses[::10]


def test_initializers_shapes_and_ranges():
    key = jax.random.PRNGKey(0)
    u = embed.Uniform(minval=-2, maxval=2)(key, (1000, 4))
    assert float(u.min()) >= -2 and float(u.max()) <= 2
    n = embed.TruncatedNormal(stddev=1.0)(key, (1000, 4))
    assert float(jnp.abs(n).max()) <= 2.0 + 1e-5
    c = embed.Constant(3.0)(key, (5, 2))
    np.testing.assert_allclose(np.asarray(c), 3.0)
    again = embed.make_initializer(embed.Uniform(-1, 1).to_config())
    assert again == embed.Uniform(-1, 1)


def test_ragged_rows_via_pad_minus_one():
    """The framework's answer to `Variable.sparse_read`'s RaggedTensor support
    (`exb.py:308-327`): variable-length id lists pad to the static field
    width with -1. End-to-end semantics pinned here: padded positions pull
    ZERO rows (so sum/mean pooling over the field dim equals the true varlen
    pooling) and their gradients train NOTHING — a 2-step train on padded
    batches is bit-identical to the same train where the pad slots point at
    a scratch row that is never read."""
    layer = embed.Embedding(50, 4, name="emb",
                            optimizer=embed.SGD(learning_rate=0.5))
    model = embed.EmbeddingModel(TinyDense(), [layer])
    trainer = embed.Trainer(model, optimizer=embed.SGD(learning_rate=0.5))
    rng = np.random.default_rng(3)
    # ragged lists of length 1..4, padded to 4 with -1
    lengths = rng.integers(1, 5, size=(8,))
    ids = np.full((8, 4), -1, np.int64)
    for r, ln in enumerate(lengths):
        ids[r, :ln] = rng.integers(0, 50, size=(ln,))
    batch = {"sparse": {"emb": jnp.asarray(ids)}, "dense": None,
             "label": jnp.asarray((lengths % 2).astype(np.float32))}
    state = trainer.init(batch)
    rows = trainer.table_lookup(model.specs["emb"], state.tables["emb"],
                                jnp.asarray(ids))
    rows = np.asarray(rows)
    for r, ln in enumerate(lengths):
        assert np.all(rows[r, ln:] == 0.0), (r, ln)   # pad rows are zero
        assert np.all(np.any(rows[r, :ln] != 0.0, axis=-1)), (r, ln)
    # pooled-sum equivalence with the true ragged pooling
    np.testing.assert_allclose(
        rows.sum(axis=1),
        np.stack([rows[r, :ln].sum(axis=0)
                  for r, ln in enumerate(lengths)]), rtol=0, atol=0)
    # training with pads still trains the REAL rows (the -1 grads go nowhere:
    # test_negative_ids_never_train_any_row pins the row-level guarantee)
    w0 = np.asarray(state.tables["emb"].weights)  # before donation
    step = trainer.jit_train_step()
    s1 = state
    for _ in range(2):
        s1, _ = step(s1, batch)
    assert not np.allclose(np.asarray(s1.tables["emb"].weights), w0)


def test_negative_ids_never_train_any_row():
    """id -1 must not wrap onto the last table row (jax scatter wraps negative
    indices; regression for the sentinel-routing in sparse_apply_dense_table).
    The last row trains ONLY from its own legitimate id, and the invalid slots
    must not poison the sorted/unique scatter promises."""
    import numpy as np
    import jax.numpy as jnp
    from openembedding_tpu import optimizers
    from openembedding_tpu.ops.sparse import sparse_apply_dense_table

    rng = np.random.default_rng(0)
    n_rows, dim = 16, 4
    opt = optimizers.Adagrad(learning_rate=0.5)
    w = jnp.asarray(rng.standard_normal((n_rows, dim)), jnp.float32)
    slots = opt.init_slots(n_rows, dim)
    ids = jnp.asarray([-1, 3, -7, 5, n_rows - 1, -1], jnp.int32)
    grads = jnp.asarray(rng.standard_normal((6, dim)), jnp.float32)
    new_w, _ = sparse_apply_dense_table(opt, w, slots, ids, grads)
    # rows 3, 5, 15 train; everything else (incl. nothing from the -1s) intact
    for r in range(n_rows):
        if r in (3, 5, n_rows - 1):
            assert not np.allclose(np.asarray(new_w[r]), np.asarray(w[r])), r
        else:
            np.testing.assert_array_equal(np.asarray(new_w[r]),
                                          np.asarray(w[r]), err_msg=str(r))
    # the last row's update must come from ITS grad only, not the -1 grads
    ref_w, _ = sparse_apply_dense_table(
        opt, w, opt.init_slots(n_rows, dim),
        jnp.asarray([n_rows - 1], jnp.int32), grads[4:5])
    np.testing.assert_allclose(np.asarray(new_w[-1]), np.asarray(ref_w[-1]),
                               rtol=1e-6)


def test_variable_prefetch_warms_hash_keys():
    """`EmbeddingVariable.prefetch` (reference `Variable.prefetch` /
    PrefetchPullWeights): hash tables insert unseen ids early, so the later
    sparse_read finds them resident; array tables no-op."""
    import numpy as np
    import openembedding_tpu as embed
    from openembedding_tpu.embedding import EmbeddingSpec
    from openembedding_tpu.tables.hash_table import hash_find
    from openembedding_tpu.ops.id64 import np_resident_ids

    spec = EmbeddingSpec(name="v", input_dim=-1, output_dim=4, capacity=64,
                         variable_id=0)
    var = embed.EmbeddingVariable(spec, embed.Adagrad(learning_rate=0.1))
    ids = np.asarray([3, 99, 12345], np.int64)
    before = np_resident_ids(np.asarray(var.state.keys))[1].size
    var.prefetch(ids)
    after = np_resident_ids(np.asarray(var.state.keys))[1].size
    assert after == before + 3
    # the later training pull reads the SAME rows it would have inserted
    rows = np.asarray(var.sparse_read(ids))
    assert rows.shape == (3, 4) and np.isfinite(rows).all()

    # array tables: prefetch is a no-op (rows are resident by construction)
    aspec = EmbeddingSpec(name="a", input_dim=32, output_dim=4, variable_id=1)
    avar = embed.EmbeddingVariable(aspec, embed.Adagrad(learning_rate=0.1))
    w0 = np.asarray(avar.state.weights)
    avar.prefetch(np.asarray([1, 2]))
    np.testing.assert_array_equal(w0, np.asarray(avar.state.weights))
